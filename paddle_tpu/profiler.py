"""Profiler (ref: python/paddle/profiler/profiler.py).

The reference profiler hooks CUDA events + host tracing (nvprof-style).
TPU-native: wall-clock step timing with block_until_ready around the
user-marked regions (XLA dispatch is async, so naive timers measure
nothing), plus jax.profiler trace export for Tensorboard/Perfetto — the
moral equivalent of the reference's Chrome-trace export. The summary()
table mirrors paddle.profiler's print format closely enough to eyeball.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from dataclasses import dataclass, field

import jax

__all__ = ["Profiler", "ProfilerTarget", "RecordEvent", "profile",
           "export_chrome_tracing"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"  # accepted for API parity; maps to the single device
    CUSTOM_DEVICE = "tpu"


@dataclass
class _EventStat:
    calls: int = 0
    total: float = 0.0
    min: float = float("inf")
    max: float = 0.0

    def add(self, dt):
        self.calls += 1
        self.total += dt
        self.min = min(self.min, dt)
        self.max = max(self.max, dt)


def _device_sync():
    """Block until every device's queued work is done (FIFO per-device
    execution: a fresh transfer lands after all previously enqueued ops)."""
    import jax.numpy as jnp
    for d in jax.devices():
        jax.device_put(jnp.zeros(()), d).block_until_ready()


class Profiler:
    """ref: paddle.profiler.Profiler(targets, scheduler, on_trace_ready).

    with Profiler(trace_dir="...") as p:
        for batch in loader:
            train_step(...)
            p.step()
    print(p.summary())
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=True, trace_dir=None, registry=None):
        # timer_only=False (paddle parity: collect more than step timers)
        # turns on the jax trace even without an explicit trace_dir
        if not timer_only and trace_dir is None:
            import tempfile
            trace_dir = os.path.join(tempfile.gettempdir(),
                                     "paddle_tpu_profile")
        self.timer_only = trace_dir is None
        self.trace_dir = trace_dir
        self.on_trace_ready = on_trace_ready
        self._events: dict[str, _EventStat] = defaultdict(_EventStat)
        self._step_t0 = None
        self._steps = 0
        self._active = False
        # registry bridge (docs/observability.md): every timed region
        # also lands in the metrics registry as
        # profiler_region_seconds{region=...}, so profiler numbers ride
        # the same metrics.json export as train/serve telemetry.
        # registry=False disables the bridge; None uses the global one.
        if registry is None:
            from .observability.metrics import get_registry
            registry = get_registry()
        self.registry = registry or None
        # span bridge (docs/observability.md): every timed region also
        # lands on this recorder with its real timestamps, so profiler
        # regions merge into ONE Perfetto timeline with serving/train
        # host spans via observability.spans.export_chrome
        from .observability.spans import SpanRecorder
        self.spans = SpanRecorder(name="profiler")

    def _publish(self, name, dt, t0=None):
        if name.startswith("__"):
            return
        if t0 is not None:
            self.spans.add(name, t0, t0 + dt, tid="regions",
                           cat="profiler")
        if self.registry is None:
            return
        self.registry.histogram(
            "profiler_region_seconds",
            help="profiler-timed region wall time",
            labels={"region": name}).observe(dt)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._active = True
        if self.trace_dir:
            os.makedirs(self.trace_dir, exist_ok=True)
            jax.profiler.start_trace(self.trace_dir)
        self._step_t0 = time.perf_counter()
        return self

    def stop(self):
        if not self._active:
            return
        self._active = False
        if self.trace_dir:
            jax.profiler.stop_trace()
        if self.on_trace_ready:
            self.on_trace_ready(self)

    def __enter__(self):
        return self.start()

    def __exit__(self, *a):
        self.stop()

    # -- step/event marking ------------------------------------------------
    def step(self, num_samples=None):
        """Mark a train-step boundary (ref Profiler.step)."""
        now = time.perf_counter()
        if self._step_t0 is not None:
            st = self._events["train_step"]
            st.add(now - self._step_t0)
            self._publish("train_step", now - self._step_t0,
                          t0=self._step_t0)
            if num_samples:
                self._events["__samples__"].add(num_samples)
        self._step_t0 = now
        self._steps += 1

    @contextlib.contextmanager
    def record_event(self, name, sync=True):
        """Time a region; sync drains each device's execution queue so the
        time covers the region's real compute, not just dispatch (TPU/CPU
        streams run FIFO, so a trailing no-op transfer completes only after
        everything the region enqueued). Drains the queue BEFORE starting
        too, so earlier async work isn't billed to this region."""
        if sync:
            _device_sync()
        t0 = time.perf_counter()
        yield
        if sync:
            _device_sync()
        dt = time.perf_counter() - t0
        self._events[name].add(dt)
        self._publish(name, dt, t0=t0)

    # -- reporting ---------------------------------------------------------
    def summary(self, sorted_by="total", time_unit="ms"):
        scale = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        rows = [(n, s) for n, s in self._events.items()
                if not n.startswith("__")]
        rows.sort(key=lambda r: -r[1].total)
        lines = [f"{'Name':<28}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
                 f"{'Avg':>10}{'Min':>10}{'Max':>10}"]
        lines.append("-" * len(lines[0]))
        for name, s in rows:
            lines.append(
                f"{name:<28}{s.calls:>8}{s.total * scale:>14.3f}"
                f"{s.total / s.calls * scale:>10.3f}{s.min * scale:>10.3f}"
                f"{s.max * scale:>10.3f}")
        samp = self._events.get("__samples__")
        step = self._events.get("train_step")
        if samp and step and step.total > 0:
            lines.append(f"throughput: {samp.total / step.total:.1f} "
                         "samples/s")
        return "\n".join(lines)

    @property
    def steps(self):
        return self._steps

    def export_flamegraph(self, path, window_s=None):
        """Render the process's CONTINUOUS profile (the always-on host
        sampling profiler, observability.contprof) as a self-contained
        flamegraph HTML at ``path`` — the Profiler is the user-facing
        surface, so the bridge lives here next to summary(). Falls
        back to a regions-only flamegraph built from this profiler's
        own timed aggregates when no continuous profiler is running
        (one frame per region, weighted by total seconds in ms), so
        the method always produces a viewable artifact. Returns the
        path."""
        from .observability import contprof
        pr = contprof.active_profiler()
        if pr is not None:
            return pr.flamegraph_html(path, window_s=window_s,
                                      title="paddle_tpu host profile")
        tmp = contprof.ContinuousProfiler(name="regions")
        with tmp._lock:
            for n, s in self._events.items():
                if n.startswith("__"):
                    continue
                w = max(int(s.total * 1e3), 1)  # weight = total ms
                tmp._root[1]["region:" + n] = [w, {}]
                tmp._nodes += 1
                tmp.samples += w
        return tmp.flamegraph_html(path,
                                   title="paddle_tpu profiler regions")


class RecordEvent:
    """ref: paddle.profiler.RecordEvent context manager."""

    def __init__(self, name, profiler: Profiler = None):
        self.name = name
        self.profiler = profiler
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__()

    def __exit__(self, *a):
        if self.profiler is not None and self._t0 is not None:
            dt = time.perf_counter() - self._t0
            self.profiler._events[self.name].add(dt)
            self.profiler._publish(self.name, dt, t0=self._t0)
        self._t0 = None


@contextlib.contextmanager
def profile(trace_dir=None, **kw):
    p = Profiler(trace_dir=trace_dir, **kw)
    p.start()
    try:
        yield p
    finally:
        p.stop()


def export_chrome_tracing(dir_name, worker_name=None):
    """ref: paddle.profiler.export_chrome_tracing — returns an
    on_trace_ready callback that COPIES the JAX trace artifacts
    (xplane protos + Perfetto/Chrome json, which land under
    trace_dir/plugins/profile/<run>/) into `dir_name`, so the export
    dir holds the trace instead of merely knowing where it was.
    worker_name prefixes the copied file names (multi-host runs)."""
    import shutil

    def cb(prof):
        prof._export_dir = dir_name
        prof._exported = []
        if not prof.trace_dir or not os.path.isdir(prof.trace_dir):
            return
        os.makedirs(dir_name, exist_ok=True)
        src_root = os.path.abspath(prof.trace_dir)
        dst_root = os.path.abspath(dir_name)
        taken = set()
        for root, _dirs, files in os.walk(src_root):
            aroot = os.path.abspath(root)
            if aroot == dst_root or aroot.startswith(dst_root + os.sep):
                continue  # exporting into trace_dir itself: no cycles
            for fn in sorted(files):
                if not fn.endswith((".json", ".json.gz", ".pb",
                                    ".perfetto-trace", ".trace")):
                    continue
                src = os.path.join(root, fn)
                name = f"{worker_name}.{fn}" if worker_name else fn
                if name in taken:
                    # two profiling runs under trace_dir carrying
                    # same-named artifacts: a flat copy would clobber
                    # the earlier one — disambiguate with the source
                    # subpath flattened into the name
                    rel = os.path.relpath(aroot, src_root)
                    rel = "root" if rel == "." else rel.replace(
                        os.sep, ".")
                    name = (f"{worker_name}.{rel}.{fn}"
                            if worker_name else f"{rel}.{fn}")
                taken.add(name)
                dst = os.path.join(dst_root, name)
                try:
                    shutil.copy2(src, dst)
                except OSError:
                    continue  # a torn trace file must not kill stop()
                prof._exported.append(dst)
    return cb
