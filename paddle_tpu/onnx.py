"""paddle.onnx gate (ref: python/paddle/onnx/export.py).

ONNX export is NOT the TPU-native serialization path — `paddle.jit.save`
emits a StableHLO artifact (`jax.export`) that reloads and runs without
model code, which is the portable format for the XLA ecosystem. This
module exists so `paddle.onnx.export` callers get a precise error with
the migration path instead of an AttributeError.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=None, **configs):
    raise NotImplementedError(
        "paddle_tpu does not emit ONNX: the portable serialization format "
        "here is StableHLO — use paddle_tpu.jit.save(layer, path, "
        "input_spec=...) which produces an artifact that "
        "paddle_tpu.jit.load can run without the model's Python code. "
        "For ONNX interchange, export from the original framework or "
        "convert the StableHLO module with external tooling.")
