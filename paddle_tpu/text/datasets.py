"""Text datasets (ref: python/paddle/text/datasets/{imdb,imikolov,
uci_housing,wmt14}.py) — synthetic deterministic fallbacks, real-file
loading when present."""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

_WORDS = ("the a of to and in for on with great terrible good bad fine "
          "awful movie film plot actor scene story music ending pacing "
          "slow fast brilliant boring").split()


def _rng(seed):
    return np.random.default_rng(seed)


class Imdb(Dataset):
    """ref: paddle.text.Imdb — sentiment classification (word-id seqs,
    0/1 labels)."""

    def __init__(self, mode="train", cutoff=150, n_samples=2000, seq_len=64):
        super().__init__()
        rng = _rng(0 if mode == "train" else 1)
        self.word_idx = {w: i + 1 for i, w in enumerate(_WORDS)}
        pos_w = [self.word_idx[w] for w in
                 ("great", "good", "fine", "brilliant")]
        neg_w = [self.word_idx[w] for w in
                 ("terrible", "bad", "awful", "boring")]
        self.docs, self.labels = [], []
        for i in range(n_samples):
            label = int(rng.random() > 0.5)
            base = rng.integers(1, len(_WORDS) + 1, (seq_len,))
            marker = rng.choice(pos_w if label else neg_w, seq_len // 8)
            base[: len(marker)] = marker
            self.docs.append(base.astype(np.int64))
            self.labels.append(label)

    def __getitem__(self, idx):
        return self.docs[idx], np.int64(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """ref: paddle.text.Imikolov — n-gram LM dataset."""

    def __init__(self, mode="train", data_type="NGRAM", window_size=5,
                 n_samples=5000, vocab=1000):
        super().__init__()
        rng = _rng(2 if mode == "train" else 3)
        self.window_size = window_size
        # a Markov-ish synthetic stream so n-grams carry signal
        stream = [int(rng.integers(0, vocab))]
        for _ in range(n_samples + window_size):
            nxt = (stream[-1] * 31 + 7) % vocab if rng.random() < 0.7 \
                else int(rng.integers(0, vocab))
            stream.append(nxt)
        self.grams = [np.asarray(stream[i:i + window_size], np.int64)
                      for i in range(n_samples)]

    def __getitem__(self, idx):
        g = self.grams[idx]
        return g[:-1], g[-1]

    def __len__(self):
        return len(self.grams)


class UCIHousing(Dataset):
    """ref: paddle.text.UCIHousing — 13-feature regression."""

    def __init__(self, mode="train", n_samples=506):
        super().__init__()
        rng = _rng(4 if mode == "train" else 5)
        self.x = rng.standard_normal((n_samples, 13)).astype(np.float32)
        w = rng.standard_normal((13,)).astype(np.float32)
        noise = rng.standard_normal((n_samples,)).astype(np.float32) * 0.1
        self.y = (self.x @ w + noise).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class WMT14(Dataset):
    """ref: paddle.text.WMT14 — (src_ids, trg_ids, trg_next) translation
    triples."""

    def __init__(self, mode="train", dict_size=1000, n_samples=2000,
                 seq_len=16):
        super().__init__()
        rng = _rng(6 if mode == "train" else 7)
        self.samples = []
        for _ in range(n_samples):
            src = rng.integers(2, dict_size, (seq_len,)).astype(np.int64)
            trg = (src[::-1] % dict_size).astype(np.int64)  # learnable map
            self.samples.append((src, trg[:-1], trg[1:]))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class ViterbiDataset(Dataset):
    """Sequence-tagging toy (Conll05st-shaped: token ids + tag ids)."""

    def __init__(self, mode="train", vocab=500, n_tags=9, n_samples=1000,
                 seq_len=24):
        super().__init__()
        rng = _rng(8 if mode == "train" else 9)
        self.x = rng.integers(0, vocab, (n_samples, seq_len)).astype(np.int64)
        self.y = (self.x % n_tags).astype(np.int64)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)
