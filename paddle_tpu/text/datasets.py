"""Text datasets (ref: python/paddle/text/datasets/{imdb,imikolov,
uci_housing,wmt14,wmt16,conll05}.py).

Each dataset parses its real on-disk format when `data_file` is given
(Imdb: aclImdb tarball/dir; Conll05st: words+props column files; WMT16:
tab-separated parallel corpus) and otherwise falls back to a
deterministic synthetic set with the same sample shapes — this
environment has no network egress, so the reference's auto-download
path is replaced by explicit local files.
"""
from __future__ import annotations

import gzip
import io
import os
import re
import tarfile
from collections import Counter

import numpy as np

from ..io.dataset import Dataset

_WORDS = ("the a of to and in for on with great terrible good bad fine "
          "awful movie film plot actor scene story music ending pacing "
          "slow fast brilliant boring").split()


def _rng(seed):
    return np.random.default_rng(seed)


_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


def _tokenize(text):
    return [t.lower() for t in _TOKEN_RE.findall(text)]


def _iter_aclimdb(data_file, mode):
    """Yield (tokens, label) from the aclImdb layout — either the
    original tarball or an extracted directory tree
    `{root}/{mode}/{pos,neg}/*.txt`
    (ref: python/paddle/text/datasets/imdb.py, which regex-matches the
    same member paths inside the tarball)."""
    want = re.compile(rf"(^|/)({re.escape(mode)})/(pos|neg)/.*\.txt$")
    if os.path.isdir(data_file):
        for sent, label in (("pos", 1), ("neg", 0)):
            d = os.path.join(data_file, mode, sent)
            if not os.path.isdir(d):
                continue
            for fname in sorted(os.listdir(d)):
                if fname.endswith(".txt"):
                    with open(os.path.join(d, fname),
                              encoding="utf-8", errors="ignore") as f:
                        yield _tokenize(f.read()), label
    else:
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                mt = want.search(m.name)
                if not mt or not m.isfile():
                    continue
                label = 1 if mt.group(3) == "pos" else 0
                data = tf.extractfile(m).read().decode(
                    "utf-8", errors="ignore")
                yield _tokenize(data), label


class Imdb(Dataset):
    """ref: paddle.text.Imdb — sentiment classification (word-id seqs,
    0/1 labels).

    data_file: path to the aclImdb tarball or extracted directory; the
    word dict is built from the requested split with frequency > cutoff
    (reference's build_dict), ids ordered by descending frequency,
    <unk> = len(dict). Without data_file: deterministic synthetic set
    with the same shapes."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 n_samples=2000, seq_len=64):
        super().__init__()
        self.mode = mode
        if data_file is not None:
            raw = list(_iter_aclimdb(data_file, mode))
            if not raw:
                raise ValueError(
                    f"no {mode}/pos|neg/*.txt documents found in "
                    f"{data_file} (expected aclImdb layout)")
            freq = Counter(t for toks, _ in raw for t in toks)
            kept = sorted((w for w, c in freq.items() if c > cutoff),
                          key=lambda w: (-freq[w], w))
            self.word_idx = {w: i for i, w in enumerate(kept)}
            unk = len(self.word_idx)
            self.word_idx["<unk>"] = unk
            self.docs = [np.asarray([self.word_idx.get(t, unk)
                                     for t in toks], np.int64)
                         for toks, _ in raw]
            self.labels = [label for _, label in raw]
            return
        rng = _rng(0 if mode == "train" else 1)
        self.word_idx = {w: i + 1 for i, w in enumerate(_WORDS)}
        pos_w = [self.word_idx[w] for w in
                 ("great", "good", "fine", "brilliant")]
        neg_w = [self.word_idx[w] for w in
                 ("terrible", "bad", "awful", "boring")]
        self.docs, self.labels = [], []
        for i in range(n_samples):
            label = int(rng.random() > 0.5)
            base = rng.integers(1, len(_WORDS) + 1, (seq_len,))
            marker = rng.choice(pos_w if label else neg_w, seq_len // 8)
            base[: len(marker)] = marker
            self.docs.append(base.astype(np.int64))
            self.labels.append(label)

    def __getitem__(self, idx):
        return self.docs[idx], np.int64(self.labels[idx])

    def __len__(self):
        return len(self.docs)


def _read_text_members(data_file, member_basenames):
    """{basename: lines} for several members in ONE pass — a tarball is
    opened and walked once, not once per member."""
    out = {}
    if os.path.isfile(data_file) and tarfile.is_tarfile(data_file):
        want = set(member_basenames)
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if m.isfile() and base in want and base not in out:
                    out[base] = tf.extractfile(m).read().decode(
                        "utf-8", errors="ignore").splitlines()
        missing = want - set(out)
        if missing:
            raise ValueError(
                f"tarball {data_file} has no member(s) {sorted(missing)}")
        return out
    for base in member_basenames:
        path = data_file
        if os.path.isdir(data_file):
            path = os.path.join(data_file, base)
        if not os.path.exists(path):
            raise ValueError(f"no '{base}' at {path}")
        with _open_maybe_gz(path) as f:
            out[base] = [l.rstrip("\n") for l in f]
    return out


def _read_text_member(data_file, member_basename):
    """Lines of `member_basename` from a directory, a plain/gz file, or
    a tarball containing it."""
    return _read_text_members(data_file, [member_basename])[
        member_basename]


class Imikolov(Dataset):
    """ref: paddle.text.Imikolov — Penn Treebank n-gram / seq LM dataset.

    data_file: the PTB release — a directory, the simple-examples
    tarball, or the `ptb.{train,valid}.txt` file itself; mode selects
    the member. Word dict built with min_word_freq (<unk> and <s>/<e>
    reference sentinels), data_type NGRAM (sliding windows) or SEQ
    (<s> sentence <e> pairs). Without data_file: deterministic
    synthetic stream of the same shapes."""

    def __init__(self, data_file=None, mode="train", data_type="NGRAM",
                 window_size=5, min_word_freq=1, n_samples=5000,
                 vocab=1000):
        super().__init__()
        self.window_size = window_size
        self.data_type = data_type
        if data_file is not None:
            member = f"ptb.{mode}.txt"
            if os.path.isfile(data_file) and \
                    not tarfile.is_tarfile(data_file):
                lines = _read_text_member(data_file,
                                          os.path.basename(data_file))
            else:
                lines = _read_text_member(data_file, member)
            sents = [l.split() for l in lines if l.strip()]
            freq = Counter(w for s in sents for w in s)
            kept = sorted((w for w, c in freq.items()
                           if c >= min_word_freq),
                          key=lambda w: (-freq[w], w))
            self.word_idx = {w: i for i, w in enumerate(kept)}
            for tok in ("<unk>", "<s>", "<e>"):
                self.word_idx.setdefault(tok, len(self.word_idx))
            unk = self.word_idx["<unk>"]
            wrapped = [[self.word_idx["<s>"]]
                       + [self.word_idx.get(w, unk) for w in s]
                       + [self.word_idx["<e>"]] for s in sents]
            if data_type.upper() == "SEQ":
                self.grams = [np.asarray(ids, np.int64)
                              for ids in wrapped]
            else:
                # reference windows over <s> words <e>, so boundary
                # n-grams exist and short sentences still contribute
                self.grams = []
                for ids in wrapped:
                    for i in range(len(ids) - window_size + 1):
                        self.grams.append(
                            np.asarray(ids[i:i + window_size], np.int64))
            return
        rng = _rng(2 if mode == "train" else 3)
        # a Markov-ish synthetic stream so n-grams carry signal
        stream = [int(rng.integers(0, vocab))]
        for _ in range(n_samples + window_size):
            nxt = (stream[-1] * 31 + 7) % vocab if rng.random() < 0.7 \
                else int(rng.integers(0, vocab))
            stream.append(nxt)
        self.grams = [np.asarray(stream[i:i + window_size], np.int64)
                      for i in range(n_samples)]

    def __getitem__(self, idx):
        g = self.grams[idx]
        if self.data_type.upper() == "SEQ":
            return g[:-1], g[1:]
        return g[:-1], g[-1]

    def __len__(self):
        return len(self.grams)


class UCIHousing(Dataset):
    """ref: paddle.text.UCIHousing — 13-feature regression.

    data_file: the UCI housing.data file (14 whitespace columns);
    features are min-max normalized like the reference and split 80/20
    train/test by order. Without data_file: synthetic regression set."""

    TRAIN_RATIO = 0.8

    def __init__(self, data_file=None, mode="train", n_samples=506):
        super().__init__()
        if data_file is not None:
            rows = []
            with _open_maybe_gz(str(data_file)) as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 14:
                        rows.append([float(p) for p in parts])
            if not rows:
                raise ValueError(
                    f"no 14-column rows in {data_file} (expected the "
                    "UCI housing.data format)")
            data = np.asarray(rows, np.float32)
            feats, target = data[:, :13], data[:, 13:]
            lo, hi = feats.min(axis=0), feats.max(axis=0)
            feats = (feats - lo) / np.maximum(hi - lo, 1e-8)
            cut = int(len(data) * self.TRAIN_RATIO)
            sl = slice(0, cut) if mode == "train" else slice(cut, None)
            self.x, self.y = feats[sl], target[sl]
            return
        rng = _rng(4 if mode == "train" else 5)
        self.x = rng.standard_normal((n_samples, 13)).astype(np.float32)
        w = rng.standard_normal((13,)).astype(np.float32)
        noise = rng.standard_normal((n_samples,)).astype(np.float32) * 0.1
        self.y = (self.x @ w + noise).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class WMT14(Dataset):
    """ref: paddle.text.WMT14 — (src_ids, trg_ids, trg_next) translation
    triples.

    data_file: a tab-separated parallel corpus (file / directory with a
    `{mode}` member / tarball) — same on-disk contract as WMT16, parsed
    by the shared reader with <s>=0 <e>=1 <unk>=2. Without data_file:
    deterministic synthetic pairs."""

    BOS, EOS, UNK = 0, 1, 2

    def __init__(self, data_file=None, mode="train", dict_size=1000,
                 n_samples=2000, seq_len=16):
        super().__init__()
        if data_file is not None:
            self.src_dict, self.trg_dict, self.samples = \
                self._parse_parallel(data_file, mode, dict_size,
                                     dict_size)
            return
        rng = _rng(6 if mode == "train" else 7)
        self.samples = []
        for _ in range(n_samples):
            src = rng.integers(2, dict_size, (seq_len,)).astype(np.int64)
            trg = (src[::-1] % dict_size).astype(np.int64)  # learnable map
            self.samples.append((src, trg[:-1], trg[1:]))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


    @classmethod
    def _parse_parallel(cls, data_file, mode, src_dict_size,
                        trg_dict_size):
        """Shared WMT14/WMT16 corpus (lives on the parent; WMT16 inherits) -> (src_dict, trg_dict, samples)."""
        pairs = cls._read_pairs(data_file, mode)
        if not pairs:
            raise ValueError(f"no parallel '{mode}' lines found in "
                             f"{data_file}")
        src_dict = cls._build_dict((p[0] for p in pairs), src_dict_size)
        trg_dict = cls._build_dict((p[1] for p in pairs), trg_dict_size)
        samples = []
        for src_toks, trg_toks in pairs:
            src = np.asarray([src_dict.get(t, cls.UNK)
                              for t in src_toks], np.int64)
            trg = np.asarray(
                [cls.BOS] + [trg_dict.get(t, cls.UNK)
                             for t in trg_toks] + [cls.EOS], np.int64)
            samples.append((src, trg[:-1], trg[1:]))
        return src_dict, trg_dict, samples

    @staticmethod
    def _read_pairs(data_file, mode):
        def parse_lines(lines):
            out = []
            for line in lines:
                if "\t" not in line:
                    continue
                src, trg = line.rstrip("\n").split("\t", 1)
                if src and trg:
                    out.append((src.split(), trg.split()))
            return out

        if os.path.isdir(data_file):
            data_file = os.path.join(data_file, mode)
        if not os.path.exists(data_file):
            raise ValueError(
                f"no '{mode}' corpus at {data_file} (expected a "
                "tab-separated parallel file, a directory containing "
                f"one named '{mode}', or the reference tarball)")
        if tarfile.is_tarfile(data_file):
            with tarfile.open(data_file, "r:*") as tf:
                for m in tf.getmembers():
                    if m.isfile() and os.path.basename(m.name) == mode:
                        data = tf.extractfile(m).read().decode("utf-8")
                        return parse_lines(data.splitlines())
            return []
        with _open_maybe_gz(data_file) as f:
            return parse_lines(f)

    @classmethod
    def _build_dict(cls, tok_seqs, dict_size):
        freq = Counter(t for toks in tok_seqs for t in toks)
        specials = {"<s>": cls.BOS, "<e>": cls.EOS, "<unk>": cls.UNK}
        d = dict(specials)
        for w in sorted(freq, key=lambda w: (-freq[w], w)):
            if len(d) >= dict_size:
                break
            if w not in d:
                d[w] = len(d)
        return d


class ViterbiDataset(Dataset):
    """Sequence-tagging toy (Conll05st-shaped: token ids + tag ids)."""

    def __init__(self, mode="train", vocab=500, n_tags=9, n_samples=1000,
                 seq_len=24):
        super().__init__()
        rng = _rng(8 if mode == "train" else 9)
        self.x = rng.integers(0, vocab, (n_samples, seq_len)).astype(np.int64)
        self.y = (self.x % n_tags).astype(np.int64)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


def _open_maybe_gz(path):
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8")
    return open(path, encoding="utf-8")


def _read_col_sentences(path):
    """Blank-line-separated sentences of whitespace-split columns —
    the CoNLL column format."""
    sents, cur = [], []
    with _open_maybe_gz(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                if cur:
                    sents.append(cur)
                    cur = []
                continue
            cur.append(line.split())
    if cur:
        sents.append(cur)
    return sents


class Conll05st(ViterbiDataset):
    """ref: paddle.text.Conll05st — SRL sequence labeling.

    With data_file=(words_path, props_path) [.gz accepted], parses the
    CoNLL-2005 column formats: `words` is one token per line, `props`
    carries the predicate column plus one bracketed-argument column per
    predicate; each (sentence, predicate) pair becomes one sample
    (word_ids, predicate_position, BIO tag_ids), matching the
    reference's per-predicate sample expansion
    (python/paddle/text/datasets/conll05.py). Without data_file:
    deterministic synthetic corpus with the same shapes."""

    def __init__(self, data_file=None, mode="train", vocab=800, n_tags=18,
                 n_samples=1500, seq_len=30):
        if data_file is not None:
            Dataset.__init__(self)
            words_path, props_path = data_file
            word_sents = _read_col_sentences(words_path)
            prop_sents = _read_col_sentences(props_path)
            if len(word_sents) != len(prop_sents):
                raise ValueError(
                    f"words/props sentence counts differ: "
                    f"{len(word_sents)} vs {len(prop_sents)}")
            freq = Counter(w[0].lower() for s in word_sents for w in s)
            self.word_idx = {w: i for i, w in
                             enumerate(sorted(freq, key=lambda w:
                                              (-freq[w], w)))}
            self.tag_idx = {}
            self.x, self.pred, self.y = [], [], []
            for ws, ps in zip(word_sents, prop_sents):
                ids = np.asarray([self.word_idx[w[0].lower()] for w in ws],
                                 np.int64)
                n_preds = len(ps[0]) - 1
                pred_rows = [i for i, row in enumerate(ps)
                             if row[0] != "-"]
                for k in range(n_preds):
                    tags = self._bio_from_brackets(
                        [row[k + 1] for row in ps])
                    # the predicate is its column's (V*) span; fall back
                    # to the k-th lemma row if the span is absent
                    pred_pos = next(
                        (i for i, t in enumerate(tags)
                         if t in ("B-V", "I-V")),
                        pred_rows[k] if k < len(pred_rows) else 0)
                    tag_ids = np.asarray(
                        [self.tag_idx.setdefault(t, len(self.tag_idx))
                         for t in tags], np.int64)
                    self.x.append(ids)
                    self.pred.append(np.int64(pred_pos))
                    self.y.append(tag_ids)
            return
        super().__init__(mode=mode, vocab=vocab, n_tags=n_tags,
                         n_samples=n_samples, seq_len=seq_len)
        rng = _rng(10 if mode == "train" else 11)
        self.pred = rng.integers(0, seq_len, (n_samples,)).astype(np.int64)

    @staticmethod
    def _bio_from_brackets(col):
        """CoNLL-2005 bracketed spans `(A0*`, `*`, `*)` -> BIO tags."""
        tags, cur = [], None
        for cell in col:
            label = None
            if "(" in cell:
                label = cell[cell.index("(") + 1:].split("*")[0]
                tags.append("B-" + label)
                cur = label
            elif cur is not None:
                tags.append("I-" + cur)
            else:
                tags.append("O")
            if ")" in cell:
                cur = None
        return tags

    def __getitem__(self, idx):
        return self.x[idx], self.pred[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


_ML_AGES = (1, 18, 25, 35, 45, 50, 56)        # ml-1m age buckets


class Movielens(Dataset):
    """ref: paddle.text.Movielens — rating prediction. Samples:
    (user_id, gender, age, job, movie_id, category_vec, title_vec,
    rating).

    data_file: the MovieLens-1M release (directory or tarball holding
    users.dat / movies.dat / ratings.dat in the `::`-separated format).
    Ratings split train/test by a deterministic hash of
    (user, movie, rand_seed) against test_ratio — membership depends
    only on the pair, not on file order, matching the reference's
    random-but-seeded split role. Without data_file: deterministic
    synthetic samples with the same tuple shape."""

    def __init__(self, data_file=None, mode="train", n_users=500,
                 n_movies=800, test_ratio=0.1, rand_seed=0,
                 **_synth_kw):
        if data_file is not None:
            super().__init__()
            members = _read_text_members(
                data_file, ["users.dat", "movies.dat", "ratings.dat"])
            users = {}
            for line in members["users.dat"]:
                if not line.strip():
                    continue
                uid, gender, age, job, _zip = line.split("::")
                users[int(uid)] = (int(gender == "M"),
                                   _ML_AGES.index(int(age)), int(job))
            genres, titles_vocab = {}, {}
            movies = {}
            for line in members["movies.dat"]:
                if not line.strip():
                    continue
                mid, title, gen = line.split("::")
                gvec = np.zeros((18,), np.int64)
                for g in gen.split("|"):
                    gi = genres.setdefault(g, len(genres))
                    if gi >= 18:
                        raise ValueError(
                            f"more than 18 distinct genres in movies.dat "
                            f"(got {g!r} as #{gi + 1}) — not the ml-1m "
                            "genre set this loader models")
                    gvec[gi] = 1
                tids = [titles_vocab.setdefault(w.lower(),
                                                len(titles_vocab) + 1)
                        for w in title.split()][:8]
                tvec = np.zeros((8,), np.int64)
                tvec[:len(tids)] = tids
                movies[int(mid)] = (gvec, tvec)
            self.samples = []
            import hashlib
            for line in members["ratings.dat"]:
                if not line.strip():
                    continue
                uid, mid, rating, _ts = line.split("::")
                uid, mid = int(uid), int(mid)
                if uid not in users or mid not in movies:
                    continue
                # order-independent split: hash the (pair, seed), not a
                # sequential RNG draw
                h = hashlib.md5(
                    f"{uid}:{mid}:{rand_seed}".encode()).digest()
                is_test = (int.from_bytes(h[:4], "big") / 2 ** 32) \
                    < test_ratio
                if is_test != (mode == "test"):
                    continue
                g, a, j = users[uid]
                cats, title = movies[mid]
                self.samples.append(
                    (np.int64(uid), np.int64(g), np.int64(a),
                     np.int64(j), np.int64(mid), cats, title,
                     np.float32(rating)))
            return
        self._init_synthetic(mode=mode, n_users=n_users,
                             n_movies=n_movies, **_synth_kw)

    def _init_synthetic(self, mode="train", n_users=500, n_movies=800,
                 n_samples=4000, n_cats=18, title_len=8):
        super().__init__()
        rng = _rng(12 if mode == "train" else 13)
        self.samples = []
        for _ in range(n_samples):
            u = int(rng.integers(0, n_users))
            m = int(rng.integers(0, n_movies))
            gender = int(rng.integers(0, 2))
            age = int(rng.integers(0, 7))
            job = int(rng.integers(0, 21))
            cats = rng.integers(0, 2, (n_cats,)).astype(np.int64)
            title = rng.integers(1, 1000, (title_len,)).astype(np.int64)
            # deterministic latent structure so models can learn
            rating = np.float32(((u * 7 + m * 13) % 50) / 10.0)
            self.samples.append((np.int64(u), np.int64(gender),
                                 np.int64(age), np.int64(job), np.int64(m),
                                 cats, title, rating))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT16(WMT14):
    """ref: paddle.text.WMT16 — same sample shape as WMT14 with BPE-sized
    vocab defaults.

    data_file: path to the corpus — a tab-separated parallel file
    (`src<TAB>trg` per line, the reference tarball's member format), a
    directory containing one named `{mode}`, or the tarball itself.
    Dicts are built per side to src/trg_dict_size by descending
    frequency with the reference's special ids <s>=0, <e>=1, <unk>=2
    (python/paddle/text/datasets/wmt16.py). Samples are
    (src_ids, trg_ids[:-1], trg_ids[1:]) with the target wrapped in
    <s>...<e>."""

    def __init__(self, data_file=None, mode="train", src_dict_size=2000,
                 trg_dict_size=2000, n_samples=2000, seq_len=24):
        if data_file is not None:
            Dataset.__init__(self)
            self.src_dict, self.trg_dict, self.samples = \
                self._parse_parallel(data_file, mode, src_dict_size,
                                     trg_dict_size)
            return
        super().__init__(mode=mode, dict_size=min(src_dict_size,
                                                  trg_dict_size),
                         n_samples=n_samples, seq_len=seq_len)

