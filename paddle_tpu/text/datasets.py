"""Text datasets (ref: python/paddle/text/datasets/{imdb,imikolov,
uci_housing,wmt14}.py) — synthetic deterministic fallbacks, real-file
loading when present."""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

_WORDS = ("the a of to and in for on with great terrible good bad fine "
          "awful movie film plot actor scene story music ending pacing "
          "slow fast brilliant boring").split()


def _rng(seed):
    return np.random.default_rng(seed)


class Imdb(Dataset):
    """ref: paddle.text.Imdb — sentiment classification (word-id seqs,
    0/1 labels)."""

    def __init__(self, mode="train", cutoff=150, n_samples=2000, seq_len=64):
        super().__init__()
        rng = _rng(0 if mode == "train" else 1)
        self.word_idx = {w: i + 1 for i, w in enumerate(_WORDS)}
        pos_w = [self.word_idx[w] for w in
                 ("great", "good", "fine", "brilliant")]
        neg_w = [self.word_idx[w] for w in
                 ("terrible", "bad", "awful", "boring")]
        self.docs, self.labels = [], []
        for i in range(n_samples):
            label = int(rng.random() > 0.5)
            base = rng.integers(1, len(_WORDS) + 1, (seq_len,))
            marker = rng.choice(pos_w if label else neg_w, seq_len // 8)
            base[: len(marker)] = marker
            self.docs.append(base.astype(np.int64))
            self.labels.append(label)

    def __getitem__(self, idx):
        return self.docs[idx], np.int64(self.labels[idx])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """ref: paddle.text.Imikolov — n-gram LM dataset."""

    def __init__(self, mode="train", data_type="NGRAM", window_size=5,
                 n_samples=5000, vocab=1000):
        super().__init__()
        rng = _rng(2 if mode == "train" else 3)
        self.window_size = window_size
        # a Markov-ish synthetic stream so n-grams carry signal
        stream = [int(rng.integers(0, vocab))]
        for _ in range(n_samples + window_size):
            nxt = (stream[-1] * 31 + 7) % vocab if rng.random() < 0.7 \
                else int(rng.integers(0, vocab))
            stream.append(nxt)
        self.grams = [np.asarray(stream[i:i + window_size], np.int64)
                      for i in range(n_samples)]

    def __getitem__(self, idx):
        g = self.grams[idx]
        return g[:-1], g[-1]

    def __len__(self):
        return len(self.grams)


class UCIHousing(Dataset):
    """ref: paddle.text.UCIHousing — 13-feature regression."""

    def __init__(self, mode="train", n_samples=506):
        super().__init__()
        rng = _rng(4 if mode == "train" else 5)
        self.x = rng.standard_normal((n_samples, 13)).astype(np.float32)
        w = rng.standard_normal((13,)).astype(np.float32)
        noise = rng.standard_normal((n_samples,)).astype(np.float32) * 0.1
        self.y = (self.x @ w + noise).astype(np.float32)[:, None]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class WMT14(Dataset):
    """ref: paddle.text.WMT14 — (src_ids, trg_ids, trg_next) translation
    triples."""

    def __init__(self, mode="train", dict_size=1000, n_samples=2000,
                 seq_len=16):
        super().__init__()
        rng = _rng(6 if mode == "train" else 7)
        self.samples = []
        for _ in range(n_samples):
            src = rng.integers(2, dict_size, (seq_len,)).astype(np.int64)
            trg = (src[::-1] % dict_size).astype(np.int64)  # learnable map
            self.samples.append((src, trg[:-1], trg[1:]))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class ViterbiDataset(Dataset):
    """Sequence-tagging toy (Conll05st-shaped: token ids + tag ids)."""

    def __init__(self, mode="train", vocab=500, n_tags=9, n_samples=1000,
                 seq_len=24):
        super().__init__()
        rng = _rng(8 if mode == "train" else 9)
        self.x = rng.integers(0, vocab, (n_samples, seq_len)).astype(np.int64)
        self.y = (self.x % n_tags).astype(np.int64)

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class Conll05st(ViterbiDataset):
    """ref: paddle.text.Conll05st — SRL sequence labeling. Synthetic
    deterministic corpus with the reference's (tokens, predicate, tags)
    sample shape."""

    def __init__(self, mode="train", vocab=800, n_tags=18, n_samples=1500,
                 seq_len=30):
        super().__init__(mode=mode, vocab=vocab, n_tags=n_tags,
                         n_samples=n_samples, seq_len=seq_len)
        rng = _rng(10 if mode == "train" else 11)
        self.pred = rng.integers(0, seq_len, (n_samples,)).astype(np.int64)

    def __getitem__(self, idx):
        return self.x[idx], self.pred[idx], self.y[idx]


class Movielens(Dataset):
    """ref: paddle.text.Movielens — rating prediction. Samples:
    (user_id, gender, age, job, movie_id, category_vec, title_vec,
    rating)."""

    def __init__(self, mode="train", n_users=500, n_movies=800,
                 n_samples=4000, n_cats=18, title_len=8):
        super().__init__()
        rng = _rng(12 if mode == "train" else 13)
        self.samples = []
        for _ in range(n_samples):
            u = int(rng.integers(0, n_users))
            m = int(rng.integers(0, n_movies))
            gender = int(rng.integers(0, 2))
            age = int(rng.integers(0, 7))
            job = int(rng.integers(0, 21))
            cats = rng.integers(0, 2, (n_cats,)).astype(np.int64)
            title = rng.integers(1, 1000, (title_len,)).astype(np.int64)
            # deterministic latent structure so models can learn
            rating = np.float32(((u * 7 + m * 13) % 50) / 10.0)
            self.samples.append((np.int64(u), np.int64(gender),
                                 np.int64(age), np.int64(job), np.int64(m),
                                 cats, title, rating))

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT16(WMT14):
    """ref: paddle.text.WMT16 — same sample shape as WMT14 with BPE-sized
    vocab defaults."""

    def __init__(self, mode="train", src_dict_size=2000, trg_dict_size=2000,
                 n_samples=2000, seq_len=24):
        super().__init__(mode=mode, dict_size=min(src_dict_size,
                                                  trg_dict_size),
                         n_samples=n_samples, seq_len=seq_len)
