"""paddle.text parity (ref: python/paddle/text/datasets/*).

The reference ships corpus loaders (Imdb, Imikolov, Movielens, UCIHousing,
WMT14/16, Conll05st). This environment has zero egress, so each dataset
synthesises a deterministic corpus with the same shapes/contract
(seeded; stable across runs) — swap in the real files by dropping them
into ~/.cache/paddle_tpu/text/<name>/ with the reference layout.
"""
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, ViterbiDataset,
    WMT14, WMT16,
)

__all__ = ["Imdb", "Imikolov", "UCIHousing", "WMT14", "WMT16",
           "Conll05st", "Movielens", "ViterbiDataset"]
