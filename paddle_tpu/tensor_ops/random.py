"""Random ops (ref: python/paddle/tensor/random.py).

Eager calls draw keys from the global generator (framework.next_rng_key);
inside an rng_scope (e.g. a traced train step) keys come from the scope so
randomness is a pure function of the scope key.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, next_rng_key
from ..tensor import Tensor, to_tensor

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "normal", "standard_normal", "gaussian", "multinomial", "bernoulli",
    "poisson", "exponential_", "uniform_", "normal_", "binomial",
    "standard_gamma",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype):
    d = convert_dtype(dtype)
    return d if d is not None else framework.get_default_dtype()


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_rng_key(), _shape(shape), dtype=_dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_rng_key(), _shape(shape), dtype=_dt(dtype)))


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.PRNGKey(seed) if seed else next_rng_key()
    return Tensor(mean + std * jax.random.normal(key, _shape(shape), dtype=_dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(next_rng_key(), shp,
                                                dtype=framework.get_default_dtype()))
    return gaussian(shape or [1], mean, std)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else next_rng_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=_dt(dtype),
                                     minval=float(min), maxval=float(max)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_rng_key(), _shape(shape),
                                     int(low), int(high),
                                     dtype=convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    dt = convert_dtype(dtype) or x.dtype
    if high is None:
        low, high = 0, low
    out = jax.random.randint(next_rng_key(), tuple(x.shape), int(low), int(high),
                             dtype=jnp.int64)
    return Tensor(out.astype(dt))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_rng_key(), int(n)).astype(convert_dtype(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = next_rng_key()
    def draw(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(key, logits, shape=(num_samples,) + p.shape[:-1]
                                          ).T if p.ndim > 1 else \
                jax.random.categorical(key, logits, shape=(num_samples,))
        # without replacement: Gumbel top-k
        g = jax.random.gumbel(key, p.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(draw(arr).astype(jnp.int64))


def bernoulli(x, name=None):
    arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    u = jax.random.uniform(next_rng_key(), arr.shape)
    return Tensor((u < arr).astype(arr.dtype))


def binomial(count, prob, name=None):
    c = count._value if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._value if isinstance(prob, Tensor) else jnp.asarray(prob)
    out = jax.random.binomial(next_rng_key(), c.astype(jnp.float32), p)
    return Tensor(out.astype(jnp.int64))


def poisson(x, name=None):
    arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(next_rng_key(), arr).astype(arr.dtype))


def standard_gamma(x, name=None):
    arr = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.gamma(next_rng_key(), arr).astype(arr.dtype))


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(next_rng_key(), tuple(x.shape), dtype=x._value.dtype)
    return x._inplace(Tensor(-jnp.log(1 - u) / lam))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    return x._inplace(uniform(x.shape, dtype=x.dtype, min=min, max=max, seed=seed))


def normal_(x, mean=0.0, std=1.0, name=None):
    return x._inplace(gaussian(x.shape, mean, std, dtype=x.dtype))
