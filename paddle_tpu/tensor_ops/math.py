"""Elementwise & reduction math ops (ref: python/paddle/tensor/math.py)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
    "mod", "pow", "float_power", "scale", "abs", "neg",
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "square", "reciprocal", "sign", "floor", "ceil", "round", "trunc",
    "frac", "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh",
    "cosh", "tanh", "asinh", "acosh", "atanh", "erf", "erfinv", "sigmoid",
    "logit", "logaddexp", "clip", "maximum", "minimum", "fmax", "fmin",
    "sum", "nansum", "mean", "nanmean", "prod", "max", "min", "amax",
    "amin", "logsumexp", "cumsum", "cumprod", "cummax", "cummin",
    "logcumsumexp", "isnan", "isinf", "isfinite", "isposinf", "isneginf",
    "lerp", "addmm", "inner", "outer", "cross", "trace", "kron", "gcd",
    "lcm", "diff", "angle", "conj", "real", "imag", "deg2rad", "rad2deg",
    "heaviside", "nan_to_num", "ldexp", "frexp", "copysign", "hypot",
    "einsum", "increment", "stanh", "softplus_raw",
    "count_nonzero", "broadcast_shape", "cumulative_trapezoid", "trapezoid",
    "vander", "i0", "i1", "sgn", "digamma", "lgamma",
    "gammaln", "polygamma", "multigammaln", "sinc", "exp2", "log_normal",
]


def _u(fn, differentiable=True):
    def op(x, name=None):
        if not isinstance(x, Tensor):
            x = to_tensor(x)
        return apply_op(fn, x, differentiable=differentiable)
    return op


def _b(fn, differentiable=True):
    def op(x, y, name=None):
        if not isinstance(x, Tensor):
            x = to_tensor(x)
        return apply_op(fn, x, y, differentiable=differentiable)
    return op


add = _b(jnp.add)
subtract = _b(jnp.subtract)
multiply = _b(jnp.multiply)
divide = _b(jnp.true_divide)
floor_divide = _b(jnp.floor_divide, differentiable=False)
remainder = _b(jnp.remainder)
mod = remainder
pow = _b(jnp.power)
float_power = _b(lambda x, y: jnp.power(x.astype(jnp.float64), y))
maximum = _b(jnp.maximum)
minimum = _b(jnp.minimum)
fmax = _b(jnp.fmax)
fmin = _b(jnp.fmin)
atan2 = _b(jnp.arctan2)
logaddexp = _b(jnp.logaddexp)
gcd = _b(jnp.gcd, differentiable=False)
lcm = _b(jnp.lcm, differentiable=False)
heaviside = _b(jnp.heaviside)
copysign = _b(jnp.copysign)
hypot = _b(jnp.hypot)
ldexp = _b(lambda x, y: jnp.ldexp(x, y.astype(jnp.int32)))
kron = _b(jnp.kron)

abs = _u(jnp.abs)
neg = _u(jnp.negative)
exp = _u(jnp.exp)
exp2 = _u(jnp.exp2)
expm1 = _u(jnp.expm1)
log = _u(jnp.log)
log2 = _u(jnp.log2)
log10 = _u(jnp.log10)
log1p = _u(jnp.log1p)
sqrt = _u(jnp.sqrt)
rsqrt = _u(lambda x: jax.lax.rsqrt(x))
square = _u(jnp.square)
reciprocal = _u(jnp.reciprocal)
sign = _u(jnp.sign, differentiable=False)
sgn = sign
floor = _u(jnp.floor, differentiable=False)
ceil = _u(jnp.ceil, differentiable=False)
round = _u(jnp.round, differentiable=False)
trunc = _u(jnp.trunc, differentiable=False)
frac = _u(lambda x: x - jnp.trunc(x))
sin = _u(jnp.sin)
cos = _u(jnp.cos)
tan = _u(jnp.tan)
asin = _u(jnp.arcsin)
acos = _u(jnp.arccos)
atan = _u(jnp.arctan)
sinh = _u(jnp.sinh)
cosh = _u(jnp.cosh)
tanh = _u(jnp.tanh)
asinh = _u(jnp.arcsinh)
acosh = _u(jnp.arccosh)
atanh = _u(jnp.arctanh)
erf = _u(jax.scipy.special.erf)
erfinv = _u(jax.scipy.special.erfinv)
sigmoid = _u(jax.nn.sigmoid)
logit = _u(lambda x: jnp.log(x / (1 - x)))
isnan = _u(jnp.isnan, differentiable=False)
isinf = _u(jnp.isinf, differentiable=False)
isfinite = _u(jnp.isfinite, differentiable=False)
isposinf = _u(jnp.isposinf, differentiable=False)
isneginf = _u(jnp.isneginf, differentiable=False)
angle = _u(jnp.angle)
conj = _u(jnp.conj)
real = _u(jnp.real)
imag = _u(jnp.imag)
deg2rad = _u(jnp.deg2rad)
rad2deg = _u(jnp.rad2deg)
sinc = _u(jnp.sinc)
i0 = _u(jax.scipy.special.i0)
i1 = _u(jax.scipy.special.i1)
digamma = _u(jax.scipy.special.digamma)
lgamma = _u(jax.scipy.special.gammaln)
gammaln = lgamma


def polygamma(x, n, name=None):
    return apply_op(lambda a: jax.scipy.special.polygamma(n, a), x)


def multigammaln(x, p, name=None):
    return apply_op(lambda a: jax.scipy.special.multigammaln(a, p), x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), x)


def softplus_raw(x):
    return apply_op(jax.nn.softplus, x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    if bias_after_scale:
        out = apply_op(lambda a: a * scale + bias, x)
    else:
        out = apply_op(lambda a: (a + bias) * scale, x)
    return out


def increment(x, value=1.0, name=None):
    return x._inplace(x + value)


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply_op(lambda a: jnp.clip(a, lo, hi), x)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..framework import convert_dtype
    dt = convert_dtype(dtype)
    return apply_op(
        lambda a: jnp.sum(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..framework import convert_dtype
    dt = convert_dtype(dtype)
    return apply_op(
        lambda a: jnp.nansum(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ..framework import convert_dtype
    dt = convert_dtype(dtype)
    return apply_op(
        lambda a: jnp.prod(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    from ..framework import convert_dtype
    dt = convert_dtype(dtype)
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=dt)
        return jnp.cumsum(a, axis=int(axis), dtype=dt)
    return apply_op(f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    from ..framework import convert_dtype
    dt = convert_dtype(dtype)
    return apply_op(lambda a: jnp.cumprod(a, axis=dim, dtype=dt), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        aa = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        vals = jax.lax.associative_scan(jnp.maximum, aa, axis=ax)
        n = aa.shape[ax]
        shape = [1] * aa.ndim
        shape[ax] = n
        ar = jnp.arange(n).reshape(shape)
        is_new = aa == vals
        idx = jax.lax.associative_scan(
            lambda p, c: jnp.where(c >= 0, jnp.maximum(p, c), p),
            jnp.where(is_new, jnp.broadcast_to(ar, aa.shape), -1), axis=ax)
        return vals, idx.astype(jnp.int64)
    return apply_op(f, x, differentiable=False)


def cummin(x, axis=None, dtype="int64", name=None):
    def f(a):
        aa = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        vals = jax.lax.associative_scan(jnp.minimum, aa, axis=ax)
        n = aa.shape[ax]
        shape = [1] * aa.ndim
        shape[ax] = n
        ar = jnp.arange(n).reshape(shape)
        is_new = aa == vals
        idx = jax.lax.associative_scan(
            lambda p, c: jnp.where(c >= 0, jnp.maximum(p, c), p),
            jnp.where(is_new, jnp.broadcast_to(ar, aa.shape), -1), axis=ax)
        return vals, idx.astype(jnp.int64)
    return apply_op(f, x, differentiable=False)


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = int(axis)
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)
    return apply_op(f, x)


def lerp(x, y, weight, name=None):
    return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y)


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), x, y)


def cross(x, y, axis=None, name=None):
    ax = -1 if axis is None else int(axis)
    return apply_op(lambda a, b: jnp.cross(a, b, axis=ax), x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [a for a in (prepend, append) if a is not None]
    def f(a, *extra):
        kw = {}
        i = 0
        if prepend is not None:
            kw["prepend"] = extra[i]; i += 1
        if append is not None:
            kw["append"] = extra[i]
        return jnp.diff(a, n=n, axis=axis, **kw)
    return apply_op(f, x, *args)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def frexp(x, name=None):
    return apply_op(jnp.frexp, x, differentiable=False)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64),
        x, differentiable=False)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply_op(lambda yy, xx: jnp.trapezoid(yy, x=xx, axis=axis), y, x)
    return apply_op(lambda yy: jnp.trapezoid(yy, dx=dx or 1.0, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    import jax.scipy.integrate as jsi  # may lack cumulative; manual impl
    def f(yy, *rest):
        xx = rest[0] if rest else None
        d = jnp.diff(xx, axis=axis) if xx is not None else (dx or 1.0)
        y0 = jnp.take(yy, jnp.arange(0, yy.shape[axis] - 1), axis=axis)
        y1 = jnp.take(yy, jnp.arange(1, yy.shape[axis]), axis=axis)
        return jnp.cumsum((y0 + y1) / 2.0 * d, axis=axis)
    return apply_op(f, y, *( [x] if x is not None else [] ))


def vander(x, n=None, increasing=False, name=None):
    return apply_op(lambda a: jnp.vander(a, N=n, increasing=increasing), x)


def einsum(equation, *operands):
    """ref: paddle.einsum."""
    ops = [to_tensor(o) if not isinstance(o, Tensor) else o for o in operands]
    return apply_op(lambda *arrs: jnp.einsum(equation, *arrs), *ops)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    from .random import gaussian
    g = gaussian(shape or [1], mean=0.0, std=1.0)
    return apply_op(lambda a: jnp.exp(mean + std * a), g)




def _c(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------------------
# round-2 long-tail additions (ref: python/paddle/tensor/math.py)
# ---------------------------------------------------------------------------
def nextafter(x, y, name=None):
    return apply_op(jnp.nextafter, _c(x), _c(y))


def xlogy(x, y, name=None):
    from jax.scipy import special as jss
    return apply_op(jss.xlogy, _c(x), _c(y))


def i0e(x, name=None):
    from jax.scipy import special as jss
    return apply_op(jss.i0e, _c(x))


def igamma(a, x, name=None):
    """Upper regularized incomplete gamma (paddle's igamma = Q(a, x))."""
    from jax.scipy import special as jss
    return apply_op(jss.gammaincc, _c(a), _c(x))


def igammac(a, x, name=None):
    """Lower regularized incomplete gamma (paddle's igammac = P(a, x))."""
    from jax.scipy import special as jss
    return apply_op(jss.gammainc, _c(a), _c(x))


def gammainc(a, x, name=None):
    from jax.scipy import special as jss
    return apply_op(jss.gammainc, _c(a), _c(x))


def gammaincc(a, x, name=None):
    from jax.scipy import special as jss
    return apply_op(jss.gammaincc, _c(a), _c(x))


def signbit(x, name=None):
    return apply_op(jnp.signbit, _c(x))


def isreal(x, name=None):
    return apply_op(jnp.isreal, _c(x))


def is_floating_point(x, name=None):
    """ref: paddle.is_floating_point."""
    from ..tensor import Tensor
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return bool(jnp.issubdtype(v.dtype, jnp.floating))


def is_complex(x, name=None):
    """ref: paddle.is_complex."""
    from ..tensor import Tensor
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return bool(jnp.issubdtype(v.dtype, jnp.complexfloating))


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    """ref: paddle.isin — elementwise membership of x in test_x."""
    return apply_op(
        lambda a, t: jnp.isin(a, t, invert=invert), _c(x), _c(test_x))


def vdot(x, y, name=None):
    return apply_op(jnp.vdot, _c(x), _c(y))


def renorm(x, p, axis, max_norm, name=None):
    """ref: paddle.renorm — rescale slices along `axis` whose p-norm
    exceeds max_norm down to exactly max_norm."""
    def f(a):
        ax = axis % a.ndim  # accept negative axes
        red = tuple(i for i in range(a.ndim) if i != ax)
        norms = jnp.sum(jnp.abs(a) ** p, axis=red, keepdims=True) ** (1 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return a * factor
    return apply_op(f, _c(x))


def combinations(x, r=2, with_replacement=False, name=None):
    """ref: paddle.combinations — r-combinations of a 1-D tensor's
    elements (static index grid; host-precomputed like the reference)."""
    import itertools as _it
    import numpy as np
    t = _c(x)
    n = int(t.shape[0])
    gen = (_it.combinations_with_replacement(range(n), r)
           if with_replacement else _it.combinations(range(n), r))
    idx = np.array(list(gen), dtype=np.int32).reshape(-1, r)
    return apply_op(lambda a: a[idx], t)


def cartesian_prod(*tensors, name=None):
    """ref: paddle.cartesian_prod — 1-D result for a single input, like
    the reference."""
    ts = [_c(t) for t in tensors]
    if len(ts) == 1:
        return apply_op(lambda a: a.reshape(-1), ts[0])

    def f(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply_op(f, *ts)


__all__ += [
    "nextafter", "xlogy", "i0e", "igamma", "igammac", "gammainc",
    "gammaincc", "signbit", "isreal", "is_floating_point", "is_complex",
    "isin", "vdot", "renorm", "combinations",
    "cartesian_prod",
]


trapz = trapezoid  # torch-style alias the reference also exposes
__all__ += ["trapz"]
