"""Bind the functional op surface onto Tensor as methods (ref: the
monkey-patching in python/paddle/tensor/__init__.py: Tensor methods are the
same kernels as the free functions)."""
from __future__ import annotations

from ..tensor import Tensor


def bind_tensor_methods():
    from . import creation, linalg, logic, manip, math, random, search, stat

    def bind(mod, names):
        for n in names:
            fn = getattr(mod, n)
            if not hasattr(Tensor, n):
                setattr(Tensor, n, fn)

    bind(math, [n for n in math.__all__ if n not in (
        "einsum", "broadcast_shape", "log_normal")])
    bind(manip, [n for n in manip.__all__ if n not in ("tolist",)])
    bind(logic, [n for n in logic.__all__ if n not in ("is_tensor", "where")])
    bind(stat, stat.__all__)
    bind(search, ["argmax", "argmin"])
    bind(linalg, ["matmul", "bmm", "dot", "norm", "dist", "t", "inv", "det",
                  "cholesky", "matrix_power", "pinv", "cond"])
    bind(creation, ["tril", "triu", "diag"])
    bind(random, ["uniform_", "normal_", "exponential_"])

    # mT / T properties
    if not hasattr(Tensor, "T"):
        Tensor.T = property(lambda self: manip.transpose(
            self, list(reversed(range(self.ndim)))))
    if not hasattr(Tensor, "mT"):
        Tensor.mT = property(lambda self: manip.swapaxes(self, -1, -2))
