"""Linear algebra (ref: python/paddle/tensor/linalg.py, paddle.linalg).

Dense linalg lowers to jax.numpy.linalg / lax.linalg; on TPU the
decompositions run via XLA's QR/SVD/eigh custom calls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = [
    "matmul", "bmm", "dot", "t", "norm", "vector_norm", "matrix_norm",
    "dist", "cond", "inv", "det", "slogdet", "svd", "svdvals", "qr", "eig",
    "eigh", "eigvals", "eigvalsh", "cholesky", "cholesky_solve",
    "cholesky_inverse", "lstsq", "lu", "lu_unpack", "matrix_power",
    "matrix_rank", "pinv", "solve", "triangular_solve", "multi_dot",
    "householder_product", "matrix_exp", "ormqr",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(f, _t(x), _t(y))


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, _t(x), _t(y))


def dot(x, y, name=None):
    def f(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)
    return apply_op(f, _t(x), _t(y))


def t(x, name=None):
    def f(a):
        return a if a.ndim < 2 else a.T
    return apply_op(f, _t(x))


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if axis is None and (p is None or p == "fro" or p == 2):
            return jnp.sqrt(jnp.sum(jnp.square(a)))
        pp = 2 if p is None else p
        if isinstance(axis, (list, tuple)) and len(axis) == 2:
            return jnp.linalg.norm(a, ord="fro" if pp in ("fro", None, 2) else pp,
                                   axis=tuple(axis), keepdims=keepdim)
        ax = axis if axis is None else int(axis)
        if pp == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pp == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if pp == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** pp, axis=ax, keepdims=keepdim) ** (1.0 / pp)
    return apply_op(f, _t(x))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply_op(lambda a: jnp.linalg.norm(a, ord=p, axis=tuple(axis),
                                              keepdims=keepdim), _t(x))


def dist(x, y, p=2, name=None):
    return norm(x - y if isinstance(x, Tensor) else _t(x) - y, p=float(p))


def cond(x, p=None, name=None):
    return apply_op(lambda a: jnp.linalg.cond(a, p=p), _t(x))


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, _t(x))


def det(x, name=None):
    return apply_op(jnp.linalg.det, _t(x))


def slogdet(x, name=None):
    def f(a):
        s, l = jnp.linalg.slogdet(a)
        return jnp.stack([s, l])
    return apply_op(f, _t(x))


def svd(x, full_matrices=False, name=None):
    return apply_op(lambda a: jnp.linalg.svd(a, full_matrices=full_matrices), _t(x))


def svdvals(x, name=None):
    return apply_op(lambda a: jnp.linalg.svd(a, compute_uv=False), _t(x))


def qr(x, mode="reduced", name=None):
    return apply_op(lambda a: jnp.linalg.qr(a, mode=mode), _t(x))


def eig(x, name=None):
    import numpy as np
    a = np.asarray(_t(x)._value)
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    import numpy as np
    a = np.asarray(_t(x)._value)
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigh(a, UPLO=UPLO), _t(x))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), _t(x))


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply_op(f, _t(x))


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return apply_op(f, _t(x), _t(y))


def cholesky_inverse(x, upper=False, name=None):
    def f(l):
        n = l.shape[-1]
        eye = jnp.eye(n, dtype=l.dtype)
        return jax.scipy.linalg.cho_solve((l, not upper), eye)
    return apply_op(f, _t(x))


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_op(f, _t(x), _t(y))


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32) + 1  # 1-based pivots like the reference
    outs = apply_op(f, _t(x))
    if get_infos:
        info = Tensor(jnp.zeros((), dtype=jnp.int32))
        return outs[0], outs[1], info
    return outs


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    def f(lu_, piv):
        n = lu_.shape[-2]
        l = jnp.tril(lu_, -1) + jnp.eye(n, lu_.shape[-1], dtype=lu_.dtype)
        u = jnp.triu(lu_)
        perm = jnp.arange(n)
        def body(i, p):
            j = piv[i] - 1
            pi, pj = p[i], p[j]
            return p.at[i].set(pj).at[j].set(pi)
        perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
        pmat = jnp.eye(n, dtype=lu_.dtype)[perm].T
        return pmat, l[..., :n, :builtins_min(lu_.shape[-2:])], u
    import builtins
    builtins_min = builtins.min
    return apply_op(f, _t(lu_data), _t(lu_pivots))


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, int(n)), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_rank(a, tol=tol),
                    _t(x), differentiable=False)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), _t(x))


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, _t(x), _t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op(f, _t(x), _t(y))


def multi_dot(x, name=None):
    xs = [_t(v) for v in x]
    return apply_op(lambda *arrs: jnp.linalg.multi_dot(arrs), *xs)


def householder_product(x, tau, name=None):
    def f(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        def body(i, q):
            v = jnp.where(jnp.arange(m) > i, a[:, i], 0.0).at[i].set(1.0)
            h = jnp.eye(m, dtype=a.dtype) - t_[i] * jnp.outer(v, v)
            return q @ h
        q = jax.lax.fori_loop(0, n, body, q)
        return q[:, :n]
    return apply_op(f, _t(x), _t(tau))


def matrix_exp(x, name=None):
    return apply_op(jax.scipy.linalg.expm, _t(x))


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    q = householder_product(x, tau)
    def f(qm, o):
        qq = jnp.swapaxes(qm, -1, -2) if transpose else qm
        return qq @ o if left else o @ qq
    return apply_op(f, q, _t(other))





# ---------------------------------------------------------------------------
# round-2 long-tail additions (ref: python/paddle/tensor/linalg.py).
# matrix_exp / lu_unpack / ormqr already exist above — only cdist is new;
# the others just gained top-level `paddle.*` exports.
# ---------------------------------------------------------------------------
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """ref: paddle.cdist — pairwise p-norm distances [.., N, M].

    p == 2 uses the matmul formulation (MXU-friendly) when the mode asks
    for it — always for use_mm_for_euclid_dist, only when either input
    has > 25 rows in the default if_necessary mode (reference semantics:
    small point sets keep the exact path, dodging ||a||^2+||b||^2-2ab
    cancellation); never for donot_use_mm. p == 0 is hamming; p == inf
    is max."""
    def _safe_root(s, power):
        # d/ds s^power is inf at 0 — mask zeros so coincident points
        # backprop 0, not NaN
        pos = s > 0
        return jnp.where(pos, jnp.where(pos, s, 1.0) ** power, 0.0)

    def f(a, b):
        # reference heuristic: if_necessary switches to mm when either
        # ROW count exceeds 25 (speed dominates); small point sets keep
        # the exact path regardless of feature dim
        n_rows = a.shape[-2]
        m_rows = b.shape[-2]
        use_mm = p == 2.0 and (
            compute_mode == "use_mm_for_euclid_dist"
            or (compute_mode == "use_mm_for_euclid_dist_if_necessary"
                and (n_rows > 25 or m_rows > 25)))
        if use_mm:
            a2 = jnp.sum(a * a, -1)[..., :, None]
            b2 = jnp.sum(b * b, -1)[..., None, :]
            ab = a @ jnp.swapaxes(b, -1, -2)
            return _safe_root(jnp.maximum(a2 + b2 - 2 * ab, 0.0), 0.5)
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 0.0:
            return jnp.sum((d != 0).astype(a.dtype), -1)
        if p == float("inf"):
            return jnp.max(jnp.abs(d), -1)
        if p == 2.0:
            return _safe_root(jnp.sum(d * d, -1), 0.5)
        return _safe_root(jnp.sum(jnp.abs(d) ** p, -1), 1.0 / p)
    return apply_op(f, _t(x), _t(y))


__all__ += ["cdist"]


def mv(x, vec, name=None):
    """ref: paddle.mv — matrix @ vector."""
    return apply_op(lambda a, v: a @ v, _t(x), _t(vec))


__all__ += ["mv"]


# reference namespace parity: paddle.linalg.corrcoef / paddle.linalg.cov
# are the canonical homes (the stats module implements them)
def corrcoef(x, rowvar=True, name=None):
    from .stat import corrcoef as _impl
    return _impl(x, rowvar=rowvar)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    from .stat import cov as _impl
    return _impl(x, rowvar=rowvar, ddof=ddof, fweights=fweights,
                 aweights=aweights)


__all__ += ["corrcoef", "cov"]
