"""Creation ops (ref: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..autograd import apply_op
from ..tensor import Tensor, to_tensor  # noqa: F401

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "tril_indices", "triu_indices", "complex", "polar",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    dt = framework.convert_dtype(dtype)
    if dt is None:
        dt = default or framework.get_default_dtype()
    return dt


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=_dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return apply_op(lambda a: jnp.zeros_like(a, dtype=framework.convert_dtype(dtype)),
                    x, differentiable=False)


def ones_like(x, dtype=None, name=None):
    return apply_op(lambda a: jnp.ones_like(a, dtype=framework.convert_dtype(dtype)),
                    x, differentiable=False)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_op(
        lambda a: jnp.full_like(a, fill_value, dtype=framework.convert_dtype(dtype)),
        x, differentiable=False)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, float):
            dtype = dtype or framework.get_default_dtype()
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    dt = framework.convert_dtype(dtype) if dtype is not None else np.dtype("int64")
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(
        float(start), float(stop), int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(
        float(start), float(stop), int(num), base=float(base), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          None if num_columns is None else int(num_columns),
                          dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    if isinstance(x, (list, tuple, np.ndarray)):
        x = to_tensor(x)
    if padding_value != 0 and x.ndim == 1:
        def g(a):
            n = a.shape[0] + abs(offset)
            out = jnp.full((n, n), padding_value, dtype=a.dtype)
            idx = jnp.arange(a.shape[0])
            r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
            return out.at[r, c].set(a)
        return apply_op(g, x)
    return apply_op(lambda a: jnp.diag(a, k=offset), x)


def diagflat(x, offset=0, name=None):
    return apply_op(lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.triu(a, k=diagonal), x)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col or row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=framework.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col or row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=framework.convert_dtype(dtype)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return apply_op(lambda *xs: jnp.meshgrid(*xs, indexing="ij"), *args)


def assign(x, output=None):
    t = apply_op(lambda a: jnp.asarray(a) + 0,
                 x if isinstance(x, Tensor) else to_tensor(np.asarray(x)))
    if output is not None:
        output._inplace(t)
        return output
    return t


def clone(x, name=None):
    return x.clone()


def complex(real, imag, name=None):
    return apply_op(lambda r, i: r + 1j * i, real, imag)


def polar(abs_t, angle, name=None):
    return apply_op(lambda a, th: a * jnp.exp(1j * th), abs_t, angle)
