"""Search ops: argmax/argmin/argwhere (ref: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import apply_op
from ..framework import convert_dtype
from ..tensor import Tensor, to_tensor

__all__ = ["argmax", "argmin", "argwhere"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    def f(a):
        if axis is None:
            return jnp.argmax(a.reshape(-1)).astype(dt)
        out = jnp.argmax(a, axis=int(axis)).astype(dt)
        return jnp.expand_dims(out, int(axis)) if keepdim else out
    return apply_op(f, _t(x), differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = convert_dtype(dtype)
    def f(a):
        if axis is None:
            return jnp.argmin(a.reshape(-1)).astype(dt)
        out = jnp.argmin(a, axis=int(axis)).astype(dt)
        return jnp.expand_dims(out, int(axis)) if keepdim else out
    return apply_op(f, _t(x), differentiable=False)


def argwhere(x, name=None):
    from .manip import nonzero
    return nonzero(x)
