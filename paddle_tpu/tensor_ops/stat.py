"""Statistics ops (ref: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = [
    "std", "var", "median", "nanmedian", "quantile", "nanquantile",
    "kthvalue", "mode", "histogram", "histogramdd", "bincount", "corrcoef",
    "cov",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                          keepdims=keepdim), _t(x))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                          keepdims=keepdim), _t(x))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(a):
        if mode == "avg":
            return jnp.median(a, axis=_axis(axis), keepdims=keepdim)
        # 'min' mode: lower of the two middle values + its index
        ax = _axis(axis)
        if ax is None:
            flat = a.reshape(-1)
            n = flat.shape[0]
            s = jnp.sort(flat)
            v = s[(n - 1) // 2]
            i = jnp.argsort(flat, stable=True)[(n - 1) // 2]
            return (v, i.astype(jnp.int64))
        n = a.shape[ax]
        s = jnp.sort(a, axis=ax)
        si = jnp.argsort(a, axis=ax)
        v = jnp.take(s, (n - 1) // 2, axis=ax)
        i = jnp.take(si, (n - 1) // 2, axis=ax)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return (v, i.astype(jnp.int64))
    return apply_op(f, _t(x), differentiable=(mode == "avg"))


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op(
        lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), _t(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = jnp.asarray(q, dtype=jnp.float64 if _t(x).dtype == jnp.float64 else jnp.float32)
    return apply_op(
        lambda a: jnp.quantile(a.astype(qq.dtype), qq, axis=_axis(axis),
                               keepdims=keepdim, method=interpolation), _t(x))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = jnp.asarray(q, dtype=jnp.float32)
    return apply_op(
        lambda a: jnp.nanquantile(a.astype(jnp.float32), qq, axis=_axis(axis),
                                  keepdims=keepdim, method=interpolation), _t(x))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        s = jnp.sort(a, axis=ax)
        si = jnp.argsort(a, axis=ax)
        v = jnp.take(s, k - 1, axis=ax)
        i = jnp.take(si, k - 1, axis=ax)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return v, i.astype(jnp.int64)
    return apply_op(f, _t(x))


def mode(x, axis=-1, keepdim=False, name=None):
    a = np.asarray(_t(x)._value)
    ax = axis % a.ndim
    moved = np.moveaxis(a, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=a.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for r in range(flat.shape[0]):
        u, c = np.unique(flat[r], return_counts=True)
        best = u[np.argmax(c)]
        vals[r] = best
        idxs[r] = np.where(flat[r] == best)[0][-1]
    shp = moved.shape[:-1]
    v = vals.reshape(shp)
    i = idxs.reshape(shp)
    if keepdim:
        v = np.expand_dims(v, ax)
        i = np.expand_dims(i, ax)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(i))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def f(a, *w):
        lo, hi = float(min), float(max)
        if lo == 0 and hi == 0:
            lo, hi = jnp.min(a).astype(jnp.float32), jnp.max(a).astype(jnp.float32)
        h, _ = jnp.histogram(a.astype(jnp.float32), bins=bins,
                             range=(lo, hi),
                             weights=w[0] if w else None, density=density)
        return h if (density or w) else h.astype(jnp.int64)
    args = [weight] if weight is not None else []
    return apply_op(f, _t(input), *args, differentiable=False)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    a = np.asarray(_t(x)._value)
    w = np.asarray(weights._value) if isinstance(weights, Tensor) else weights
    h, edges = np.histogramdd(a, bins=bins, range=ranges, density=density, weights=w)
    return Tensor(jnp.asarray(h)), [Tensor(jnp.asarray(e)) for e in edges]


def bincount(x, weights=None, minlength=0, name=None):
    a = np.asarray(_t(x)._value)
    length = max(int(a.max()) + 1 if a.size else 0, minlength)
    def f(arr, *w):
        return jnp.bincount(arr, weights=w[0] if w else None, length=length)
    args = [weights] if weights is not None else []
    out = apply_op(f, _t(x), *args, differentiable=False)
    return out if weights is not None else out.astype("int64")


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), _t(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    def f(a, *rest):
        fw = aw = None
        i = 0
        if fweights is not None:
            fw = rest[i]; i += 1
        if aweights is not None:
            aw = rest[i]
        return jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                       fweights=fw, aweights=aw)
    args = [w for w in (fweights, aweights) if w is not None]
    return apply_op(f, _t(x), *args)


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    """ref: paddle.histogram_bin_edges."""
    if float(max) < float(min):
        raise ValueError("histogram_bin_edges: max must be larger than min")

    def f(a):
        lo, hi = (float(min), float(max))
        if lo == 0 and hi == 0:
            lo = jnp.min(a)
            hi = jnp.max(a)
        # numpy semantics: a collapsed range expands by +-0.5 so the bins
        # have nonzero width even for constant input
        same = hi <= lo
        lo = jnp.where(same, lo - 0.5, lo)
        hi = jnp.where(same, hi + 0.5, hi)
        return jnp.linspace(lo, hi, int(bins) + 1)
    return apply_op(f, _t(x))


__all__ += ["histogram_bin_edges"]
