"""Shape/layout manipulation ops (ref: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = [
    "reshape", "reshape_", "transpose", "concat", "stack", "split", "chunk",
    "squeeze", "unsqueeze", "flatten", "tile", "expand", "expand_as",
    "broadcast_to", "broadcast_tensors", "gather", "gather_nd", "scatter",
    "scatter_nd", "scatter_nd_add", "index_select", "index_sample",
    "index_add", "index_put", "masked_select", "masked_fill",
    "masked_scatter", "flip", "roll", "unbind", "repeat_interleave",
    "take_along_axis", "put_along_axis", "slice", "strided_slice", "unique",
    "unique_consecutive", "sort", "argsort", "topk", "searchsorted",
    "bucketize", "nonzero", "rot90", "moveaxis", "swapaxes", "as_strided",
    "view", "view_as", "unfold", "pad", "take", "tensordot", "tolist",
    "crop", "shard_index", "unstack", "as_complex", "as_real", "atleast_1d",
    "atleast_2d", "atleast_3d", "select_scatter", "diagonal",
    "diagonal_scatter", "fill_diagonal_", "block_diag", "flatten_",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape, name=None):
    s = _shape_arg(shape)
    return apply_op(lambda a: jnp.reshape(a, s), _t(x))


def reshape_(x, shape, name=None):
    return x._inplace(reshape(x, shape))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm=None, name=None):
    return apply_op(lambda a: jnp.transpose(a, axes=perm), _t(x))


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda a: jnp.moveaxis(a, source, destination), _t(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), _t(x))


def concat(x, axis=0, name=None):
    xs = [_t(v) for v in x]
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda *arrs: jnp.concatenate(arrs, axis=ax), *xs)


def stack(x, axis=0, name=None):
    xs = [_t(v) for v in x]
    return apply_op(lambda *arrs: jnp.stack(arrs, axis=axis), *xs)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    n = x.shape[ax]
    if isinstance(num_or_sections, int):
        if n % num_or_sections != 0:
            raise ValueError(
                f"split: dim {ax} size {n} not divisible by {num_or_sections}")
        sizes = [n // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s == -1 for s in sizes):
            rest = n - sum(s for s in sizes if s != -1)
            sizes = [rest if s == -1 else s for s in sizes]
    offsets = np.cumsum([0] + sizes)[:-1]
    outs = []
    for off, sz in zip(offsets, sizes):
        outs.append(apply_op(
            lambda a, off=int(off), sz=int(sz): jax.lax.slice_in_dim(a, off, off + sz, axis=ax),
            x))
    return outs


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    outs = split(x, x.shape[axis], axis)
    return [squeeze(o, axis=axis) for o in outs]


unstack = unbind


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply_op(f, _t(x))


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a.item()) if isinstance(a, Tensor) else int(a) for a in axes]
    def f(a):
        for ax in sorted(axes):
            a = jnp.expand_dims(a, ax)
        return a
    return apply_op(f, _t(x))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply_op(f, _t(x))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    return x._inplace(flatten(x, start_axis, stop_axis))


def tile(x, repeat_times, name=None):
    r = _shape_arg(repeat_times)
    return apply_op(lambda a: jnp.tile(a, r), _t(x))


def expand(x, shape, name=None):
    s = _shape_arg(shape)
    def f(a):
        tgt = list(s)
        # -1 keeps the original dim
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply_op(f, _t(x))


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    xs = [_t(v) for v in inputs]
    return apply_op(lambda *arrs: jnp.broadcast_arrays(*arrs), *xs)


def gather(x, index, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1), axis=ax), _t(x), _t(index))


def gather_nd(x, index, name=None):
    def f(a, idx):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply_op(f, _t(x), _t(index))


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].add(u)
    return apply_op(f, _t(x), _t(index), _t(updates))


def scatter_nd_add(x, index, updates, name=None):
    def f(a, i, u):
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply_op(f, _t(x), _t(index), _t(updates))


def scatter_nd(index, updates, shape, name=None):
    z = to_tensor(jnp.zeros(_shape_arg(shape),
                            dtype=_t(updates)._value.dtype))
    return scatter_nd_add(z, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1), axis=axis),
                    _t(x), _t(index))


def index_sample(x, index, name=None):
    return apply_op(lambda a, i: jnp.take_along_axis(a, i, axis=1),
                    _t(x), _t(index))


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i.reshape(-1)].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return apply_op(f, _t(x), _t(index), _t(value))


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(_t(i) for i in indices)
    def f(a, v, *iarrs):
        if accumulate:
            return a.at[iarrs].add(v)
        return a.at[iarrs].set(v)
    return apply_op(f, _t(x), _t(value), *idx)


def masked_select(x, mask, name=None):
    return apply_op(lambda a, m: a[m.astype(bool)], _t(x), _t(mask))


def masked_fill(x, mask, value, name=None):
    v = value.item() if isinstance(value, Tensor) and value.size == 1 else value
    if isinstance(v, Tensor):
        return apply_op(lambda a, m, vv: jnp.where(m.astype(bool), vv, a),
                        _t(x), _t(mask), v)
    return apply_op(lambda a, m: jnp.where(m.astype(bool), v, a), _t(x), _t(mask))


def masked_scatter(x, mask, value, name=None):
    def f(a, m, v):
        m = m.astype(bool)
        mb = jnp.broadcast_to(m, a.shape)
        cnt = jnp.cumsum(mb.reshape(-1)) - 1
        vflat = v.reshape(-1)
        return jnp.where(mb, vflat[jnp.clip(cnt, 0, vflat.shape[0] - 1)].reshape(a.shape), a)
    return apply_op(f, _t(x), _t(mask), _t(value))


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda a: jnp.flip(a, axis=tuple(axes)), _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _t(x))


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda a: jnp.roll(a, shifts, axis=axis), _t(x))


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return apply_op(
            lambda a, r: jnp.repeat(a, r, axis=axis,
                                    total_repeat_length=int(np.asarray(repeats._value).sum())),
            _t(x), repeats)
    return apply_op(lambda a: jnp.repeat(a, repeats, axis=axis), _t(x))


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_op(lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                    _t(arr), _t(indices))


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if not hasattr(v, "shape") or v.shape != i.shape else v
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        mode = {"add": "add", "multiply": "multiply", "mul": "multiply",
                "amax": "max", "amin": "min"}[reduce]
        am = jnp.moveaxis(a, axis, -1)
        im = jnp.moveaxis(i, axis, -1)
        vm = jnp.moveaxis(jnp.broadcast_to(v, i.shape), axis, -1)
        lead = am.shape[:-1]
        gi = jnp.indices(lead + (im.shape[-1],), sparse=False)
        idx = tuple(gi[k] for k in range(len(lead))) + (im,)
        at = am.at[idx]
        out = {"add": at.add, "multiply": at.multiply, "max": at.max,
               "min": at.min}[mode](vm)
        return jnp.moveaxis(out, -1, axis)
    if not isinstance(values, Tensor):
        values = to_tensor(values)
    return apply_op(f, _t(arr), _t(indices), values)


def take(x, index, mode="raise", name=None):
    md = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply_op(lambda a, i: jnp.take(a.reshape(-1), i.reshape(-1) if i.ndim else i,
                                          mode=md).reshape(i.shape),
                    _t(x), _t(index))


def slice(x, axes, starts, ends, name=None):
    def f(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            s = int(s.item()) if isinstance(s, Tensor) else int(s)
            e = int(e.item()) if isinstance(e, Tensor) else int(e)
            n = out.shape[ax]
            s = max(s + n, 0) if s < 0 else min(s, n)
            e = max(e + n, 0) if e < 0 else min(e, n)
            out = jax.lax.slice_in_dim(out, s, e, axis=ax)
        return out
    return apply_op(f, _t(x))


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [jnp.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = jnp.s_[s:e:st]
        return a[tuple(idx)]
    return apply_op(f, _t(x))


def crop(x, shape=None, offsets=None, name=None):
    s = _shape_arg(shape)
    off = [0] * len(s) if offsets is None else [
        int(o.item()) if isinstance(o, Tensor) else int(o) for o in offsets]
    def f(a):
        sl = tuple(jnp.s_[o:o + (dim if dim != -1 else a.shape[i] - o)]
                   for i, (o, dim) in enumerate(zip(off, s)))
        return a[sl]
    return apply_op(f, _t(x))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    a = np.asarray(_t(x)._value)
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    a = np.asarray(_t(x)._value)
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
    else:
        diff = (np.diff(a, axis=axis) != 0).any(
            axis=tuple(i for i in range(a.ndim) if i != axis))
        keep = np.concatenate([[True], diff])
    vals = np.compress(keep, a, axis=axis or 0)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, a.shape[axis or 0]))
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis, stable=stable)
        return jnp.flip(s, axis=axis) if descending else s
    return apply_op(f, _t(x))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        i = jnp.argsort(a, axis=axis, stable=stable)
        return jnp.flip(i, axis=axis).astype(jnp.int64) if descending else i.astype(jnp.int64)
    return apply_op(f, _t(x), differentiable=False)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    kk = int(k.item()) if isinstance(k, Tensor) else int(k)
    def f(a):
        ax = axis % a.ndim
        am = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(am, kk)
        else:
            v, i = jax.lax.top_k(-am, kk)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i.astype(jnp.int64), -1, ax)
    return apply_op(f, _t(x))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int64
    def f(seq, v):
        if seq.ndim == 1:
            return jnp.searchsorted(seq, v, side=side).astype(dt)
        return jax.vmap(lambda s, vv: jnp.searchsorted(s, vv, side=side))(
            seq.reshape(-1, seq.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape).astype(dt)
    return apply_op(f, _t(sorted_sequence), _t(values), differentiable=False)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def nonzero(x, as_tuple=False):
    a = np.asarray(_t(x)._value)
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def as_strided(x, shape, stride, offset=0, name=None):
    a = np.asarray(_t(x)._value)
    out = np.lib.stride_tricks.as_strided(
        a.reshape(-1)[offset:], shape=shape,
        strides=[s * a.itemsize for s in stride])
    return Tensor(jnp.asarray(out.copy()))


def unfold(x, axis, size, step, name=None):
    def f(a):
        n = (a.shape[axis] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        am = jnp.moveaxis(a, axis, 0)
        out = am[idx]  # [n, size, ...rest]
        out = jnp.moveaxis(out, 0, axis)
        return jnp.moveaxis(out, axis + 1 if axis >= 0 else axis, -1)
    return apply_op(f, _t(x))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def f(a):
        p = [int(v.item()) if isinstance(v, Tensor) else int(v) for v in pad]
        nd = a.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pad applies to the last len(p)//2 spatial dims
            # in (left, right, top, bottom, front, back) order, innermost first
            npairs = len(p) // 2
            width = [(0, 0)] * (nd - npairs)
            pairs = [(p[2 * i], p[2 * i + 1]) for i in range(npairs)]
            width += list(reversed(pairs))
        if mode == "constant":
            return jnp.pad(a, width, mode="constant", constant_values=value)
        jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        return jnp.pad(a, width, mode=jmode)
    return apply_op(f, _t(x))


def tensordot(x, y, axes=2, name=None):
    def norm_axes(ax):
        if isinstance(ax, Tensor):
            ax = ax.tolist()
        if isinstance(ax, (list, tuple)):
            return tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in ax)
        return ax
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=norm_axes(axes)),
                    _t(x), _t(y))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(i):
        size = index_num // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        inside = (i >= lo) & (i < hi)
        return jnp.where(inside, i - lo, ignore_value)
    return apply_op(f, _t(input), differentiable=False)


def as_complex(x, name=None):
    return apply_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), _t(x))


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), _t(x))


def atleast_1d(*xs, name=None):
    outs = [apply_op(jnp.atleast_1d, _t(x)) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*xs, name=None):
    outs = [apply_op(jnp.atleast_2d, _t(x)) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*xs, name=None):
    outs = [apply_op(jnp.atleast_3d, _t(x)) for x in xs]
    return outs[0] if len(outs) == 1 else outs


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2),
                    _t(x))


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, b):
        n = builtins_min(a.shape[axis1], a.shape[axis2])
        i = jnp.arange(n - builtins_abs(offset) if offset else n)
        r = i if offset >= 0 else i - offset
        c = i + offset if offset >= 0 else i
        am = jnp.moveaxis(jnp.moveaxis(a, axis1, 0), axis2 if axis2 > axis1 else axis2 + 1, 1)
        am = am.at[r, c].set(jnp.moveaxis(b, -1, 0))
        return jnp.moveaxis(jnp.moveaxis(am, 1, axis2 if axis2 > axis1 else axis2 + 1), 0, axis1)
    return apply_op(f, _t(x), _t(y))


import builtins as _builtins
builtins_min = _builtins.min
builtins_abs = _builtins.abs


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        am = jnp.moveaxis(a, axis, 0)
        am = am.at[index].set(v)
        return jnp.moveaxis(am, 0, axis)
    return apply_op(f, _t(x), _t(values))


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    n = builtins_min(x.shape[0], x.shape[1]) if x.ndim >= 2 else 0
    i = jnp.arange(n - builtins_abs(offset) if offset else n)
    r = i if offset >= 0 else i - offset
    c = i + offset if offset >= 0 else i
    x._value = x._value.at[r, c].set(value)
    return x


def block_diag(inputs, name=None):
    xs = [_t(v) for v in inputs]
    return apply_op(lambda *arrs: jax.scipy.linalg.block_diag(*arrs), *xs)


def tolist(x):
    return x.tolist()


# ---------------------------------------------------------------------------
# round-2 long-tail additions (ref: python/paddle/tensor/manipulation.py)
# ---------------------------------------------------------------------------
def unflatten(x, axis, shape, name=None):
    """ref: paddle.unflatten — expand one axis into `shape`."""
    def f(a):
        ax = axis % a.ndim
        shp = tuple(int(s) for s in shape)
        return a.reshape(a.shape[:ax] + shp + a.shape[ax + 1:])
    return apply_op(f, _t(x))


def index_fill(x, index, axis, value, name=None):
    """ref: paddle.index_fill."""
    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[idx.astype(jnp.int32)].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return apply_op(f, _t(x), _t(index))


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """ref: paddle.slice_scatter."""
    def f(a, v):
        import builtins
        sl = [builtins.slice(None)] * a.ndim  # paddle.slice shadows builtin
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(int(st), int(en), int(sd))
        return a.at[tuple(sl)].set(v)
    return apply_op(f, _t(x), _t(value))


def column_stack(x, name=None):
    ts = [_t(v) for v in x]
    return apply_op(lambda *arrs: jnp.column_stack(arrs), *ts)


def row_stack(x, name=None):
    ts = [_t(v) for v in x]
    return apply_op(lambda *arrs: jnp.vstack(arrs), *ts)


def hsplit(x, num_or_indices, name=None):
    return apply_op(lambda a: tuple(jnp.hsplit(a, num_or_indices)), _t(x))


def vsplit(x, num_or_indices, name=None):
    return apply_op(lambda a: tuple(jnp.vsplit(a, num_or_indices)), _t(x))


def dsplit(x, num_or_indices, name=None):
    return apply_op(lambda a: tuple(jnp.dsplit(a, num_or_indices)), _t(x))


def tensor_split(x, num_or_indices, axis=0, name=None):
    return apply_op(
        lambda a: tuple(jnp.array_split(a, num_or_indices, axis=axis)),
        _t(x))


__all__ += ["unflatten", "index_fill", "slice_scatter", "column_stack",
            "row_stack", "hsplit", "vsplit", "dsplit", "tensor_split"]
