"""Comparison / logical ops (ref: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_xor", "logical_not", "is_empty", "is_tensor",
    "where", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _cmp(fn):
    def op(x, y, name=None):
        return apply_op(fn, _t(x), y, differentiable=False)
    return op


equal = _cmp(jnp.equal)
not_equal = _cmp(jnp.not_equal)
less_than = _cmp(jnp.less)
less_equal = _cmp(jnp.less_equal)
greater_than = _cmp(jnp.greater)
greater_equal = _cmp(jnp.greater_equal)
logical_and = _cmp(jnp.logical_and)
logical_or = _cmp(jnp.logical_or)
logical_xor = _cmp(jnp.logical_xor)
bitwise_and = _cmp(jnp.bitwise_and)
bitwise_or = _cmp(jnp.bitwise_or)
bitwise_xor = _cmp(jnp.bitwise_xor)
bitwise_left_shift = _cmp(jnp.left_shift)
bitwise_right_shift = _cmp(jnp.right_shift)


def logical_not(x, name=None):
    return apply_op(jnp.logical_not, _t(x), differentiable=False)


def bitwise_not(x, name=None):
    return apply_op(jnp.bitwise_not, _t(x), differentiable=False)


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), _t(x), _t(y),
                    differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        _t(x), _t(y), differentiable=False)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        _t(x), _t(y), differentiable=False)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .manip import nonzero
        return nonzero(condition, as_tuple=True)
    return apply_op(lambda c, a, b: jnp.where(c.astype(bool), a, b),
                    _t(condition), x, y)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    """ref: paddle.all."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(lambda a: jnp.all(a, axis=ax, keepdims=keepdim), _t(x),
                    differentiable=False)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    """ref: paddle.any."""
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(lambda a: jnp.any(a, axis=ax, keepdims=keepdim), _t(x),
                    differentiable=False)


__all__ += ["all", "any"]
