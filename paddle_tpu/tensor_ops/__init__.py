"""Functional tensor op surface (ref: python/paddle/tensor/*).

Every public op both lives at paddle_tpu.<op> and is bound as a Tensor
method where the reference has one. All ops dispatch through
autograd.apply_op so the eager tape sees them; under jit they trace straight
to jnp/lax.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manip import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from . import linalg  # noqa: F401
from .linalg import (  # noqa: F401
    cdist, lu_unpack, matmul, matrix_exp, dot, ormqr, t, bmm, dist,
)
from ._bind import bind_tensor_methods

bind_tensor_methods()
