"""Measured roofline for the ResNet-50 conv segments (VERDICT r3 weak #2:
"close or experimentally bound the gap" — this produces the bound).

For each distinct conv shape in the ResNet-50 forward (dominated by the
1x1 convs BENCHLOG diagnosed as bandwidth-bound), times an isolated
jitted conv+BN+ReLU block at the training batch size and reports:
  - achieved TFLOP/s vs the 197 TFLOP/s bf16 MXU peak
  - achieved GB/s (input + weight + output bytes) vs the 819 GB/s HBM
    peak of one v5e chip
  - which roof binds (arithmetic intensity vs the ridge point)

One JSON line per segment + a summary line; structure runs on CPU with
--smoke (tiny shapes) so the tool itself is testable without the TPU.

Usage: python tools/resnet_roofline.py [--batch 256] [--smoke]
"""
from __future__ import annotations

import argparse
import json
import time

HBM_PEAK_GBS = 819.0
MXU_PEAK_TFLOPS = 197.0


# (name, in_c, out_c, k, stride, spatial_in) — the distinct conv shapes
# of ResNet-50 at 224x224 (each appears `count` times per forward)
RESNET50_SEGMENTS = [
    ("stem7x7", 3, 64, 7, 2, 224, 1),
    ("s1_1x1a", 64, 64, 1, 1, 56, 1),
    ("s1_3x3", 64, 64, 3, 1, 56, 3),
    ("s1_1x1b", 64, 256, 1, 1, 56, 3),
    ("s1_1x1r", 256, 64, 1, 1, 56, 2),
    ("s1_proj", 64, 256, 1, 1, 56, 1),
    ("s2_1x1a", 256, 128, 1, 1, 56, 1),
    ("s2_proj", 256, 512, 1, 2, 56, 1),
    ("s2_3x3s2", 128, 128, 3, 2, 56, 1),
    ("s2_1x1b", 128, 512, 1, 1, 28, 4),
    ("s2_1x1r", 512, 128, 1, 1, 28, 3),
    ("s2_3x3", 128, 128, 3, 1, 28, 3),
    ("s3_1x1a", 512, 256, 1, 1, 28, 1),
    ("s3_proj", 512, 1024, 1, 2, 28, 1),
    ("s3_3x3s2", 256, 256, 3, 2, 28, 1),
    ("s3_1x1b", 256, 1024, 1, 1, 14, 6),
    ("s3_1x1r", 1024, 256, 1, 1, 14, 5),
    ("s3_3x3", 256, 256, 3, 1, 14, 5),
    ("s4_1x1a", 1024, 512, 1, 1, 14, 1),
    ("s4_proj", 1024, 2048, 1, 2, 14, 1),
    ("s4_3x3s2", 512, 512, 3, 2, 14, 1),
    ("s4_1x1b", 512, 2048, 1, 1, 7, 3),
    ("s4_1x1r", 2048, 512, 1, 1, 7, 2),
    ("s4_3x3", 512, 512, 3, 1, 7, 2),
]


def segment_cost(batch, in_c, out_c, k, stride, spatial_in, dtype_bytes=2):
    """(flops, bytes) of one conv at the given shape (NCHW bf16)."""
    out_sp = spatial_in // stride
    flops = 2 * batch * out_c * out_sp * out_sp * in_c * k * k
    bytes_ = dtype_bytes * (
        batch * in_c * spatial_in * spatial_in      # activations in
        + in_c * out_c * k * k                      # weights
        + batch * out_c * out_sp * out_sp)          # activations out
    return flops, bytes_


def bench_segment(batch, in_c, out_c, k, stride, spatial_in, reps=20):
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng0 = np.random.default_rng(1)
    cw = jnp.asarray(rng0.standard_normal((out_c, in_c, k, k)) * 0.05,
                     jnp.bfloat16)

    @jax.jit
    def f(x, w):
        y = jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(k // 2, k // 2)] * 2,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            preferred_element_type=jnp.float32)
        return jax.nn.relu(y).astype(jnp.bfloat16)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal(
        (batch, in_c, spatial_in, spatial_in)), jnp.bfloat16)
    out = f(x, cw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(x, cw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes on CPU: exercises the tool, the "
                    "numbers are meaningless")
    args = ap.parse_args()

    segments = RESNET50_SEGMENTS
    batch = args.batch
    if args.smoke:
        segments = [("smoke1x1", 8, 16, 1, 1, 8, 1),
                    ("smoke3x3", 8, 8, 3, 1, 8, 1)]
        batch = 4

    ridge = MXU_PEAK_TFLOPS * 1e12 / (HBM_PEAK_GBS * 1e9)  # FLOPs/byte
    total_t = total_flops = total_bytes = roof_t = 0.0
    rows = []
    for name, ic, oc, k, s, sp, count in segments:
        dt = bench_segment(batch, ic, oc, k, s, sp)
        flops, bytes_ = segment_cost(batch, ic, oc, k, s, sp)
        roof_t += max(flops / (MXU_PEAK_TFLOPS * 1e12),
                      bytes_ / (HBM_PEAK_GBS * 1e9)) * count
        ai = flops / bytes_
        row = {
            "segment": name, "count": count,
            "tflops": round(flops / dt / 1e12, 1),
            "gbs": round(bytes_ / dt / 1e9, 1),
            "ai_flops_per_byte": round(ai, 1),
            "bound": "compute" if ai > ridge else "bandwidth",
            "pct_of_roof": round(100 * max(
                (flops / dt / 1e12) / MXU_PEAK_TFLOPS,
                (bytes_ / dt / 1e9) / HBM_PEAK_GBS), 1),
            "ms": round(dt * 1e3, 3),
        }
        rows.append(row)
        print(json.dumps(row), flush=True)
        total_t += dt * count
        total_flops += flops * count
        total_bytes += bytes_ * count

    # roof_t (accumulated above) is the experimentally-bound ceiling:
    # every segment running exactly AT its binding roof
    print(json.dumps({
        "metric": "resnet50_conv_stack_roofline",
        "measured_ms": round(total_t * 1e3, 1),
        "roofline_ms": round(roof_t * 1e3, 1),
        "roof_utilization": round(roof_t / total_t, 3) if total_t else 0,
        "agg_tflops": round(total_flops / total_t / 1e12, 1),
        "agg_gbs": round(total_bytes / total_t / 1e9, 1),
        "implied_img_per_sec_ceiling": round(batch / roof_t, 0),
        "note": "fwd conv stack only; x3 for training (fwd+bwd) and add "
                "BN/elementwise passes for the full step bound",
    }), flush=True)


if __name__ == "__main__":
    main()
