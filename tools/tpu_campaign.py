"""One-command TPU measurement campaign for when the axon tunnel is up.

Runs, in order of scoreboard value, each piece subprocess-isolated so a
wedge costs one stage (results land incrementally in campaign_out/):

  1. backend probe (tiny matmul)                 -> probe.json
  2. bench full suite (gpt, ernie, resnet50,     -> bench_full.json
     gpt-1.3b) — the BENCH_r03 shape
  3. resnet50 --s2d A/B                          -> bench_resnet_s2d.json
  3b. resnet50 NHWC layout / fused-bottleneck    -> bench_resnet_nhwc.json
      A/B (the r6 "win ResNet" directive)           bench_resnet_nhwc_fused.json
  4. gpt moment_dtype=bfloat16 A/B               -> bench_gpt_bf16m.json
  5. decode bisection probes (kernel/scan/full)  -> decode_probe.json
  6. decode bench (safe jnp path)                -> bench_decode.json
  7. fusion audit (gpt + resnet optimized HLO)   -> fusion_audit.md

Usage: python tools/tpu_campaign.py [--skip N,M] [--only N]
Each stage prints PASS/FAIL + seconds; stop/resume freely — stages are
independent. After a FAIL the campaign reprobes the backend and stops
if the terminal is wedged (leaving earlier artifacts intact).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "campaign_out")

sys.path.insert(0, REPO)
from bench import _proc_starttime  # noqa: E402  (single owner of the
#                                     'pid starttime' pidfile format)


def run(cmd, timeout, log_name, env_extra=None):
    os.makedirs(OUT, exist_ok=True)
    log_path = os.path.join(OUT, log_name)
    env = dict(os.environ)
    # stages must not trigger bench.py's driver-preemption path (which
    # exists to kill *us* when the round-end driver bench starts)
    env["CAMPAIGN_CHILD"] = "1"
    # per-stage telemetry dir: bench workers (and telemetry_smoke)
    # write telemetry.jsonl + metrics.json here, next to <stage>.log —
    # validate_stages checks completed stages produced a parseable one.
    # Cleared first: the worker-side finalize MERGES an existing
    # metrics.json (same-run multi-worker stages), so a previous run's
    # leftovers would pollute this run's counters and keep a
    # historical unexpected-retrace in the report forever
    tele_dir = os.path.join(OUT, "telemetry",
                            os.path.splitext(log_name)[0])
    shutil.rmtree(tele_dir, ignore_errors=True)
    env["BENCH_TELEMETRY_DIR"] = tele_dir
    env.update(env_extra or {})
    pid_path = os.path.join(OUT, "current_stage.pid")
    t0 = time.monotonic()
    with open(log_path, "w") as log:
        proc = subprocess.Popen(cmd, cwd=REPO, stdout=log,
                                stderr=subprocess.STDOUT,
                                start_new_session=True, env=env)
        try:
            # "pid starttime": the kernel starttime (field 22 of
            # /proc/<pid>/stat) lets the driver-bench preemptor prove
            # the pid was not recycled before it SIGKILLs the group
            with open(pid_path, "w") as f:
                f.write(f"{proc.pid} {_proc_starttime(proc.pid)}")
        except OSError:
            pass
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait()
            rc = "timeout"
        finally:
            try:
                os.remove(pid_path)
            except OSError:
                pass
    dt = round(time.monotonic() - t0, 1)
    tail = open(log_path).read()[-400:]
    return rc, dt, tail


def last_json(log_name):
    try:
        for line in reversed(open(os.path.join(OUT, log_name)).readlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
    except (OSError, json.JSONDecodeError):
        pass
    return None


PY = sys.executable

DRIVER_MARKER = os.path.join(OUT, "driver_bench_active")


def _driver_bench_active(max_age_s=45 * 60):
    """True while the round-end driver bench holds the chip (marker is
    removed on its clean exit; mtime bounds a crashed run's hold)."""
    try:
        return (time.time() - os.path.getmtime(DRIVER_MARKER)) < max_age_s
    except OSError:
        return False

STAGES = [
    ("probe", [PY, "bench.py", "--worker", "probe"], 600, {}),
    # static invariant sweep (ISSUE 13, CPU, seconds): tools/tpulint
    # over paddle_tpu/ + tools/ + bench.py — trace-safety, durability,
    # concurrency, telemetry-JSON and doc-catalogue contracts checked
    # BEFORE any chaos stage burns minutes discovering the same bug at
    # runtime. Zero tunnel window; the stage's lint_report.json lands
    # in its telemetry dir (the CLI honors BENCH_TELEMETRY_DIR) where
    # validate_stages requires non_baselined == 0.
    ("staticcheck", [PY, "-m", "tools.tpulint", "--json"], 600,
     {"JAX_PLATFORMS": "cpu"}),
    # resilience chaos drill (ISSUE 3): fault-injection suite with a
    # fixed seed, forced onto CPU — it validates the build's failure
    # handling (guard/rollback, preemption resume, serving
    # degradation) WITHOUT burning tunnel window, so it runs first
    ("chaos_smoke", [PY, "-m", "pytest", "tests/test_resilience.py",
                     "-q", "-m", "chaos", "-p", "no:cacheprovider",
                     "-p", "no:randomly"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # observability drill (ISSUE 4, CPU): 5-step guarded fit + serve
    # wave, asserts the metric catalogue + zero unexpected retraces and
    # writes the same telemetry.jsonl/metrics.json shape bench stages do
    ("telemetry_smoke", [PY, "tools/telemetry_smoke.py"], 1200,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # fleet chaos drill (ISSUE 6 + 8 + 9, CPU): in-process serving
    # replicas under a seeded fault wave (replica crash/wedge/slow,
    # flaky transport, drain/rejoin, hedging, shed storms, router
    # crash + journal disk faults) — asserts 100% request completion
    # with token-exact failover dedup, one causally-linked trace tree
    # per request with attribution within tolerance, SLO burn-rate
    # alerting, exactly-once delivery across router crashes, and 0
    # unexpected retraces fleet-wide. The stage exports a merged fleet
    # metrics.json that the fleet canary gate below diffs against the
    # committed golden (which therefore also covers the
    # fleet_journal_* recovery counters).
    # (PADDLE_TPU_RUN_SLOW=1 unmasks the slow-marked real-subprocess
    # supervisor drills so the canary golden also covers the
    # fleet_respawns/crash_loops/boot counters.)
    ("fleet_chaos_smoke", [PY, "-m", "pytest",
                           "tests/test_fleet_serving.py",
                           "tests/test_fleet_tracing.py",
                           "tests/test_fleet_recovery.py",
                           "tests/test_fleet_proc.py",
                           "tests/test_fleet_autoscale.py",
                           "tests/test_prefix_cache.py",
                           "tests/test_spec_decode.py", "-q",
                           "-m", "chaos", "-p", "no:cacheprovider",
                           "-p", "no:randomly"], 3600,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0",
      "PADDLE_TPU_RUN_SLOW": "1"}),
    # router durability drill in isolation (ISSUE 9, CPU): seeded
    # kill-router-mid-wave (crash seam, SIGTERM preemption, torn
    # journal writes, transient disk errors), recover against the
    # same live replicas, assert token-exact + exactly-once + frozen
    # compile counts + a parseable fleet_router_recovery flight dump.
    # DELIBERATELY duplicates the recovery slice inside
    # fleet_chaos_smoke (~4 CPU-minutes): the chaos stage must
    # include these tests so the canary golden covers the
    # fleet_journal_* counters, while this stage gives the durability
    # path its own pass/fail line + flight-dump validation
    # (validate_stages.FLIGHT_STAGES) for fast triage.
    ("fleet_recovery_smoke", [PY, "-m", "pytest",
                              "tests/test_fleet_recovery.py", "-q",
                              "-m", "chaos", "-p", "no:cacheprovider",
                              "-p", "no:randomly"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # process-supervision drill in isolation (ISSUE 10, CPU): REAL
    # subprocess replicas — kill -9 mid-decode → router failover +
    # supervisor respawn + warm-boot health-gated rejoin (token-exact,
    # zero steady-state recompiles), a persistent exit-at-boot seed
    # tripping the crash-loop breaker (quarantine + flight dump),
    # SIGTERM child drain, slow-boot gate kills. DELIBERATELY overlaps
    # the proc slice inside fleet_chaos_smoke (golden/canary coverage
    # vs fast triage — the same split fleet_recovery_smoke uses), and
    # its own pass/fail line validates flight dumps
    # (validate_stages.FLIGHT_STAGES).
    ("fleet_supervisor_smoke", [PY, "-m", "pytest",
                                "tests/test_fleet_proc.py", "-q",
                                "-m", "chaos", "-p",
                                "no:cacheprovider", "-p",
                                "no:randomly"], 2400,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0",
      "PADDLE_TPU_RUN_SLOW": "1"}),
    # telemetry-history / tenancy / anomaly-sentinel drill (ISSUE 11,
    # CPU, seeded): a clean golden wave the sentinel must stay quiet
    # on (including a replay over the COMMITTED clean golden archive
    # tools/golden/history_clean_wave.json — band drift that alarms
    # on known-good history fails here), then a wave with an injected
    # mid-wave latency regression the sentinel MUST fire on (leaving
    # a parseable fleet_anomaly flight dump); per-tenant token totals
    # must sum EXACTLY to fleet counters and compile counts stay
    # frozen with accounting on. The stage's history_snapshot.json is
    # then driven through the history gate below (metrics_diff
    # --history --at/--vs): quiet span clean, regression span trips.
    ("history_smoke", [PY, "tools/history_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # traffic capture & deterministic replay drill (ISSUE 12, CPU,
    # seeded): the committed 20-request wave
    # (tools/golden/replay_wave.json) is captured live through a
    # capture-armed fleet (archive complete, zero capture<->trace
    # sampling divergences, compile counts frozen with capture on),
    # the COMMITTED archive replays golden (token-exact per rid, zero
    # new XLA traces), the live capture replays clean under the
    # default verdict gates (per-hop attribution deltas within 5%),
    # and an injected replica_slow regression MUST trip the same gate
    # spec — both gate directions proven, vacuity-guarded. Artifacts:
    # replay_verdict.json + replay_verdict_regression.json + the
    # capture archive, next to the stage's metrics.json.
    ("replay_smoke", [PY, "tools/replay_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # elastic autoscaling drill (ISSUE 15, CPU, seeded): a one-replica
    # fleet under a pinned-slow burst — multi-window TTFT burn fires →
    # scale-out through the warm-boot gate (adopted replica takes
    # traffic with zero new steady-state traces), recovery + budget
    # refill + idle hold → scale-in (hedge-safe drain → remove).
    # Asserts no lost rid (exactly-once), ok results token-exact vs
    # an uninterrupted golden, bounded SLO breach, zero flaps, frozen
    # compile counts, scale_out/scale_in journal records reconcile,
    # and parseable fleet_scale_out/in flight dumps
    # (validate_stages.FLIGHT_STAGES).
    ("autoscale_smoke", [PY, "tools/autoscale_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # copy-on-write prefix-cache drill (ISSUE 16, CPU, seeded): a
    # shared-prefix wave through a cache-ON engine vs a cache-OFF
    # control — ON streams token-exact vs OFF across two waves (the
    # hard invariant), cumulative page hit rate >= 0.5, ON TTFT p50
    # strictly below OFF (hits run the short tail-prefill ladder, not
    # the full bucket), compile counts frozen with caching ON (zero
    # unexpected retraces), and every page back on the free list
    # after close (shared-page refcounts conserve).
    ("prefix_cache_smoke", [PY, "tools/prefix_cache_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # speculative-decoding drill (ISSUE 20, CPU, seeded): a long-decode
    # wave through a spec-ON engine (K=8, ngram prompt-lookup draft)
    # vs a spec-OFF control at steps_per_dispatch=1 — ON streams
    # token-exact vs OFF (the hard invariant: speculation may change
    # latency, never tokens), cumulative draft acceptance >= 0.5,
    # ON decode tok/s strictly above OFF (an accepting dispatch
    # commits up to K+1 tokens against one folded-batch verify),
    # compile counts frozen with speculation ON (the verify scan is
    # pre-traced by warmup), zero unexpected retraces.
    ("spec_smoke", [PY, "tools/spec_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # continuous-profiling drill (ISSUE 22, CPU, seeded): a wave
    # through a profiler-ARMED engine — compile counts frozen with
    # profiling ON (the sampler is host-side only), serving-phase
    # markers observed live on the dispatch path (decode + a prefill
    # bucket), self-measured overhead at/under the 1% duty-cycle cap,
    # /profile endpoint + flamegraph HTML render from the same run,
    # and the profile_diff gate proven BOTH directions (clean-vs-clean
    # passes, an injected decode busy-loop trips phase:decode>+10%).
    ("profile_smoke", [PY, "tools/profile_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # device-memory ledger drill (HBM ledger round, CPU, seeded): a
    # prefix-hitting wave through a ledger-ARMED engine — compile
    # counts frozen with accounting ON (track/release is host-side
    # dict arithmetic), typed segments + unattributed residual
    # conserve against ground truth within 1%, /memory endpoint +
    # engine_mem_* gauges render live, the residual alarm stays QUIET
    # on the clean wave, and the leak drill (an untracked device page
    # block + pages popped off the free list, never returned) must
    # trip BOTH the residual alarm and the mem_diff gate
    # (clean-vs-clean passes, clean-vs-leaked fails
    # segment:unattributed>+50%).
    ("mem_smoke", [PY, "tools/mem_smoke.py"], 1800,
     {"JAX_PLATFORMS": "cpu", "PYTHONHASHSEED": "0"}),
    # AOT serving-artifact boot probe (ISSUE 21, seeded): traced
    # warmup control -> export_artifact -> warm_boot a second engine
    # off the store. Asserts the artifact path was taken (mode=aot,
    # zero fallbacks), token-exact generation vs the traced control,
    # zero post-boot traces, and artifact boot wall strictly below
    # traced. No platform pin: on the first live TPU window this IS
    # the measured artifact-boot-vs-traced number (tunnel_watch rung).
    ("aot_boot", [PY, "tools/aot_boot_probe.py"], 1800,
     {"PYTHONHASHSEED": "0"}),
    ("bench_full", [PY, "bench.py"], 7200, {}),
    ("bench_resnet_s2d", [PY, "bench.py", "--model", "resnet50", "--s2d"],
     2400, {}),
    # NHWC-native conv stack + Pallas fused bottleneck: the round-6
    # "win ResNet" levers (VERDICT r5 directive #3). NCHW baseline is
    # bench_full's resnet50; these are the two rungs on top.
    ("bench_resnet_nhwc", [PY, "bench.py", "--model", "resnet50",
                           "--layout", "nhwc"], 2400, {}),
    ("bench_resnet_nhwc_fused", [PY, "bench.py", "--model", "resnet50",
                                 "--layout", "nhwc",
                                 "--fused-bottleneck"], 2400, {}),
    # s2d stem stacked on the NHWC pipeline (the stems compose)
    ("bench_resnet_nhwc_s2d", [PY, "bench.py", "--model", "resnet50",
                               "--layout", "nhwc", "--s2d"], 2400, {}),
    ("bench_gpt_bf16m", [PY, "bench.py", "--model", "gpt",
                         "--moment-dtype", "bfloat16"], 2400, {}),
    # continuous-batching serving ladder (nlp/serving.py): batch x
    # cache-dtype cross product, zero-recompile asserted per rung.
    # Hardware flash rungs stay gated until decode_probe --paged
    # proves the paged kernel (bench_serve_flashk below arms it).
    ("bench_serve_gpt", [PY, "bench.py", "--serve"], 3600, {}),
    ("bench_serve_llama", [PY, "bench.py", "--serve", "--serve-model",
                           "llama"], 3600, {}),
    # llama pretrain: the GQA flagship's first-ever training number
    ("bench_llama", [PY, "bench.py", "--model", "llama"], 2400, {}),
    ("decode_probe", [PY, "tools/decode_probe.py"], 2400, {}),
    # paged-path bisection: GQA kernel alone, then the serving engine
    # with per-rung compile counts (killable children — r2 lesson)
    ("decode_probe_paged", [PY, "tools/decode_probe.py", "--paged"],
     2400, {}),
    ("bench_decode", [PY, "bench.py", "--decode"], 2400, {}),
    ("bench_decode_bf16kv", [PY, "bench.py", "--decode",
                             "--cache-dtype", "bfloat16"], 2400, {}),
    ("bench_decode_int8", [PY, "bench.py", "--decode", "--weight-only",
                           "int8", "--cache-dtype", "bfloat16"], 2400,
     {}),
    ("bench_decode_bf16w", [PY, "bench.py", "--decode", "--serve-dtype",
                            "bfloat16", "--cache-dtype", "bfloat16"],
     2400, {}),
    ("bench_decode_int4", [PY, "bench.py", "--decode", "--weight-only",
                           "int4", "--cache-dtype", "bfloat16"], 2400,
     {}),
    # Pallas flash-decode kernel (env-gated; run AFTER decode_probe's
    # bisection says the kernel compiles — r2's decode wedge came from
    # exactly this path, which is why it is last in the ladder)
    ("bench_decode_flashk", [PY, "bench.py", "--decode", "--cache-dtype",
                             "bfloat16"], 2400,
     {"PADDLE_TPU_FLASH_DECODE": "1"}),
    # flash rungs of the serving ladder with the paged Pallas kernel
    # armed (run AFTER decode_probe_paged passes — same caution as
    # bench_decode_flashk); --flash-only skips the ref rungs
    # bench_serve_gpt already measured
    ("bench_serve_flashk", [PY, "bench.py", "--serve", "--flash-only"],
     3600, {"PADDLE_TPU_FLASH_DECODE": "1"}),
    ("fusion_audit", [PY, "tools/fusion_audit.py", "--out",
                      "campaign_out/fusion_audit.md"], 3600, {}),
    ("fusion_audit_nhwc", [PY, "tools/fusion_audit.py", "--model",
                           "resnet", "--layout", "nhwc",
                           "--fused-bottleneck", "--out",
                           "campaign_out/fusion_audit_nhwc.md"], 3600,
     {}),
    ("resnet_roofline", [PY, "tools/resnet_roofline.py"], 2400, {}),
    # serving throughput +/- conv-bn folding (conv_bn_fuse_pass parity)
    ("bench_resnet_serve", [PY, "bench.py", "--model", "resnet50",
                            "--serve"], 2400, {}),
    ("bench_resnet_serve_fold", [PY, "bench.py", "--model", "resnet50",
                                 "--serve", "--fold-bn"], 2400, {}),
    # training-throughput attempts the r4 verdict asked for
    ("bench_resnet_b512", [PY, "bench.py", "--model", "resnet50",
                           "--batch", "512"], 2400, {}),
    # retry queue (r4: the tunnel died mid-campaign after 45 min; these
    # are what remained — tools/tunnel_watch.py fires them on revival)
    ("bench_gpt13b", [PY, "bench.py", "--model", "gpt-1.3b",
                      "--no-scan-fallback"], 2400, {}),
    # scan-over-layers variant: O(1-block) program — the mitigation for
    # the remote_compile RPC cutoff that killed the unrolled 1.3B
    ("bench_gpt13b_scan", [PY, "bench.py", "--model", "gpt-1.3b",
                           "--scan-layers"], 2400, {}),
    # + fused head/loss: the [N,vocab] logits never materialize —
    # the memory headroom lever for bigger 1.3B batches
    ("bench_gpt13b_scan_cce", [PY, "bench.py", "--model", "gpt-1.3b",
                               "--scan-layers", "--chunked-ce", "2048"],
     2400, {}),
    ("bench_gpt_chunkedce", [PY, "bench.py", "--model", "gpt",
                             "--chunked-ce", "2048"], 2400, {}),
    # one-HBM-pass Pallas optimizer update A/B (step anatomy: the
    # jnp AdamW chain ran at ~2x its bandwidth floor)
    ("bench_gpt_fusedadamw", [PY, "bench.py", "--model", "gpt",
                              "--fused-adamw"], 2400, {}),
    # headline batch-scaling probe: MFU 0.40 at b8 — check whether b16
    # lifts backward-pass efficiency (fits: 345M + Adam fp32 ~4.2 GB,
    # acts at b16 s1024 with flash ~4 GB)
    ("bench_gpt_b16", [PY, "bench.py", "--model", "gpt", "--batch", "16"],
     2400, {}),
    # fused [h,3h] qkv matmul A/B on the headline config
    ("bench_gpt_fusedqkv", [PY, "bench.py", "--model", "gpt",
                            "--fused-qkv"], 2400, {}),
    # fused residual-add+LayerNorm Pallas pass A/B (elementwise-HBM
    # lever from the r4 step anatomy)
    ("bench_gpt_fusedln", [PY, "bench.py", "--model", "gpt",
                           "--fused-ln"], 2400, {}),
    ("bench_gpt_fusedboth", [PY, "bench.py", "--model", "gpt",
                             "--fused-ln", "--fused-qkv"], 2400, {}),
    ("bench_ernie_fusedqkv", [PY, "bench.py", "--model", "ernie",
                              "--fused-qkv"], 2400, {}),
    ("bench_ernie_fusedln", [PY, "bench.py", "--model", "ernie",
                             "--fused-ln"], 2400, {}),
    # masked-position gather before the MLM head: ~20%% of ERNIE's
    # step FLOPs are vocab logits for unmasked positions
    ("bench_ernie_mlmgather", [PY, "bench.py", "--model", "ernie",
                               "--mlm-gather", "0.25"], 2400, {}),
    # long-context: flash 512-blocks beat XLA fused attention 1.77x at
    # s=4096 (r2 microbench) — measure the end-to-end train step there
    ("bench_gpt_s4k", [PY, "bench.py", "--model", "gpt", "--batch", "2",
                       "--seq", "4096"], 2400, {}),
    ("step_anatomy", [PY, "tools/step_anatomy.py"], 2400, {}),
    ("step_anatomy_fused", [PY, "tools/step_anatomy.py", "--fused-qkv"],
     2400, {}),
    ("step_anatomy_fusedln", [PY, "tools/step_anatomy.py",
                              "--fused-ln"], 2400, {}),
    # single-chip schedule-overhead A/B: ms/tick of FThenB vs
    # interleaved-v2 vs sequential (bounds what pipeline_cost ignores)
    ("pipeline_overhead", [PY, "tools/pipeline_overhead.py"], 2400, {}),
]

# stages addressable via --only but excluded from the default sweep
# (bench_full's workload list already includes gpt-1.3b — running the
# standalone stage too would duplicate up to 2400s on a fragile tunnel)
RETRY_ONLY = {"bench_gpt13b", "bench_gpt13b_scan", "bench_gpt_b16",
              "bench_decode_flashk", "bench_serve_flashk",
              "bench_gpt_fusedqkv",
              "bench_ernie_fusedqkv", "step_anatomy", "step_anatomy_fused",
              "bench_gpt_s4k", "pipeline_overhead", "bench_gpt_fusedln",
              "bench_gpt_fusedboth", "bench_ernie_fusedln", "bench_resnet_serve",
              "bench_resnet_serve_fold", "bench_resnet_b512",
              "bench_gpt13b_scan_cce", "bench_gpt_chunkedce",
              "step_anatomy_fusedln", "bench_gpt_fusedadamw",
              "bench_ernie_mlmgather", "bench_resnet_nhwc_s2d",
              "fusion_audit_nhwc"}


# fleet canary gate (tools/README): after fleet_chaos_smoke, its
# merged fleet metrics.json is diffed against the committed golden
# with regression thresholds on the rates a canary rollout pages on.
# Thresholds are generous (the chaos wave's exact failover count is
# timing-dependent) — the gate exists to catch a failover/shed STORM
# or a placement-latency cliff, not single-event jitter.
FLEET_CANARY_GOLDEN = os.path.join("tools", "golden",
                                   "fleet_chaos_metrics.json")
FLEET_CANARY_FAIL_ON = (
    "fleet_failovers_total>200%",
    "fleet_shed_total>200%",
    "fleet_placement_wait_seconds:p99>400%",
    # router-durability counters (ISSUE 9): a journal-error or
    # recovery STORM beyond the seeded drills' deterministic counts
    # is a durability regression, not jitter
    "fleet_journal_errors_total>200%",
    "fleet_journal_recovered_requests_total>400%",
    # process-supervision counters (ISSUE 10): respawns beyond the
    # seeded drills' deterministic count = a flapping fleet; ANY
    # crash-loop breaker trip beyond the golden's deliberate one is a
    # self-healing regression (>0% = any increase)
    "fleet_respawns_total>200%",
    "fleet_crash_loops_total>0%",
    # anomaly-sentinel counters (ISSUE 11): any sentinel excursion
    # beyond the golden's count is a live regression the offline gate
    # would otherwise only see post-mortem (series skipped until the
    # golden is regenerated with a sentinel-armed chaos suite); a
    # sampled-out-trace storm likewise means the sampling knob is
    # eating observability
    "fleet_anomaly_fired_total>0%",
    "fleet_traces_sampled_out_total>200%",
    # traffic-capture counters (ISSUE 12): ANY capture write error is
    # a loss of the replay corpus, and ANY capture<->trace sampling
    # divergence means archived requests lost their attribution —
    # both ship-stoppers, not jitter. (Series skipped by metrics_diff
    # until the golden is regenerated with a capture-armed chaos
    # suite — same bootstrap as the sentinel counters above.)
    "fleet_capture_errors_total>0%",
    "fleet_capture_trace_missing_total>0%",
    # elastic-autoscaling counter (ISSUE 15): ANY controller flap
    # (opposite-direction decisions inside flap_window_s) beyond the
    # golden is an oscillating policy — the "never flaps" contract
    # made enforceable. (Overload sheds are NOT gated separately:
    # they count into fleet_shed_total, whose storm gate above
    # already covers them, and their exact count is timing-sensitive
    # on a loaded CI box.)
    "fleet_autoscale_flaps_total>0%",
    # prefix-cache counter (ISSUE 16): the chaos suite's prefix drill
    # produces a deterministic hit count — hits falling >50% below
    # the golden means shared prompts stopped matching (fingerprint
    # or admission regression) while everything else still passes
    # token-exactness. (Series skipped by metrics_diff until the
    # golden is regenerated with the prefix drill in the suite.)
    "fleet_prefix_hits_total<50%",
    # speculative-decoding counter (ISSUE 20): the chaos suite's spec
    # drill produces a deterministic accepted-draft count — acceptance
    # falling >50% below the golden means the flagship stopped
    # confirming drafts (proposer or verify regression) while
    # token-exactness still passes (speculation never changes tokens,
    # so only the acceptance counter can reveal a dead proposer).
    "fleet_spec_accepted_total<50%",
    # continuous-profiling counters (ISSUE 22): the profiler gauges
    # its OWN cost — a duty-cycle ratio above the golden's by >100%
    # means the sampler got expensive (a stack-depth or thread-count
    # explosion), and a truncated-sample STORM means the trie bound
    # is eating the profile (both are observability regressions the
    # flamegraph would silently hide). (Series skipped by
    # metrics_diff until the golden is regenerated with a
    # profiler-armed chaos suite — same bootstrap as the sentinel
    # counters above.)
    "profile_overhead_ratio>100%",
    "profile_samples_dropped_total>200%",
    # device-memory ledger gauge (HBM ledger round): the fleet-max
    # unattributed residual growing >200% past the golden means
    # replicas are allocating device memory the segment tree cannot
    # name — the exact drift the ledger exists to catch, surfaced at
    # the fleet rollup before any single replica OOMs. (Series
    # skipped by metrics_diff until the golden is regenerated with a
    # ledger-armed chaos suite — same bootstrap as the sentinel
    # counters above.)
    "fleet_mem_unattributed_bytes>200%",
)

# history gate (ISSUE 11): ONE archive, two instants, both directions
# proven — the clean span must show no fleet_anomaly_* increase, the
# injected-regression span MUST trip the same spec (a gate that never
# fires is not a gate). Uses the stage's marks.json epoch marks.
HISTORY_GATE_FAIL_ON = ("fleet_anomaly_fired_total>0%",)


def run_history_gate(stage_name):
    """Drive tools/metrics_diff.py --history over the stage's
    archive at its clean/regression marks; leave history_verdict.json
    (required by tools/validate_stages.py on _history_gate-marked
    summaries). ok = clean span quiet AND regression span tripped."""
    tele = os.path.join(OUT, "telemetry", stage_name)
    snap = os.path.join(tele, "history_snapshot.json")
    verdict = {"gate": "history", "snapshot": snap,
               "fail_on": list(HISTORY_GATE_FAIL_ON)}
    try:
        with open(os.path.join(tele, "marks.json")) as f:
            marks = json.load(f)

        def gate(t0, t1):
            cmd = [PY, "tools/metrics_diff.py", "--history", snap,
                   "--at", repr(float(t0)), "--vs", repr(float(t1)),
                   "--quiet"]
            for spec in HISTORY_GATE_FAIL_ON:
                cmd += ["--fail-on", spec]
            proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                  text=True, timeout=120)
            lines = [l for l in proc.stdout.strip().splitlines() if l]
            return json.loads(lines[-1]) if lines else {"ok": False}

        clean = gate(marks["t0"], marks["t_clean"])
        regression = gate(marks["t_clean"], marks["t_end"])
        # vacuity guard: the gated series must actually be present in
        # the clean-span diff — a quiet verdict over snapshots that
        # never carried fleet_anomaly_* would prove nothing
        covered = any(k.startswith("fleet_anomaly_fired_total")
                      for k in (clean.get("counters") or {}))
        verdict["clean_span"] = {"ok": clean.get("ok"),
                                 "covered": covered,
                                 "failures": clean.get("failures")}
        verdict["regression_span"] = {
            "ok": regression.get("ok"),
            "failures": regression.get("failures")}
        verdict["ok"] = bool(clean.get("ok")) and covered \
            and not regression.get("ok")
    except Exception as e:  # noqa: BLE001 — the gate must leave a
        #                     verdict either way
        verdict.update(ok=False, error=f"{type(e).__name__}: {e}")
    os.makedirs(tele, exist_ok=True)
    with open(os.path.join(tele, "history_verdict.json"), "w") as f:
        json.dump(verdict, f, indent=1)
    return verdict


def run_fleet_canary_gate(stage_name):
    """Run tools/metrics_diff.py golden-vs-stage and leave the
    verdict file tools/validate_stages.py requires
    (telemetry/<stage>/canary_verdict.json). Returns the verdict."""
    tele = os.path.join(OUT, "telemetry", stage_name)
    candidate = os.path.join(tele, "metrics.json")
    cmd = [PY, "tools/metrics_diff.py", FLEET_CANARY_GOLDEN,
           candidate, "--quiet"]
    for spec in FLEET_CANARY_FAIL_ON:
        cmd += ["--fail-on", spec]
    try:
        proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                              text=True, timeout=120)
        lines = [l for l in proc.stdout.strip().splitlines() if l]
        verdict = json.loads(lines[-1]) if lines \
            else {"ok": False, "error": "metrics_diff emitted nothing"}
    except Exception as e:  # noqa: BLE001 — the gate must leave a
        #                     verdict either way
        verdict = {"ok": False, "error": f"{type(e).__name__}: {e}"}
    verdict["gate"] = "fleet_canary"
    verdict["golden"] = FLEET_CANARY_GOLDEN
    verdict["fail_on"] = list(FLEET_CANARY_FAIL_ON)
    os.makedirs(tele, exist_ok=True)
    with open(os.path.join(tele, "canary_verdict.json"), "w") as f:
        json.dump(verdict, f, indent=1)
    return verdict


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help="comma-separated stage names to run")
    ap.add_argument("--skip", default="",
                    help="comma-separated stage names to skip")
    args = ap.parse_args()
    only = args.only.split(",") if args.only else None
    skip = set(args.skip.split(",")) if args.skip else set()
    scale = float(os.environ.get("CAMPAIGN_TIMEOUT_SCALE", "1"))
    # _captured_at orders archived summaries reliably (file mtimes
    # collapse after a fresh checkout; bench.py's null-run diagnostic
    # sorts on this). Dict-shaped so readers iterating stage entries
    # skip it via the missing "ok" key.
    # _telemetry marks a summary produced by a campaign that exports
    # per-stage telemetry dirs — validate_stages only enforces the
    # metrics.json check on such summaries (a pre-telemetry archive
    # must not read as an observability regression). _flightrec
    # likewise marks that chaos-family stages dump crash flight
    # records into their telemetry dir (round-10 introspection layer)
    # _fleet_canary marks a campaign whose fleet_chaos_smoke stage is
    # gated by the metrics_diff canary diff — validate_stages requires
    # the gate's verdict file on such summaries. _history_gate
    # likewise marks that history_smoke is gated by the two-instant
    # history diff (run_history_gate)
    summary = {"_captured_at": {"epoch": int(time.time())},
               "_telemetry": 1, "_flightrec": 1, "_fleet_canary": 1,
               "_history_gate": 1}
    stages = [s for s in STAGES if s[0] not in RETRY_ONLY]
    if only:  # run in the order the caller listed, not STAGES order
        by_name = {s[0]: s for s in STAGES}
        unknown = [n for n in only if n not in by_name]
        if unknown:
            sys.exit(f"unknown stage(s): {unknown}; "
                     f"known: {sorted(by_name)}")
        stages = [by_name[n] for n in only]
    for name, cmd, timeout, env in stages:
        timeout = max(10, int(timeout * scale))
        if name in skip:
            continue
        if _driver_bench_active():
            print("driver bench owns the chip — campaign yields "
                  "(remaining stages left pending)", flush=True)
            break
        print(f"=== {name} (timeout {timeout}s) ===", flush=True)
        rc, dt, tail = run(cmd, timeout, f"{name}.log", env)
        parsed = last_json(f"{name}.log")
        ok = rc == 0
        summary[name] = {"ok": ok, "rc": rc, "seconds": dt,
                         "ended_at": int(time.time()), "result": parsed}
        if name == "fleet_chaos_smoke" and ok:
            verdict = run_fleet_canary_gate(name)
            gate_ok = bool(verdict.get("ok"))
            summary[name]["canary"] = {
                "ok": gate_ok,
                "failures": verdict.get("failures", []),
                "error": verdict.get("error")}
            if not gate_ok:
                ok = summary[name]["ok"] = False
                print("=== fleet canary gate FAILED: "
                      f"{verdict.get('failures') or verdict.get('error')}"
                      " ===", flush=True)
        if name == "history_smoke" and ok:
            verdict = run_history_gate(name)
            gate_ok = bool(verdict.get("ok"))
            summary[name]["history_gate"] = {
                "ok": gate_ok,
                "clean_span": verdict.get("clean_span"),
                "regression_span": verdict.get("regression_span"),
                "error": verdict.get("error")}
            if not gate_ok:
                ok = summary[name]["ok"] = False
                print("=== history gate FAILED: "
                      f"{json.dumps(verdict)[:300]} ===", flush=True)
        print(f"=== {name}: rc={rc} {dt}s "
              f"{json.dumps(parsed) if parsed else tail[-150:]!r} ===",
              flush=True)
        with open(os.path.join(OUT, "summary.json"), "w") as f:
            json.dump(summary, f, indent=1)
        if not ok and name != "probe":
            rc2, _, _ = run([PY, "bench.py", "--worker", "probe"],
                            max(10, int(600 * scale)), "reprobe.log")
            if rc2 != 0:
                print("backend wedged after failure — stopping campaign "
                      "(earlier artifacts kept)", flush=True)
                break
        if name == "probe" and not ok:
            print("backend unreachable — campaign aborted", flush=True)
            break
    print(json.dumps(summary))
    # nonzero exit when anything failed or was never reached, so a
    # wrapper (tools/tunnel_watch.py) can re-arm instead of reading a
    # half-done campaign as success
    stage_rows = {k: v for k, v in summary.items()
                  if not k.startswith("_")}
    ran_all = all(s["ok"] for s in stage_rows.values()) and \
        len(stage_rows) == len([s for s in stages if s[0] not in skip])
    sys.exit(0 if ran_all else 1)


if __name__ == "__main__":
    main()
