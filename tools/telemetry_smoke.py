"""telemetry_smoke — the campaign's CPU observability drill.

Runs the acceptance shape of docs/observability.md end to end without
burning tunnel window: a 5-step guarded Model.fit (with one injected
NaN step, so the guard counters are provably live) and a 4-request
serve wave, both publishing into the process registry, then asserts
the expected metric names exist, the latency histograms have non-zero
counts, and the RecompileTracer saw 0 unexpected retraces — and writes
telemetry.jsonl + metrics.json exactly like a bench stage.

Output dir: $BENCH_TELEMETRY_DIR (tpu_campaign sets it per stage) or
campaign_out/telemetry/telemetry_smoke. Last stdout line is a JSON
verdict; exit 0 only when every assertion holds.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

EXPECTED_TRAIN = [
    "train_step_seconds", "train_steps_total", "train_loss",
    "train_samples_per_s", "train_skipped_steps_total",
    "train_rollbacks_total",
]
EXPECTED_SERVE = [
    "serve_ttft_seconds", "serve_decode_token_seconds",
    "serve_queue_wait_seconds", "serve_dispatch_seconds",
    "serve_requests_total", "serve_page_occupancy", "serve_free_pages",
    "serve_decode_tokens_total", "serve_deadline_misses_total",
    "serve_evictions_total",
]
EXPECTED_LOADER = ["dataloader_batch_wait_seconds",
                   "dataloader_batches_total"]
# histograms the acceptance criterion requires to hold real samples
NONZERO_HISTS = ["train_step_seconds", "serve_ttft_seconds",
                 "serve_decode_token_seconds"]


def run_guarded_fit(run_dir):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.hapi.callbacks import TelemetryCallback
    from paddle_tpu.resilience import TrainGuard, faults

    paddle.seed(0)
    net = paddle.nn.Linear(8, 4)
    model = paddle.Model(net)
    guard = TrainGuard(snapshot_every=1, rollback_after=3)
    model.prepare(paddle.optimizer.AdamW(1e-2,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), guard=guard)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((20, 8)).astype("float32")
    Y = rng.integers(0, 4, (20,)).astype("int64")
    cb = TelemetryCallback(run_dir=run_dir, write_metrics=False)
    faults.clear()
    faults.inject("nan_grads", step=3)   # one provably-skipped step
    model.fit(paddle.io.TensorDataset([X, Y]), epochs=1, batch_size=4,
              verbose=0, shuffle=False, callbacks=[cb])
    faults.clear()
    return {"skipped": guard.skipped_steps,
            "good_steps": guard.good_steps,
            "jsonl_records": cb.logger.records}


def run_serve_wave(n_requests=4):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine

    from paddle_tpu.observability.metrics import get_registry

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny",
                                           num_attention_heads=1))
    # an engine's registry is private by default; the smoke asserts the
    # whole catalogue in one process-global export, so share it
    eng = ServingEngine(model, max_slots=2, page_size=8, max_seq_len=32,
                        steps_per_dispatch=2, registry=get_registry())
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.config.vocab_size, (6 + i,))
               for i in range(n_requests)]
    out = eng.generate(prompts, max_new_tokens=4)
    h = eng.health()
    return {"requests": len(out),
            "tokens": sum(len(t) for t in out),
            "unexpected_retraces": eng.tracer.unexpected_retraces(),
            "ok": h["status_counts"]["ok"]}


def main():
    t0 = time.perf_counter()
    run_dir = (os.environ.get("BENCH_TELEMETRY_DIR")
               or os.path.join(REPO, "campaign_out", "telemetry",
                               "telemetry_smoke"))
    fit = run_guarded_fit(run_dir)
    serve = run_serve_wave()

    from paddle_tpu.observability.metrics import get_registry
    from paddle_tpu.observability.trace import report_all
    reg = get_registry()
    names = set(reg.names())
    problems = []
    for name in EXPECTED_TRAIN + EXPECTED_SERVE + EXPECTED_LOADER:
        if name not in names:
            problems.append(f"metric missing: {name}")
    for name in NONZERO_HISTS:
        series = [m for m in reg.series() if m.name == name]
        if series and not sum(m.count for m in series):
            problems.append(f"histogram empty: {name}")
    if fit["skipped"] != 1:
        problems.append(f"guard skipped {fit['skipped']} steps, "
                        "expected exactly 1 (injected NaN)")
    if serve["ok"] != serve["requests"]:
        problems.append(f"serve wave finished {serve['ok']}/"
                        f"{serve['requests']} ok")
    rep = report_all()
    if rep["unexpected_retraces"]:
        problems.append(f"{rep['unexpected_retraces']} unexpected "
                        "retraces — a compiled program was rebuilt")

    metrics_path = reg.dump(os.path.join(run_dir, "metrics.json"),
                            extra={"recompile_report": rep})
    verdict = {
        "telemetry_smoke": "ok" if not problems else "FAIL",
        "problems": problems,
        "fit": fit, "serve": serve,
        "metric_names": len(names),
        "unexpected_retraces": rep["unexpected_retraces"],
        "metrics_json": os.path.relpath(metrics_path, REPO),
        "seconds": round(time.perf_counter() - t0, 1),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
