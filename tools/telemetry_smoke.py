"""telemetry_smoke — the campaign's CPU observability drill.

Runs the acceptance shape of docs/observability.md end to end without
burning tunnel window: a 5-step guarded Model.fit (with one injected
NaN step, so the guard counters are provably live), a 4-request serve
wave scraped MID-FLIGHT through the live /metrics endpoint (final
scrape must match the in-process registry byte-for-byte — the
no-torn-histogram contract), and a 3-step NaN rollback storm that must
leave a parseable flight-recorder dump carrying the storm's own step
records. Asserts the expected metric names exist (including the
compiled-cost xla_cost_flops and measured-MFU gauges — the smoke pins
PADDLE_TPU_PEAK_FLOPS so the MFU plumbing runs on CPU), the latency
histograms have non-zero counts, and the RecompileTracer saw 0
unexpected retraces — and writes telemetry.jsonl + metrics.json
exactly like a bench stage.

Output dir: $BENCH_TELEMETRY_DIR (tpu_campaign sets it per stage) or
campaign_out/telemetry/telemetry_smoke. Last stdout line is a JSON
verdict; exit 0 only when every assertion holds.
"""
from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# exercise the MFU plumbing on CPU: without a resolvable peak the MFU
# gauges are (correctly) absent and this drill could not pin them
os.environ.setdefault("PADDLE_TPU_PEAK_FLOPS", "197e12")

EXPECTED_TRAIN = [
    "train_step_seconds", "train_steps_total", "train_loss",
    "train_samples_per_s", "train_skipped_steps_total",
    "train_rollbacks_total",
    # round-10 introspection layer (docs/observability.md): compiled
    # cost analysis + measured MFU against the pinned peak
    "train_peak_flops", "train_mfu_measured", "xla_cost_flops",
]
EXPECTED_SERVE = [
    "serve_ttft_seconds", "serve_decode_token_seconds",
    "serve_queue_wait_seconds", "serve_dispatch_seconds",
    "serve_requests_total", "serve_page_occupancy", "serve_free_pages",
    "serve_decode_tokens_total", "serve_deadline_misses_total",
    "serve_evictions_total",
]
EXPECTED_LOADER = ["dataloader_batch_wait_seconds",
                   "dataloader_batches_total"]
# histograms the acceptance criterion requires to hold real samples
NONZERO_HISTS = ["train_step_seconds", "serve_ttft_seconds",
                 "serve_decode_token_seconds"]


def run_guarded_fit(run_dir):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.hapi.callbacks import TelemetryCallback
    from paddle_tpu.resilience import TrainGuard, faults

    paddle.seed(0)
    net = paddle.nn.Linear(8, 4)
    model = paddle.Model(net)
    guard = TrainGuard(snapshot_every=1, rollback_after=3)
    model.prepare(paddle.optimizer.AdamW(1e-2,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), guard=guard)
    rng = np.random.default_rng(0)
    X = rng.standard_normal((20, 8)).astype("float32")
    Y = rng.integers(0, 4, (20,)).astype("int64")
    cb = TelemetryCallback(run_dir=run_dir, write_metrics=False)
    faults.clear()
    faults.inject("nan_grads", step=3)   # one provably-skipped step
    model.fit(paddle.io.TensorDataset([X, Y]), epochs=1, batch_size=4,
              verbose=0, shuffle=False, callbacks=[cb])
    faults.clear()
    return {"skipped": guard.skipped_steps,
            "good_steps": guard.good_steps,
            "jsonl_records": cb.logger.records}


def run_serve_wave(n_requests=4):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine

    from paddle_tpu.observability.metrics import get_registry

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny",
                                           num_attention_heads=1))
    # an engine's registry is private by default; the smoke asserts the
    # whole catalogue in one process-global export, so share it
    eng = ServingEngine(model, max_slots=2, page_size=8, max_seq_len=32,
                        steps_per_dispatch=2, registry=get_registry())
    exp = eng.serve_metrics(port=0)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, model.config.vocab_size, (6 + i,))
               for i in range(n_requests)]
    for p in prompts:
        eng.submit(p, max_new_tokens=4)
    # drive the wave by hand so the endpoint is scraped WHILE requests
    # are in flight — the live-scrape acceptance, not a post-hoc read
    finished, mid_scrape_ok, rounds = [], False, 0
    while eng._queue or any(s is not None for s in eng._slots):
        finished.extend(eng.step())
        rounds += 1
        if rounds == 1:
            txt = urllib.request.urlopen(exp.url + "/metrics",
                                         timeout=10).read().decode()
            mid_scrape_ok = ("serve_decode_tokens_total" in txt
                             and "serve_ttft_seconds_bucket" in txt)
        if rounds > 1000:
            raise RuntimeError("serve wave did not drain")
    # quiesced: the scraped exposition must equal the in-process
    # registry's own rendering — series-for-series, value-for-value
    final_txt = urllib.request.urlopen(exp.url + "/metrics",
                                       timeout=10).read().decode()
    parity = final_txt == get_registry().to_prometheus()
    health = json.load(urllib.request.urlopen(exp.url + "/healthz",
                                              timeout=10))
    report = json.load(urllib.request.urlopen(exp.url + "/report",
                                              timeout=10))
    exp.close()
    h = eng.health()
    res = {"requests": len(finished),
           "tokens": sum(len(r["tokens"]) for r in finished),
           "unexpected_retraces": eng.tracer.unexpected_retraces(),
           "ok": h["status_counts"]["ok"],
           "scrape_mid_wave": mid_scrape_ok,
           "scrape_parity": parity,
           "healthz_ok": health.get("status") == "ok"
           and "status_counts" in health,
           "report_cost_sites": len(((report.get("cost_report") or {})
                                     .get("sites") or {}))}
    eng.close()
    return res


def run_rollback_storm(run_dir):
    """A 3-consecutive-NaN storm through a guarded fit: rollback MUST
    trip and MUST leave a parseable flight_rollback*.json carrying the
    storm's own guard_step records (the chaos acceptance shape that
    validate_stages also enforces on campaign chaos stages)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.resilience import TrainGuard, faults

    os.environ["PADDLE_TPU_FLIGHT_DIR"] = run_dir
    paddle.seed(1)
    net = paddle.nn.Linear(8, 4)
    model = paddle.Model(net)
    guard = TrainGuard(snapshot_every=1, rollback_after=3)
    model.prepare(paddle.optimizer.AdamW(
        1e-2, parameters=net.parameters()),
        paddle.nn.CrossEntropyLoss(), guard=guard)
    rng = np.random.default_rng(1)
    X = rng.standard_normal((24, 8)).astype("float32")
    Y = rng.integers(0, 4, (24,)).astype("int64")
    faults.clear()
    faults.inject("nan_grads", step=2, count=3)
    model.fit(paddle.io.TensorDataset([X, Y]), epochs=1, batch_size=4,
              verbose=0, shuffle=False)
    faults.clear()
    dumps = sorted(f for f in os.listdir(run_dir)
                   if f.startswith("flight_rollback")
                   and f.endswith(".json"))
    parsed = bad_step_records = 0
    for fn in dumps:
        with open(os.path.join(run_dir, fn)) as fh:
            doc = json.load(fh)
        if isinstance(doc.get("records"), list):
            parsed += 1
            bad_step_records += sum(
                1 for r in doc["records"]
                if r.get("kind") == "guard_step" and not r.get("ok"))
    return {"rollbacks": guard.rollbacks, "dumps": len(dumps),
            "parsed": parsed, "bad_step_records": bad_step_records}


def main():
    t0 = time.perf_counter()
    run_dir = (os.environ.get("BENCH_TELEMETRY_DIR")
               or os.path.join(REPO, "campaign_out", "telemetry",
                               "telemetry_smoke"))
    fit = run_guarded_fit(run_dir)
    serve = run_serve_wave()
    storm = run_rollback_storm(run_dir)

    from paddle_tpu.observability.metrics import get_registry
    from paddle_tpu.observability.trace import report_all
    reg = get_registry()
    names = set(reg.names())
    problems = []
    for name in EXPECTED_TRAIN + EXPECTED_SERVE + EXPECTED_LOADER:
        if name not in names:
            problems.append(f"metric missing: {name}")
    for name in NONZERO_HISTS:
        series = [m for m in reg.series() if m.name == name]
        if series and not sum(m.count for m in series):
            problems.append(f"histogram empty: {name}")
    if fit["skipped"] != 1:
        problems.append(f"guard skipped {fit['skipped']} steps, "
                        "expected exactly 1 (injected NaN)")
    if serve["ok"] != serve["requests"]:
        problems.append(f"serve wave finished {serve['ok']}/"
                        f"{serve['requests']} ok")
    if not serve["scrape_mid_wave"]:
        problems.append("mid-wave /metrics scrape missing serve series")
    if not serve["scrape_parity"]:
        problems.append("/metrics scrape != in-process registry "
                        "exposition (torn or diverged endpoint)")
    if not serve["healthz_ok"]:
        problems.append("/healthz missing engine health snapshot")
    if not serve["report_cost_sites"]:
        problems.append("/report carries no compiled-cost sites")
    if storm["rollbacks"] < 1:
        problems.append("rollback storm did not trip a rollback")
    if not storm["dumps"]:
        problems.append("rollback left no flight_rollback*.json dump")
    if storm["parsed"] != storm["dumps"]:
        problems.append(f"{storm['dumps'] - storm['parsed']} flight "
                        "dump(s) unparseable")
    if storm["bad_step_records"] < 3:
        problems.append("flight dump missing the storm's own "
                        f"guard_step records "
                        f"({storm['bad_step_records']}/3)")
    rep = report_all()
    if rep["unexpected_retraces"]:
        problems.append(f"{rep['unexpected_retraces']} unexpected "
                        "retraces — a compiled program was rebuilt")

    metrics_path = reg.dump(os.path.join(run_dir, "metrics.json"),
                            extra={"recompile_report": rep})
    verdict = {
        "telemetry_smoke": "ok" if not problems else "FAIL",
        "problems": problems,
        "fit": fit, "serve": serve, "flight": storm,
        "metric_names": len(names),
        "unexpected_retraces": rep["unexpected_retraces"],
        "metrics_json": os.path.relpath(metrics_path, REPO),
        "seconds": round(time.perf_counter() - t0, 1),
    }
    print(json.dumps(verdict), flush=True)
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
