"""fleet_replay — deterministic open-loop replay of captured fleet
traffic: the scoring harness for every autotune/autoscale what-if.

The capture half (``observability/trafficrec.py``, armed via
``FleetRouter(capture=dir)``) archives every admitted request with its
arrival offset, prompt, tenant/priority/deadline and — at resolve —
its output tokens and per-hop latency attribution. This tool re-drives
a fresh fleet from such an archive (or from a seeded synthetic wave)
and emits a ``replay_verdict.json`` scoring the replay against the
original:

- **open-loop arrivals**: requests are submitted at their recorded
  offsets regardless of completions (the load generator never
  back-pressures itself — queueing behaviour is part of what is
  being measured). ``--mode scaled --time-scale 0.5`` compresses the
  schedule 2x; ``--mode rate --rate 50`` re-spaces arrivals uniformly
  at 50 req/s — the "what if this traffic came faster" drills;
- **what-if knob overrides** (``--knob k=v``, repeatable): router
  knobs (``hedge_after_ms``, ``max_queue``, ``replica_queue_limit``,
  ``placement.<weight>``, the overload/brownout controller's
  ``overload_target_ms``/``brownout_*``) and engine knobs
  (``steps_per_dispatch``, ``page_size`` — the prefill-bucket-ladder
  granularity — ``max_slots``, ``max_seq_len``, ``temperature``,
  ``top_k``, ``seed``, ``prefix_cache``, ``min_prefix_pages``) —
  score a knob setting against recorded traffic without touching
  production. ``placement.prefix_affinity`` scores prefix-affinity
  routing offline; the verdict's ``prefix_stats`` section reports
  what the replay fleet's caches did (hit rate, pages shared, TTFT
  ratios), and ``--report-prefix-stats`` scans the archive's
  recorded prompts WITHOUT replaying — the expected page-level hit
  rate per page-size/min-prefix knob, the measure-before-build
  number. ``autoscale.<param>`` knobs
  (``autoscale.max_replicas=3 autoscale.scale_out_cooldown_s=0.5``
  ...) additionally arm a FleetAutoscaler over the replay fleet, so
  an autoscaling POLICY is scorable offline against a recorded
  archive — the verdict grows an ``autoscale`` section (decision
  events, flap count, final fleet size) and spawned replicas join
  the zero-new-traces math with their adoption-time frozen counts;
- **golden mode** (``--golden``): asserts token-exact outputs per
  original rid (valid when seeds/params match — greedy decoding and
  the same weights make replay bit-deterministic) and ZERO new XLA
  traces across the replay (every wave bucket is pre-warmed, compile
  counts frozen after warmup);
- **the verdict**: side-by-side SLO quantiles (TTFT/e2e p50/p99 from
  the per-request records, cross-checked against the replay fleet's
  live history plane), per-hop attribution shares (original vs
  replay, deltas), and gates — ``hop_share_delta`` (default 5%),
  ``e2e_p99_ratio``/``ttft_p99_ratio`` (replay vs original) — whose
  failures flip ``ok`` to false. The replay fleet captures its own
  archive, so original and replay are compared in the same format.

Usage:

  python tools/fleet_replay.py --archive campaign_out/capture \
      --golden --out replay_verdict.json
  python tools/fleet_replay.py --archive ... --knob hedge_after_ms=50 \
      --knob placement.queued=16
  python tools/fleet_replay.py --synth 20 --synth-seed 7 \
      --write-wave wave.json           # seeded synthetic wave drill

Importable: tools/replay_smoke.py and tests drive ``synth_wave`` /
``build_fleet`` / ``replay`` / ``make_verdict`` directly.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

DEFAULT_GATES = {
    # per-hop attribution share delta (fraction of total e2e a hop
    # explains, original vs replay) — the ISSUE-12 5% clean-wave bar
    "hop_share_delta": 0.05,
    # replay-vs-original latency regression ratios (a replay that is
    # FASTER never trips; slower than these multiples does)
    "e2e_p99_ratio": 1.5,
    "ttft_p99_ratio": 1.5,
    # absolute slack under the ratio gates: a ratio trips only when
    # the replay is ALSO slower by at least this much — a 1.5x on a
    # 7 ms p99 is scheduler noise, a 1.5x on 200 ms is a regression
    "latency_floor_s": 0.05,
}

ROUTER_KNOBS = {"hedge_after_ms", "max_queue", "replica_queue_limit",
                "wedge_timeout_s", "overload_target_ms",
                "overload_interval_s", "brownout_max_new",
                "brownout_levels", "brownout_step_s"}
ENGINE_KNOBS = {"steps_per_dispatch", "page_size", "max_slots",
                "max_seq_len", "temperature", "top_k", "seed",
                "num_pages", "prefix_cache", "min_prefix_pages"}
# --knob autoscale.<param>: arms a FleetAutoscaler over the replay
# fleet (spawn_fn builds extra warmed replicas up to max_replicas) so
# an autoscale POLICY is scorable against a recorded archive — the
# verdict grows an "autoscale" section (events, flaps, final size)
AUTOSCALE_KNOBS = {"min_replicas", "max_replicas",
                   "scale_out_cooldown_s", "scale_in_cooldown_s",
                   "recovery_hold_s", "budget_floor", "scale_in_util",
                   "boot_timeout_s", "retire_timeout_s",
                   "flap_window_s"}


# -- wave sources ----------------------------------------------------------


def synth_wave(seed, n, *, burst=4, burst_gap_s=0.05,
               prompt_lens=((4, 21, 3.0), (22, 40, 1.0)),
               tenants=("tenant-0", "tenant-1", "tenant-2"),
               priorities=(0, 0, 0, 1), max_new=8, eos=None,
               vocab=256):
    """Seeded synthetic traffic wave in the archive-entry shape.

    Bursty arrivals (``burst`` requests per pulse, pulses
    ``burst_gap_s`` apart), a weighted prompt-length mixture
    (``(lo, hi, weight)`` ranges), and tenant/priority blends — the
    scale-drill generator for fleets with no recorded traffic yet.
    Pure stdlib ``random.Random(seed)``: the same seed replays the
    same wave bit-identically on any box."""
    rng = random.Random(int(seed))
    ranges = [(int(lo), int(hi), float(w))
              for lo, hi, w in prompt_lens]
    total_w = sum(w for _, _, w in ranges) or 1.0
    entries = []
    for i in range(int(n)):
        r = rng.random() * total_w
        lo, hi = ranges[-1][:2]
        for rlo, rhi, w in ranges:
            if r < w:
                lo, hi = rlo, rhi
                break
            r -= w
        plen = rng.randint(lo, max(hi, lo))
        entries.append({
            "rid": i,
            "arrival_s": round((i // int(burst)) * float(burst_gap_s),
                               6),
            "tenant": rng.choice(list(tenants)) if tenants else None,
            "priority": int(rng.choice(list(priorities))),
            "deadline_ms": None,
            "prompt": [rng.randrange(int(vocab)) for _ in range(plen)],
            "max_new": int(max_new), "eos": eos,
            "status": None, "tokens": None, "ttft_s": None,
            "e2e_s": None, "hops": None, "failovers": 0,
            "hedged": False, "replica": None})
    return entries


def load_wave(path):
    """Entries from a capture-archive DIRECTORY (trafficrec) or a
    committed wave FILE (replay_wave.json: {"entries": [...]}) —
    returns (entries, meta, stats)."""
    if os.path.isdir(path):
        from paddle_tpu.observability.trafficrec import load_archive
        return load_archive(path)
    with open(path) as f:
        doc = json.load(f)
    return (doc.get("entries") or [], doc.get("meta") or {},
            {"segments": 0, "records": len(doc.get("entries") or []),
             "torn_drops": 0, "unresolved": 0})


# -- prefix-cache what-if scan ---------------------------------------------


def prefix_stats(entries, *, page_sizes=(8, 16, 32), min_pages=1):
    """Expected page-level prefix-cache hit rate of a recorded wave,
    per page-size knob — the measure-BEFORE-build number (r19).

    Replays the archive's prompts in arrival order against an ideal
    single-replica index: a request's leading pages hit when an
    earlier request already published the same fingerprint chain.
    This is the upper bound a real fleet approaches as affinity
    routing concentrates each fingerprint on one replica; no engine
    (or jax) is involved — pure host-side hashing."""
    from paddle_tpu.nlp.paged_cache import prefix_fingerprints
    order = sorted(range(len(entries)),
                   key=lambda i: (float(entries[i].get("arrival_s")
                                        or 0.0), i))
    mp = max(int(min_pages), 1)
    out = {}
    for ps in page_sizes:
        seen = set()
        pages = hit_pages = reqs = reqs_shareable = reqs_hit = 0
        for i in order:
            fps = prefix_fingerprints(
                entries[i].get("prompt") or [], int(ps))
            reqs += 1
            pages += len(fps)
            if len(fps) >= mp:
                reqs_shareable += 1
            matched = 0
            for fp in fps:
                if fp not in seen:
                    break
                matched += 1
            if matched >= mp:
                hit_pages += matched
                reqs_hit += 1
            seen.update(fps)
        out[str(int(ps))] = {
            "page_size": int(ps), "min_prefix_pages": mp,
            "requests": reqs, "shareable_requests": reqs_shareable,
            "expected_hit_requests": reqs_hit,
            "shareable_pages": pages,
            "expected_hit_pages": hit_pages,
            "expected_page_hit_rate": None if not pages
            else round(hit_pages / pages, 4)}
    return out


# -- speculative-decoding what-if scan -------------------------------------


def spec_stats(entries, *, k_values=(2, 4, 8), nmin=1, nmax=3):
    """Expected speculative-decoding acceptance of a recorded wave,
    per ``spec.k`` knob — the measure-BEFORE-build number (r20).

    Replays each archived request's RECORDED token stream through the
    ngram proposer's exact matching rule (prompt-lookup over prompt +
    generated-so-far): at every speculative round the proposer drafts
    K tokens and the recorded stream itself adjudicates how many land
    — the target model never runs, so this is pure host work, and
    because accepted tokens are bit-identical to plain decode the
    recorded stream IS what verify would have sampled. Reports per-K
    acceptance rate and expected committed tokens per verify dispatch
    (>= 1 + acceptance * K intuition, measured exactly)."""
    from paddle_tpu.nlp.speculative import _ngram_propose
    out = {}
    for k in k_values:
        k = int(k)
        rounds = proposed = accepted = committed = streams = 0
        for e in entries:
            toks = [int(t) for t in (e.get("tokens") or [])]
            if len(toks) < 2:
                continue
            streams += 1
            ctx = [int(t) for t in (e.get("prompt") or [])] + toks[:1]
            i = 1                     # first token rides prefill
            while i < len(toks):
                drafts = _ngram_propose(ctx, k, -1, nmin, nmax)
                rounds += 1
                proposed += k
                com = 0
                for j in range(k + 1):
                    t = toks[i]
                    ctx.append(t)
                    com += 1
                    i += 1
                    hit = j < k and drafts[j] == t
                    if hit:
                        accepted += 1
                    if i >= len(toks) or not hit:
                        break
                committed += com
        out[str(k)] = {
            "k": k, "streams": streams, "rounds": rounds,
            "proposed": proposed, "accepted": accepted,
            "acceptance_rate": None if not proposed
            else round(accepted / proposed, 4),
            "tokens_per_dispatch": None if not rounds
            else round(committed / rounds, 4)}
    return out


# -- fleet construction ----------------------------------------------------


def parse_knobs(pairs):
    """--knob k=v pairs -> (router_kw, engine_kw, placement_weights,
    autoscale_kw). Unknown knobs fail loudly — a typo'd what-if is
    not a what-if. Any ``autoscale.<param>`` knob arms an autoscaler
    over the replay fleet (autoscale_kw is None when absent)."""
    router_kw, engine_kw, weights = {}, {}, {}
    autoscale_kw = None
    for pair in pairs or ():
        if "=" not in pair:
            raise ValueError(f"--knob {pair!r}: expected k=v")
        k, v = pair.split("=", 1)
        k = k.strip()
        try:
            val = json.loads(v)
        except json.JSONDecodeError:
            val = v
        if k.startswith("placement."):
            weights[k[len("placement."):]] = float(val)
        elif k.startswith("autoscale."):
            param = k[len("autoscale."):]
            if param not in AUTOSCALE_KNOBS:
                raise ValueError(
                    f"unknown knob {k!r}; autoscale params: "
                    f"{sorted(AUTOSCALE_KNOBS)}")
            if autoscale_kw is None:
                autoscale_kw = {}
            autoscale_kw[param] = val
        elif k.startswith("spec."):
            # speculative-decoding knobs: spec.k / spec.draft imply
            # arming (a what-if on K with speculation off would be
            # vacuous); spec.decode=false is the explicit OFF lever
            param = k[len("spec."):]
            if param == "k":
                engine_kw["spec_k"] = int(val)
                engine_kw.setdefault("spec_decode", True)
            elif param == "draft":
                engine_kw["spec_draft"] = str(val)
                engine_kw.setdefault("spec_decode", True)
            elif param == "decode":
                engine_kw["spec_decode"] = bool(val)
            else:
                raise ValueError(
                    f"unknown knob {k!r}; spec params: k, draft, "
                    "decode")
        elif k in ROUTER_KNOBS:
            router_kw[k] = val
        elif k in ENGINE_KNOBS:
            engine_kw[k] = val
        else:
            raise ValueError(
                f"unknown knob {k!r}; router: {sorted(ROUTER_KNOBS)}, "
                f"engine: {sorted(ENGINE_KNOBS)}, plus placement.<w> "
                "and autoscale.<param>")
    return router_kw, engine_kw, weights, autoscale_kw


def build_fleet(entries, *, model="gpt-tiny", replicas=2,
                model_seed=0, engine_kw=None, router_kw=None,
                placement_weights=None, capture_dir=None, warm=True,
                autoscale_kw=None):
    """A fresh in-process fleet sized for a replay: engines warmed on
    every prefill bucket the wave can land in (plus the decode scan),
    compile counts frozen AFTER the warmup. Returns
    (router, engines, frozen_counts).

    autoscale_kw (a dict, possibly empty) arms a FleetAutoscaler over
    the fleet: ``spawn_fn`` builds additional warmed replicas named
    ``as<N>`` (appended to ``engines`` so callers can close them),
    the autoscaler attaches as ``router.autoscaler`` and ``replay``
    drives its ``poll()`` — the what-if path for scoring an
    autoscale policy against recorded traffic."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine
    from paddle_tpu.serving_fleet import FleetAutoscaler, \
        FleetRouter, InprocReplica

    paddle.seed(int(model_seed))
    mdl = GPTForCausalLM(_resolve_config(model))
    mdl.eval()
    ekw = dict(max_slots=2, page_size=16, max_seq_len=64,
               steps_per_dispatch=4)
    ekw.update(engine_kw or {})
    engines = []
    warm_lens = sorted({len(e["prompt"]) for e in entries}) if warm \
        else []

    def _engine():
        eng = ServingEngine(mdl, **ekw)
        if warm_lens:
            eng.warmup(buckets=warm_lens, decode=True)
        engines.append(eng)
        return eng

    for _ in range(int(replicas)):
        _engine()
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    rkw = dict(history=True, history_interval_s=0.05)
    rkw.update(router_kw or {})
    if placement_weights:
        rkw["placement_weights"] = placement_weights
    if capture_dir is not None:
        rkw["capture"] = capture_dir
    router = FleetRouter(reps, **rkw)
    if autoscale_kw is not None:
        # pre-build + warm the spare engines NOW, before the replay
        # clock starts: spawn_fn inside asc.poll() runs on the
        # control thread, and paying multi-second XLA warmups there
        # mid-burst would freeze router.step() and charge the scored
        # policy for the harness's own spawn stall. The pool is sized
        # from the policy's max_replicas when given (else one spare);
        # an exhausted pool falls back to a lazy build.
        mr = autoscale_kw.get("max_replicas")
        pool_n = max(int(mr) - int(replicas), 0) if mr is not None \
            else 1
        pool = [_engine() for _ in range(pool_n)]
        FleetAutoscaler(router, lambda i: InprocReplica(
            f"as{i}", pool.pop(0) if pool else _engine()),
            **autoscale_kw)
    return router, engines, frozen


# -- replay loop -----------------------------------------------------------


def schedule(entries, mode="recorded", time_scale=1.0, rate=None):
    """Per-entry submit offsets (seconds from replay start)."""
    if mode == "rate":
        if not rate or rate <= 0:
            raise ValueError("--mode rate needs --rate > 0")
        return [i / float(rate) for i in range(len(entries))]
    scale = float(time_scale) if mode == "scaled" else 1.0
    return [float(e.get("arrival_s") or 0.0) * scale for e in entries]


def replay(router, entries, *, mode="recorded", time_scale=1.0,
           rate=None, timeout_s=120.0, keep_deadlines=True):
    """Open-loop re-drive: submit each entry at its scheduled offset
    (never waiting for earlier completions), stepping the router
    throughout. Returns (results_by_orig_rid, wall_s, rid_map) where
    rid_map maps the replay router's rids back to the ORIGINAL
    entries' rids — an archive's rids are whatever the capturing
    router minted (non-zero-based after prior traffic, gappy after
    ring rotation or capture sampling), so nothing downstream may
    assume they line up with a fresh router's 0..n-1."""
    offs = schedule(entries, mode=mode, time_scale=time_scale,
                    rate=rate)
    order = sorted(range(len(entries)), key=lambda i: (offs[i], i))
    rid_map = {}
    results = {}
    # boot gate: the clock starts against a BOOTED fleet (every
    # replica heartbeating) — otherwise the first pulse's placement
    # wait measures fleet boot, not placement, and the original-vs-
    # replay hop shares diverge on a transient neither run owns
    t_boot = time.monotonic() + min(float(timeout_s), 10.0)
    while not router.booted and time.monotonic() < t_boot:
        router.step()
        time.sleep(0.001)
    t0 = time.monotonic()
    t_end = t0 + float(timeout_s)
    nxt = 0
    autoscaler = getattr(router, "autoscaler", None)
    while True:
        now = time.monotonic() - t0
        while nxt < len(order) and offs[order[nxt]] <= now:
            e = entries[order[nxt]]
            rid = router.submit(
                e["prompt"], e["max_new"], e.get("eos"),
                priority=int(e.get("priority") or 0),
                deadline_ms=e.get("deadline_ms")
                if keep_deadlines else None,
                tenant=e.get("tenant"))
            rid_map[rid] = e["rid"]
            nxt += 1
        router.step()
        if autoscaler is not None:
            autoscaler.poll()
        for r in router.results():
            results[rid_map.get(r["id"], r["id"])] = r
        if nxt >= len(order) and len(results) >= len(entries):
            break
        if time.monotonic() > t_end:
            raise RuntimeError(
                f"replay did not drain within {timeout_s}s "
                f"({len(results)}/{len(entries)} resolved)")
        time.sleep(0.001)
    return results, time.monotonic() - t0, rid_map


# -- verdict ---------------------------------------------------------------


def _quantile(values, q):
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    if len(vals) == 1:
        return vals[0]
    pos = q * (len(vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (pos - lo)


def latency_summary(entries):
    """TTFT/e2e p50/p99 (+ counts) from per-request records."""
    e2e = [e.get("e2e_s") for e in entries
           if e.get("status") == "ok"]
    ttft = [e.get("ttft_s") for e in entries
            if e.get("status") == "ok"]
    return {"requests": len(entries),
            "ok": sum(1 for e in entries if e.get("status") == "ok"),
            "e2e_p50_s": _quantile(e2e, 0.50),
            "e2e_p99_s": _quantile(e2e, 0.99),
            "ttft_p50_s": _quantile(ttft, 0.50),
            "ttft_p99_s": _quantile(ttft, 0.99)}


def hop_shares(entries):
    """Fraction of total ok-request e2e each hop name explains —
    the aggregate form of the r12 per-request attribution (shares,
    not absolute seconds, so two runs of different overall speed
    still compare hop-for-hop)."""
    total = 0.0
    sums = {}
    for e in entries:
        if e.get("status") != "ok" or not e.get("hops") \
                or e.get("e2e_s") is None:
            continue
        total += float(e["e2e_s"])
        for h in e["hops"]:
            if h.get("dur_s") is not None:
                sums[h["name"]] = sums.get(h["name"], 0.0) \
                    + float(h["dur_s"])
    if total <= 0:
        return {}
    return {name: s / total for name, s in sums.items()}


def history_quantiles(router, window_s=3600.0):
    """The replay fleet's live history plane read back (cross-check
    against the per-request summary: the history numbers are what a
    production scrape would have seen)."""
    h = getattr(router, "history", None)
    if h is None:
        return None
    return {
        "ttft_p99_s": h.quantile_over_time(
            "fleet_ttft_seconds", 0.99, window_s),
        "e2e_p99_s": h.quantile_over_time(
            "fleet_e2e_seconds", 0.99, window_s),
        "placement_wait_p99_s": h.quantile_over_time(
            "fleet_placement_wait_seconds", 0.99, window_s)}


def make_verdict(orig_entries, replay_entries, *, golden=False,
                 golden_facts=None, gates=None, mode="recorded",
                 knobs=None, history=None):
    """Score a replay against its original. Returns the verdict dict
    (``ok`` = every enabled gate and golden assertion held; failures
    are listed, vacuity-guarded — a gate that compared nothing is a
    failure, not a pass)."""
    gates = dict(DEFAULT_GATES, **(gates or {}))
    failures = []
    by_rid = {e["rid"]: e for e in replay_entries}

    # -- golden: token-exact per rid + frozen compiles ---------------------
    gsec = None
    if golden:
        compared, mismatched = 0, []
        for e in orig_entries:
            if e.get("status") != "ok" or e.get("tokens") is None:
                continue
            r = by_rid.get(e["rid"])
            if r is None or r.get("tokens") is None:
                mismatched.append(e["rid"])
                continue
            compared += 1
            if list(r["tokens"]) != list(e["tokens"]):
                mismatched.append(e["rid"])
        facts = golden_facts or {}
        gsec = {"enabled": True, "compared": compared,
                "mismatched_rids": mismatched[:32],
                "token_exact": compared > 0 and not mismatched,
                "compile_frozen": facts.get("compile_frozen"),
                "unexpected_retraces": facts.get(
                    "unexpected_retraces"),
                "new_traces": facts.get("new_traces")}
        if compared == 0:
            failures.append({"gate": "golden",
                             "reason": "nothing compared (vacuous)"})
        elif mismatched:
            failures.append({"gate": "golden",
                             "reason": f"{len(mismatched)} rid(s) not "
                                       "token-exact",
                             "rids": mismatched[:32]})
        if facts.get("compile_frozen") is False \
                or (facts.get("new_traces") or 0) > 0 \
                or (facts.get("unexpected_retraces") or 0) > 0:
            failures.append({"gate": "golden",
                             "reason": "replay traced new programs",
                             "new_traces": facts.get("new_traces"),
                             "unexpected_retraces": facts.get(
                                 "unexpected_retraces")})

    # -- SLO quantiles side by side ----------------------------------------
    orig_lat = latency_summary(orig_entries)
    rep_lat = latency_summary(replay_entries)
    ratios = {}
    for stat in ("e2e_p50_s", "e2e_p99_s", "ttft_p50_s",
                 "ttft_p99_s"):
        a, b = orig_lat.get(stat), rep_lat.get(stat)
        ratios[stat] = None if not a or b is None else round(b / a, 4)
    floor = float(gates.get("latency_floor_s") or 0.0)
    for gate_name, stat in (("e2e_p99_ratio", "e2e_p99_s"),
                            ("ttft_p99_ratio", "ttft_p99_s")):
        lim = gates.get(gate_name)
        r = ratios.get(stat)
        if lim is None:
            continue
        if r is None:
            if orig_lat.get(stat) is not None:
                failures.append({"gate": gate_name,
                                 "reason": "replay produced no "
                                           f"{stat} (vacuous)"})
        elif r > float(lim) and (rep_lat[stat] - orig_lat[stat]
                                 > floor):
            failures.append({"gate": gate_name, "ratio": r,
                             "limit": float(lim),
                             "floor_s": floor,
                             "original": orig_lat.get(stat),
                             "replay": rep_lat.get(stat)})

    # -- per-hop attribution deltas ----------------------------------------
    orig_sh = hop_shares(orig_entries)
    rep_sh = hop_shares(replay_entries)
    hop_rows = {}
    max_delta = 0.0
    for name in sorted(set(orig_sh) | set(rep_sh)):
        a = orig_sh.get(name, 0.0)
        b = rep_sh.get(name, 0.0)
        d = abs(b - a)
        max_delta = max(max_delta, d)
        hop_rows[name] = {"orig_share": round(a, 4),
                          "replay_share": round(b, 4),
                          "delta": round(d, 4)}
    lim = gates.get("hop_share_delta")
    if lim is not None and orig_sh:
        if not hop_rows:
            failures.append({"gate": "hop_share_delta",
                             "reason": "no hops compared (vacuous)"})
        elif max_delta > float(lim):
            worst = max(hop_rows, key=lambda n: hop_rows[n]["delta"])
            failures.append({"gate": "hop_share_delta",
                             "max_delta": round(max_delta, 4),
                             "limit": float(lim), "worst_hop": worst})

    return {"ok": not failures, "mode": mode,
            "knobs": dict(knobs or {}),
            "requests": {"original": len(orig_entries),
                         "replay": len(replay_entries)},
            "golden": gsec,
            "slo": {"original": orig_lat, "replay": rep_lat,
                    "ratios": ratios},
            "history": history,
            "attribution": {"hops": hop_rows,
                            "max_share_delta": round(max_delta, 4)},
            "gates": gates, "failures": failures}


# -- one-shot driver (CLI + replay_smoke's engine) -------------------------


def run_replay(entries, *, out_dir, mode="recorded", time_scale=1.0,
               rate=None, golden=False, gates=None, knob_pairs=None,
               replicas=2, model="gpt-tiny", model_seed=0,
               timeout_s=120.0, faults_arm=None):
    """Build a capture-armed fleet, re-drive ``entries``, and return
    (verdict, replay_entries). ``faults_arm`` is an optional callable
    run after warmup (the injected-regression drill's seam)."""
    from paddle_tpu.observability.trafficrec import load_archive
    from paddle_tpu.observability.trace import report_all

    router_kw, engine_kw, weights, autoscale_kw = \
        parse_knobs(knob_pairs)
    cap_dir = os.path.join(out_dir, "replay_archive")
    router, engines, frozen = build_fleet(
        entries, model=model, replicas=replicas,
        model_seed=model_seed, engine_kw=engine_kw,
        router_kw=router_kw, placement_weights=weights,
        capture_dir=cap_dir, autoscale_kw=autoscale_kw)
    autoscale_facts = None
    try:
        if faults_arm is not None:
            faults_arm()
        _results, wall_s, rid_map = replay(
            router, entries, mode=mode, time_scale=time_scale,
            rate=rate, timeout_s=timeout_s)
        hist = history_quantiles(router)
        asc = getattr(router, "autoscaler", None)
        base_n = len(frozen)
        compare = list(engines[:base_n])
        if asc is not None:
            # spawned replicas joined with their compile counts
            # frozen at adoption — fold them into the zero-new-traces
            # math (engines spawned but never adopted have no frozen
            # baseline and stay out of the comparison)
            spawn_frozen = {id(rep.engine): fz
                            for rep, fz in asc.spawned
                            if fz is not None
                            and hasattr(rep, "engine")}
            for e in engines[base_n:]:
                fz = spawn_frozen.get(id(e))
                if fz is not None:
                    compare.append(e)
                    frozen = frozen + [fz]
            autoscale_facts = {
                "events": asc.health()["decisions"],
                "flaps": int(router.registry.get(
                    "fleet_autoscale_flaps_total").value),
                "replicas_final": len(router.replicas),
                "state": asc.state}
        counts = [e.compile_counts() for e in compare]
        new_traces = sum(
            sum(c.values()) for c in counts) - sum(
            sum(c.values()) for c in frozen)
        golden_facts = {
            "compile_frozen": counts == frozen,
            "new_traces": new_traces,
            "unexpected_retraces":
                router.compile_report()["unexpected_retraces"]}
        # live prefix-cache facts, harvested before teardown: what
        # the replay fleet's caches actually did with this traffic
        # (vs prefix_stats' ideal scan) — the verdict's prefix_stats
        # section folds in the TTFT ratios so one JSON answers "did
        # the knob pay?"
        prefix_live = {"engines": 0, "hits": 0, "misses": 0,
                       "hit_pages": 0, "total_pages": 0,
                       "shared_pages": 0, "cow_copies": 0,
                       "evictions": 0}
        for e in engines:
            pc = e.health().get("prefix_cache")
            if not pc:
                continue
            prefix_live["engines"] += 1
            for k in ("hits", "misses", "hit_pages", "total_pages",
                      "shared_pages", "cow_copies", "evictions"):
                prefix_live[k] += int(pc.get(k) or 0)
        prefix_live["page_hit_rate"] = None \
            if not prefix_live["total_pages"] else round(
                prefix_live["hit_pages"]
                / prefix_live["total_pages"], 4)
        # live speculative-decoding facts (engines armed via --knob
        # spec.*): what the draft/verify loop actually accepted on
        # this traffic, vs spec_stats' offline scan
        spec_live = {"engines": 0, "proposed": 0, "accepted": 0,
                     "dispatches": 0}
        for e in engines:
            sp = e.health().get("spec")
            if not sp:
                continue
            spec_live["engines"] += 1
            for k in ("proposed", "accepted", "dispatches"):
                spec_live[k] += int(sp.get(k) or 0)
        spec_live["acceptance_rate"] = None \
            if not spec_live["proposed"] else round(
                spec_live["accepted"] / spec_live["proposed"], 4)
    finally:
        router.close()
        for e in engines:
            e.close()
    replay_entries, _meta, _stats = load_archive(cap_dir)
    # the replay fleet's archive carries ITS router's fresh rids —
    # translate back to the original rids before scoring, or golden
    # token-exactness would only ever match 0-based contiguous
    # archives (the rid_map is the ground truth, not arithmetic)
    for e in replay_entries:
        e["rid"] = rid_map.get(e["rid"], e["rid"])
    verdict = make_verdict(
        entries, replay_entries, golden=golden,
        golden_facts=golden_facts, gates=gates, mode=mode,
        knobs={"pairs": list(knob_pairs or ()),
               "replicas": replicas}, history=hist)
    verdict["wall_s"] = round(wall_s, 3)
    verdict["autoscale"] = autoscale_facts
    verdict["prefix_stats"] = None if not prefix_live["engines"] \
        else dict(prefix_live,
                  ttft_p50_ratio=verdict["slo"]["ratios"]
                  .get("ttft_p50_s"),
                  ttft_p99_ratio=verdict["slo"]["ratios"]
                  .get("ttft_p99_s"))
    verdict["spec_stats"] = None if not spec_live["engines"] \
        else dict(spec_live,
                  e2e_p50_ratio=verdict["slo"]["ratios"]
                  .get("e2e_p50_s"),
                  e2e_p99_ratio=verdict["slo"]["ratios"]
                  .get("e2e_p99_s"))
    report_all()  # keep the tracer rollup warm for post-hoc reads
    return verdict, replay_entries


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="open-loop replay of captured fleet traffic")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--archive", metavar="DIR_OR_JSON",
                     help="capture archive dir (trafficrec) or a "
                          "committed wave json")
    src.add_argument("--synth", type=int, metavar="N",
                     help="generate a seeded synthetic wave of N "
                          "requests instead")
    ap.add_argument("--synth-seed", type=int, default=0)
    ap.add_argument("--synth-burst", type=int, default=4)
    ap.add_argument("--synth-gap", type=float, default=0.05,
                    help="seconds between synthetic bursts")
    ap.add_argument("--write-wave", metavar="PATH",
                    help="save the (synthetic) wave as a committed "
                         "wave json and exit")
    ap.add_argument("--mode", choices=("recorded", "scaled", "rate"),
                    default="recorded")
    ap.add_argument("--time-scale", type=float, default=1.0)
    ap.add_argument("--rate", type=float, default=None,
                    help="req/s for --mode rate")
    ap.add_argument("--knob", action="append", default=[],
                    metavar="K=V", help="what-if override (repeat)")
    ap.add_argument("--golden", action="store_true",
                    help="assert token-exact + zero new traces")
    ap.add_argument("--report-prefix-stats", action="store_true",
                    help="scan the wave's recorded prompts and "
                         "report expected page-level prefix-cache "
                         "hit rates (no replay; honors --knob "
                         "page_size/min_prefix_pages, else sweeps "
                         "page sizes 8/16/32)")
    ap.add_argument("--report-spec-stats", action="store_true",
                    help="replay the wave's recorded token streams "
                         "through the ngram proposer and report "
                         "expected speculative acceptance rate / "
                         "tokens-per-dispatch (no replay; honors "
                         "--knob spec.k, else sweeps K 2/4/8)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--model", default="gpt-tiny")
    ap.add_argument("--model-seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--out", default=None,
                    help="verdict path (default "
                         "<outdir>/replay_verdict.json)")
    args = ap.parse_args(argv)

    if args.synth is not None:
        entries = synth_wave(args.synth_seed, args.synth,
                             burst=args.synth_burst,
                             burst_gap_s=args.synth_gap)
        meta = {"synth_seed": args.synth_seed}
    else:
        entries, meta, stats = load_wave(args.archive)
        if not entries:
            print(json.dumps({"ok": False,
                              "error": f"no entries in "
                                       f"{args.archive}",
                              "stats": stats}))
            return 1
    if args.write_wave:
        with open(args.write_wave, "w") as f:
            json.dump({"format": 1, "meta": meta,
                       "entries": entries}, f, indent=1)
        print(json.dumps({"ok": True, "wrote_wave": args.write_wave,
                          "entries": len(entries)}))
        return 0
    if args.report_prefix_stats:
        _rkw, ekw, _w, _a = parse_knobs(args.knob)
        pss = [int(ekw["page_size"])] if "page_size" in ekw \
            else [8, 16, 32]
        mp = int(ekw.get("min_prefix_pages") or 1)
        print(json.dumps({
            "ok": True, "entries": len(entries),
            "prefix_stats": prefix_stats(entries, page_sizes=pss,
                                         min_pages=mp)}))
        return 0
    if args.report_spec_stats:
        _rkw, ekw, _w, _a = parse_knobs(args.knob)
        ks = [int(ekw["spec_k"])] if "spec_k" in ekw else [2, 4, 8]
        print(json.dumps({
            "ok": True, "entries": len(entries),
            "spec_stats": spec_stats(entries, k_values=ks)}))
        return 0

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "fleet_replay")
    os.makedirs(out_dir, exist_ok=True)
    verdict, _rep = run_replay(
        entries, out_dir=out_dir, mode=args.mode,
        time_scale=args.time_scale, rate=args.rate,
        golden=args.golden, knob_pairs=args.knob,
        replicas=args.replicas, model=args.model,
        model_seed=args.model_seed, timeout_s=args.timeout)
    out_path = args.out or os.path.join(out_dir,
                                        "replay_verdict.json")
    with open(out_path, "w") as f:
        json.dump(verdict, f, indent=1)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
