"""mem_smoke — the campaign's CPU drill for the device-memory ledger
plane (ISSUE 20).

Shape (seeded, CPU-only, no tunnel window burned):

1. build a seeded wave of short prompts — half of them REPEATED so the
   prefix cache serves real hits — and run it through a ServingEngine
   with the memory ledger ARMED (mem_ledger=True, an explicit
   capacity so headroom/used-ratio forecasting is live);
2. invariants, asserted hard:
   - **zero-recompile untouched**: compile counts frozen across the
     wave with the ledger armed, zero unexpected retraces — track/
     release are host-side dict arithmetic and must never perturb the
     trace plane;
   - **conservation**: typed segments + ``unattributed_bytes`` equal
     the ground-truth live-array byte count within 1% after the full
     wave (prefill, prefix hits, decode) — the cross-check the whole
     plane hangs off;
   - **the seams fired**: kv_pages/weights tracked, prefix_sidecar
     level non-zero after a served hit, one admission consult per
     request counted;
   - **/memory endpoint renders**: a live HTTP scrape returns the
     armed segment tree, ``engine_mem_*`` gauges are in /metrics, and
     ``exporter_scrape_seconds`` self-timed the route;
   - **the residual alarm is quiet on a clean wave** — an alarm that
     cries on healthy traffic would be muted in a week;
3. leak drill + differential gate, BOTH directions: save the clean
   ledger snapshot (A), ``mark_baseline()``, then inject a deliberate
   leak — an UNTRACKED device page block (allocated behind the
   ledger's back, never released) plus pages popped off the engine's
   free list and never returned — sweep, and prove the
   ``unattributed_bytes`` residual alarm TRIPS, and that
   ``tools/mem_diff.py --fail-on 'segment:unattributed>+50%'``
   PASSES on A-vs-A and TRIPS on A-vs-B. A gate that cannot fail
   proves nothing;
4. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (registry +
   recompile report — the validate_stages contract),
   ``mem_clean.json`` / ``mem_leaked.json`` (the diffable ledger
   snapshots), a ``mem_smoke`` flight dump with the live segment tree
   attached (the anomaly-evidence path, exercised end-to-end), and
   ``mem_smoke.json`` (the drill's facts).

Last stdout line is a JSON verdict; exit 0 only when every assertion
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NEW_TOK = 24
PROMPT_LEN = 24            # 3 whole pages at page_size=8: enough
#                            boundary fingerprints for real sharing
REQUESTS = 8               # 4 distinct prompts, each submitted twice
MAX_SEQ_LEN = 64
NUM_PAGES = 128
PAGE_SIZE = 8
CAPACITY = 1 << 30         # explicit budget: CPU memory_stats has no
#                            bytes_limit, and the headroom/used-ratio
#                            forecast (and hard admission) need one
LEAK_MIN_BYTES = 8 << 20   # leak floor: far past the residual
#                            alarm's 1 MiB slack floor AND the diff
#                            gate's +50% bar at any clean baseline


def build_wave(seed=0, vocab=256):
    """REQUESTS prompts, each distinct prompt appearing twice — the
    second submission of a prompt is a guaranteed prefix-cache hit
    once the first registered its boundary pages."""
    import numpy as np
    rng = np.random.default_rng(seed)
    base = [rng.integers(1, vocab, (PROMPT_LEN,)).astype(np.int32)
            for _ in range(REQUESTS // 2)]
    return [p for p in base for _ in range(2)]


def run_engine(model, prompts):
    """One ledger-armed engine through the wave; returns the
    still-open engine + facts (caller closes — the drill scrapes the
    live /memory endpoint and runs the leak injection first)."""
    from paddle_tpu.nlp.serving import ServingEngine
    eng = ServingEngine(model, max_slots=4, page_size=PAGE_SIZE,
                        max_seq_len=MAX_SEQ_LEN,
                        num_pages=NUM_PAGES, steps_per_dispatch=1,
                        mem_ledger=True, mem_capacity_bytes=CAPACITY)
    eng.warmup(buckets=sorted({len(p) for p in prompts}), decode=True)
    frozen = eng.compile_counts()
    eng.generate(prompts, max_new_tokens=NEW_TOK)
    facts = {
        "compile_frozen": eng.compile_counts() == frozen,
        "unexpected_retraces": eng.tracer.unexpected_retraces(),
        "conservation": eng.ledger.conservation(tolerance=0.01),
        "prefix_stats": eng.prefix.stats(),
        "ledger_stats": eng.ledger.stats(),
        "segments": eng.ledger.segments(),
    }
    return eng, facts


def inject_leak(eng):
    """The deliberate leak: a device page block allocated BEHIND the
    ledger's back (never tracked, never released — the bug class the
    residual series exists to catch) plus free-list pages popped and
    never returned (the engine-side page leak, visible as a free_pages
    shortfall). Returns (held buffers, leaked page ids, leak bytes) —
    the caller must keep the buffers alive through the sweep."""
    from paddle_tpu.nlp.paged_cache import alloc_pages
    per_page = 2 * PAGE_SIZE * eng.kv_heads * eng.head_dim * 4
    n_pages = max(-(-LEAK_MIN_BYTES // per_page), 2)
    block = alloc_pages(n_pages, PAGE_SIZE, eng.kv_heads,
                        eng.head_dim, "float32")
    leak_bytes = sum(int(b.nbytes) for b in block if b is not None)
    leaked_ids = [eng._free_pages.pop() for _ in range(4)]
    return block, leaked_ids, leak_bytes


def _diff(a, b, fail_on):
    """Run the real mem_diff gate as a subprocess (what the campaign
    preflight would run); returns (exit_code, report)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mem_diff.py"),
         a, b, "--quiet", "--fail-on", fail_on],
        capture_output=True, text=True, timeout=120)
    try:
        report = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        report = {"unparseable": proc.stdout[-500:]}
    return proc.returncode, report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gate", default="segment:unattributed>+50%",
                    help="mem_diff --fail-on spec the injected leak "
                         "must trip")
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "mem_smoke")
    os.makedirs(out_dir, exist_ok=True)

    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.observability import flightrec, memledger
    from paddle_tpu.observability.trace import report_all

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    prompts = build_wave(args.seed)

    # -- clean ledger-armed run + live endpoint scrape ---------------------
    eng, clean = run_engine(model, prompts)
    exporter = eng.serve_metrics(port=0)
    url = f"http://{exporter.host}:{exporter.port}"
    with urllib.request.urlopen(f"{url}/memory?window=60",
                                timeout=10) as r:
        live = json.loads(r.read().decode())
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        prom = r.read().decode()
    # the anomaly-evidence path, end-to-end: a flight dump carrying
    # the live segment tree (validate_stages' FLIGHT_STAGES contract)
    flightrec.note("mem_smoke",
                   attributed=clean["segments"].get("kv_pages", 0))
    flightrec.dump("mem_smoke",
                   extra={"memory": memledger.current_memory()})
    eng.registry.dump(os.path.join(out_dir, "metrics.json"),
                      extra={"recompile_report": report_all(),
                             "stage": "mem_smoke"})
    snap_a = os.path.join(out_dir, "mem_clean.json")
    eng.ledger.save(snap_a)
    alarm_clean = eng.ledger.residual_alarm

    # -- leak drill --------------------------------------------------------
    eng.ledger.mark_baseline()
    free_before = len(eng._free_pages)
    block, leaked_ids, leak_bytes = inject_leak(eng)
    eng.ledger.sweep(force=True)
    alarm_leaked = eng.ledger.residual_alarm
    snap_b = os.path.join(out_dir, "mem_leaked.json")
    eng.ledger.save(snap_b)
    free_short = len(eng._free_pages)
    del block  # buffers held alive through the sweep above
    t_health = time.perf_counter()
    h = eng.health()
    health_s = time.perf_counter() - t_health
    eng.close()

    # -- differential gate, both directions --------------------------------
    rc_clean, rep_clean = _diff(snap_a, snap_a, args.gate)
    rc_trip, rep_trip = _diff(snap_a, snap_b, args.gate)

    cons = clean["conservation"]
    stats = clean["ledger_stats"]
    checks = {
        "zero_new_traces_after_warmup": (
            clean["compile_frozen"]
            and clean["unexpected_retraces"] == 0),
        "conservation_within_1pct": cons.get("ok") is True,
        "kv_pages_tracked": clean["segments"].get("kv_pages", 0) > 0,
        "weights_tracked": clean["segments"].get("weights", 0) > 0,
        "prefix_hit_served": clean["prefix_stats"]["hits"] > 0,
        "prefix_sidecar_tracked": (
            clean["segments"].get("prefix_sidecar", 0) > 0),
        "admission_checks_counted": (
            stats["admission_checks"] >= REQUESTS),
        "memory_endpoint_renders": bool(
            live.get("armed") is True
            and (live.get("tree") or {}).get("kv_pages")),
        "mem_series_exported": (
            "engine_mem_attributed_bytes" in prom
            and "engine_mem_hbm_used_ratio" in prom),
        "exporter_scrape_self_timed": (
            "exporter_scrape_seconds" in prom),
        "residual_alarm_quiet_on_clean_wave": not alarm_clean,
        "residual_alarm_trips_on_leak": alarm_leaked,
        "leak_visible_in_health": (
            (h.get("mem") or {}).get("residual_alarm") is True),
        "pages_leaked_off_free_list": free_short == free_before - 4,
        "diff_gate_passes_clean": rc_clean == 0,
        "diff_gate_trips_leaked": rc_trip == 1,
    }

    with open(os.path.join(out_dir, "mem_smoke.json"), "w") as f:
        json.dump({"clean": clean, "gate": args.gate,
                   "leak_bytes": leak_bytes,
                   "leaked_page_ids": leaked_ids,
                   "health_s": round(health_s, 6),
                   "diff_clean": rep_clean,
                   "diff_leaked": rep_trip}, f, indent=1, default=str)

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({
        "ok": ok, "checks": checks,
        "conservation": cons,
        "segments": clean["segments"],
        "gate": args.gate,
        "leak_bytes": leak_bytes,
        "out_dir": out_dir}, default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
