"""profile_smoke — the campaign's CPU drill for the continuous
profiling plane (ISSUE 22).

Shape (seeded, CPU-only, no tunnel window burned):

1. build a seeded wave of short random prompts and run it through a
   ServingEngine with the continuous profiler ARMED (profile=True) —
   the always-on configuration the flag ships for;
2. invariants, asserted hard:
   - **zero-recompile untouched**: compile counts frozen across the
     wave with profiling ON, zero unexpected retraces — the sampler
     is host-side only and must never perturb the trace plane;
   - **phase attribution is live**: a 1 kHz watcher thread polling
     the dispatch thread's phase marker during the wave observes
     real serving phases (``decode`` and at least one
     ``prefill_<bucket>``) — the markers the engine sets around its
     dispatch path are actually raised where the sampler would see
     them (the sampler itself is then proven on the injected run,
     whose multi-second decode burn guarantees ``decode`` samples in
     the folded profile regardless of backoff state);
   - **overhead under the cap**: the profiler's self-measured duty
     cycle (EWMA of sample cost / period) sits at or under its 1%
     cap on CPU — backoffs may have fired (they are counted, not
     hidden) but the steady state must comply;
   - **/profile endpoint renders**: a live HTTP scrape of
     ``/profile?window=60`` returns the folded profile +
     self-measurement digest, and ``exporter_scrape_seconds``
     self-timed the route;
   - **flamegraph is machine-parseable**: the self-contained HTML's
     embedded JSON ``<script>`` block parses back out and its folded
     map is non-empty — the artifact a triage dir holds years later
     still yields data;
3. differential gate, BOTH directions: save the clean run's folded
   profile (A), then re-run the wave with an injected busy-loop in
   the decode dispatch path (B — a deliberate host-side regression,
   sized at half the clean run's MEASURED wall so the decode-share
   delta clears the +10pp bar on a loaded host as surely as an idle
   one) and prove ``tools/profile_diff.py --fail-on
   'phase:decode>+10%'`` PASSES on A-vs-A and TRIPS on A-vs-B. A
   gate that cannot fail proves nothing;
4. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (registry +
   recompile report — the validate_stages contract),
   ``profile_clean.folded`` / ``profile_injected.folded``,
   ``flamegraph.html``, a ``profile_smoke`` flight dump with the live
   profile attached (the anomaly-evidence path, exercised
   end-to-end), and ``profile_smoke.json`` (the drill's facts).

Last stdout line is a JSON verdict; exit 0 only when every assertion
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NEW_TOK = 48
PROMPT_LEN = 12
REQUESTS = 6
MAX_SEQ_LEN = 128
NUM_PAGES = 128
PROFILE_HZ = 59.0      # prime, dense enough to catch phases on a
#                        short CPU wave while the duty cycle stays
#                        far under the 1% cap
MIN_HZ = PROFILE_HZ / 4.0   # backoff floor for the drill's engines:
#                        overhead spikes on a loaded host may halve the
#                        rate (counted, checked) but must not collapse
#                        it to 1 Hz, where a multi-second decode burn
#                        could land between samples
BURN_FRACTION = 0.5    # injected decode burn, as a fraction of the
#                        measured CLEAN run's wall: sizing the
#                        regression relative to the baseline keeps the
#                        decode-share delta (~burn/(1+burn) ≈ +33pp)
#                        comfortably past the +10pp gate on any host,
#                        loaded or idle — a fixed burn constant would
#                        dilute to nothing when warmup compiles run
#                        slow under contention
BURN_MIN_S = 2.0       # absolute burn floor (sample-count floor at
#                        the backed-off rate)


def build_wave(seed=0, vocab=256):
    import numpy as np
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, (PROMPT_LEN,)).astype(np.int32)
            for _ in range(REQUESTS)]


def run_engine(model, prompts, *, burn_total=0.0):
    """One profiled engine through the wave; returns facts + the
    still-open engine (caller closes — the clean run scrapes its
    live /profile endpoint first)."""
    from paddle_tpu.nlp.serving import ServingEngine
    eng = ServingEngine(model, max_slots=4, page_size=16,
                        max_seq_len=MAX_SEQ_LEN, steps_per_dispatch=1,
                        num_pages=NUM_PAGES,
                        profile=True, profile_hz=PROFILE_HZ)
    eng.profiler.min_hz = MIN_HZ
    eng.warmup(buckets=sorted({len(p) for p in prompts}), decode=True)
    frozen = eng.compile_counts()
    if burn_total > 0.0:
        # the deliberate regression: burn host time inside the decode
        # dispatch — the phase wrapper is already open, so attribution
        # is automatic and the folded profile's decode share must grow.
        # The budget is spread across dispatches (the wave has at
        # least NEW_TOK decode rounds, so a NEW_TOK/2 divisor always
        # drains it) rather than burned in one lump, so the profile
        # shows a hot *path*, not one monster sample.
        orig = eng._dispatch_decode_impl
        remaining = [float(burn_total)]
        step_cap = max(burn_total / (NEW_TOK / 2.0), 0.01)

        def burn():
            if remaining[0] > 0.0:
                t0 = time.perf_counter()
                quota = min(step_cap, remaining[0])
                while time.perf_counter() - t0 < quota:
                    sum(i * i for i in range(200))
                remaining[0] -= time.perf_counter() - t0
            orig()
        eng._dispatch_decode_impl = burn
    # deterministic phase-wiring witness: generate() runs on THIS
    # thread, so a 1 kHz watcher polling this thread's phase marker
    # observes every phase the dispatch path raises — orders of
    # magnitude denser than the sampler, immune to its Hz backoff
    from paddle_tpu.observability import contprof
    observed = set()
    stop = threading.Event()
    me = threading.get_ident()

    def watch():
        while not stop.is_set():
            ph = contprof.current_phase(me)
            if ph:
                observed.add(ph)
            time.sleep(0.001)
    w = threading.Thread(target=watch, daemon=True)
    w.start()
    try:
        eng.generate(prompts, max_new_tokens=NEW_TOK)
    finally:
        stop.set()
        w.join(2.0)
    facts = {
        "compile_frozen": eng.compile_counts() == frozen,
        "unexpected_retraces": eng.tracer.unexpected_retraces(),
        "digest": eng.profiler.digest(),
        "observed_phases": sorted(observed),
    }
    return eng, facts


def _parse_flame(path):
    """Extract the embedded profile JSON back out of the flamegraph
    HTML — the machine-parseability contract."""
    with open(path, encoding="utf-8") as f:
        html = f.read()
    marker = '<script id="profile-data" type="application/json">'
    i = html.index(marker) + len(marker)
    j = html.index("</script>", i)
    return json.loads(html[i:j].replace("<\\/", "</"))


def _diff(a, b, fail_on):
    """Run the real profile_diff gate as a subprocess (what the
    campaign preflight would run); returns (exit_code, report)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profile_diff.py"),
         a, b, "--quiet", "--fail-on", fail_on],
        capture_output=True, text=True, timeout=120)
    try:
        report = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        report = {"unparseable": proc.stdout[-500:]}
    return proc.returncode, report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gate", default="phase:decode>+10%",
                    help="profile_diff --fail-on spec the injected "
                         "regression must trip")
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "profile_smoke")
    os.makedirs(out_dir, exist_ok=True)

    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.observability import contprof, flightrec
    from paddle_tpu.observability.trace import report_all

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    prompts = build_wave(args.seed)

    # -- clean profiled run + live endpoint scrape -------------------------
    t0 = time.perf_counter()
    eng, clean = run_engine(model, prompts)
    t_clean = time.perf_counter() - t0
    folded_a = os.path.join(out_dir, "profile_clean.folded")
    eng.profiler.save(folded_a)
    flame_path = eng.profiler.flamegraph_html(
        os.path.join(out_dir, "flamegraph.html"))
    exporter = eng.serve_metrics(port=0)
    url = f"http://{exporter.host}:{exporter.port}"
    with urllib.request.urlopen(f"{url}/profile?window=60",
                                timeout=10) as r:
        live = json.loads(r.read().decode())
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        prom = r.read().decode()
    # the anomaly-evidence path, end-to-end: a flight dump carrying
    # the live profile (validate_stages' FLIGHT_STAGES contract)
    flightrec.note("profile_smoke", samples=clean["digest"]["samples"])
    flightrec.dump("profile_smoke",
                   extra={"profile": contprof.current_profile()})
    eng.registry.dump(os.path.join(out_dir, "metrics.json"),
                      extra={"recompile_report": report_all(),
                             "stage": "profile_smoke"})
    eng.close()

    # -- injected-regression run -------------------------------------------
    burn_total = max(BURN_MIN_S, BURN_FRACTION * t_clean)
    eng2, injected = run_engine(model, prompts, burn_total=burn_total)
    folded_b = os.path.join(out_dir, "profile_injected.folded")
    eng2.profiler.save(folded_b)
    eng2.close()

    # -- differential gate, both directions --------------------------------
    rc_clean, rep_clean = _diff(folded_a, folded_a, args.gate)
    rc_trip, rep_trip = _diff(folded_a, folded_b, args.gate)

    flame = _parse_flame(flame_path)
    dg = clean["digest"]
    phases = dg["phases"]
    checks = {
        "zero_new_traces_after_warmup": (
            clean["compile_frozen"]
            and clean["unexpected_retraces"] == 0),
        "decode_phase_marked": "decode" in clean["observed_phases"],
        "prefill_phase_marked": any(
            p.startswith("prefill_") for p in clean["observed_phases"]),
        "decode_phase_sampled": (
            injected["digest"]["phases"].get("decode", 0) > 0),
        "overhead_under_cap": dg["overhead_ratio"] <= 0.01,
        "profile_endpoint_renders": (
            live.get("folded") and live.get("digest") is not None),
        "exporter_scrape_self_timed": (
            "exporter_scrape_seconds" in prom),
        "flamegraph_parseable": bool(flame.get("folded")),
        "diff_gate_passes_clean": rc_clean == 0,
        "diff_gate_trips_injected": rc_trip == 1,
        "injected_run_still_frozen": (
            injected["compile_frozen"]
            and injected["unexpected_retraces"] == 0),
    }

    with open(os.path.join(out_dir, "profile_smoke.json"), "w") as f:
        json.dump({"clean_digest": dg,
                   "injected_digest": injected["digest"],
                   "observed_phases": clean["observed_phases"],
                   "gate": args.gate,
                   "diff_clean": rep_clean,
                   "diff_injected": rep_trip}, f, indent=1)

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({
        "ok": ok, "checks": checks,
        "samples": dg["samples"],
        "overhead_ratio": dg["overhead_ratio"],
        "backoffs": dg["backoffs"],
        "phases": phases,
        "gate": args.gate,
        "burn_total_s": round(burn_total, 3),
        "injected_decode_delta_pp": next(
            (fl.get("delta_pp") for fl in rep_trip.get("failures", [])
             if fl.get("key") == "phase:decode"), None),
        "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
