"""Step anatomy: where a training step's time goes (fwd / bwd / opt).

Times three separately-jitted programs on the bench config:
  loss        = forward + criterion                 (fwd)
  grad        = value_and_grad of the same          (fwd + bwd)
  train_batch = the Engine's full step              (+ clip/opt/amp)
and reports seconds plus the deltas (bwd = grad - loss, opt+misc =
full - grad). The r2 BENCHLOG anatomy (fwd 78.6 ms / bwd 143.5 ms /
AdamW 22.8 ms at gpt3-345M b8 s1024) was produced by hand; this makes
it a one-command campaign stage so each lever (fused qkv, scan layers)
can be localized to the phase it moves.

Usage: python tools/step_anatomy.py [--model gpt|gpt-1.3b] [--batch N]
         [--seq N] [--fused-qkv] [--scan-layers] [--smoke]
Prints one JSON line. ref parity: paddle.profiler's kernel breakdown.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=("gpt", "gpt-1.3b"), default="gpt")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--steps", type=int, default=10,
                    help="timed iterations per program (>= 1)")
    ap.add_argument("--fused-qkv", action="store_true")
    ap.add_argument("--fused-ln", action="store_true")
    ap.add_argument("--chunked-ce", type=int, default=0)
    ap.add_argument("--scan-layers", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()
    if args.steps < 1:
        ap.error("--steps must be >= 1")
    if args.smoke:
        import _cpu_env  # noqa: F401

    import jax
    import jax.numpy as jnp
    import numpy as np
    from bench import build_engine
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.tensor import Tensor

    on_tpu = jax.default_backend() == "tpu"
    if args.smoke or not on_tpu:
        cfg, batch, seq = "gpt-tiny", 2, 64
    elif args.model == "gpt-1.3b":
        cfg, batch, seq = "gpt3-1.3B", 4, 1024
    else:
        cfg, batch, seq = "gpt3-345M", 8, 1024
    batch = args.batch or batch
    seq = args.seq or seq
    big = args.model == "gpt-1.3b" and not args.smoke and on_tpu
    eng = build_engine(cfg, batch, seq, amp=on_tpu and not args.smoke,
                      recompute=big, moment_dtype="bfloat16" if big else None,
                      scan_layers=args.scan_layers,
                      fused_qkv=args.fused_qkv, fused_ln=args.fused_ln,
                      chunked_ce=args.chunked_ce)
    model, crit = eng.network, eng.loss
    params, buffers = model.raw_state()
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    amp_dt = jnp.bfloat16 if (on_tpu and not args.smoke) else None

    # the Engine's own forward+loss closure (single source of truth for
    # the AMP cast / buffer-dtype-restore) so the fwd and fwd+bwd
    # programs measure EXACTLY the computation inside the full step
    inner = Engine._make_loss_fn(model, crit, amp_dt, {}, buffers,
                                 [Tensor(ids)], [Tensor(labels)],
                                 jax.random.PRNGKey(0))

    def scalar_loss(p):
        return inner(p)[0]

    fwd = jax.jit(scalar_loss)
    grad = jax.jit(jax.value_and_grad(scalar_loss))

    def timeit(fn, sync):
        sync(fn())                      # compile + warm
        sync(fn())
        t0 = time.perf_counter()
        for _ in range(args.steps):
            r = fn()
        sync(r)
        return (time.perf_counter() - t0) / args.steps

    t_fwd = timeit(lambda: fwd(params), lambda r: float(r))
    t_grad = timeit(lambda: grad(params),
                    lambda r: float(r[0]))
    # full engine step LAST (it donates params — they are consumed)
    loss, _ = eng.train_batch([ids], [labels])    # compile
    float(loss)  # sync: the async remote backend must finish the warm
    # step before the timer starts (float() is the only reliable sync
    # on axon — see bench.run)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss, _ = eng.train_batch([ids], [labels])
    float(loss)
    t_full = (time.perf_counter() - t0) / args.steps
    print(json.dumps({
        "metric": "gpt_step_anatomy", "config": cfg,
        "batch": batch, "seq": seq,
        "fused_qkv": args.fused_qkv, "scan_layers": args.scan_layers,
        "fused_ln": args.fused_ln, "chunked_ce": args.chunked_ce,
        "fwd_ms": round(t_fwd * 1e3, 2),
        "fwd_bwd_ms": round(t_grad * 1e3, 2),
        "full_step_ms": round(t_full * 1e3, 2),
        "bwd_ms": round((t_grad - t_fwd) * 1e3, 2),
        "opt_misc_ms": round((t_full - t_grad) * 1e3, 2),
        "tokens_per_sec": round(batch * seq / t_full, 1),
        "backend": jax.default_backend(),
    }), flush=True)


if __name__ == "__main__":
    main()
