"""Decode-path bisection probes (VERDICT r2 next #2).

Round 2's decode attempt wedged the shared TPU terminal: the first
`generate()` compile (prefill + lax.scan of single-token steps +
flash_decode) hung >9.5 min and took the tunnel down (BENCHLOG.md
"Decode-path incident"). This tool isolates WHICH piece hangs, with
every stage in its own killable subprocess under a hard timeout, so a
hung compile costs one child process — never the terminal:

  stage 1  flash_decode kernel alone        (AOT lower + compile + run)
  stage 2  scan decode, use_flash=False     (jnp attention in the scan)
  stage 3  full generate() with flash       (the thing that hung)

Run on the TPU terminal:  python tools/decode_probe.py
Each stage prints PASS/FAIL(timeout) + seconds; results feed BENCHLOG.

`--paged` runs the round-7 serving bisection instead: the paged GQA
flash-decode kernel alone (AOT lower/compile/run + reference parity),
then a small ServingEngine batch-1-vs-8 A/B with per-program compile
counts and a steady-state zero-recompile assertion. On a dead tunnel
both stages run on CPU, so the artifact still carries a machine-
relative A/B row.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

# run as `python tools/decode_probe.py`: sys.path[0] is tools/, so the
# child stages (the only processes importing paddle_tpu) need the repo
# root on the path explicitly
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES = {}


def stage(name):
    def deco(fn):
        STAGES[name] = fn
        return fn
    return deco


@stage("kernel")
def probe_kernel():
    """flash_decode alone: [B,1,H,D] query vs a padded KV cache."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import flash_decode
    b, s, h, d = 8, 1024, 12, 64
    interp = jax.default_backend() != "tpu"
    q = jnp.ones((b, 1, h, d), jnp.bfloat16)
    k = jnp.ones((b, s, h, d), jnp.bfloat16)
    v = jnp.ones((b, s, h, d), jnp.bfloat16)
    lens = jnp.full((b,), 64, jnp.int32)
    t0 = time.perf_counter()
    lowered = jax.jit(
        lambda *a: flash_decode(*a, interpret=interp)).lower(q, k, v, lens)
    print(f"lowered in {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    print(f"compiled in {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    out = compiled(q, k, v, lens)
    s0 = float(jnp.sum(out.astype(jnp.float32)))
    print(f"ran in {time.perf_counter()-t0:.1f}s sum={s0}", flush=True)


@stage("scan_noflash")
def probe_scan_noflash():
    """generate() with use_flash_attention=False: isolates the KV-cache
    lax.scan + dynamic_update_slice structure from the Pallas kernel."""
    _generate_probe(use_flash=False)


@stage("full")
def probe_full():
    """The round-2 killer: generate() with the flash decode kernel
    (explicitly un-gated for this isolated child)."""
    os.environ["PADDLE_TPU_FLASH_DECODE"] = "1"
    _generate_probe(use_flash=True)


@stage("paged_kernel")
def probe_paged_kernel():
    """Paged GQA flash-decode kernel alone (ops/pallas/flash_decode):
    AOT lower + compile + run against the jnp paged reference. The
    serving analogue of the 'kernel' stage — proves the Mosaic compile
    in a killable child before bench_serve_flashk arms it."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.nlp.paged_cache import paged_attention_ref
    from paddle_tpu.ops.pallas.flash_decode import paged_flash_decode
    b, hkv, g, d, ps, p, mp = 8, 4, 4, 64, 128, 33, 4
    interp = jax.default_backend() != "tpu"
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, hkv, g, d)), jnp.bfloat16)
    kp = jnp.asarray(rng.standard_normal((hkv, p, ps, d)), jnp.bfloat16)
    vp = jnp.asarray(rng.standard_normal((hkv, p, ps, d)), jnp.bfloat16)
    pt = jnp.asarray(rng.integers(1, p, (b, mp)), jnp.int32)
    lens = jnp.asarray(rng.integers(1, mp * ps, (b,)), jnp.int32)
    t0 = time.perf_counter()
    lowered = jax.jit(lambda *a: paged_flash_decode(
        *a, interpret=interp)).lower(q, kp, vp, pt, lens)
    print(f"lowered in {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    print(f"compiled in {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    out = compiled(q, kp, vp, pt, lens)
    s0 = float(jnp.sum(out.astype(jnp.float32)))
    print(f"ran in {time.perf_counter()-t0:.1f}s sum={s0}", flush=True)
    ref = paged_attention_ref(q, kp, vp, pt, lens)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                - ref.astype(jnp.float32))))
    print(f"max |kernel - ref| = {err:.2e}", flush=True)
    assert err < 5e-2, "paged kernel diverges from the jnp reference"


@stage("paged_serve")
def probe_paged_serve():
    """ServingEngine smoke: batch-1 vs batch-8 steady-state decode
    tok/s + per-program compile counts. On a dead tunnel this runs on
    CPU, so the bisection still yields a machine-relative A/B row
    instead of nothing."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine
    on_tpu = jax.default_backend() == "tpu"
    paddle.seed(0)
    cfg = "gpt2-en" if on_tpu else "gpt-tiny"
    model = GPTForCausalLM(_resolve_config(
        cfg, hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    model.eval()
    rng = np.random.default_rng(0)
    vocab = model.config.vocab_size
    new_tok = 32 if on_tpu else 8
    rows = {}
    for batch in (1, 8):
        eng = ServingEngine(model, max_slots=batch, page_size=16,
                            max_seq_len=64, steps_per_dispatch=4)
        prompts = [rng.integers(0, vocab, (12,)) for _ in range(batch)]
        eng.generate(prompts, max_new_tokens=new_tok)   # warmup/compile
        counts = eng.compile_counts()
        eng.reset_counters()
        eng.generate([rng.integers(0, vocab, (12,))
                      for _ in range(2 * batch)], max_new_tokens=new_tok)
        assert eng.compile_counts() == counts, (
            "steady-state recompile", counts, eng.compile_counts())
        tok_s = eng.decode_tokens / max(eng.decode_seconds, 1e-9)
        rows[batch] = round(tok_s, 1)
        print(f"batch {batch}: {rows[batch]} tok/s decode "
              f"(compiles {counts}, steady recompiles 0)", flush=True)
    print(json.dumps({"paged_serve": rows,
                      "b8_vs_b1": round(rows[8] / rows[1], 2),
                      "backend": jax.default_backend()}), flush=True)


def _generate_probe(use_flash):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.generation import generate
    cfg = "gpt2-en" if jax.default_backend() == "tpu" else "gpt-tiny"
    batch, new_tok = (8, 32) if jax.default_backend() == "tpu" else (2, 8)
    model = GPTForCausalLM(_resolve_config(
        cfg, max_position_embeddings=1024, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, use_flash_attention=use_flash))
    model.eval()
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, model.config.vocab_size, (batch, 64)), jnp.int32)
    t0 = time.perf_counter()
    out = generate(model, prompt, max_new_tokens=new_tok)
    arr = out._value if hasattr(out, "_value") else out
    float(jnp.sum(arr))
    dt = time.perf_counter() - t0
    print(f"generate({cfg}, flash={use_flash}) compile+run {dt:.1f}s",
          flush=True)
    t0 = time.perf_counter()
    out = generate(model, prompt, max_new_tokens=new_tok)
    arr = out._value if hasattr(out, "_value") else out
    float(jnp.sum(arr))
    dt2 = time.perf_counter() - t0
    print(f"warm decode: {batch * new_tok / dt2:.1f} tok/s "
          f"({dt2 / new_tok * 1e3:.2f} ms/step)", flush=True)


def run_stage_child(name):
    # in-child watchdog: the orchestrator SIGKILLs too, but a self-exit
    # gives a cleaner diagnostic when only the backend (not python) hangs
    def watch():
        time.sleep(STAGE_TIMEOUT - 5)
        print(f"[{name}] in-child watchdog fired", file=sys.stderr,
              flush=True)
        os._exit(9)
    threading.Thread(target=watch, daemon=True).start()
    STAGES[name]()


STAGE_TIMEOUT = int(os.environ.get("DECODE_PROBE_TIMEOUT", "600"))


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        run_stage_child(sys.argv[2])
        return
    argv = sys.argv[1:]
    if argv and argv[0] == "--paged":
        # the serving-path bisection: kernel first (the piece that can
        # wedge a terminal), then the engine with compile counts
        argv = argv[1:] or ["paged_kernel", "paged_serve"]
    order = argv or ["kernel", "scan_noflash", "full"]
    results = {}
    for name in order:
        print(f"=== stage {name} (timeout {STAGE_TIMEOUT}s) ===", flush=True)
        t0 = time.monotonic()
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child", name],
            start_new_session=True)
        try:
            rc = proc.wait(timeout=STAGE_TIMEOUT)
            results[name] = {"ok": rc == 0, "rc": rc,
                             "seconds": round(time.monotonic() - t0, 1)}
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait()
            results[name] = {"ok": False, "rc": "timeout",
                             "seconds": round(time.monotonic() - t0, 1)}
        print(f"=== stage {name}: {results[name]} ===", flush=True)
        if not results[name]["ok"]:
            print("stopping: a hung/failed stage can leave the backend "
                  "wedged — reprobe before trusting later stages",
                  file=sys.stderr, flush=True)
            break
    print(json.dumps(results), flush=True)
    # nonzero when any stage failed/timed out: the campaign marks this
    # stage by rc, and a silently-green half-failed bisection would
    # read as "decode path proven" in summary.json
    return 0 if results and all(r["ok"] for r in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
