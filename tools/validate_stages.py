"""Preflight: every campaign stage's command line must parse.

A stage with a bad flag (or a renamed script) would burn a scarce
tunnel window on an instant failure. This runs each STAGES entry with
a 5s probe budget: an argparse failure or instant crash is flagged; a
healthy command reaches the probe (which then times out on a dead
tunnel — the expected PASS signal here). Run after editing the
ladder, while the tunnel is DOWN (on a live tunnel this would consume
window time): python tools/validate_stages.py
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tpu_campaign import OUT, REPO, STAGES  # noqa: E402

_BUDGET_S = 120
_INSTANT_S = 3.0  # a real stage spends longer than this just importing

# stages the current round's measurement plan depends on: a rename or
# accidental drop in tpu_campaign.STAGES must fail preflight loudly,
# not surface as tunnel_watch silently skipping "unknown" stages
REQUIRED_STAGES = {
    "probe", "bench_full", "bench_gpt13b_scan_cce",
    # static invariant sweep — tpulint over the shipping source
    # (CPU-only, runs before chaos_smoke — ISSUE 13)
    "staticcheck",
    # round-7 serving + llama rungs
    "bench_serve_gpt", "bench_serve_llama", "bench_serve_flashk",
    "bench_llama", "decode_probe_paged",
    # round-8 resilience drill (CPU-only, seeded — ISSUE 3)
    "chaos_smoke",
    # round-9 observability drill (CPU-only — ISSUE 4)
    "telemetry_smoke",
    # fleet failover/drain/hedge/shed chaos drill (CPU-only — ISSUE 6)
    "fleet_chaos_smoke",
    # router write-ahead-journal durability drill (CPU-only — ISSUE 9)
    "fleet_recovery_smoke",
    # process-isolated replicas + self-healing supervisor drill
    # (CPU-only, real subprocesses — ISSUE 10)
    "fleet_supervisor_smoke",
    # telemetry-history / tenancy / anomaly-sentinel drill + the
    # two-instant history gate (CPU-only — ISSUE 11)
    "history_smoke",
    # traffic capture & deterministic replay drill: committed-wave
    # golden replay + verdict-gate both-directions proof (CPU-only —
    # ISSUE 12)
    "replay_smoke",
    # elastic autoscaling drill: burst → scale-out → recovery →
    # scale-in with no lost rid + bounded SLO breach (CPU-only —
    # ISSUE 15)
    "autoscale_smoke",
    # copy-on-write prefix-cache drill: shared-prefix wave token-exact
    # ON vs OFF, hit rate over floor, ON TTFT p50 strictly better,
    # zero new traces (CPU-only — ISSUE 16)
    "prefix_cache_smoke",
    # speculative-decoding drill: long-decode wave token-exact ON vs
    # OFF, acceptance over floor, ON decode tok/s strictly above OFF,
    # zero new traces (CPU-only — ISSUE 20)
    "spec_smoke",
    # AOT serving-artifact boot probe: artifact boot token-exact vs
    # traced control, zero fallbacks, strictly faster (ISSUE 21; the
    # tunnel ladder's artifact-boot-vs-traced rung)
    "aot_boot",
    # continuous-profiling drill: profiler-armed wave with frozen
    # compile counts, phase attribution live, overhead under the 1%
    # cap, and the profile_diff gate proven both directions (CPU-only
    # — ISSUE 22)
    "profile_smoke",
    # device-memory ledger drill: ledger-armed wave with frozen
    # compile counts, typed-segment conservation within 1%, the
    # residual alarm + mem_diff gate proven both directions via an
    # injected untracked leak (CPU-only — HBM ledger round)
    "mem_smoke",
}


def _emits_metrics(cmd):
    """Stages built on bench.py workers or telemetry_smoke write
    telemetry.jsonl + metrics.json into campaign_out/telemetry/<stage>;
    the fleet chaos pytest stage exports its merged fleet registry the
    same way (conftest session fixture — the canary gate's input);
    other bare tools (decode_probe, fusion_audit) do not."""
    return any(os.path.basename(str(a)) in ("bench.py",
                                            "telemetry_smoke.py",
                                            "history_smoke.py",
                                            "replay_smoke.py",
                                            "autoscale_smoke.py",
                                            "prefix_cache_smoke.py",
                                            "spec_smoke.py",
                                            "profile_smoke.py",
                                            "mem_smoke.py",
                                            "aot_boot_probe.py",
                                            "test_fleet_serving.py",
                                            "test_fleet_recovery.py",
                                            "test_fleet_proc.py")
               for a in cmd)


def check_completed_stage_metrics():
    """Every COMPLETED stage of the live campaign summary that is
    expected to emit run telemetry must have left a parseable
    metrics.json — a stage that measured but exported nothing is a
    silent observability regression. Returns (problems, checked):
    the list of problems plus how many stages were actually
    inspected (0 when there is nothing eligible to validate)."""
    path = os.path.join(OUT, "summary.json")
    try:
        with open(path) as f:
            summary = json.load(f)
    except (OSError, json.JSONDecodeError):
        return [], 0   # no live campaign to validate
    if not summary.get("_telemetry"):
        # summary predates the telemetry subsystem: its stages never
        # wrote metrics.json — historical artifacts are not a regression
        return [], 0
    by_name = {s[0]: s[1] for s in STAGES}
    problems = []
    checked = 0
    for name, row in summary.items():
        if name.startswith("_") or not isinstance(row, dict) \
                or not row.get("ok"):
            continue
        cmd = by_name.get(name)
        if cmd is None or not _emits_metrics(cmd):
            continue
        checked += 1
        mpath = os.path.join(OUT, "telemetry", name, "metrics.json")
        try:
            with open(mpath) as f:
                doc = json.load(f)
            if not isinstance(doc.get("metrics"), dict):
                problems.append(
                    f"{name}: {mpath} parses but has no 'metrics' map")
        except OSError:
            problems.append(f"{name}: completed but left no "
                            f"metrics.json at {mpath}")
        except json.JSONDecodeError as e:
            problems.append(f"{name}: unparseable metrics.json ({e})")
    return problems, checked


# chaos-family stages: each drives at least one flight-recorder
# trigger (guard rollback, router crash/recovery), so a completed run
# must have left parseable flight dump(s) in its telemetry dir (the
# dumps land there because the campaign exports BENCH_TELEMETRY_DIR
# per stage — flightrec's dump-dir fallback)
FLIGHT_STAGES = {"chaos_smoke", "telemetry_smoke",
                 "fleet_recovery_smoke", "fleet_supervisor_smoke",
                 "history_smoke", "autoscale_smoke",
                 # the anomaly-evidence path end-to-end: its dump
                 # carries the live profile (ISSUE 22)
                 "profile_smoke",
                 # likewise: its dump carries the live segment tree
                 # (HBM ledger round)
                 "mem_smoke"}


def check_flight_dumps():
    """Completed chaos-family stages of a _flightrec-marked campaign
    summary must have left at least one parseable flight_*.json whose
    ring actually holds records — a chaos stage that tripped the guard
    but dumped nothing (or dumped garbage) is a silent loss of the
    post-mortem path. Returns (problems, checked)."""
    path = os.path.join(OUT, "summary.json")
    try:
        with open(path) as f:
            summary = json.load(f)
    except (OSError, json.JSONDecodeError):
        return [], 0
    if not summary.get("_flightrec"):
        return [], 0   # pre-flight-recorder archive: nothing to hold
    problems = []
    checked = 0
    for name in sorted(FLIGHT_STAGES):
        row = summary.get(name)
        if not isinstance(row, dict) or not row.get("ok"):
            continue
        checked += 1
        tdir = os.path.join(OUT, "telemetry", name)
        try:
            dumps = sorted(f for f in os.listdir(tdir)
                           if f.startswith("flight_")
                           and f.endswith(".json"))
        except OSError:
            dumps = []
        if not dumps:
            problems.append(f"{name}: completed but left no "
                            f"flight_*.json under {tdir}")
            continue
        for fn in dumps:
            fp = os.path.join(tdir, fn)
            try:
                with open(fp) as f:
                    doc = json.load(f)
                if not isinstance(doc.get("records"), list) \
                        or not doc.get("reason"):
                    problems.append(f"{name}: {fn} parses but has no "
                                    "records ring / reason")
            except (OSError, json.JSONDecodeError) as e:
                problems.append(f"{name}: unparseable flight dump "
                                f"{fn} ({e})")
    return problems, checked


def check_canary_verdict():
    """A _fleet_canary-marked campaign whose fleet_chaos_smoke stage
    completed must have left the metrics_diff gate's verdict file
    (telemetry/fleet_chaos_smoke/canary_verdict.json, parseable, with
    an 'ok' flag) — a gate that silently never ran would let a
    failover/shed regression ship as a green campaign. Returns
    (problems, checked)."""
    path = os.path.join(OUT, "summary.json")
    try:
        with open(path) as f:
            summary = json.load(f)
    except (OSError, json.JSONDecodeError):
        return [], 0
    if not summary.get("_fleet_canary"):
        return [], 0   # pre-gate archive: nothing to hold it to
    row = summary.get("fleet_chaos_smoke")
    if not isinstance(row, dict) or row.get("rc") is None:
        return [], 0   # stage never ran
    vpath = os.path.join(OUT, "telemetry", "fleet_chaos_smoke",
                         "canary_verdict.json")
    # the gate runs only on a completed stage; a failed stage leaves
    # no verdict and is already red on its own
    if not row.get("ok") and not row.get("canary"):
        return [], 0
    try:
        with open(vpath) as f:
            verdict = json.load(f)
    except OSError:
        return [f"fleet_chaos_smoke: completed but the canary gate "
                f"left no verdict at {vpath}"], 1
    except json.JSONDecodeError as e:
        return [f"fleet_chaos_smoke: unparseable canary verdict "
                f"({e})"], 1
    if "ok" not in verdict:
        return [f"fleet_chaos_smoke: canary verdict {vpath} has no "
                "'ok' flag"], 1
    return [], 1


def check_history_verdict():
    """A _history_gate-marked campaign whose history_smoke stage
    completed must have left the two-instant history gate's verdict
    (telemetry/history_smoke/history_verdict.json, parseable, with an
    'ok' flag) — a silently-skipped gate would let a sentinel
    regression ship as a green campaign. Returns (problems, checked)."""
    path = os.path.join(OUT, "summary.json")
    try:
        with open(path) as f:
            summary = json.load(f)
    except (OSError, json.JSONDecodeError):
        return [], 0
    if not summary.get("_history_gate"):
        return [], 0   # pre-gate archive
    row = summary.get("history_smoke")
    if not isinstance(row, dict) or row.get("rc") is None:
        return [], 0   # stage never ran
    if not row.get("ok") and not row.get("history_gate"):
        return [], 0   # failed on its own; no verdict expected
    vpath = os.path.join(OUT, "telemetry", "history_smoke",
                         "history_verdict.json")
    try:
        with open(vpath) as f:
            verdict = json.load(f)
    except OSError:
        return [f"history_smoke: completed but the history gate left "
                f"no verdict at {vpath}"], 1
    except json.JSONDecodeError as e:
        return [f"history_smoke: unparseable history verdict ({e})"], 1
    if "ok" not in verdict:
        return [f"history_smoke: history verdict {vpath} has no "
                "'ok' flag"], 1
    return [], 1


def check_lint_report():
    """A completed staticcheck stage must have left a parseable
    lint_report.json with non_baselined == 0 in its telemetry dir —
    a lint stage that 'passed' without a report (or with unreported
    new findings) would let a contract violation ship as a green
    campaign. Returns (problems, checked)."""
    path = os.path.join(OUT, "summary.json")
    try:
        with open(path) as f:
            summary = json.load(f)
    except (OSError, json.JSONDecodeError):
        return [], 0
    row = summary.get("staticcheck")
    if not isinstance(row, dict) or not row.get("ok"):
        return [], 0   # never ran, or already red on its own
    rpath = os.path.join(OUT, "telemetry", "staticcheck",
                         "lint_report.json")
    try:
        with open(rpath) as f:
            report = json.load(f)
    except OSError:
        return [f"staticcheck: completed but left no lint report at "
                f"{rpath}"], 1
    except json.JSONDecodeError as e:
        return [f"staticcheck: unparseable lint_report.json ({e})"], 1
    nb = report.get("non_baselined")
    if not isinstance(nb, int):
        return [f"staticcheck: lint report {rpath} has no "
                "'non_baselined' count"], 1
    if nb != 0:
        return [f"staticcheck: {nb} non-baselined finding(s) in a "
                f"stage marked ok — the gate was bypassed"], 1
    return [], 1


def _child_pgids(pid):
    """Process groups of `pid`'s direct children: bench.py/decode_probe
    start their workers with start_new_session=True, so killpg on the
    stage's own group does NOT reach them — collect their groups before
    killing. (Workers also self-limit via the 5s probe budget; this
    sweep just avoids leaving them to that.)"""
    pgids = set()
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    fields = f.read().rsplit(")", 1)[1].split()
                ppid, pgrp = int(fields[1]), int(fields[2])
            except (OSError, IndexError, ValueError):
                continue
            if ppid == pid:
                pgids.add(pgrp)
    except OSError:
        pass
    return pgids


def _run_stage(cmd, env):
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=_BUDGET_S)
        return proc.returncode, err, time.monotonic() - t0, False
    except subprocess.TimeoutExpired:
        groups = _child_pgids(proc.pid) | {proc.pid}
        for pg in groups:
            try:
                os.killpg(pg, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass
        proc.kill()
        proc.wait()
        return None, "", time.monotonic() - t0, True


def main():
    missing = REQUIRED_STAGES - {s[0] for s in STAGES}
    if missing:
        print(f"MISSING REQUIRED STAGES: {sorted(missing)}")
        return 1
    metric_problems, metrics_checked = check_completed_stage_metrics()
    flight_problems, flights_checked = check_flight_dumps()
    canary_problems, canary_checked = check_canary_verdict()
    history_problems, history_checked = check_history_verdict()
    lint_problems, lint_checked = check_lint_report()
    metric_problems += flight_problems + canary_problems \
        + history_problems + lint_problems
    metrics_checked += flights_checked + canary_checked \
        + history_checked + lint_checked
    for p in metric_problems:
        print(f"  metrics: SUSPECT ({p})", flush=True)
    tmp = tempfile.mkdtemp(prefix="stage_preflight_")
    env = dict(os.environ)
    env.update({"BENCH_PROBE_TIMEOUT": "5", "BENCH_WORK_TIMEOUT": "5",
                "CAMPAIGN_CHILD": "1",
                # >=30: decode_probe's in-child watchdog sleeps
                # STAGE_TIMEOUT-5 — a 5s budget would make it fire at
                # t=0 and read as an instant crash
                "DECODE_PROBE_TIMEOUT": "30"})
    bad = []
    for name, cmd, _timeout, env_extra in STAGES:
        e = dict(env)
        e.update(env_extra)
        # a stage that COMPLETES must not clobber real campaign
        # artifacts with preflight junk — point any --out at a temp
        # dir, and the telemetry finalize (which MERGES into an
        # existing metrics.json) at preflight-private dirs so it can
        # never pollute or double-count real campaign telemetry
        e["BENCH_CAMPAIGN_DIR"] = os.path.join(tmp, "campaign_out")
        e["BENCH_TELEMETRY_DIR"] = os.path.join(tmp, "telemetry", name)
        cmd = list(cmd)
        for i, a in enumerate(cmd):
            if a == "--out" and i + 1 < len(cmd):
                cmd[i + 1] = os.path.join(tmp,
                                          os.path.basename(cmd[i + 1]))
        rc, err, dt, timed_out = _run_stage(cmd, e)
        if timed_out:
            print(f"  {name}: ran past preflight budget (OK — command "
                  "parsed, killed group)", flush=True)
            continue
        argparse_fail = "usage:" in err and (
            "unrecognized" in err or "invalid" in err or "error:" in err)
        # slow nonzero exits are the EXPECTED dead-tunnel outcome
        # (bench probe rc=2, decode_probe rc=1); a fast nonzero exit is
        # a launch failure (typo'd script, SyntaxError, ImportError)
        instant_crash = rc != 0 and dt < _INSTANT_S
        if argparse_fail or instant_crash:
            tail = err.strip().splitlines()[-1] if err.strip() else ""
            bad.append((name, f"rc={rc} after {dt:.1f}s: {tail}"))
            print(f"  {name}: SUSPECT ({bad[-1][1]})", flush=True)
        else:
            print(f"  {name}: ok (rc={rc} in {dt:.1f}s)", flush=True)
    if bad or metric_problems:
        print("\nBROKEN/SUSPECT STAGES:")
        for name, line in bad:
            print(f"  {name}: {line}")
        for p in metric_problems:
            print(f"  metrics: {p}")
        return 1
    # claim the metrics verification ONLY when stages were actually
    # inspected — a pre-telemetry archive (or no summary) is skipped,
    # not validated
    print(f"\nall {len(STAGES)} stage command lines parse"
          + (f"; {metrics_checked} completed stages all exported "
             "metrics.json" if metrics_checked else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
