"""history_smoke — the campaign's CPU drill for the telemetry history
plane, per-tenant accounting and the anomaly sentinel (ISSUE 11).

Shape (seeded, CPU-only, no tunnel window burned):

1. build a 2-replica in-process fleet with the history plane, tenancy
   and the sentinel armed; warm every prefill bucket and FREEZE the
   compile counts;
2. **clean golden wave**: tenant-tagged traffic in steady pulses —
   the sentinel learns its bands and must stay QUIET (zero
   ``fleet_anomaly_fired_total``); the clean-wave history is what the
   committed golden archive (tools/golden/history_clean_wave.json,
   regenerate with ``--write-golden``) holds, and this run REPLAYS
   the sentinel over that committed golden asserting zero firings —
   band drift that starts alarming on known-good history fails here;
3. **regression wave**: the same traffic with an injected per-round
   replica slowdown (``replica_slow`` on every replica — the
   mid-wave latency regression). The sentinel MUST fire (TTFT p99 /
   queue-wait / decode-tok/s excursion) and leave a parseable
   ``flight_fleet_anomaly*.json``;
4. invariants, asserted hard: per-tenant token totals sum EXACTLY to
   the fleet counters (space-saving sketch conservation), and compile
   counts are FROZEN across both waves with accounting on;
5. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (fleet
   registry + recompile report), ``history_snapshot.json`` (the
   torn-tolerant archive), ``tenants.json``, ``health.json``,
   ``marks.json`` ({"t0","t_clean","t_end"} epoch marks). The
   campaign's history gate then drives ``tools/metrics_diff.py
   --history --at --vs`` over the archive: the clean span must show
   no ``fleet_anomaly_*`` increase, the regression span MUST trip it
   (the gate is proven live, not assumed).

Last stdout line is a JSON verdict; exit 0 only when every assertion
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

GOLDEN = os.path.join(REPO, "tools", "golden",
                      "history_clean_wave.json")
NEW_TOK = 8
SCRAPE_S = 0.05

# band knobs shared by the live run and the committed-golden replay
# (one source of truth: quiet/fire claims must test the SAME detector)
SENTINEL_KW = dict(warmup=10, min_consecutive=3, z=5.0, rel_floor=0.5)


def _signals():
    from paddle_tpu.observability.sentinel import default_signals
    # 1s windows over a 0.05s scrape cadence: ~20 samples per window
    return [dict(s, window_s=1.0) for s in default_signals()]


def _build_fleet():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine
    from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    engines = []
    for _ in range(2):
        eng = ServingEngine(model, max_slots=2, page_size=16,
                            max_seq_len=64, steps_per_dispatch=4)
        # warm every bucket the waves can land in, then reset the
        # measurement window
        eng.generate([np.arange(5, dtype=np.int32),
                      np.arange(17, dtype=np.int32)], max_new_tokens=4)
        eng.reset_counters()
        engines.append(eng)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    router = FleetRouter(
        reps, history=True, history_interval_s=SCRAPE_S,
        sentinel=True,
        sentinel_kw=dict(SENTINEL_KW, signals=_signals()))
    return router, engines, frozen


def _wave(router, rng, *, pulses, per_pulse, pulse_gap_s, tenants):
    """Steady tenant-tagged pulses; drains between pulses so the
    cadence (and so every latency signal) is reproducible."""
    import numpy as np
    for pulse in range(pulses):
        rids = []
        for i in range(per_pulse):
            n = int(rng.integers(4, 22))
            prompt = rng.integers(0, 256, (n,)).astype(np.int32)
            rids.append(router.submit(
                prompt, NEW_TOK,
                tenant=tenants[(pulse + i) % len(tenants)]))
        t_end = time.monotonic() + 30.0
        results = []
        while len(results) < len(rids):
            results += router.step()
            router.results()
            if time.monotonic() > t_end:
                raise RuntimeError("wave did not drain in 30s")
            time.sleep(0.002)
        # idle gap: the history plane keeps scraping (the sentinel's
        # bands need BETWEEN-pulse samples too)
        t_gap = time.monotonic() + pulse_gap_s
        while time.monotonic() < t_gap:
            router.step()
            time.sleep(0.01)
        yield results


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--write-golden", action="store_true",
                    help="save the clean wave's history archive as "
                         "the committed golden and exit")
    ap.add_argument("--pulses", type=int, default=24)
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "history_smoke")
    os.makedirs(out_dir, exist_ok=True)
    # flight dumps (fleet_anomaly) land next to the other artifacts
    os.environ.setdefault("PADDLE_TPU_FLIGHT_DIR", out_dir)

    import numpy as np
    from paddle_tpu.observability.history import HistoryStore
    from paddle_tpu.observability.sentinel import AnomalySentinel
    from paddle_tpu.observability.trace import report_all
    from paddle_tpu.resilience import faults

    checks = {}
    router, engines, frozen = _build_fleet()
    # t0 marks AFTER the first history scrape: the clean-span gate
    # (--at t0 --vs t_clean) needs the fleet_anomaly_* series present
    # at BOTH instants — a pre-boot t0 reconstructs an empty snapshot
    # and check_fail_on would skip every series, making the gate
    # vacuous instead of proving the clean span quiet
    while router.history.scrapes == 0:
        router.step()
        time.sleep(0.01)
    t0 = time.time()
    rng = np.random.default_rng(0)
    tenants = [f"tenant-{i}" for i in range(4)]
    all_results = []

    # -- clean golden wave: sentinel learns, must stay quiet ---------------
    for res in _wave(router, rng, pulses=args.pulses, per_pulse=4,
                     pulse_gap_s=0.08, tenants=tenants):
        all_results += res
    fired_clean = router.sentinel.fired_total
    checks["clean_wave_quiet"] = fired_clean == 0
    t_clean = time.time()

    if args.write_golden:
        router.history.save(GOLDEN)
        print(json.dumps({"ok": True, "wrote_golden": GOLDEN,
                          "fired_clean": fired_clean}))
        router.close()
        return 0 if fired_clean == 0 else 1

    # -- committed-golden replay: the archived clean wave must also
    # read quiet under TODAY's detector (band-drift guard)
    if os.path.exists(GOLDEN):
        golden_firings = AnomalySentinel.replay(
            HistoryStore.load(GOLDEN), signals=_signals(),
            **SENTINEL_KW)
        checks["golden_replay_quiet"] = not golden_firings
    else:
        checks["golden_replay_quiet"] = False

    # -- regression wave: injected mid-wave latency cliff ------------------
    for name in ("r0", "r1"):
        faults.inject("replica_slow", count=10_000,
                      seconds=0.06, replica=name)
    try:
        for res in _wave(router, rng, pulses=8, per_pulse=4,
                         pulse_gap_s=0.08, tenants=tenants):
            all_results += res
    finally:
        faults.clear()
    t_end = time.time()

    fired = router.sentinel.fired_total
    checks["sentinel_fired_on_regression"] = fired > fired_clean
    alerting = sorted(
        {f for st in [router.sentinel.state()] for f, r in st.items()
         if r.get("alert")})

    # the fleet_anomaly flight dump must exist and parse
    dumps = sorted(f for f in os.listdir(out_dir)
                   if f.startswith("flight_fleet_anomaly")
                   and f.endswith(".json"))
    parsed = False
    for fn in dumps:
        try:
            with open(os.path.join(out_dir, fn)) as f:
                doc = json.load(f)
            parsed = bool(doc.get("reason") == "fleet_anomaly"
                          and doc.get("signal"))
        except (OSError, json.JSONDecodeError):
            parsed = False
        if parsed:
            break
    checks["anomaly_flight_dump_parseable"] = parsed

    # -- tenancy: per-tenant token totals sum EXACTLY to fleet totals ------
    rep = router.tenants.report()
    fleet_out = int(router.registry.get("fleet_tokens_out_total").value)
    fleet_in = int(router.registry.get("fleet_tokens_in_total").value)
    res_out = sum(len(r["tokens"]) for r in all_results)
    sketch_out = sum(t["tokens_out"] for t in rep["tenants"])
    sketch_in = sum(t["tokens_in"] for t in rep["tenants"])
    checks["tenant_tokens_out_exact"] = (
        sketch_out == rep["totals"]["tokens_out"] == fleet_out
        == res_out)
    checks["tenant_tokens_in_exact"] = (
        sketch_in == rep["totals"]["tokens_in"] == fleet_in)
    checks["tenant_kv_page_seconds_nonzero"] = \
        rep["totals"]["kv_page_s"] > 0

    # -- zero new recompiles with accounting on ----------------------------
    checks["compile_counts_frozen"] = all(
        engines[i].compile_counts() == frozen[i]
        for i in range(len(engines))) and \
        router.compile_report()["unexpected_retraces"] == 0

    # -- artifacts ---------------------------------------------------------
    router.history.save(os.path.join(out_dir, "history_snapshot.json"))
    with open(os.path.join(out_dir, "marks.json"), "w") as f:
        json.dump({"t0": t0, "t_clean": t_clean, "t_end": t_end}, f)
    with open(os.path.join(out_dir, "tenants.json"), "w") as f:
        json.dump(rep, f, indent=1)
    with open(os.path.join(out_dir, "health.json"), "w") as f:
        json.dump(router.health(), f, indent=1)
    router.registry.dump(os.path.join(out_dir, "metrics.json"),
                         extra={"recompile_report": report_all(),
                                "stage": "history_smoke"})
    router.close()
    for e in engines:
        e.close()

    ok = all(checks.values())
    print(json.dumps({"ok": ok, "checks": checks,
                      "anomaly_fired": fired,
                      "alerting": alerting,
                      "requests": len(all_results),
                      "tokens_out": res_out,
                      "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
