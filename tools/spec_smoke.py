"""spec_smoke — the campaign's CPU drill for speculative decoding
(ISSUE 20 / round 20).

Shape (seeded, CPU-only, no tunnel window burned):

1. build a seeded wave of short random prompts and decode LONG
   (max_new 96): a tiny greedy model collapses into short token
   cycles within a few steps, which is exactly the regime the
   zero-weight prompt-lookup (ngram) proposer feeds on — the CPU
   stand-in for the natural repetitiveness of real decode traffic;
2. run the wave through a spec-ON engine (K=8, ngram draft) and a
   spec-OFF control (same model, same sampling, same warmup), both
   at steps_per_dispatch=1 — the interactive setting speculation
   exists for, where every committed token otherwise costs one
   serial target dispatch;
3. invariants, asserted hard:
   - **token-exact**: every ON stream equals its OFF stream token
     for token (the hard invariant — speculation may change latency,
     never tokens; the verify pass applies the target model's own
     per-position sampler to every lane);
   - **acceptance ≥ floor** (default 0.5): cumulative acceptance
     rate from the ON engine's health()["spec"] — the drill is
     non-vacuous only when the flagship actually confirms drafts;
   - **decode tok/s strictly better ON**: committed decode tokens
     over decode wall-time beats the OFF control on the same wave
     (a high-acceptance dispatch commits up to K+1 tokens against
     ONE folded-batch verify where the control pays one dispatch
     per token);
   - **zero new traces after warmup**: compile counts frozen across
     the wave with speculation ON, zero unexpected retraces — the
     verify scan is pre-traced by warmup();
4. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (the ON
   engine's registry + recompile report — the validate_stages
   contract), ``spec_decode.json`` (both engines' facts).

Last stdout line is a JSON verdict; exit 0 only when every assertion
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NEW_TOK = 96                 # long decode: the cycle tail dominates,
#                              so acceptance reflects steady-state
PROMPT_LEN = 12              # short prompts — prefill stays cheap,
#                              the drill times decode
REQUESTS = 8
SPEC_K = 8                   # an accepting dispatch commits up to 9
#                              tokens where the OFF control's
#                              single-step dispatch commits one
MAX_SEQ_LEN = 128            # gpt-tiny's max_position_embeddings
NUM_PAGES = 128


def build_wave(seed=0, vocab=256):
    """Seeded wave of short random prompts. Repetitiveness comes from
    the MODEL, not the prompts: tiny greedy decode converges to short
    cycles the prompt-lookup proposer then predicts near-perfectly."""
    import numpy as np
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, (PROMPT_LEN,)).astype(np.int32)
            for _ in range(REQUESTS)]


def run_engine(model, prompts, *, spec):
    """One engine through the wave; returns (tokens, facts)."""
    from paddle_tpu.nlp.serving import ServingEngine
    eng = ServingEngine(model, max_slots=4, page_size=16,
                        max_seq_len=MAX_SEQ_LEN, steps_per_dispatch=1,
                        num_pages=NUM_PAGES,
                        spec_decode=spec, spec_k=SPEC_K,
                        spec_draft="ngram")
    eng.warmup(buckets=sorted({len(p) for p in prompts}), decode=True)
    frozen = eng.compile_counts()
    out = eng.generate(prompts, max_new_tokens=NEW_TOK)
    facts = {
        "spec": eng.health().get("spec"),
        "decode_tokens": eng.decode_tokens,
        "decode_seconds": eng.decode_seconds,
        "decode_tok_s": (eng.decode_tokens / eng.decode_seconds
                         if eng.decode_seconds else None),
        "compile_frozen": eng.compile_counts() == frozen,
        "unexpected_retraces": eng.tracer.unexpected_retraces(),
        "registry": eng.registry,
    }
    eng.close()
    return out, facts


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--acceptance-floor", type=float, default=0.5,
                    help="minimum cumulative draft acceptance rate")
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "spec_smoke")
    os.makedirs(out_dir, exist_ok=True)

    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.observability.trace import report_all

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    prompts = build_wave(args.seed)

    on_toks, on = run_engine(model, prompts, spec=True)
    off_toks, off = run_engine(model, prompts, spec=False)

    sp = on["spec"] or {}
    acc_rate = sp.get("acceptance_rate")

    checks = {
        "token_exact_on_vs_off": on_toks == off_toks,
        "acceptance_over_floor": (
            acc_rate is not None
            and acc_rate >= args.acceptance_floor),
        "decode_tok_s_on_above_off": (
            on["decode_tok_s"] is not None
            and off["decode_tok_s"] is not None
            and on["decode_tok_s"] > off["decode_tok_s"]),
        "zero_new_traces_after_warmup": (
            on["compile_frozen"]
            and on["unexpected_retraces"] == 0),
        "off_control_spec_disabled": off["spec"] is None,
    }

    on["registry"].dump(os.path.join(out_dir, "metrics.json"),
                        extra={"recompile_report": report_all(),
                               "stage": "spec_smoke"})
    with open(os.path.join(out_dir, "spec_decode.json"), "w") as f:
        json.dump({"on": sp,
                   "acceptance_rate": acc_rate,
                   "decode_tok_s_on": on["decode_tok_s"],
                   "decode_tok_s_off": off["decode_tok_s"],
                   "decode_tokens_on": on["decode_tokens"],
                   "decode_tokens_off": off["decode_tokens"]},
                  f, indent=1)

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({
        "ok": ok, "checks": checks,
        "acceptance_rate": acc_rate,
        "acceptance_floor": args.acceptance_floor,
        "proposed": sp.get("proposed"), "accepted": sp.get("accepted"),
        "dispatches": sp.get("dispatches"),
        "decode_tok_s_on": on["decode_tok_s"],
        "decode_tok_s_off": off["decode_tok_s"],
        "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
