"""fleet_top — htop for the serving fleet.

A terminal live view over a running FleetRouter's observability
endpoints: one frame per interval showing the fleet headline (request
rate, delivered tok/s, TTFT/queue-wait p99 from the history plane),
SLO burn alerts + anomaly-sentinel excursions, the per-replica table
(state, incarnation, queue/running, free pages, scrape age, boot
path aot/traced + wall clock), the
AUTOSCALER panel (controller state + size bounds, degraded/brownout
level with the clamped tenants, last decision + reason, per-replica
role incl. booting/retiring members), the per-tenant heavy-hitter
table (space-saving sketch: weight, tokens in/out, KV-page-seconds,
the error bound) and the recent-resolved request table (rid, status,
ttft/e2e, traffic-archive locator).

Live mode reads ``/healthz`` + ``/history`` + ``/tenants`` +
``/requests`` off the router exporter
(``FleetRouter.serve_metrics``):

  python tools/fleet_top.py --url http://127.0.0.1:9101
  python tools/fleet_top.py --url ... --once        # one frame, exit

Offline mode (``--snapshot <dir>``) renders the SAME frame from a
post-mortem triage dir — the ``history_smoke`` stage's artifacts, or
anything holding a ``history_snapshot.json`` (HistoryStore save) and
optionally ``tenants.json`` / ``health.json``:

  python tools/fleet_top.py --snapshot campaign_out/telemetry/history_smoke

Stdlib-only (urllib + the standalone-loadable observability modules
via bench._obs_mod); plain ANSI clear-screen, no curses.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import _obs_mod  # noqa: E402

WINDOW_S = 30.0


def _get(url, timeout=3.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _fmt(v, unit="", nd=3):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}{unit}"
    return f"{v}{unit}"


def _fmt_bytes(n):
    """1536 -> '1.5K', 3<<30 -> '3.0G' (the HEADROOM column's unit)."""
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "-"
    for suffix in ("B", "K", "M", "G", "T"):
        if abs(n) < 1024.0 or suffix == "T":
            return f"{n:.1f}{suffix}" if suffix != "B" \
                else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}T"


def collect_live(base):
    """One frame's data off a live router exporter."""
    health = _get(base + "/healthz")
    try:
        tenants = _get(base + "/tenants")
    except Exception:  # noqa: BLE001 — tenancy may be off
        tenants = None
    try:
        requests = _get(base + "/requests")
    except Exception:  # noqa: BLE001 — pre-capture routers lack it
        requests = None

    def roll(series, op, **kw):
        from urllib.parse import quote
        try:
            q = "&".join([f"series={quote(series, safe='')}",
                          f"op={op}",
                          f"window={kw.get('window', WINDOW_S)}"]
                         + ([f"q={kw['q']}"] if "q" in kw else []))
            return _get(f"{base}/history?{q}").get("value")
        except Exception:  # noqa: BLE001 — history may be off
            return None

    return {
        "ts": time.time(), "source": base, "health": health,
        "tenants": tenants, "requests": requests,
        "rates": {
            "req_s": roll("fleet_requests_total{status=\"ok\"}",
                          "rate"),
            "tok_s": roll("fleet_tokens_out_total", "rate"),
            "ttft_p99_s": roll("fleet_ttft_seconds", "quantile",
                               q=0.99),
            "queue_p99_s": roll("fleet_placement_wait_seconds",
                                "quantile", q=0.99)}}


def collect_snapshot(directory):
    """The same frame from a triage dir (offline post-mortem mode)."""
    HistoryStore = _obs_mod("history").HistoryStore
    store = HistoryStore.load(
        os.path.join(directory, "history_snapshot.json"))
    _first, last = store.span()

    def read_json(name):
        try:
            with open(os.path.join(directory, name)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def roll(series, op, q=None):
        if last is None:
            return None
        if op == "rate":
            return store.rate(series, WINDOW_S, now=last)
        return store.quantile_over_time(series, q, WINDOW_S, now=last)

    return {
        "ts": last, "source": directory,
        "health": read_json("health.json"),
        "tenants": read_json("tenants.json"),
        "requests": read_json("requests.json"),
        "rates": {
            "req_s": roll("fleet_requests_total{status=\"ok\"}",
                          "rate"),
            "tok_s": roll("fleet_tokens_out_total", "rate"),
            "ttft_p99_s": roll("fleet_ttft_seconds", "quantile",
                               q=0.99),
            "queue_p99_s": roll("fleet_placement_wait_seconds",
                                "quantile", q=0.99)}}


def render(frame):
    """One frame -> text (pure; pinned by tests/test_history.py)."""
    out = []
    r = frame.get("rates") or {}
    out.append(f"fleet_top  {time.strftime('%H:%M:%S', time.localtime(frame.get('ts') or 0))}"
               f"  src={frame.get('source')}")
    out.append(
        f"  req/s {_fmt(r.get('req_s'), nd=1)}"
        f"  tok/s {_fmt(r.get('tok_s'), nd=1)}"
        f"  ttft p99 {_fmt(r.get('ttft_p99_s'), 's')}"
        f"  queue p99 {_fmt(r.get('queue_p99_s'), 's')}"
        f"  (over {WINDOW_S:g}s)")
    h = frame.get("health")
    if h:
        slo = h.get("slo") or {}
        anom = h.get("anomaly") or {}
        alerting = list(slo.get("alerting") or []) \
            + [f"anomaly:{n}" for n in (anom.get("alerting") or [])]
        out.append(f"  queue={h.get('queue_depth')} "
                   f"pending={h.get('pending')} "
                   f"lost={h.get('lost') or []} "
                   f"alerts={alerting or 'none'}")
        reps = h.get("replicas") or {}
        if reps:
            # HOST% (r22): 100*(1 - idle share) from the replica's
            # continuous-profiler heartbeat digest — how much of the
            # host's sampled wall time was real serving work; "-" for
            # replicas with no profiler armed
            prof = (h.get("profile") or {}).get("replicas") or {}
            # MEM%/HEADROOM (r23): device-memory used ratio and
            # forecast free bytes from the replica's memory-ledger
            # heartbeat digest; "-" for replicas with no ledger armed
            # (or capacity-blind backends)
            mem = (h.get("mem") or {}).get("replicas") or {}
            out.append("  REPLICA     STATE     INC  Q/R    FREE_PG "
                       "SCRAPE_AGE  BOOT         HOST%  MEM%   "
                       "HEADROOM  FLAGS")
            for name in sorted(reps):
                row = reps[name]
                flags = "".join(
                    f for f, on in (("L", row.get("lost")),
                                    ("Q", row.get("quarantined")))
                    if on) or "-"
                # boot path + wall clock (r21): aot = restored from a
                # serving artifact, traced = full trace + compile;
                # pre-artifact replicas carry no boot dict at all
                bi = row.get("boot") or {}
                boot = "-" if not bi.get("mode") else (
                    f"{bi['mode']}"
                    + ("" if bi.get("boot_s") is None
                       else f" {float(bi['boot_s']):.1f}s"))
                hp = (prof.get(name) or {}).get("host_pct")
                host = "-" if hp is None else f"{float(hp):.1f}"
                mrow = mem.get(name) or {}
                mr = mrow.get("used_ratio")
                memp = "-" if mr is None else f"{100.0 * float(mr):.1f}"
                hr = mrow.get("headroom_bytes")
                head = "-" if hr is None else _fmt_bytes(hr)
                if mrow.get("residual_alarm"):
                    flags = (flags.replace("-", "") or "") + "M" \
                        if flags != "-" else "M"
                out.append(
                    f"  {name:<11} {str(row.get('state')):<9} "
                    f"{str(row.get('incarnation')):<4} "
                    f"{_fmt(row.get('queued'))}/"
                    f"{_fmt(row.get('running')):<4} "
                    f"{_fmt(row.get('free_pages')):<7} "
                    f"{_fmt(row.get('scrape_age_s'), 's'):<11} "
                    f"{boot:<12} {host:<6} {memp:<6} "
                    f"{head:<9} {flags}")
    if h:
        asc = h.get("autoscale")
        ov = h.get("overload") or {}
        if asc or ov.get("degraded") or ov.get("brownout_level"):
            bits = []
            if asc:
                bits.append(
                    f"state={asc.get('state')} "
                    f"size={asc.get('replicas')} "
                    f"[{asc.get('min')}..{asc.get('max')}]")
            bits.append(
                f"degraded={'yes' if ov.get('degraded') else 'no'} "
                f"brownout=L{ov.get('brownout_level') or 0}")
            if ov.get("clamped_tenants"):
                bits.append(
                    f"clamped={','.join(ov['clamped_tenants'])}")
            out.append("  AUTOSCALER  " + "  ".join(bits))
            last = (asc or {}).get("last_decision")
            if last:
                detail = " ".join(
                    f"{k}={v}" for k, v in sorted(last.items())
                    if k not in ("event", "t") and v is not None)
                out.append(f"    last: {last.get('event')} "
                           f"{detail}".rstrip())
            reps = h.get("replicas") or {}
            if asc and reps:
                roles = []
                for name in sorted(reps):
                    role = "retiring" if name == asc.get("retiring") \
                        else str(reps[name].get("state"))
                    roles.append(f"{name}={role}")
                if asc.get("booting"):
                    roles.append(f"{asc['booting']}=booting")
                out.append("    ROLE  " + " ".join(roles))
    t = frame.get("tenants")
    if t:
        out.append(
            f"  TENANTS tracked={t.get('tracked')}/"
            f"{t.get('capacity')} evictions={t.get('evictions')} "
            f"err_bound={t.get('error_bound')} "
            f"totals: in={t['totals']['tokens_in']} "
            f"out={t['totals']['tokens_out']} "
            f"kv_page_s={_fmt(t['totals']['kv_page_s'], nd=1)}")
        out.append("  TENANT        WEIGHT  TOK_IN  TOK_OUT "
                   "QWAIT_S  KV_PG_S  PFX_HIT  SPEC_ACC  ERR")
        for row in (t.get("tenants") or [])[:16]:
            # page-level prefix hit rate (r19): the share of this
            # tenant's shareable prompt pages served from cache
            ppg = row.get("prefix_pages") or 0
            pfx = "-" if not ppg else \
                f"{100.0 * (row.get('prefix_hit_pages') or 0) / ppg:.0f}%"
            # speculative acceptance rate (r20): the share of this
            # tenant's draft tokens the target model confirmed
            spp = row.get("spec_proposed") or 0
            spc = "-" if not spp else \
                f"{100.0 * (row.get('spec_accepted') or 0) / spp:.0f}%"
            out.append(
                f"  {row['tenant']:<13} {row['weight']:<7} "
                f"{row['tokens_in']:<7} {row['tokens_out']:<8}"
                f"{_fmt(row['queue_wait_s'], nd=2):<9}"
                f"{_fmt(row['kv_page_s'], nd=2):<9}"
                f"{pfx:<9}{spc:<10}{row['err']}")
    rq = frame.get("requests")
    if rq and rq.get("requests"):
        cap = rq.get("capture") or {}
        out.append(
            "  RECENT REQUESTS"
            + (f"  (capture: {cap.get('dir')}"
               f" @ sample={cap.get('sample')})" if cap else ""))
        out.append("  RID    TENANT        STATUS     TTFT_S   E2E_S"
                   "    REPLICA  ARCHIVE")
        for row in (rq.get("requests") or [])[-8:]:
            arch = row.get("archive") or {}
            loc = (f"{arch.get('segment')}@{arch.get('offset')}"
                   if arch else "-")
            out.append(
                f"  {row['rid']:<6} {str(row.get('tenant')):<13} "
                f"{row['status']:<10} "
                f"{_fmt(row.get('ttft_s'), nd=3):<8} "
                f"{_fmt(row.get('e2e_s'), nd=3):<8} "
                f"{str(row.get('replica')):<8} {loc}")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="terminal live view of a serving fleet")
    ap.add_argument("--url", default=None,
                    help="router exporter base url "
                         "(http://host:port)")
    ap.add_argument("--snapshot", default=None, metavar="DIR",
                    help="offline mode: render from a triage dir "
                         "(history_snapshot.json [+ tenants.json, "
                         "health.json])")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (offline mode "
                         "implies it)")
    args = ap.parse_args(argv)
    if bool(args.url) == bool(args.snapshot):
        ap.error("exactly one of --url / --snapshot")
    if args.snapshot:
        print(render(collect_snapshot(args.snapshot)))
        return 0
    while True:
        frame = collect_live(args.url.rstrip("/"))
        text = render(frame)
        if args.once:
            print(text)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + text + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
