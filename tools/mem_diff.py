"""mem_diff — compare two memory-ledger snapshots per segment.

"The KV pool grew", "prefix sidecars doubled", "unattributed bytes
are climbing" become CHECKABLE: point this at two ledger snapshot
files (``MemoryLedger.save()`` artifacts — the typed segment tree +
the ground-truth residual) and it reports per-SEGMENT byte deltas as
percent of the baseline — optionally failing on drift thresholds in
BOTH directions so a campaign stage can gate on them (the
profile_diff idiom, applied to device memory).

Percent of side A, not absolute bytes: two runs may serve different
models/pool sizes, so each segment's delta is normalized to its own
baseline (``(b - a) / max(a, 1) * 100``). A segment absent from a
side reads as 0 bytes — a brand-new segment on side B reads as a
huge growth and DOES trip a ``>`` gate (that is the point); a
segment that vanished trips a ``<`` gate.

Usage:
  python tools/mem_diff.py old.json new.json
  python tools/mem_diff.py A.json B.json \\
      --fail-on 'segment:kv_pages>+25%' \\
      --fail-on 'segment:unattributed>+50%' \\
      --fail-on 'segment:weights<-10%'

--fail-on SPEC grammar: ``segment:<name>{>|<}{+|-}PCT%`` — <name> a
typed ledger segment (kv_pages, prefix_sidecar, spec_draft_pool,
weights, optimizer_state, grads, activations_peak, other) or one of
the pseudo-segments ``attributed`` / ``unattributed`` / ``total``
(attributed + unattributed). ``>`` fails when B exceeds A by more
than PCT percent of A (leak-like: growing is worse); ``<`` fails
when B undershoots A by more than PCT percent (coverage-like: a
segment that vanished). The sign on PCT is cosmetic.

Vacuity guard: two snapshots whose totals are BOTH zero fail loudly
instead of green-lighting — a gate that compared nothing proved
nothing.

Last stdout line is a JSON report; exit 0 iff no --fail-on tripped.
Stdlib-only (loads memledger straight from its file via
bench._obs_mod — no jax, no package import).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import _obs_mod  # noqa: E402

PSEUDO = ("attributed", "unattributed", "total")

_SPEC_RE = re.compile(
    r"^segment:(?P<key>.+?)"
    r"(?P<op>[<>])(?P<sign>[+-]?)(?P<pct>\d+(?:\.\d+)?)%?$")


def parse_spec(s):
    m = _SPEC_RE.match(s.strip())
    if not m:
        raise argparse.ArgumentTypeError(
            f"bad --fail-on spec {s!r} "
            "(grammar: segment:<name>{>|<}{+|-}PCT%)")
    return {"key": m.group("key"), "op": m.group("op"),
            "pct": float(m.group("pct")), "spec": s.strip()}


def load_segments(path):
    """Snapshot file -> {segment: bytes} incl. the pseudo-segments."""
    ml = _obs_mod("memledger")
    doc = ml.load_snapshot(path)
    dg = doc.get("digest") or {}
    segs = {str(k): int(v) for k, v in (dg.get("segments")
                                        or {}).items()}
    att = int(dg.get("attributed_bytes") or sum(segs.values()))
    un = int(dg.get("unattributed_bytes") or 0)
    segs["attributed"] = att
    segs["unattributed"] = un
    segs["total"] = att + un
    return segs


def _delta_table(a, b):
    """Per-segment table {seg: {a, b, delta_pct}} — B's bytes as a
    percent change over A's (A==0, B>0 reads as +inf growth: a
    brand-new segment is maximal drift, not division noise). Sorted
    by |delta|."""
    rows = {}
    for key in set(a) | set(b):
        ba, bb = int(a.get(key, 0)), int(b.get(key, 0))
        if ba == 0:
            d = 0.0 if bb == 0 else float("inf")
        else:
            d = (bb - ba) / float(ba) * 100.0
        rows[key] = {"a": ba, "b": bb,
                     "delta_pct": (d if d in (float("inf"),)
                                   else round(d, 4))}
    return dict(sorted(
        rows.items(),
        key=lambda kv: -abs(kv[1]["delta_pct"])
        if kv[1]["delta_pct"] != float("inf") else float("-inf")))


def check_fail_on(rows, specs):
    failures = []
    for spec in specs:
        row = rows.get(spec["key"],
                       {"a": 0, "b": 0, "delta_pct": 0.0})
        d = row["delta_pct"]
        bad = d > spec["pct"] if spec["op"] == ">" \
            else d < -spec["pct"]
        if bad:
            failures.append({"spec": spec["spec"],
                             "key": f"segment:{spec['key']}",
                             "a": row["a"], "b": row["b"],
                             "delta_pct": d})
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two memory-ledger snapshots on per-segment "
                    "byte deltas (percent of baseline)")
    ap.add_argument("a", help="baseline ledger snapshot (.json)")
    ap.add_argument("b", help="candidate ledger snapshot (.json)")
    ap.add_argument("--fail-on", action="append", type=parse_spec,
                    default=[], metavar="segment:NAME{>|<}PCT%",
                    help="byte-drift threshold as percent of the "
                         "baseline segment (repeatable; both "
                         "directions)")
    ap.add_argument("--top", type=int, default=12,
                    help="rows in the human-readable table")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human-readable section")
    args = ap.parse_args(argv)

    segs_a = load_segments(args.a)
    segs_b = load_segments(args.b)
    rows = _delta_table(segs_a, segs_b)
    failures = check_fail_on(rows, args.fail_on)
    vacuous = segs_a["total"] == 0 and segs_b["total"] == 0
    if vacuous:
        failures.append({
            "spec": "(vacuity guard)", "key": None, "a": 0, "b": 0,
            "delta_pct": 0.0,
            "error": "both snapshots are empty — nothing was "
                     "compared"})

    report = {"a": args.a, "b": args.b,
              "total_bytes": {"a": segs_a["total"],
                              "b": segs_b["total"]},
              "segments": rows,
              "fail_on": [s["spec"] for s in args.fail_on],
              "failures": failures, "vacuous": vacuous,
              "ok": not failures}

    if not args.quiet:
        for key, r in list(rows.items())[:args.top]:
            print(f"  segment {key}: {r['a']} -> {r['b']} B "
                  f"({r['delta_pct']:+}%)", file=sys.stderr)
        for f in failures:
            print(f"  FAIL {f['spec']}: {f.get('key')} "
                  f"{f.get('a')} -> {f.get('b')} "
                  f"({f.get('delta_pct'):+}%)", file=sys.stderr)
    print(json.dumps(report, default=str))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
