"""tpu-lint — AST invariant checkers for this repo's hard-won
contracts (trace-safety, durability, concurrency, telemetry validity,
doc-catalogue sync). Stdlib-only; see docs/static_analysis.md.

Entry points: ``python -m tools.tpulint`` (CLI, the campaign's
``staticcheck`` stage) and ``run_lint()`` (in-process — what
tests/test_tpulint.py drives).
"""
from .core import (Baseline, Finding, load_baseline, run_lint,  # noqa: F401
                   write_baseline, write_report)
from .rules import RULES, active_rules  # noqa: F401
