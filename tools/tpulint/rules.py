"""tpulint rules — the repo's hard-won contracts as AST checkers.

Each rule distills a class of bug this repo has actually shipped and
then chased through chaos drills (docs/static_analysis.md maps every
rule to the CHANGES.md incident that motivated it):

- TRC01 untraced-jit       raw jax.jit/pjit outside the RecompileTracer
- TRC02 retrace-risk       host impurity / Python branches in traced bodies
- DUR01 raw-durable-write  journal/checkpoint/flight/golden writes
                           bypassing io/atomic
- CON01 lock-discipline    guarded registry/store state touched outside
                           the owning lock
- OBS01 json-validity      telemetry json.dump(s) without the
                           non-finite-safe (allow_nan=False) discipline
- MEM01 untracked-alloc    alloc_pages sites with no memory-ledger
                           pairing (track/track_bytes/set_level) in
                           the same function
- DOC01 catalogue-drift    emitted fleet_* metrics / PADDLE_TPU_* knobs
                           vs the committed doc tables, both directions

All stdlib. Checkers must stay SYNTACTIC and conservative: a rule that
cries wolf gets disabled; a miss is caught by the chaos drills the way
it always was. Suppress intentional sites inline
(``# tpulint: disable=RULE`` with a reason in the same comment) or
grandfather them in ``baseline.json`` with a justification.
"""
from __future__ import annotations

import ast
import fnmatch
import glob
import os
import re

from .core import Finding

__all__ = ["RULES", "active_rules"]


class Rule:
    def __init__(self, id, name, doc, fn, project_level=False):
        self.id = id
        self.name = name
        self.doc = doc
        self._fn = fn
        self.project_level = project_level

    def check(self, ctx):
        return self._fn(ctx)

    def check_project(self, ctxs, root):
        return self._fn(ctxs, root)


RULES = {}


def _register(id, name, doc, project_level=False):
    def deco(fn):
        RULES[id] = Rule(id, name, doc, fn, project_level)
        return fn
    return deco


def active_rules(ids=None):
    if not ids:
        return list(RULES.values())
    unknown = [i for i in ids if i not in RULES]
    if unknown:
        raise KeyError(f"unknown rule(s): {unknown}; "
                       f"known: {sorted(RULES)}")
    return [RULES[i] for i in ids]


# -- shared AST helpers -----------------------------------------------------

def _dotted(node):
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _Imports:
    """Per-file import facts: what names mean 'jax' / 'jax.jit'."""

    def __init__(self, tree):
        self.jax_aliases = set()        # names bound to the jax module
        self.jit_names = set()          # names bound to jax.jit / pjit
        self.pjit_mod_aliases = set()   # names bound to the pjit module
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        self.jax_aliases.add(a.asname
                                             or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                if node.module == "jax":
                    for a in node.names:
                        if a.name in ("jit", "pjit"):
                            self.jit_names.add(a.asname or a.name)
                if node.module.startswith("jax.experimental"):
                    for a in node.names:
                        if a.name == "pjit":
                            # `from jax.experimental.pjit import pjit`
                            # binds the FUNCTION; `from jax.experimental
                            # import pjit` binds the MODULE — treat the
                            # name as both (call-form disambiguates)
                            self.jit_names.add(a.asname or a.name)
                            self.pjit_mod_aliases.add(a.asname
                                                      or a.name)

    @classmethod
    def of(cls, ctx):
        imp = ctx.cache.get("imports")
        if imp is None:
            imp = ctx.cache["imports"] = cls(ctx.tree)
        return imp

    def raw_jit_symbol(self, node):
        """'jax.jit' / 'pjit' when `node` is a raw jit/pjit reference
        (NOT a tracer's .jit method), else None."""
        if isinstance(node, ast.Name):
            return node.id if node.id in self.jit_names else None
        d = _dotted(node)
        if not d:
            return None
        root, leaf = d.split(".")[0], d.split(".")[-1]
        if leaf in ("jit", "pjit") and root in self.jax_aliases:
            return d
        if leaf == "pjit" and root in self.pjit_mod_aliases:
            return d
        return None


def _call_mode_arg(call):
    """The `mode` argument of an open() call, if a string constant."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None


# -- TRC01: untraced jit ----------------------------------------------------

_TRC01_EXEMPT = {
    # the tracer IS the sanctioned jax.jit site
    "paddle_tpu/observability/trace.py",
}


@_register(
    "TRC01", "untraced-jit",
    "jax.jit/pjit not routed through a RecompileTracer site — the "
    "compile is invisible to zero-recompile accounting "
    "(report_all(), the serving compile-count freeze, the sentinel's "
    "delta signal). Route through tracer.jit(site, fn); probes that "
    "measure compiles themselves belong in the baseline.")
def _trc01(ctx):
    if ctx.path in _TRC01_EXEMPT:
        return []
    imports = _Imports.of(ctx)
    out = []

    def hit(node, expr):
        sym = imports.raw_jit_symbol(expr)
        if sym:
            out.append(ctx.finding(
                "TRC01", node, sym,
                f"raw {sym} call bypasses the RecompileTracer — route "
                f"through tracer.jit(site, fn) so the compile lands in "
                f"zero-recompile accounting"))
            return True
        return False

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            hit(node, node.func)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    # @partial(jax.jit, ...): the Call walk above sees
                    # partial(), not jax.jit — check its first arg
                    d = _dotted(dec.func) or ""
                    if d.split(".")[-1] == "partial" and dec.args:
                        hit(dec, dec.args[0])
                else:
                    hit(dec, dec)
    return out


# -- TRC02: retrace risk ----------------------------------------------------

_TRC02_IMPURE = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.now", "os.urandom", "uuid.uuid1", "uuid.uuid4",
    "random.random", "random.randint", "random.uniform",
    "random.choice", "random.shuffle", "random.sample", "os.getenv",
}
_TRC02_IMPURE_PREFIXES = ("np.random.", "numpy.random.", "os.environ")
_TRACED_WRAPPER_LEAVES = {"scan", "while_loop", "fori_loop", "cond"}


def _traced_bodies(ctx, imports):
    """FunctionDef/Lambda nodes whose bodies run under a jax trace:
    jit-decorated defs, fns passed to jax.jit / tracer.jit(site, fn),
    and bodies handed to lax.scan / while_loop / fori_loop / cond.
    Name references resolve in the CALL's enclosing scope (innermost
    def whose body defines that name), so a scan body called `step`
    can never alias an unrelated method named `step` elsewhere in the
    file."""
    traced_nodes = set()
    parents = ctx.parents()

    def _find_def(scope, name):
        # DIRECT children only — lexical scoping, so a class method
        # named like a scan body elsewhere can never alias it
        for n in getattr(scope, "body", ()):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name == name:
                return n
        return None

    def note_arg(arg, at):
        if isinstance(arg, (ast.Lambda, ast.FunctionDef)):
            traced_nodes.add(id(arg))
            return
        if not isinstance(arg, ast.Name):
            return
        node = at
        while id(node) in parents:
            node = parents[id(node)]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Module)):
                hit = _find_def(node, arg.id)
                if hit is not None:
                    traced_nodes.add(id(hit))
                    return
                if isinstance(node, ast.Module):
                    return

    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                expr = dec
                if isinstance(dec, ast.Call):
                    d = _dotted(dec.func) or ""
                    expr = dec.args[0] if (
                        d.split(".")[-1] == "partial" and dec.args) \
                        else dec.func
                if imports.raw_jit_symbol(expr):
                    traced_nodes.add(id(node))
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func) or ""
        leaf = d.split(".")[-1] if d else ""
        root = d.split(".")[0] if d else ""
        if imports.raw_jit_symbol(node.func) and node.args:
            note_arg(node.args[0], node)
        elif leaf == "jit" and not imports.raw_jit_symbol(node.func) \
                and len(node.args) >= 2:
            # tracer.jit(site, fn) — the fn is still a traced body
            note_arg(node.args[1], node)
        elif leaf in _TRACED_WRAPPER_LEAVES and root and (
                root in imports.jax_aliases or root == "lax"):
            # scan/while_loop(cond_fn, body_fn)/fori_loop(lo, hi, body)
            # /cond(pred, true_fn, false_fn): every callable positional
            # arg is a traced body
            for a in node.args:
                note_arg(a, node)
    return traced_nodes


def _param_names(fn):
    names = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.Lambda)):
        a = fn.args
        for arg in (list(a.posonlyargs) + list(a.args)
                    + list(a.kwonlyargs)):
            names.add(arg.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
    return names


@_register(
    "TRC02", "retrace-risk",
    "host impurity (wall clocks, RNG, environment reads) or a Python "
    "if/while on a traced value inside a jitted/scanned body — the "
    "impurity freezes at trace time or forces data-dependent "
    "retracing; use lax.cond/jnp.where and pass host values as args.")
def _trc02(ctx):
    imports = _Imports.of(ctx)
    traced = _traced_bodies(ctx, imports)
    if not traced:
        return []
    out = []
    analyzed = set()   # a body nested inside a traced body is reached
    #                    both via visit()'s recursion and the traced
    #                    set — analyze once or findings double-count

    def analyze(fn):
        if id(fn) in analyzed:
            return
        analyzed.add(id(fn))
        tainted = set(_param_names(fn))
        body = fn.body if isinstance(fn.body, list) else [fn.body]

        def _static_roots(e):
            """Subtrees whose VALUE is static under trace even when
            rooted at a tainted name: `.ndim/.shape/.dtype/.size`
            attribute reads and `len(x)` — trace-time Python ints the
            bucket-drift bug can't ride on."""
            roots = set()
            for n in ast.walk(e):
                if isinstance(n, ast.Attribute) and n.attr in (
                        "ndim", "shape", "dtype", "size"):
                    roots.update(id(c) for c in ast.walk(n))
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Name) \
                        and n.func.id in ("len", "isinstance", "type"):
                    roots.update(id(c) for c in ast.walk(n))
            return roots

        def expr_tainted(e, ignore_static=False):
            skip = _static_roots(e) if ignore_static else ()
            return any(isinstance(n, ast.Name) and n.id in tainted
                       and id(n) not in skip
                       for n in ast.walk(e))

        def test_on_traced(test):
            """True only for a COMPARISON or arithmetic on a tainted
            VALUE (`if x > 0`, `while n < k`) — the bucket-drift bug.
            Bare truthiness (`if labels:`, `if not labels:`),
            `is None`, and static-metadata reads (`x.ndim == 3`,
            `len(xs) > 1`) are trace-time pytree/shape tests, legal
            under trace."""
            for n in ast.walk(test):
                if isinstance(n, ast.Compare):
                    none_cmp = all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in n.ops)
                    if not none_cmp and (
                            expr_tainted(n.left, ignore_static=True)
                            or any(expr_tainted(c, ignore_static=True)
                                   for c in n.comparators)):
                        return True
                elif isinstance(n, ast.UnaryOp) \
                        and not isinstance(n.op, ast.Not) \
                        and expr_tainted(n, ignore_static=True):
                    return True
                elif isinstance(n, ast.BinOp) \
                        and expr_tainted(n, ignore_static=True):
                    return True
            return False

        def visit(node):
            # a nested def/lambda inherits the traced context
            # (closures over tracers trace too) but gets its own
            # params — and must go through analyze() exactly once,
            # whether reached here or via the traced set
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                analyze(node)
                return
            if isinstance(node, ast.Assign):
                if expr_tainted(node.value):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d and (d in _TRC02_IMPURE or any(
                        d.startswith(p)
                        for p in _TRC02_IMPURE_PREFIXES)):
                    out.append(ctx.finding(
                        "TRC02", node, d,
                        f"{d}() inside a traced body executes at "
                        f"TRACE time only — its value freezes into "
                        f"the compiled program (pass it in as an "
                        f"argument instead)"))
            if isinstance(node, ast.Subscript):
                d = _dotted(node.value)
                if d == "os.environ":
                    out.append(ctx.finding(
                        "TRC02", node, "os.environ",
                        "os.environ read inside a traced body freezes "
                        "at trace time"))
            if isinstance(node, (ast.If, ast.While)):
                if test_on_traced(node.test):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(ctx.finding(
                        "TRC02", node, f"{kind}-on-traced",
                        f"Python `{kind}` on a traced value — this "
                        f"either fails to trace or silently retraces "
                        f"per branch; use lax.cond/lax.select/"
                        f"jnp.where"))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in body:
            visit(stmt)

    for node in ast.walk(ctx.tree):
        if id(node) in traced:
            analyze(node)
    return out


# -- DUR01: raw durable writes ----------------------------------------------

_DUR01_DURABLE_FILES = {
    "paddle_tpu/io/checkpoint.py",
    "paddle_tpu/serving_fleet/journal.py",
    "paddle_tpu/observability/flightrec.py",
    "paddle_tpu/observability/history.py",
    "paddle_tpu/observability/trafficrec.py",
    # the AOT serving-artifact store: torn StableHLO blobs or manifests
    # feed straight into jax.export.deserialize at the next boot
    "paddle_tpu/jit/serving_artifact.py",
}
_DUR01_EXEMPT = {
    # io/atomic.py IS the write-then-rename discipline
    "paddle_tpu/io/atomic.py",
}
_DUR01_TOKENS = ("journal", "wal-", "ckpt", "checkpoint", "flight_",
                 "golden", ".complete", ".stablehlo", "manifest")


@_register(
    "DUR01", "raw-durable-write",
    "write-mode open()/os.rename/os.replace on a durable artifact "
    "path (journal/checkpoint/flight/golden) outside io/atomic — a "
    "crash mid-write leaves a torn file no reader tolerates; route "
    "through io.atomic.atomic_replace/write_marker/unique_path.")
def _dur01(ctx):
    if ctx.path in _DUR01_EXEMPT:
        return []
    durable_file = ctx.path in _DUR01_DURABLE_FILES
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        sym = None
        if isinstance(node.func, ast.Name) and node.func.id == "open":
            mode = _call_mode_arg(node)
            if mode and ("w" in mode or "x" in mode):
                sym = f'open(mode="{mode}")'
        elif d in ("os.rename", "os.replace"):
            sym = d
        if sym is None:
            continue
        if durable_file:
            out.append(ctx.finding(
                "DUR01", node, sym,
                f"{sym} inside a durable-artifact module bypasses "
                f"io/atomic's write-then-rename + marker discipline"))
            continue
        seg = ctx.segment(node).lower()
        if any(t in seg for t in _DUR01_TOKENS):
            out.append(ctx.finding(
                "DUR01", node, sym,
                f"{sym} on what looks like a durable artifact path — "
                f"route through io/atomic so a crash can't tear it"))
    return out


# -- CON01: lock discipline -------------------------------------------------

# scoped to the classes the exporter's HTTP threads actually read
# concurrently with dispatch (the ISSUE 13 contract); widen the set as
# new shared-state stores grow scrape-side readers
_CON01_FILES = {
    "paddle_tpu/observability/metrics.py",
    "paddle_tpu/observability/dtrace.py",
}


def _con01_class_findings(ctx, cls):
    init = next((n for n in cls.body
                 if isinstance(n, ast.FunctionDef)
                 and n.name == "__init__"), None)
    if init is None:
        return []
    lock_attr = None
    containers = set()
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            continue
        v = node.value
        d = _dotted(getattr(v, "func", None)) or ""
        leaf = d.split(".")[-1]
        if leaf in ("Lock", "RLock") and "lock" in t.attr.lower():
            lock_attr = lock_attr or t.attr
        elif t.attr.startswith("_") and (
                isinstance(v, (ast.Dict, ast.List, ast.Set))
                or leaf in ("dict", "list", "set", "OrderedDict",
                            "deque", "defaultdict", "Counter")):
            containers.add(t.attr)
    if not lock_attr or not containers:
        return []

    locked_attrs = set()
    unlocked_sites = []   # (node, attr, method_name)

    def scan(node, in_lock, method):
        if isinstance(node, ast.With):
            # exact match on `self.<lock_attr>`: a substring test
            # would count `with global_lock:` / `with other._lock:`
            # as holding THIS object's lock and miss the torn-scrape
            # race the rule exists to catch
            holds = any(_dotted(item.context_expr)
                        == f"self.{lock_attr}"
                        for item in node.items)
            for item in node.items:
                scan(item, in_lock, method)
            for child in node.body:
                scan(child, in_lock or holds, method)
            return
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr in containers:
            if in_lock:
                locked_attrs.add(node.attr)
            else:
                unlocked_sites.append((node, node.attr, method))
        for child in ast.iter_child_nodes(node):
            scan(child, in_lock, method)

    for meth in cls.body:
        if isinstance(meth, ast.FunctionDef) and meth.name != "__init__":
            for stmt in meth.body:
                scan(stmt, False, meth.name)

    out = []
    for node, attr, method in unlocked_sites:
        if attr not in locked_attrs:
            continue   # never lock-guarded anywhere: not this rule's
            #            contract (single-thread state)
        out.append(ctx.finding(
            "CON01", node, f"self.{attr}",
            f"{cls.name}.{method} touches self.{attr} outside `with "
            f"self.{lock_attr}` — an exporter scrape thread can see "
            f"it mid-mutation (torn dict resize / inconsistent "
            f"snapshot)"))
    return out


@_register(
    "CON01", "lock-discipline",
    "state of a lock-owning class (MetricsRegistry, TraceStore) read "
    "or mutated outside the owning lock's `with` scope — the exporter "
    "HTTP threads scrape these concurrently with dispatch.")
def _con01(ctx):
    if ctx.path not in _CON01_FILES:
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            out.extend(_con01_class_findings(ctx, node))
    return out


# -- OBS01: telemetry JSON validity -----------------------------------------

_OBS01_SCOPES = ("paddle_tpu/observability/", "paddle_tpu/serving_fleet/")


@_register(
    "OBS01", "json-validity",
    "json.dump(s) without allow_nan=False on a telemetry path — a NaN "
    "gauge (a storm's train_loss) would emit a bare NaN token that "
    "jq/JS consumers reject; use the try/allow_nan=False + _finite() "
    "fallback discipline every exporter in the repo follows.")
def _obs01(ctx):
    if not ctx.path.startswith(_OBS01_SCOPES):
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d not in ("json.dump", "json.dumps"):
            continue
        ok = any(kw.arg == "allow_nan"
                 and isinstance(kw.value, ast.Constant)
                 and kw.value.value is False
                 for kw in node.keywords)
        if not ok:
            out.append(ctx.finding(
                "OBS01", node, d,
                f"{d} without allow_nan=False on a telemetry path — "
                f"non-finite floats would emit invalid JSON; use the "
                f"allow_nan=False + _finite() fallback discipline"))
    return out


# -- MEM01: untracked device allocation -------------------------------------

# a ledger pairing is any call whose attribute leaf is one of these —
# track/track_bytes for owner-managed buffers, set_level for
# recomputed inventories (the two attribution channels)
_MEM01_PAIRING = {"track", "track_bytes", "set_level"}
_MEM01_EXEMPT = {
    # the allocator's own home: defines alloc_pages, never consumes it
    "paddle_tpu/nlp/paged_cache.py",
}


def _mem01_scope(ctx, node):
    """Innermost enclosing function of ``node`` (module tree when
    top-level) — the scope a pairing call must appear in."""
    parents = ctx.parents()
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(id(cur))
    return ctx.tree


@_register(
    "MEM01", "untracked-alloc",
    "alloc_pages(...) with no memory-ledger pairing (a .track / "
    ".track_bytes / .set_level call) in the same function — device "
    "bytes the segment tree cannot name land in unattributed_bytes "
    "and eventually trip the residual alarm with no owner to blame. "
    "Pair the allocation in the same function (dormant engines: "
    "guard on `ledger is not None`, the serving/speculative seam "
    "pattern), or baseline a deliberate exception with a reason.")
def _mem01(ctx):
    if ctx.path in _MEM01_EXEMPT:
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func) or ""
        if d.split(".")[-1] != "alloc_pages":
            continue
        scope = _mem01_scope(ctx, node)
        paired = any(
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and n.func.attr in _MEM01_PAIRING
            for n in ast.walk(scope))
        if not paired:
            out.append(ctx.finding(
                "MEM01", node, d,
                "alloc_pages call with no ledger pairing "
                "(track/track_bytes/set_level) in the same function "
                "— the block's bytes are invisible to the memory "
                "ledger's segment tree"))
    return out


# -- DOC01: catalogue drift -------------------------------------------------

_KNOB_RE = re.compile(r"PADDLE_TPU_[A-Z][A-Z0-9_]*")
_DOC_METRIC_FILE = "docs/observability.md"
_DOC_KNOB_FILES = ("README.md", "tools/README.md")
# a call creates a metric series when its callee name carries one of
# these markers — covers registry.counter(...), the shared
# labeled_counter() helper, and per-class wrappers like slo's
# self._gauge(...)
_EMIT_MARKERS = ("counter", "gauge", "histogram", "metric", "labeled")
_METRIC_NAME_RE = re.compile(r"fleet_[a-z0-9_]+\Z")


def _is_emit_call(node):
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    leaf = (d or "").split(".")[-1].lower()
    return any(m in leaf for m in _EMIT_MARKERS)


def _resolve_fstring(ctx, call, joined):
    """Resolve the repo's for-loop metric-name idiom:
    ``for name, h in (("a", ...), ("b", ...)): reg.counter(
    f"fleet_x_{name}_total")`` -> concrete names. Unresolvable parts
    become '*' (a wildcard pattern)."""
    parents = ctx.parents()

    def enclosing_for_binding(name):
        node = call
        while id(node) in parents:
            node = parents[id(node)]
            if not isinstance(node, ast.For):
                continue
            t, it = node.target, node.iter
            if not isinstance(it, (ast.Tuple, ast.List)):
                continue
            if isinstance(t, ast.Name) and t.id == name:
                vals = [e.value for e in it.elts
                        if isinstance(e, ast.Constant)]
                if len(vals) == len(it.elts):
                    return vals
            if isinstance(t, ast.Tuple):
                for i, el in enumerate(t.elts):
                    if isinstance(el, ast.Name) and el.id == name:
                        vals = []
                        for row in it.elts:
                            if isinstance(row, (ast.Tuple, ast.List)) \
                                    and i < len(row.elts) \
                                    and isinstance(row.elts[i],
                                                   ast.Constant):
                                vals.append(row.elts[i].value)
                            else:
                                return None
                        return vals
        return None

    results = [""]
    exact = True
    for part in joined.values:
        if isinstance(part, ast.Constant):
            results = [r + str(part.value) for r in results]
        elif isinstance(part, ast.FormattedValue) \
                and isinstance(part.value, ast.Name):
            vals = enclosing_for_binding(part.value.id)
            if vals:
                results = [r + str(v) for r in results for v in vals]
            else:
                results = [r + "*" for r in results]
                exact = False
        else:
            results = [r + "*" for r in results]
            exact = False
    return results, exact


def _expand_braces(token):
    m = re.search(r"\{([^{}]*)\}", token)
    if not m:
        return [token]
    out = []
    for alt in m.group(1).split(","):
        out.extend(_expand_braces(token[:m.start()] + alt.strip()
                                  + token[m.end():]))
    return out


def _doc_metric_rows(root):
    """fleet_* names (with line numbers) from docs/observability.md's
    '## Metric catalogue' table, brace lists and comma cells
    expanded."""
    path = os.path.join(root, _DOC_METRIC_FILE)
    rows = {}
    try:
        lines = open(path, encoding="utf-8").read().splitlines()
    except OSError:
        return rows, False
    in_section = False
    for i, line in enumerate(lines, start=1):
        if line.startswith("## "):
            in_section = line.strip() == "## Metric catalogue"
            continue
        if not in_section or not line.startswith("|"):
            continue
        first_cell = line.split("|")[1] if "|" in line[1:] else ""
        for tok in re.findall(r"`([^`]+)`", first_cell):
            for name in _expand_braces(tok.strip()):
                if re.fullmatch(r"fleet_[a-z0-9_]+", name):
                    rows.setdefault(name, i)
    return rows, True


def _doc_knob_mentions(root):
    """PADDLE_TPU_* tokens across the committed doc set, with one
    (file, line) locator each."""
    out = {}
    files = [os.path.join(root, f) for f in _DOC_KNOB_FILES]
    files += sorted(glob.glob(os.path.join(root, "docs", "*.md")))
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            lines = open(path, encoding="utf-8").read().splitlines()
        except OSError:
            continue
        for i, line in enumerate(lines, start=1):
            for knob in _KNOB_RE.findall(line):
                out.setdefault(knob, (rel, i))
    return out


@_register(
    "DOC01", "catalogue-drift",
    "emitted fleet_* metrics and PADDLE_TPU_* env knobs must match "
    "the committed doc tables (docs/observability.md catalogue + env "
    "knob table), BOTH directions: an undocumented emission is "
    "invisible to operators; a documented ghost misleads them.",
    project_level=True)
def _doc01(ctxs, root):
    out = []
    code_metrics = {}     # literal name -> (ctx, node)
    code_patterns = {}    # wildcard pattern -> (ctx, node)
    code_knobs = {}       # knob -> (ctx, lineno)
    code_strings = set()  # every fleet_* string constant anywhere —
    #                       the generous "still alive in code" set the
    #                       docs->code direction checks against
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if _is_emit_call(node):
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                        if kw.arg in (None, "name")]:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str) \
                            and _METRIC_NAME_RE.fullmatch(arg.value):
                        code_metrics.setdefault(arg.value, (ctx, node))
                    elif isinstance(arg, ast.JoinedStr):
                        names, exact = _resolve_fstring(ctx, node, arg)
                        for n in names:
                            if not n.startswith("fleet_"):
                                continue
                            if exact:
                                code_metrics.setdefault(n, (ctx, node))
                            else:
                                code_patterns.setdefault(n, (ctx, node))
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                for knob in _KNOB_RE.findall(node.value):
                    code_knobs.setdefault(
                        knob, (ctx, getattr(node, "lineno", 1)))
                code_strings.update(
                    re.findall(r"fleet_[a-z0-9_]+", node.value))
            elif isinstance(node, ast.JoinedStr):
                for part in node.values:
                    if isinstance(part, ast.Constant):
                        for knob in _KNOB_RE.findall(str(part.value)):
                            code_knobs.setdefault(
                                knob,
                                (ctx, getattr(node, "lineno", 1)))

    doc_metrics, have_doc = _doc_metric_rows(root)
    if have_doc:
        for name, (ctx, node) in sorted(code_metrics.items()):
            if name not in doc_metrics:
                out.append(ctx.finding(
                    "DOC01", node, name,
                    f"emitted metric `{name}` has no row in "
                    f"{_DOC_METRIC_FILE}'s metric catalogue"))
        for pat, (ctx, node) in sorted(code_patterns.items()):
            if not any(fnmatch.fnmatchcase(n, pat)
                       for n in doc_metrics):
                out.append(ctx.finding(
                    "DOC01", node, pat,
                    f"no catalogue row in {_DOC_METRIC_FILE} matches "
                    f"emitted metric pattern `{pat}`"))
        for name, line in sorted(doc_metrics.items()):
            if name in code_metrics or name in code_strings:
                continue
            if any(fnmatch.fnmatchcase(name, p)
                   for p in code_patterns):
                continue
            out.append(Finding(
                "DOC01", _DOC_METRIC_FILE, line, 0,
                "metric-catalogue", name,
                f"catalogue row `{name}` appears nowhere in the "
                f"scanned code — stale doc row (or a lost emission)"))

    doc_knobs = _doc_knob_mentions(root)
    for knob, (ctx, lineno) in sorted(code_knobs.items()):
        if knob not in doc_knobs:
            out.append(Finding(
                "DOC01", ctx.path, lineno, 0, "env-knobs", knob,
                f"env knob {knob} is read in code but documented "
                f"nowhere (docs/*.md, README.md, tools/README.md) — "
                f"add it to docs/observability.md's knob table"))
    for knob, (rel, line) in sorted(doc_knobs.items()):
        if knob not in code_knobs:
            out.append(Finding(
                "DOC01", rel, line, 0, "env-knobs", knob,
                f"doc mention of {knob} matches no string in the "
                f"scanned code — stale knob (or a renamed one)"))
    return out
