"""tpulint core — findings, suppressions, baseline, per-file driver.

The analyzer half of the suite: rules live in ``rules.py``, the CLI in
``__main__.py``. Everything here is stdlib-only (``ast``) so
the linter runs in the jax-free campaign orchestrator, CI shells and
the tier-1 test process alike, and never pays an accelerator import.

Design contracts (docs/static_analysis.md is the operator page):

- **Findings are line-drift-stable.** A finding's identity is
  ``(rule, path, qualname, symbol)`` — the enclosing function/class
  qualname plus a stable symbol (the offending call/name), NEVER the
  line number. Reformatting a file cannot invalidate the baseline.
- **Suppressions are inline and rule-scoped.** ``# tpulint:
  disable=RULE[,RULE]`` on the finding's first line, or
  ``# tpulint: disable-next-line=RULE`` on the line above. A
  suppression silences exactly the named rules, nothing else.
- **The baseline grandfathers, never hides.** ``baseline.json``
  entries carry a one-line justification; matched findings are still
  reported (``baselined: true``) and counted, they just don't fail
  the gate. Unused baseline entries are reported so the file can only
  shrink as debt is paid down.
"""
from __future__ import annotations

import ast
import json
import os
import re

__all__ = ["Finding", "FileCtx", "Baseline", "run_lint",
           "load_baseline", "write_baseline", "write_report",
           "DEFAULT_TARGETS", "repo_root"]

# scan scope when the CLI is given no paths: the shipping source
# (tests/ is deliberately out — fixtures there seed violations)
DEFAULT_TARGETS = ("paddle_tpu", "tools", "bench.py")

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*(disable|disable-next-line)="
    r"([A-Z][A-Z0-9]*(?:\s*,\s*[A-Z][A-Z0-9]*)*)")


def repo_root():
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


class Finding:
    """One rule violation. Identity (``key``) is line-drift-stable:
    rule + file + enclosing qualname + symbol — never the line."""

    __slots__ = ("rule", "path", "line", "col", "qualname", "symbol",
                 "message", "baselined")

    def __init__(self, rule, path, line, col, qualname, symbol,
                 message):
        self.rule = rule
        self.path = path          # repo-relative, posix separators
        self.line = int(line)
        self.col = int(col)
        self.qualname = qualname or "<module>"
        self.symbol = symbol
        self.message = message
        self.baselined = False

    def key(self):
        return (self.rule, self.path, self.qualname, self.symbol)

    def to_json(self):
        return {"rule": self.rule, "path": self.path,
                "line": self.line, "col": self.col,
                "qualname": self.qualname, "symbol": self.symbol,
                "message": self.message, "baselined": self.baselined}

    def __repr__(self):
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.qualname}] {self.message}")


class FileCtx:
    """Parsed view of one source file handed to every checker."""

    def __init__(self, abspath, relpath, source, tree):
        self.abspath = abspath
        self.path = relpath
        self.source = source
        self.tree = tree
        self._qualnames = _qualname_map(tree)
        # per-file memo shared across rules (one thread per file, so
        # no lock needed): import facts, parent maps, … — rebuilding
        # these per rule (or per emit call) is O(file²) on bench.py
        self.cache = {}

    def parents(self):
        """id(child) -> parent node, built once per file."""
        p = self.cache.get("parents")
        if p is None:
            p = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    p[id(child)] = node
            self.cache["parents"] = p
        return p

    def qualname_of(self, node):
        return self._qualnames.get(id(node), "<module>")

    def segment(self, node):
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:  # noqa: BLE001 — cosmetic helper only
            return ""

    def finding(self, rule, node, symbol, message):
        return Finding(rule, self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0),
                       self.qualname_of(node), symbol, message)


def _qualname_map(tree):
    """id(node) -> dotted qualname of the innermost enclosing
    function/class (module-level nodes map to '<module>')."""
    out = {}

    def walk(node, stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            stack = stack + [node.name]
        qn = ".".join(stack) if stack else "<module>"
        out[id(node)] = qn
        for child in ast.iter_child_nodes(node):
            walk(child, stack)

    walk(tree, [])
    return out


def _suppressions(source):
    """{line_no: set(rules)} honoring both inline forms."""
    out = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        target = i + 1 if m.group(1) == "disable-next-line" else i
        out.setdefault(target, set()).update(rules)
    return out


# -- baseline ---------------------------------------------------------------

class Baseline:
    def __init__(self, entries):
        self.entries = list(entries)
        self._by_key = {}
        for e in self.entries:
            k = (e["rule"], e["path"], e.get("qualname", "<module>"),
                 e.get("symbol", ""))
            self._by_key[k] = e
        self._used = set()

    def matches(self, finding):
        k = finding.key()
        if k in self._by_key:
            self._used.add(k)
            return True
        return False

    def unused(self):
        return [e for e in self.entries
                if (e["rule"], e["path"], e.get("qualname", "<module>"),
                    e.get("symbol", "")) not in self._used]


def default_baseline_path():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path=None):
    path = path or default_baseline_path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        return Baseline([])
    return Baseline(doc.get("entries", []))


def write_baseline(findings, path=None, previous=None):
    """Regenerate the baseline from current findings, preserving the
    justification of every entry whose key survives; new entries get
    an UNREVIEWED marker that a reviewer must replace or fix.
    Returns (path, n_written, n_skipped) — skipped are PARSE/
    checker-error findings that must be FIXED, never grandfathered
    (the gate stays red until they are)."""
    path = path or default_baseline_path()
    prev = {}
    if previous is not None:
        for e in previous.entries:
            prev[(e["rule"], e["path"], e.get("qualname", "<module>"),
                  e.get("symbol", ""))] = e.get("justification", "")
    entries, seen, skipped = [], set(), 0
    for f in sorted(findings, key=lambda f: f.key()):
        k = f.key()
        if k in seen:
            continue
        seen.add(k)
        if f.rule == "PARSE" or f.symbol == "checker-error":
            # never grandfather an infrastructure failure: its key
            # carries no error content, so one baselined syntax error
            # would mask EVERY future syntax error in that file —
            # fix the file (or the checker), don't baseline it
            skipped += 1
            continue
        entries.append({
            "rule": f.rule, "path": f.path, "qualname": f.qualname,
            "symbol": f.symbol,
            "justification": prev.get(
                k, "UNREVIEWED — justify this grandfathering or fix "
                   "the finding"),
        })
    doc = {"version": 1,
           "comment": "Grandfathered tpulint findings. Match is on "
                      "(rule, path, qualname, symbol) — stable under "
                      "line drift. Every entry needs a one-line "
                      "justification; delete entries as debt is paid.",
           "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path, len(entries), skipped


# -- driver -----------------------------------------------------------------

def _collect_files(root, targets):
    """(files, barren): `barren` are targets that contributed zero
    .py files — nonexistent, not-a-.py, or a dir with nothing to
    scan. Each must be a loud gate failure: a typo'd or hollowed-out
    CI path scanning nothing would otherwise read as green (or, for
    DOC01, as a stale-row storm over an empty scan set)."""
    files, barren = [], []
    for t in targets:
        p = os.path.join(root, t)
        n_before = len(files)
        if os.path.isfile(p) and p.endswith(".py"):
            files.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in sorted(dirnames)
                               if d not in ("__pycache__", ".git",
                                            "fixtures")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
        if len(files) == n_before:
            barren.append(t)
    return files, barren


def _parse_one(root, abspath):
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    try:
        with open(abspath, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel)
    except (OSError, SyntaxError, ValueError) as e:
        return rel, None, f"{type(e).__name__}: {e}"
    return rel, FileCtx(abspath, rel, source, tree), None


def run_lint(paths=None, rules=None, root=None, baseline=None):
    """Lint `paths` (files/dirs relative to `root`); returns the
    report dict (see write_report). `rules` filters to a subset of
    rule ids; `baseline` a Baseline (default: the committed one)."""
    from . import rules as rules_mod  # late: registry import order
    root = root or repo_root()
    targets = list(paths) if paths else list(DEFAULT_TARGETS)
    baseline = baseline if baseline is not None else load_baseline()
    active = rules_mod.active_rules(rules)
    per_file = [r for r in active if not r.project_level]
    project = [r for r in active if r.project_level]

    files, findings = [], []
    parsed = []
    collected, barren = _collect_files(root, targets)
    for t in barren:
        findings.append(Finding(
            "PARSE", t, 1, 0, "<module>", "missing-target",
            f"lint target {t!r} contributed zero .py files under "
            f"{root} — typo'd or hollowed-out path? (a vacuous scan "
            f"must not pass the gate)"))
    for abspath in collected:
        rel, ctx, err = _parse_one(root, abspath)
        files.append(rel)
        if err is not None:
            findings.append(Finding("PARSE", rel, 1, 0, "<module>",
                                    "syntax", err))
        else:
            parsed.append(ctx)

    def lint_file(ctx):
        out = []
        for r in per_file:
            try:
                out.extend(r.check(ctx) or ())
            except Exception as e:  # noqa: BLE001 — one broken rule
                #                     must not silently pass the file
                out.append(Finding(r.id, ctx.path, 1, 0, "<module>",
                                   "checker-error",
                                   f"checker crashed: "
                                   f"{type(e).__name__}: {e}"))
        return out

    # serial on purpose: the checkers are pure-Python AST walks, so a
    # thread pool is GIL-bound (no speedup, real overhead) — the whole
    # default sweep is single-digit seconds
    for ctx in parsed:
        findings.extend(lint_file(ctx))
    for r in project:
        try:
            findings.extend(r.check_project(parsed, root) or ())
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(r.id, "<project>", 1, 0,
                                    "<module>", "checker-error",
                                    f"checker crashed: "
                                    f"{type(e).__name__}: {e}"))

    # suppression pass (per finding line, against its own file)
    supp_by_path = {c.path: _suppressions(c.source) for c in parsed}
    kept, suppressed = [], 0
    for f in findings:
        rules_at = supp_by_path.get(f.path, {}).get(f.line, ())
        if f.rule in rules_at:
            suppressed += 1
            continue
        kept.append(f)

    non_baselined = 0
    for f in kept:
        f.baselined = baseline.matches(f)
        if not f.baselined:
            non_baselined += 1
    kept.sort(key=lambda f: (f.path, f.line, f.rule))

    by_rule = {}
    for f in kept:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    # only entries this run COULD have matched may be called unused:
    # a --rule/path-filtered run never sees the other rules'/paths'
    # findings, and reporting their entries as dead debt invites
    # deleting live justifications the full gate still needs
    active_ids = {r.id for r in active}
    tnorm = [t.rstrip("/") for t in targets]
    unused = [e for e in baseline.unused()
              if e["rule"] in active_ids
              and any(e["path"] == t or e["path"].startswith(t + "/")
                      for t in tnorm)]
    return {
        "version": 1,
        "tool": "tpulint",
        "targets": targets,
        "files_scanned": len(files),
        "rules_run": [r.id for r in active],
        "findings": [f.to_json() for f in kept],
        "counts": by_rule,
        "suppressed": suppressed,
        "baselined": sum(1 for f in kept if f.baselined),
        "non_baselined": non_baselined,
        "unused_baseline": unused,
        "_findings_objs": kept,   # in-process callers; stripped on dump
    }


def write_report(report, path):
    doc = {k: v for k, v in report.items() if not k.startswith("_")}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path
