"""tpulint CLI — `python -m tools.tpulint [paths...]`.

Exit status is the gate: 0 = no non-baselined findings, 1 = new
findings (or a syntax error in a scanned file). The machine-readable
report always lands at --report (default: $BENCH_TELEMETRY_DIR/
lint_report.json when the campaign exports one, else
./lint_report.json) so `tools/validate_stages.py` can verify the
staticcheck stage actually ran and came back clean.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (load_baseline, repo_root, run_lint, write_baseline,
                   write_report)
from .rules import RULES


def _default_report_path():
    tele = os.environ.get("BENCH_TELEMETRY_DIR")
    if tele:
        return os.path.join(tele, "lint_report.json")
    return "lint_report.json"


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="tpulint",
        description="AST invariant checkers for paddle_tpu's "
                    "trace-safety/durability/concurrency contracts")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: paddle_tpu, "
                         "tools, bench.py)")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID", help="run only these rule ids "
                    "(repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON on stdout "
                         "(last line stays machine-parseable either "
                         "way)")
    ap.add_argument("--report", default=None, metavar="PATH",
                    help="where to write lint_report.json (default: "
                         "$BENCH_TELEMETRY_DIR/lint_report.json or "
                         "./lint_report.json)")
    ap.add_argument("--baseline", default=None, metavar="PATH",
                    help="baseline file (default: the committed "
                         "tools/tpulint/baseline.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(keeps existing justifications; new entries "
                         "are marked UNREVIEWED)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--root", default=None,
                    help="repo root override (tests lint fixture "
                         "trees)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULES.values():
            print(f"{r.id}  {r.name}\n    {r.doc}\n")
        return 0

    if args.update_baseline and (args.rule or args.paths):
        # a filtered run sees only a slice of the findings; rewriting
        # from it would silently delete every other rule's entries —
        # and their hand-written justifications
        print("tpulint: --update-baseline requires a FULL run "
              "(no --rule, no explicit paths) — a filtered rewrite "
              "would drop every unseen entry", file=sys.stderr)
        return 2
    if args.update_baseline and args.root and not args.baseline:
        # a foreign-root run over DEFAULT_TARGETS finds (at best)
        # nothing and (at worst) missing-target PARSE rows — writing
        # THAT over the committed baseline deletes every justification
        print("tpulint: --update-baseline with --root needs an "
              "explicit --baseline — refusing to rewrite the "
              "committed tools/tpulint/baseline.json from a foreign "
              "tree", file=sys.stderr)
        return 2

    root = os.path.abspath(args.root) if args.root else repo_root()
    baseline = load_baseline(args.baseline)
    report = run_lint(paths=args.paths or None, rules=args.rule,
                      root=root, baseline=baseline)
    findings = report["_findings_objs"]

    if args.update_baseline:
        path, n, skipped = write_baseline(findings, path=args.baseline,
                                          previous=baseline)
        print(f"baseline rewritten: {path} "
              f"({n} finding(s) grandfathered)")
        if skipped:
            # an honest verdict: these can't be baselined, so the
            # very next gate run still exits 1 — say so now
            print(f"tpulint: {skipped} PARSE/checker-error finding(s) "
                  f"NOT grandfathered — fix them; the gate stays red",
                  file=sys.stderr)
            return 1
        return 0

    report_path = args.report or _default_report_path()
    write_report(report, report_path)

    if args.json:
        doc = {k: v for k, v in report.items()
               if not k.startswith("_")}
        print(json.dumps(doc, indent=1))
    else:
        for f in findings:
            mark = " [baselined]" if f.baselined else ""
            print(f"{f.path}:{f.line}: {f.rule} {f.message}{mark}")
        for e in report["unused_baseline"]:
            print(f"baseline: UNUSED entry {e['rule']} {e['path']} "
                  f"[{e.get('qualname')}] {e.get('symbol')} — delete "
                  f"it (the debt is paid)")
    # the machine-readable last line (campaign log convention: the
    # last stdout line of every stage parses)
    print(json.dumps({
        "ok": report["non_baselined"] == 0,
        "non_baselined": report["non_baselined"],
        "baselined": report["baselined"],
        "suppressed": report["suppressed"],
        "files_scanned": report["files_scanned"],
        "counts": report["counts"],
        "report": os.path.abspath(report_path),
    }))
    return 0 if report["non_baselined"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
