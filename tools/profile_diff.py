"""profile_diff — compare two folded continuous-profile snapshots.

"The decode phase got cheaper" or "fingerprinting no longer dominates
placement" become CHECKABLE: point this at two folded-profile files
(``ContinuousProfiler.save()`` artifacts — one ``stack weight`` line
per collapsed stack, ``phase:decode;mod.fn;... N``) and it reports
per-PHASE and per-leaf-FRAME wall-share deltas in absolute percentage
points — optionally failing on drift thresholds so a campaign
preflight can gate on them (the metrics_diff idiom, applied to
profiles).

Shares, not raw sample counts: the two runs may have sampled at
different rates or for different durations, so each side is first
normalized to shares of its own total weight. A delta of ``+5%`` means
the phase/frame absorbs five percentage points MORE of the host's
sampled wall time than it did in the baseline.

Usage:
  python tools/profile_diff.py old.folded new.folded
  python tools/profile_diff.py A.folded B.folded \\
      --fail-on 'phase:decode>+5%' \\
      --fail-on 'frame:paddle_tpu.nlp.serving._prefill_full>+3%'

--fail-on SPEC grammar: ``{phase|frame}:<key>{>|<}{+|-}PCT%`` —
``phase:`` gates a serving-phase share, ``frame:`` a leaf-frame share;
``>`` fails when B's share exceeds A's by more than PCT percentage
points (hot-path-like: growing is worse), ``<`` fails when B's share
UNDERSHOOTS A's by more than PCT points (coverage-like: a phase that
vanished). The sign on PCT is cosmetic (``>+5%`` == ``>5%``). A key
absent from a side reads as share 0.0 — a brand-new hot frame DOES
trip a ``>`` gate (that is the point).

Vacuity guard: two EMPTY profiles (zero total weight on both sides)
fail loudly instead of green-lighting — a gate that compared nothing
proved nothing.

Last stdout line is a JSON report; exit 0 iff no --fail-on tripped.
Stdlib-only (loads contprof straight from its file via bench._obs_mod
— no jax, no package import).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import _obs_mod  # noqa: E402

_SPEC_RE = re.compile(
    r"^(?P<kind>phase|frame):(?P<key>.+?)"
    r"(?P<op>[<>])(?P<sign>[+-]?)(?P<pct>\d+(?:\.\d+)?)%?$")


def parse_spec(s):
    m = _SPEC_RE.match(s.strip())
    if not m:
        raise argparse.ArgumentTypeError(
            f"bad --fail-on spec {s!r} "
            "(grammar: {phase|frame}:<key>{>|<}{+|-}PCT%)")
    return {"kind": m.group("kind"), "key": m.group("key"),
            "op": m.group("op"), "pct": float(m.group("pct")),
            "spec": s.strip()}


def _shares(path):
    cp = _obs_mod("contprof")
    folded = cp.load_folded(path)
    phases, frames = cp.fold_shares(folded)
    return folded, phases, frames


def _delta_table(a, b):
    """Per-key share table: {key: {a, b, delta_pp}} with shares and
    the delta in absolute percentage points, sorted by |delta|."""
    rows = {}
    for key in set(a) | set(b):
        sa, sb = a.get(key, 0.0), b.get(key, 0.0)
        rows[key] = {"a": round(sa, 6), "b": round(sb, 6),
                     "delta_pp": round((sb - sa) * 100.0, 4)}
    return dict(sorted(rows.items(),
                       key=lambda kv: -abs(kv[1]["delta_pp"])))


def check_fail_on(phase_rows, frame_rows, specs):
    failures = []
    for spec in specs:
        rows = phase_rows if spec["kind"] == "phase" else frame_rows
        row = rows.get(spec["key"],
                       {"a": 0.0, "b": 0.0, "delta_pp": 0.0})
        d = row["delta_pp"]
        bad = d > spec["pct"] if spec["op"] == ">" else d < -spec["pct"]
        if bad:
            failures.append({"spec": spec["spec"],
                             "key": f"{spec['kind']}:{spec['key']}",
                             "a": row["a"], "b": row["b"],
                             "delta_pp": d})
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two folded continuous-profile files on "
                    "per-phase / per-frame wall-share deltas")
    ap.add_argument("a", help="baseline folded profile")
    ap.add_argument("b", help="candidate folded profile")
    ap.add_argument("--fail-on", action="append", type=parse_spec,
                    default=[], metavar="{phase|frame}:KEY{>|<}PCT%",
                    help="share-drift threshold in absolute "
                         "percentage points (repeatable)")
    ap.add_argument("--top", type=int, default=12,
                    help="rows in the human-readable tables")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human-readable section")
    args = ap.parse_args(argv)

    folded_a, phases_a, frames_a = _shares(args.a)
    folded_b, phases_b, frames_b = _shares(args.b)
    total_a = sum(folded_a.values())
    total_b = sum(folded_b.values())

    phase_rows = _delta_table(phases_a, phases_b)
    frame_rows = _delta_table(frames_a, frames_b)
    failures = check_fail_on(phase_rows, frame_rows, args.fail_on)
    vacuous = total_a == 0 and total_b == 0
    if vacuous:
        failures.append({
            "spec": "(vacuity guard)", "key": None, "a": 0, "b": 0,
            "delta_pp": 0.0,
            "error": "both profiles are empty — nothing was compared"})

    report = {"a": args.a, "b": args.b,
              "total_weight": {"a": total_a, "b": total_b},
              "phases": phase_rows,
              "frames": dict(list(frame_rows.items())[:64]),
              "fail_on": [s["spec"] for s in args.fail_on],
              "failures": failures, "vacuous": vacuous,
              "ok": not failures}

    if not args.quiet:
        for key, r in list(phase_rows.items())[:args.top]:
            print(f"  phase {key}: {r['a']:.3f} -> {r['b']:.3f} "
                  f"({r['delta_pp']:+.2f}pp)", file=sys.stderr)
        for key, r in list(frame_rows.items())[:args.top]:
            print(f"  frame {key}: {r['a']:.3f} -> {r['b']:.3f} "
                  f"({r['delta_pp']:+.2f}pp)", file=sys.stderr)
        for f in failures:
            print(f"  FAIL {f['spec']}: {f.get('key')} "
                  f"{f.get('a')} -> {f.get('b')} "
                  f"({f.get('delta_pp'):+}pp)", file=sys.stderr)
    print(json.dumps(report, default=str))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
