"""Single-chip pipeline schedule-overhead A/B (VERDICT r4 next #8).

The interleaved pipeline's bubble win — (S-1)/(m*v+S-1) vs FThenB's
(S-1)/(m+S-1) — is CPU-pinned tick *math* (pipeline_cost); what the
cost model ignores is the compiled schedule's per-tick overhead: the
lax.scan step, the out-buffer dynamic-update-slice, the warmup/drain
predication, and (interleaved only) the per-tick jnp.take gather of the
chunk's params from the stacked [v, ...] store. One chip can bound all
of those: with p=1 the ppermute hop drops out, so

    overhead/tick = (T_schedule - T_sequential) / n_ticks

isolates exactly the machinery the cost model assumes free. A ring hop
is the one term this cannot see; the multi-chip dryrun certifies that
path's correctness, and its cost is ICI-bandwidth math, not schedule
machinery.

ref parity: fleet.meta_parallel PipelineParallel schedules; the
reference's analogous question is p2p/schedule overhead per microbatch
vs GPU compute time.

Emits one JSON line:
  {"metric": "pipeline_tick_overhead", "sequential_ms": ..,
   "fthenb": {...}, "interleaved_v2": {...}, ...}
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def stage_chain(n):
    """stage_fn(params, act): act through n MLP blocks (params is a
    list of n {'up','down'} dicts). ≈ a transformer block's MLP — two
    [D,4D]/[4D,D] matmuls + residual + rms-normish elementwise —
    realistic per-tick compute."""
    import jax
    import jax.numpy as jnp

    def fn(params, x):
        for w in params:
            h = jnp.einsum("bd,df->bf", x, w["up"])
            h = jax.nn.gelu(h)
            h = jnp.einsum("bf,fd->bd", h, w["down"])
            x = x + h
            x = x / jnp.sqrt(jnp.mean(jnp.square(x), -1, keepdims=True)
                             + 1e-6)
        return x
    return fn


def measure(fn, *args, reps=5, warmup=2):
    import jax
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes on the CPU backend (the box's "
                         "sitecustomize would otherwise route jax to "
                         "the axon TPU tunnel and hang when it is "
                         "dead); same code path")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--d-model", type=int, default=None)
    ap.add_argument("--n-micro", type=int, default=8)
    ap.add_argument("--layers-per-stage", type=int, default=4)
    args = ap.parse_args()

    if args.smoke:
        import _cpu_env  # noqa: F401  (forces cpu before jax import)
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    import numpy as np

    from paddle_tpu.distributed.fleet.pipeline import (
        pipeline_apply, pipeline_cost, stack_stage_params)

    on_tpu = jax.default_backend() == "tpu"
    B, D = (64, 2048) if on_tpu and not args.smoke else (16, 64)
    B = args.batch or B
    D = args.d_model or D
    m = args.n_micro
    L = args.layers_per_stage  # layers in ONE stage (v=2 splits them)
    if L % 2:
        sys.exit("--layers-per-stage must be even (v=2 splits the stage)")
    dtype = jnp.bfloat16 if on_tpu else jnp.float32

    key = jax.random.PRNGKey(0)
    layers = []
    for _ in range(L):
        key, k1, k2 = jax.random.split(key, 3)
        layers.append({
            "up": jax.random.normal(k1, (D, 4 * D), dtype) * (D ** -0.5),
            "down": (jax.random.normal(k2, (4 * D, D), dtype)
                     * ((4 * D) ** -0.5)),
        })
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D), dtype)
    mesh = Mesh(np.array(jax.devices()[:1]), ("pp",))

    # sequential reference: same L layers, full batch, no schedule
    # machinery — what XLA compiles when there is no pipeline
    print(f"[pipeline_overhead] B={B} D={D} m={m} L={L} "
          f"backend={jax.default_backend()}", file=sys.stderr, flush=True)
    seq = jax.jit(stage_chain(L))
    t_seq = measure(seq, layers, x)
    results = {"sequential_ms": round(t_seq * 1e3, 3)}
    print(f"[pipeline_overhead] sequential {t_seq*1e3:.3f} ms",
          file=sys.stderr, flush=True)

    # FThenB (v=1): 1 stage x m microbatches (ticks = m); interleaved
    # (v=2): 2 chunks of L/2 layers (ticks = 2m + per-tick param take)
    half = L // 2
    variants = (
        ("fthenb", 1, stack_stage_params([layers]), stage_chain(L)),
        ("interleaved_v2", 2,
         stack_stage_params([layers[:half], layers[half:]]),
         stage_chain(half)),
    )
    ref = seq(layers, x)
    for name, v, sp, sfn in variants:
        fn = jax.jit(lambda p, xx, _sfn=sfn, _v=v: pipeline_apply(
            mesh, p, xx, _sfn, n_micro=m, remat=False, n_virtual=_v))
        t = measure(fn, sp, x)
        print(f"[pipeline_overhead] {name} {t*1e3:.3f} ms",
              file=sys.stderr, flush=True)
        ticks = pipeline_cost(1, m, v)["ticks"]
        got = fn(sp, x)
        err = float(jnp.max(jnp.abs(ref.astype(jnp.float32)
                                    - got.astype(jnp.float32))))
        results[name] = {
            "ms": round(t * 1e3, 3),
            "ticks": ticks,
            "overhead_ms_per_tick": round((t - t_seq) / ticks * 1e3, 4),
            "overhead_frac": round((t - t_seq) / t_seq, 4),
            "max_abs_err_vs_sequential": err,
        }

    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    for name in ("fthenb", "interleaved_v2"):
        if results[name]["max_abs_err_vs_sequential"] > tol:
            print(f"[pipeline_overhead] {name} DIVERGES from sequential "
                  f"by {results[name]['max_abs_err_vs_sequential']}",
                  file=sys.stderr, flush=True)
            print(json.dumps({"metric": "pipeline_tick_overhead",
                              "value": None, "unit": "ms/tick",
                              "vs_baseline": None,
                              "error": f"{name} diverges", **results}),
                  flush=True)
            return 1
    out = {"metric": "pipeline_tick_overhead",
           "value": results["interleaved_v2"]["overhead_ms_per_tick"],
           "unit": "ms/tick", "vs_baseline": None,
           "batch": B, "d_model": D, "n_micro": m,
           "layers_per_stage": L, "backend": jax.default_backend(),
           **results}
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
