"""aot_boot_probe — artifact-boot vs traced-boot wall clock (r21).

The campaign's measured rung for the AOT serving-artifact store
(jit/serving_artifact.py): on the first live TPU window this is the
number that says what a scale-out actually costs with and without the
artifact path.

1. **traced control**: build a ServingEngine and pay the full traced
   warmup (prefill buckets + decode scan) — wall-clocked;
2. **export**: lower the warmed program set into a serving artifact
   (``export_artifact`` — staged, checksummed, marker-published);
3. **artifact boot**: build a second engine over the SAME model and
   ``warm_boot`` it off the store — wall-clocked, asserted to have
   taken the AOT path (``boot_info.mode == "aot"``, zero fallbacks);
4. invariants, asserted hard: the artifact-booted engine generates
   TOKEN-EXACT vs the traced control on a seeded prompt wave, serves
   with ZERO post-boot traces (compile counts frozen across the
   wave, zero unexpected retraces), and the artifact boot wall
   strictly beats the traced wall.

Artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (the
validate_stages contract) and the artifact store itself. Last stdout
line is a JSON verdict; exit 0 only when every assertion holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NEW_TOK = 8
PROMPT_LENS = (5, 12, 17, 9, 12, 5, 17, 12)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--store", default=None,
                    help="artifact store dir (default: "
                         "$BENCH_TELEMETRY_DIR/aot_store)")
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "aot_boot")
    os.makedirs(out_dir, exist_ok=True)
    store = args.store or os.path.join(out_dir, "aot_store")

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.serving_artifact import export_artifact, \
        warm_boot
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine
    from paddle_tpu.observability.trace import report_all

    paddle.seed(0)
    # ONE model instance for both engines: gpt-tiny draws random
    # weights at construction, so a second build would be a different
    # model and "token-exact" would be vacuous-false
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 200, (int(n),)).astype(np.int32)
               for n in PROMPT_LENS]
    buckets = sorted(set(PROMPT_LENS))

    def build():
        return ServingEngine(model, max_slots=2, page_size=16,
                             max_seq_len=64, steps_per_dispatch=4)

    # traced control + export
    a = build()
    t = time.monotonic()
    a.warmup(buckets=buckets, decode=True)
    traced_s = time.monotonic() - t
    export_artifact(a, store)
    refs = a.generate(prompts, max_new_tokens=NEW_TOK)

    # artifact boot
    b = build()
    t = time.monotonic()
    info = warm_boot(b, buckets=buckets, artifact_dir=store)
    aot_s = time.monotonic() - t
    frozen = b.compile_counts()
    toks = b.generate(prompts, max_new_tokens=NEW_TOK)

    fb = [s for s in b.registry.series()
          if s.name == "serve_aot_fallback_total" and s.value]
    checks = {
        "booted_aot": info.get("mode") == "aot" and not fb,
        "token_exact": toks == refs,
        "zero_post_boot_traces": (
            b.compile_counts() == frozen
            and b.tracer.unexpected_retraces() == 0),
        "aot_beats_traced": aot_s < traced_s,
    }

    b.registry.dump(os.path.join(out_dir, "metrics.json"),
                    extra={"recompile_report": report_all(),
                           "stage": "aot_boot"})
    a.close()
    b.close()

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({"ok": ok, "checks": checks,
                      "traced_boot_s": round(traced_s, 3),
                      "aot_boot_s": round(aot_s, 3),
                      "speedup": round(traced_s / max(aot_s, 1e-9), 2),
                      "artifact": info.get("artifact"),
                      "platform": str(
                          __import__("jax").devices()[0].platform),
                      "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
