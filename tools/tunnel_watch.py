"""Tunnel watcher: probe the axon TPU backend on an interval; the moment
it answers, run the queued retry stages via tools/tpu_campaign.py.

The r3/r4 pattern is a tunnel that comes and goes in windows of tens of
minutes — hardware time is too precious to depend on a human noticing,
so this automates "the moment the tunnel returns, measure" (VERDICT r3
next #1). Every probe attempt is logged with a timestamp so an all-dead
stretch is externally verifiable evidence, not an excuse.

If the tunnel dies again mid-campaign, the watcher re-arms with only
the stages that have not yet succeeded (read from campaign_out/
summary.json) instead of declaring victory on a half-done run.

Usage: python tools/tunnel_watch.py [--interval 300] [--stages a,b,c]
Exits once every requested stage has succeeded.
"""
from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "campaign_out")
PY = sys.executable

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tpu_campaign import (run,  # noqa: E402  (shared runner)
                          _driver_bench_active)


def log_line(path, msg):
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    with open(path, "a") as f:
        f.write(f"{stamp} {msg}\n")
    print(f"{stamp} {msg}", flush=True)


def succeeded_stages():
    return {k for k, v in ran_stages().items() if v.get("ok")}


def ran_stages():
    """Stage rows of this attempt's summary.json (meta keys dropped)."""
    try:
        with open(os.path.join(OUT, "summary.json")) as f:
            return {k: v for k, v in json.load(f).items()
                    if isinstance(v, dict) and not k.startswith("_")}
    except (OSError, json.JSONDecodeError):
        return {}


def driver_marker_mtime():
    from tpu_campaign import DRIVER_MARKER
    try:
        return os.path.getmtime(DRIVER_MARKER)
    except OSError:
        return 0


def main():
    import time
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=300)
    ap.add_argument("--probe-timeout", type=int, default=150)
    ap.add_argument(
        "--stages",
        # ORDER IS THE SCHEDULE (tpu_campaign --only runs stages as
        # listed): the flagship 1.3B number and the full suite go FIRST
        # so even a minutes-long window produces the scoreboard metric
        # (VERDICT r5 directive #1), then the serving/llama rungs (the
        # round-7 subsystem's first hardware numbers) and the r6 NHWC
        # ResNet A/B (still unmeasured on hardware), then the decode
        # ladder and the long tail of A/B stages. Kernel-arming stages
        # (bench_decode_flashk, bench_serve_flashk) stay LAST, after
        # their probes have bisected the paged/flash compile (r2 wedge).
        # aot_boot rides just after the serving rungs: the first live
        # window also prices artifact-boot vs traced-boot on real
        # hardware (the r21 scale-out latency claim).
        default="bench_gpt13b_scan_cce,bench_full,"
                "bench_serve_gpt,bench_serve_llama,aot_boot,bench_llama,"
                "bench_resnet_nhwc,bench_resnet_nhwc_fused,"
                "bench_gpt13b_scan,decode_probe,decode_probe_paged,"
                "bench_decode,bench_decode_bf16kv,"
                "bench_decode_int8,bench_decode_bf16w,bench_decode_int4,bench_gpt13b,"
                "bench_gpt_b16,bench_gpt_fusedqkv,bench_gpt_fusedln,bench_gpt_chunkedce,bench_gpt_fusedadamw,bench_gpt_fusedboth,bench_ernie_fusedqkv,bench_ernie_fusedln,bench_ernie_mlmgather,bench_gpt_s4k,step_anatomy,step_anatomy_fused,step_anatomy_fusedln,resnet_roofline,bench_resnet_serve,bench_resnet_serve_fold,bench_resnet_b512,bench_resnet_nhwc_s2d,fusion_audit,fusion_audit_nhwc,pipeline_overhead,bench_decode_flashk,bench_serve_flashk")
    ap.add_argument("--log", default=os.path.join(OUT, "probe_r4b.log"))
    ap.add_argument("--max-attempts", type=int, default=3,
                    help="drop a stage after this many failed campaign "
                         "launches with a live probe (code bug, not "
                         "tunnel — stop burning the window)")
    args = ap.parse_args()
    os.makedirs(OUT, exist_ok=True)
    pending = args.stages.split(",")
    attempts = {s: 0 for s in pending}
    while pending:
        # the round-end driver bench owns the chip: hold off while its
        # marker is fresh (it also SIGKILLs any in-flight stage)
        if _driver_bench_active():
            log_line(args.log, "driver bench owns the chip — holding "
                               f"off {args.interval}s")
            time.sleep(args.interval)
            continue
        rc, dt, _ = run([PY, "bench.py", "--worker", "probe"],
                        args.probe_timeout, "watch_probe.log")
        if rc != 0:
            log_line(args.log, f"probe DEAD rc={rc} after {dt:.1f}s "
                               f"(next try in {args.interval}s)")
            time.sleep(args.interval)
            continue
        log_line(args.log, f"probe OK in {dt:.1f}s — launching stages "
                           f"{','.join(pending)}")
        # a stale summary.json from an earlier campaign must not mark
        # stages succeeded that never ran this attempt — archive it (the
        # record of earlier windows feeds bench.py's null-run diagnostic)
        try:
            import time as _time
            os.rename(os.path.join(OUT, "summary.json"),
                      os.path.join(OUT, f"summary_{int(_time.time())}.json"))
        except OSError:
            pass
        for s in pending:
            attempts[s] += 1
        camp = subprocess.run(
            [PY, "tools/tpu_campaign.py", "--only", ",".join(pending)],
            cwd=REPO)
        done = succeeded_stages()
        preempted = _driver_bench_active()
        ran = ran_stages()
        pending = [s for s in pending if s not in done]
        if preempted:
            # stages cut short by the driver bench did not genuinely
            # fail — give their attempt back. But ONLY stages the
            # campaign never reached, or whose run ended at/after the
            # preemption started (i.e. the driver's SIGKILL cut them):
            # a stage that failed on its own merits before the driver
            # arrived keeps its strike (3-strike cap stays meaningful).
            preempt_t0 = driver_marker_mtime()
            refunded = []
            for s in pending:
                row = ran.get(s)
                if row is None or (preempt_t0 and
                                   row.get("ended_at", 0) >= preempt_t0):
                    attempts[s] -= 1
                    refunded.append(s)
            log_line(args.log, "campaign preempted by driver bench — "
                               f"attempts refunded for {refunded}")
        # a stage that keeps failing while the probe stays green is a
        # code/config problem, not the tunnel — stop burning the scarce
        # window on it (3 strikes), keep going with the rest
        exhausted = [s for s in pending if attempts[s] >= args.max_attempts]
        if exhausted:
            log_line(args.log, f"GIVING UP on {exhausted} after "
                               f"{args.max_attempts} attempts each — "
                               "investigate their stage logs")
            pending = [s for s in pending if s not in exhausted]
        log_line(args.log, f"campaign rc={camp.returncode}; "
                           f"pending after run: {pending or 'NONE'}")
        if pending:
            time.sleep(args.interval)  # backoff before relaunching
    log_line(args.log, "watcher done (all stages succeeded or exhausted)")


if __name__ == "__main__":
    main()
