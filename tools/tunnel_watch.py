"""Tunnel watcher: probe the axon TPU backend on an interval; the moment
it answers, run the queued retry stages via tools/tpu_campaign.py.

The r3/r4 pattern is a tunnel that comes and goes in windows of tens of
minutes — hardware time is too precious to depend on a human noticing,
so this automates "the moment the tunnel returns, measure" (VERDICT r3
next #1). Every probe attempt is logged with a timestamp so an all-dead
stretch is externally verifiable evidence, not an excuse.

Usage: python tools/tunnel_watch.py [--interval 300] [--stages a,b,c]
Exits after the staged campaign finishes (one-shot: rerun to re-arm).
"""
from __future__ import annotations

import argparse
import datetime
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "campaign_out")
PY = sys.executable


def log_line(path, msg):
    stamp = datetime.datetime.now().isoformat(timespec="seconds")
    with open(path, "a") as f:
        f.write(f"{stamp} {msg}\n")
    print(f"{stamp} {msg}", flush=True)


def probe(timeout):
    t0 = time.monotonic()
    proc = subprocess.Popen([PY, "bench.py", "--worker", "probe"],
                            cwd=REPO, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    try:
        rc = proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        proc.wait()
        return "timeout", time.monotonic() - t0
    return rc, time.monotonic() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=300)
    ap.add_argument("--probe-timeout", type=int, default=150)
    ap.add_argument(
        "--stages",
        default="bench_gpt13b,bench_decode,bench_decode_bf16kv,"
                "bench_decode_int8,decode_probe,resnet_roofline,"
                "fusion_audit")
    ap.add_argument("--log", default=os.path.join(OUT, "probe_r4b.log"))
    args = ap.parse_args()
    os.makedirs(OUT, exist_ok=True)
    while True:
        rc, dt = probe(args.probe_timeout)
        if rc == 0:
            log_line(args.log, f"probe OK in {dt:.1f}s — launching stages "
                               f"{args.stages}")
            camp = subprocess.run(
                [PY, "tools/tpu_campaign.py", "--only", args.stages],
                cwd=REPO)
            log_line(args.log, f"stages done rc={camp.returncode}")
            return
        log_line(args.log, f"probe DEAD rc={rc} after {dt:.1f}s "
                           f"(next try in {args.interval}s)")
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
