"""autoscale_smoke — the campaign's CPU drill for elastic fleet
autoscaling (ISSUE 15).

Shape (seeded, CPU-only, no tunnel window burned):

1. build a ONE-replica in-process fleet (journaled, history plane on,
   tight TTFT/e2e SLOs with sub-second burn windows) plus a
   FleetAutoscaler whose ``spawn_fn`` builds warmed replicas up to
   ``max_replicas``;
2. **burst wave**: the base replica is pinned slow (``replica_slow``
   — the saturation seam) while a seeded burst arrives open-loop.
   TTFT burn fires the multi-window alert → the autoscaler spawns a
   replica, holds it at the warm-boot gate, and adopts it only on a
   ``serving`` + ``warmed`` heartbeat;
3. **recovery**: the wave drains, the burn windows clear, budgets
   recover and the fleet runs idle for the hold — the autoscaler
   retires capacity (hedge-safe drain → ``remove_replica``) back to
   ``min_replicas``;
4. invariants, asserted hard: NO LOST RID (every submitted request
   resolves exactly once), every ok result TOKEN-EXACT vs an
   uninterrupted single-engine golden (scale events never corrupt a
   stream), bounded SLO breach (ok fraction over the whole drill),
   compile counts FROZEN — the base engine from warmup, spawned
   engines from their adoption snapshot (a new replica takes traffic
   with zero new steady-state traces), zero unexpected retraces,
   ZERO flaps, ``scale_out``+``scale_in`` records in the journal
   (``reconcile()["autoscale"]``), parseable
   ``flight_fleet_scale_out``/``flight_fleet_scale_in`` dumps, and —
   r21 — the alert-to-serving latency bar: the base replica's traced
   boot exports an AOT serving artifact, every autoscaler spawn boots
   off it (``mode=aot``, counted in ``fleet_boots_total``), and every
   AOT boot wall beats the traced-boot control measured on the same
   drill;
5. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (fleet
   registry + recompile report — the validate_stages contract),
   ``health.json``, ``autoscale_events.json``, the journal dir and
   the flight dumps.

Last stdout line is a JSON verdict; exit 0 only when every assertion
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NEW_TOK = 8
WAVE_LENS = (5, 12, 17, 9, 12, 5, 17, 12, 9, 5, 12, 17,
             5, 9, 12, 17, 5, 12, 9, 17, 9, 5, 17, 12)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "autoscale_smoke")
    os.makedirs(out_dir, exist_ok=True)
    # scale-event flight dumps land next to the other artifacts
    os.environ.setdefault("PADDLE_TPU_FLIGHT_DIR", out_dir)

    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine
    from paddle_tpu.observability.slo import SLObjective
    from paddle_tpu.observability.trace import report_all
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving_fleet import FleetAutoscaler, \
        FleetRouter, InprocReplica
    from paddle_tpu.serving_fleet.journal import reconcile, replay

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 200, (int(n),)).astype(np.int32)
               for n in WAVE_LENS]

    # uninterrupted single-engine golden: greedy decoding makes every
    # scale-event stream comparable token for token
    g = ServingEngine(model, max_slots=2, page_size=16, max_seq_len=64,
                      steps_per_dispatch=4)
    refs = g.generate(prompts, max_new_tokens=NEW_TOK)
    g.close()

    engines = []
    boots = []   # (wall_s, boot_info) per built engine — the AOT-vs-
    #              traced alert-to-serving latency assertion's data
    store = os.path.join(out_dir, "aot_store")

    def build_engine(aot=False):
        eng = ServingEngine(model, max_slots=2, page_size=16,
                            max_seq_len=64, steps_per_dispatch=4)
        t = time.monotonic()
        if aot:
            # the r21 scale-out spawn path: restore serialized
            # programs from the artifact e0's traced boot exported
            warm_boot(eng, buckets=sorted(set(WAVE_LENS)),
                      artifact_dir=store)
        else:
            eng.warmup(buckets=sorted(set(WAVE_LENS)), decode=True)
        boots.append((time.monotonic() - t, dict(eng.boot_info)))
        engines.append(eng)
        return eng

    from paddle_tpu.jit.serving_artifact import export_artifact, \
        warm_boot

    # traced-boot CONTROL: e0 pays the full trace+compile wall, then
    # exports the artifact every autoscaler spawn boots from
    e0 = build_engine(aot=False)
    traced_boot_s = boots[0][0]
    export_artifact(e0, store)
    frozen0 = e0.compile_counts()
    slos = (SLObjective("ttft", "latency", target=0.99,
                        threshold_s=0.05),
            SLObjective("e2e", "latency", target=0.99, threshold_s=2.0),
            SLObjective("availability", "availability", target=0.999))
    windows = ({"short_s": 0.5, "long_s": 2.0, "burn": 1.0},)
    jdir = os.path.join(out_dir, "journal")
    router = FleetRouter(
        [InprocReplica("r0", e0)], slos=slos, slo_windows=windows,
        history=True, history_interval_s=0.05, journal_dir=jdir,
        overload_target_ms=5000.0)
    asc = FleetAutoscaler(
        router, lambda i: InprocReplica(f"as{i}",
                                        build_engine(aot=True)),
        min_replicas=1, max_replicas=3,
        scale_out_cooldown_s=0.5, scale_in_cooldown_s=0.5,
        recovery_hold_s=0.75, boot_timeout_s=60.0,
        flap_window_s=0.05)

    # saturate the base replica for the first ~2s of the wave only —
    # the recovery half of the drill needs the fleet fast again
    faults.inject("replica_slow", replica="r0", count=50, seconds=0.04)

    checks = {}
    events, results = [], []
    rids = []
    max_size = 1
    t0 = time.monotonic()
    t_end = t0 + float(args.timeout)
    nxt = 0
    try:
        while time.monotonic() < t_end:
            now = time.monotonic() - t0
            while nxt < len(prompts) and now > nxt * 0.01:
                rids.append(router.submit(prompts[nxt], NEW_TOK))
                nxt += 1
            router.step()
            events += asc.poll()
            results += router.results()
            max_size = max(max_size, len(router.replicas))
            if nxt >= len(prompts) and len(results) >= len(prompts) \
                    and asc.state == "steady" \
                    and len(router.replicas) <= asc.min_replicas \
                    and any(e[0] == "scaled_in" for e in events):
                break
            time.sleep(0.002)
    finally:
        faults.clear()

    by_rid = {r["id"]: r for r in results}
    checks["no_lost_rid_exactly_once"] = (
        sorted(by_rid) == sorted(rids)
        and len(results) == len(rids))
    ok_n = sum(1 for r in results if r["status"] == "ok")
    checks["bounded_slo_breach"] = ok_n >= int(0.8 * len(rids))
    checks["ok_results_token_exact"] = all(
        by_rid[rid]["tokens"] == refs[i]
        for i, rid in enumerate(rids)
        if rid in by_rid and by_rid[rid]["status"] == "ok") and ok_n > 0
    checks["scaled_out_then_in"] = (
        any(e[0] == "scaled_out" for e in events)
        and any(e[0] == "scaled_in" for e in events)
        and max_size > 1 and len(router.replicas) == 1)
    checks["zero_flaps"] = int(router.registry.get(
        "fleet_autoscale_flaps_total").value) == 0
    # frozen compiles: the base engine vs its warmup snapshot; every
    # ADOPTED spawned engine vs its adoption snapshot (a boot-failed
    # spawn never took traffic and is exempt)
    spawned_ok = all(
        rep.engine.compile_counts() == fz
        for rep, fz in asc.spawned if fz is not None)
    checks["compile_counts_frozen"] = (
        e0.compile_counts() == frozen0 and spawned_ok
        and router.compile_report()["unexpected_retraces"] == 0)

    # r21 alert-to-serving latency, asserted HARD: every autoscaler
    # spawn must have booted off the AOT artifact (mode=aot, counted
    # in fleet_boots_total{mode="aot"}) and every such boot must beat
    # the traced-boot control wall measured on the SAME drill
    aot_boots = [w for w, bi in boots[1:] if bi.get("mode") == "aot"]
    checks["spawns_booted_aot"] = (
        len(aot_boots) == len(boots) - 1 and len(boots) > 1)
    mb = router.registry.get("fleet_boots_total", labels={"mode": "aot"})
    checks["fleet_boots_aot_counted"] = (
        mb is not None and int(mb.value) >= len(
            [1 for _rep, fz in asc.spawned if fz is not None]) > 0)
    checks["aot_boot_beats_traced"] = bool(aot_boots) and (
        max(aot_boots) < traced_boot_s)

    # journal: the scale decisions must be durable + reconcilable
    try:
        records, _stats = replay(jdir)
        state = reconcile(records)
        kinds = {r.get("kind") for r in state["autoscale"]}
        checks["journal_scale_records"] = {"scale_out",
                                           "scale_in"} <= kinds
    except Exception:  # noqa: BLE001 — an unreadable journal fails
        checks["journal_scale_records"] = False

    def _dump_ok(prefix):
        for fn in sorted(os.listdir(out_dir)):
            if fn.startswith(f"flight_{prefix}") \
                    and fn.endswith(".json"):
                try:
                    with open(os.path.join(out_dir, fn)) as f:
                        doc = json.load(f)
                    if doc.get("reason") == prefix \
                            and isinstance(doc.get("records"), list):
                        return True
                except (OSError, json.JSONDecodeError):
                    pass
        return False

    checks["scale_flight_dumps_parseable"] = (
        _dump_ok("fleet_scale_out") and _dump_ok("fleet_scale_in"))

    # artifacts
    with open(os.path.join(out_dir, "health.json"), "w") as f:
        json.dump(router.health(), f, indent=1)
    with open(os.path.join(out_dir, "autoscale_events.json"),
              "w") as f:
        json.dump({"events": [list(e) for e in events],
                   "decisions": asc.health()["decisions"]}, f,
                  indent=1)
    router.registry.dump(os.path.join(out_dir, "metrics.json"),
                         extra={"recompile_report": report_all(),
                                "stage": "autoscale_smoke"})
    router.close()
    for e in engines:
        e.close()

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({"ok": ok, "checks": checks,
                      "requests": len(rids), "ok_results": ok_n,
                      "max_fleet_size": max_size,
                      "traced_boot_s": round(traced_boot_s, 3),
                      "aot_boot_s": [round(w, 3) for w in aot_boots],
                      "events": [list(e) for e in events],
                      "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
