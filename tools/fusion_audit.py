"""CINN-parity fusion audit (SURVEY §7 R3 / VERDICT r2 next #6).

The reference's CINN pass fuses elementwise chains (LN -> residual ->
GELU) into generated kernels so activations make one HBM round trip.
On TPU the same job belongs to XLA; this tool checks XLA actually did
it by compiling the REAL train steps (GPT decoder block / ResNet-50)
and reporting, from the backend-optimized HLO:

  - kernel count (top-level instructions of the entry computation —
    each is roughly one dispatched kernel)
  - fusion count + the largest fusions' op mixes
  - standalone (unfused) elementwise/reduce ops — each one is an extra
    full HBM round trip of an activation tensor
  - cost_analysis bytes-accessed / FLOPs -> arithmetic intensity

Usage (results are backend-specific — run on the TPU terminal):
  python tools/fusion_audit.py [--model gpt|resnet] [--out report.md]
CPU runs exercise the tooling but say nothing about TPU fusion.
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import Counter

ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "exponential", "tanh", "rsqrt", "sqrt", "negate", "abs", "power",
    "select", "compare", "convert", "and", "or", "not", "xor",
    "log", "logistic", "sign", "floor", "ceil", "clamp",
}
HEAVY = {"dot", "convolution", "custom-call", "fusion", "all-reduce",
         "reduce-scatter", "all-gather", "scatter", "gather", "sort",
         "rng", "while", "conditional", "call"}


# opcode after "= <type> ": the type is either a tuple "(...)" or a
# single token; TPU-optimized HLO annotates layouts inside the type
# (e.g. bf16[8,128]{1,0:T(8,128)(2,1)S(1)}), so the type is matched as
# "anything without spaces" / a parenthesized tuple, never enumerated
_INSTR_RE = re.compile(
    r"(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(?:\([^=\n]*?\)|\S+)\s+"
    r"([a-z][\w\-]*)\(")


def _block_after(header_re, hlo_text):
    """Yield (name, body) for each computation whose header matches.
    Headers end with '{' at end of line; the body runs to the first
    line that is exactly '}' — signatures may contain braces (TPU
    layout annotations), so never scan for 'first { after name'."""
    for fm in re.finditer(header_re + r"[^\n]*\{[ ]*$\n(.*?)^\}",
                          hlo_text, re.MULTILINE | re.DOTALL):
        yield fm.group(1), fm.group(2)


def parse_entry_computation(hlo_text):
    """Return the instruction opcodes of the ENTRY computation plus the
    full per-fusion bodies keyed by fusion name."""
    ops = []
    for _, entry in _block_after(r"^(ENTRY)\s", hlo_text):
        for line in entry.splitlines():
            mm = _INSTR_RE.match(line.strip())
            if mm:
                ops.append(mm.group(1))
        break
    bodies = {}
    for name, body in _block_after(
            r"^%?((?:fused_|wrapped_)[\w.\-]*)", hlo_text):
        bodies[name] = Counter(
            m.group(1) for m in (
                _INSTR_RE.match(l.strip()) for l in body.splitlines())
            if m)
    return ops, bodies


def audit(fn_or_layer, args, label):
    from paddle_tpu import jit as pjit
    import jax

    txt = pjit.get_hlo(fn_or_layer, *args, optimized=True)
    ops, bodies = parse_entry_computation(txt)
    if not ops and "ENTRY" in txt:
        # loud failure beats a vacuous all-zeros report that burns a
        # scarce TPU window looking like a measurement (the r4 campaign
        # shipped exactly that when TPU layout annotations broke the
        # old regexes)
        raise RuntimeError(
            f"HLO parser matched 0 entry instructions for '{label}' but "
            f"the dump contains an ENTRY computation ({len(txt)} chars) "
            "— the HLO text dialect has drifted; fix "
            "parse_entry_computation (see tests/test_fusion_audit_parser"
            ".py)")
    counts = Counter(ops)
    n_fusion = counts.get("fusion", 0)
    unfused_ew = {o: c for o, c in counts.items()
                  if o in ELEMENTWISE and o not in ("convert",)}
    report = [f"## {label}", ""]
    report.append(f"- entry instructions (~kernels): **{len(ops)}**")
    report.append(f"- fusions: **{n_fusion}**; "
                  f"dots/convs: {counts.get('dot', 0)}/"
                  f"{counts.get('convolution', 0)}; "
                  f"custom-calls: {counts.get('custom-call', 0)}")
    if unfused_ew:
        report.append(f"- **standalone elementwise ops (extra HBM "
                      f"round trips): {sum(unfused_ew.values())}** "
                      f"{dict(unfused_ew)}")
    else:
        report.append("- standalone elementwise ops: **0** — every "
                      "elementwise chain is inside a fusion")
    other = {o: c for o, c in counts.items()
             if o not in ELEMENTWISE and o not in HEAVY
             and o not in ("parameter", "constant", "tuple",
                           "get-tuple-element", "bitcast", "copy",
                           "reshape", "transpose", "broadcast", "iota",
                           "slice", "concatenate", "pad",
                           "dynamic-slice", "dynamic-update-slice",
                           "reduce")}
    if other:
        report.append(f"- other standalone ops: {dict(other)}")
    if counts.get("reduce", 0):
        report.append(f"- standalone reduces: {counts['reduce']}")
    # biggest fusions: what XLA chose to glue together
    big = sorted(bodies.items(), key=lambda kv: -sum(kv[1].values()))[:5]
    if big:
        report.append("- largest fusions:")
        for name, body in big:
            mix = ", ".join(f"{o}x{c}" for o, c in body.most_common(6))
            report.append(f"    - `{name}` ({sum(body.values())} ops): "
                          f"{mix}")
    return "\n".join(report), txt


def gpt_step(tiny=False):
    import jax.numpy as jnp
    import numpy as np
    sys.path.insert(0, ".")
    from bench import build_engine
    cfg = "gpt-tiny" if tiny else "gpt3-345M"
    seq = 128 if tiny else 1024
    batch = 2 if tiny else 8
    eng = build_engine(cfg, batch, seq, amp=not tiny)
    rng = np.random.default_rng(0)
    vocab = eng.network.config.vocab_size
    ids = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, vocab, (batch, seq)), jnp.int32)
    # materialize opt state + the jitted fn exactly as train_batch would
    eng.train_batch([ids], [labels])
    fn = eng._train_fn
    return (lambda p, b, o, lr, st, key: fn(p, b, o, lr, st, st, key,
                                            [ids], [labels]),
            (eng._params, eng._buffers, eng._opt_state,
             np.float32(1e-4), np.int32(2), eng._rng_key))


def resnet_step(tiny=False, s2d=False, layout=None,
                fused_bottleneck=False):
    import jax.numpy as jnp
    import numpy as np
    sys.path.insert(0, ".")
    from bench import _resnet_layout, build_resnet_engine
    eng = build_resnet_engine(amp=not tiny, s2d=s2d,
                              layout=_resnet_layout(layout,
                                                    fused_bottleneck),
                              fused_bottleneck=fused_bottleneck)
    hw = 64 if tiny else 224
    batch = 2 if tiny else 256
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 3, hw, hw)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 1000, (batch,)))
    eng.train_batch([x], [y])
    fn = eng._train_fn
    return (lambda p, b, o, lr, st, key: fn(p, b, o, lr, st, st, key,
                                            [x], [y]),
            (eng._params, eng._buffers, eng._opt_state,
             np.float32(0.1), np.int32(2), eng._rng_key))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=("gpt", "resnet", "both"),
                    default="both")
    ap.add_argument("--tiny", action="store_true",
                    help="CPU-sized configs (tooling smoke only)")
    ap.add_argument("--s2d", action="store_true")
    ap.add_argument("--layout", choices=("auto", "nhwc", "nchw"),
                    default=None,
                    help="resnet: channels-last A/B (see bench.py "
                         "--layout)")
    ap.add_argument("--fused-bottleneck", action="store_true",
                    help="resnet: Pallas fused bottleneck 1x1 chains "
                         "(implies nhwc while --layout is auto)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--dump-hlo", default=None,
                    help="also write the raw optimized HLO here (prefix)")
    args = ap.parse_args()
    import jax
    sections = [f"# Fusion audit (backend: {jax.default_backend()})", ""]
    todo = []
    if args.model in ("gpt", "both"):
        todo.append(("gpt train step", lambda: gpt_step(args.tiny)))
    if args.model in ("resnet", "both"):
        todo.append((f"resnet50 train step (s2d={args.s2d}, "
                     f"layout={args.layout or 'auto'}, "
                     f"fused_bottleneck={args.fused_bottleneck})",
                     lambda: resnet_step(args.tiny, args.s2d,
                                         args.layout,
                                         args.fused_bottleneck)))
    for label, build in todo:
        fn, a = build()
        rep, txt = audit(fn, a, label)
        sections.append(rep)
        sections.append("")
        if args.dump_hlo:
            path = f"{args.dump_hlo}_{label.split()[0]}.hlo.txt"
            with open(path, "w") as f:
                f.write(txt)
            print(f"raw HLO -> {path}", file=sys.stderr)
    out = "\n".join(sections)
    if args.out:
        with open(args.out, "w") as f:
            f.write(out)
    print(out)


if __name__ == "__main__":
    main()
