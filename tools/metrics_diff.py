"""metrics_diff — compare two metrics.json snapshots.

BENCHLOG claims like "decode p99 held under 2 ms" or "zero extra
retraces vs round 9" become CHECKABLE: point this at two bench/campaign
`metrics.json` artifacts (the registry snapshots every stage exports)
and it reports counter deltas, histogram quantile shifts (p50/p99/mean,
rebuilt from the snapshot's buckets with the registry's own
interpolation), and series added/removed between the runs — optionally
failing on regression thresholds so a campaign preflight can gate on
them.

Usage:
  python tools/metrics_diff.py old/metrics.json new/metrics.json
  python tools/metrics_diff.py A.json B.json \
      --fail-on 'serve_decode_token_seconds:p99>10%' \
      --fail-on 'recompile_unexpected_retraces_total:value>0%'

History mode — ONE archive, any two points in time: with
``--history <snapshot>`` (a HistoryStore save, e.g. the
``history_smoke`` stage's ``history_snapshot.json``) the A/B
snapshots are RECONSTRUCTED from the archive's rings at ``--at t0``
and ``--vs t1`` instead of read from two files, so a single history
archive supports the canary gate at any two instants:

  python tools/metrics_diff.py --history history_snapshot.json \
      --at +0 --vs -0 --fail-on 'fleet_anomaly_fired_total>0%'

``--at``/``--vs`` take epoch seconds, or ``+S`` (S seconds after the
archive's first sample) / ``-S`` (S seconds before its last).

--fail-on SPEC grammar: ``name[:stat]{>|<}PCT%`` — `name` matches a
series key exactly or every series of that metric name; `stat` is
``value`` (counter/gauge, the default) or ``p50``/``p99``/``mean``/
``count`` (histogram, default p50); ``>`` fails when B exceeds A by
more than PCT percent (latency-like: bigger is worse), ``<`` fails
when B undershoots A by more than PCT (throughput-like: smaller is
worse). A series missing from either side never trips a threshold (it
shows up under added/removed instead). PCT may be 0 ("any increase").

Last stdout line is a JSON report; exit 0 iff no --fail-on tripped.
Stdlib-only (loads the registry module straight from its file via
bench._obs_mod — no jax, no package import).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import _obs_mod  # noqa: E402

_SPEC_RE = re.compile(
    r"^(?P<name>[^:<>]+?)(?::(?P<stat>value|count|mean|p\d{1,2}))?"
    r"(?P<op>[<>])(?P<pct>\d+(?:\.\d+)?)%?$")


def parse_spec(s):
    m = _SPEC_RE.match(s.strip())
    if not m:
        raise argparse.ArgumentTypeError(
            f"bad --fail-on spec {s!r} (grammar: name[:stat]{{>|<}}PCT%)")
    return {"name": m.group("name"), "stat": m.group("stat"),
            "op": m.group("op"), "pct": float(m.group("pct")),
            "spec": s.strip()}


def load_snapshot(path):
    with open(path) as f:
        doc = json.load(f)
    metrics = doc.get("metrics")
    if not isinstance(metrics, dict):
        raise ValueError(f"{path}: no 'metrics' map — not a registry "
                         "snapshot/dump")
    return doc


def _hist_stats(entry):
    """Rebuild a Histogram from its snapshot and read the rollup stats
    with the registry's own quantile interpolation."""
    H = _obs_mod("metrics").Histogram
    h = H(entry["name"], buckets=entry["bounds"])
    h.merge(entry)
    if not h.count:
        return {"count": 0}
    return {"count": h.count, "mean": h.mean(),
            "p50": h.quantile(0.5), "p99": h.quantile(0.99),
            "min": h.min, "max": h.max}


def _pct(a, b):
    if a is None or b is None:
        return None
    if a == 0:
        return None if b == 0 else float("inf")
    return (b - a) / abs(a) * 100.0


def _round(v, n=4):
    if v is None:
        return None
    if v in (float("inf"), float("-inf")):
        return None  # JSON-safe; the raw a/b values tell the story
    return round(v, n)


def diff(a_doc, b_doc):
    a, b = a_doc["metrics"], b_doc["metrics"]
    report = {"counters": {}, "gauges": {}, "histograms": {},
              "added": sorted(set(b) - set(a)),
              "removed": sorted(set(a) - set(b))}
    for key in sorted(set(a) & set(b)):
        ea, eb = a[key], b[key]
        if ea["type"] != eb["type"]:
            report.setdefault("type_changed", []).append(key)
            continue
        if ea["type"] in ("counter", "gauge"):
            row = {"a": ea["value"], "b": eb["value"],
                   "delta": eb["value"] - ea["value"],
                   "pct": _round(_pct(ea["value"], eb["value"]), 2)}
            bucket = ("counters" if ea["type"] == "counter"
                      else "gauges")
            report[bucket][key] = row
        else:
            try:
                sa, sb = _hist_stats(ea), _hist_stats(eb)
            except (KeyError, ValueError) as e:
                report.setdefault("unreadable", []).append(
                    f"{key}: {e}")
                continue
            row = {"a": {k: _round(v, 6) for k, v in sa.items()},
                   "b": {k: _round(v, 6) for k, v in sb.items()}}
            for stat in ("mean", "p50", "p99"):
                row[f"{stat}_shift_pct"] = _round(
                    _pct(sa.get(stat), sb.get(stat)), 2)
            report["histograms"][key] = row
    return report


def _series_stat(doc, key, stat):
    entry = doc["metrics"].get(key)
    if entry is None:
        return None
    if entry["type"] in ("counter", "gauge"):
        return entry["value"] if stat in (None, "value") else None
    stat = stat or "p50"
    if stat in ("count", "mean"):
        return _hist_stats(entry).get(stat)
    m = re.match(r"p(\d{1,2})$", stat)
    if m:
        H = _obs_mod("metrics").Histogram
        h = H(entry["name"], buckets=entry["bounds"])
        h.merge(entry)
        return h.quantile(int(m.group(1)) / 100.0) if h.count else None
    return None


def check_fail_on(a_doc, b_doc, specs):
    """Evaluate each spec against every matching series present in
    BOTH snapshots; returns the list of failures."""
    failures = []
    for spec in specs:
        keys = [k for k in a_doc["metrics"]
                if k in b_doc["metrics"]
                and (k == spec["name"]
                     or a_doc["metrics"][k]["name"] == spec["name"])]
        for key in keys:
            va = _series_stat(a_doc, key, spec["stat"])
            vb = _series_stat(b_doc, key, spec["stat"])
            if va is None or vb is None:
                continue
            lim = spec["pct"] / 100.0
            if spec["op"] == ">":
                bad = vb > va + abs(va) * lim if va else vb > va
            else:
                bad = vb < va - abs(va) * lim if va else vb < va
            if bad:
                failures.append({
                    "spec": spec["spec"], "series": key,
                    "a": _round(va, 6), "b": _round(vb, 6),
                    "shift_pct": _round(_pct(va, vb), 2)})
    return failures


def _resolve_t(spec, first, last):
    """--at/--vs grammar: absolute epoch seconds, or +S from the
    archive's first sample / -S from its last."""
    s = str(spec).strip()
    if s.startswith("+"):
        return first + float(s[1:])
    if s.startswith("-"):
        return last - float(s[1:])
    return float(s)


def load_history_pair(path, at, vs):
    """(a_doc, b_doc) reconstructed from a HistoryStore snapshot at
    two instants — the history plane's registry_snapshot_at."""
    HistoryStore = _obs_mod("history").HistoryStore
    store = HistoryStore.load(path)
    first, last = store.span()
    if first is None:
        raise ValueError(f"{path}: empty/unreadable history snapshot")
    t0 = _resolve_t(at, first, last)
    t1 = _resolve_t(vs, first, last)
    return store.registry_snapshot_at(t0), \
        store.registry_snapshot_at(t1), t0, t1


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="diff two metrics.json registry snapshots, or one "
                    "history archive at two points in time")
    ap.add_argument("a", nargs="?", help="baseline metrics.json")
    ap.add_argument("b", nargs="?", help="candidate metrics.json")
    ap.add_argument("--history", default=None, metavar="SNAPSHOT",
                    help="HistoryStore snapshot to reconstruct both "
                         "sides from (with --at/--vs)")
    ap.add_argument("--at", default=None, metavar="T0",
                    help="history baseline instant (epoch s, +S from "
                         "first sample, -S from last)")
    ap.add_argument("--vs", default=None, metavar="T1",
                    help="history candidate instant (same grammar)")
    ap.add_argument("--fail-on", action="append", type=parse_spec,
                    default=[], metavar="name[:stat]{>|<}PCT%",
                    help="regression threshold (repeatable)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the human-readable section")
    args = ap.parse_args(argv)

    if args.history is not None:
        if args.at is None or args.vs is None:
            ap.error("--history requires --at and --vs")
        a_doc, b_doc, t0, t1 = load_history_pair(
            args.history, args.at, args.vs)
        a_name = f"{args.history}@{t0:.3f}"
        b_name = f"{args.history}@{t1:.3f}"
    else:
        if not args.a or not args.b:
            ap.error("need two snapshot paths (or --history "
                     "--at --vs)")
        a_doc, b_doc = load_snapshot(args.a), load_snapshot(args.b)
        a_name, b_name = args.a, args.b
    report = diff(a_doc, b_doc)
    failures = check_fail_on(a_doc, b_doc, args.fail_on)
    report.update({"a": a_name, "b": b_name,
                   "fail_on": [s["spec"] for s in args.fail_on],
                   "failures": failures, "ok": not failures})

    if not args.quiet:
        changed = [(k, r) for k, r in report["counters"].items()
                   if r["delta"]]
        for k, r in changed[:40]:
            print(f"  counter {k}: {r['a']} -> {r['b']} "
                  f"({r['delta']:+})", file=sys.stderr)
        for k, r in list(report["histograms"].items())[:40]:
            if r.get("p99_shift_pct") is not None:
                print(f"  hist {k}: p50 {r['a'].get('p50')} -> "
                      f"{r['b'].get('p50')}, p99 {r['a'].get('p99')} "
                      f"-> {r['b'].get('p99')} "
                      f"({r['p99_shift_pct']:+}%)", file=sys.stderr)
        for k in report["added"][:20]:
            print(f"  added   {k}", file=sys.stderr)
        for k in report["removed"][:20]:
            print(f"  removed {k}", file=sys.stderr)
        for f in failures:
            print(f"  FAIL {f['spec']}: {f['series']} {f['a']} -> "
                  f"{f['b']} ({f['shift_pct']}%)", file=sys.stderr)
    print(json.dumps(report, default=str))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
