"""replay_smoke — the campaign's CPU drill for the traffic-capture &
deterministic-replay plane (ISSUE 12).

Shape (seeded, CPU-only, no tunnel window burned):

1. **wave-drift guard**: regenerate the seeded 20-request synthetic
   wave (``fleet_replay.synth_wave``) and assert its spec fields
   (prompts, arrival offsets, tenants, priorities) equal the
   committed golden ``tools/golden/replay_wave.json`` — a silently
   drifted generator would invalidate every cross-round comparison;
2. **capture**: a 2-replica in-process fleet with capture armed
   drives the committed wave open-loop; the archive must hold all 20
   requests, resolve-complete, zero torn drops, zero
   capture<->trace-sampling divergences, and the fleet's compile
   counts stay frozen with capture on;
3. **committed-archive golden replay**: replay the COMMITTED archive
   (which carries the tokens recorded at golden-write time) in
   golden mode — token-exact per rid, zero new XLA traces. Timing
   gates are disabled here (the committed latencies were recorded on
   the golden-write box); tokens and compile counts are what the
   committed golden pins;
4. **clean-wave gate proof**: replay THIS run's live capture in
   golden mode with the default gates — per-hop attribution share
   deltas must land within 5% and the latency ratios inside their
   limits (vacuity-guarded: the verdict must actually have compared
   tokens and hops);
5. **regression gate proof**: replay the live capture again with an
   injected per-round replica slowdown (``replica_slow`` — the
   mid-wave latency regression) — the SAME gate spec MUST trip (a
   gate that never fires is not a gate);
6. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (capture
   fleet registry incl. the ``fleet_capture_*`` series + recompile
   report), ``replay_verdict.json`` (clean),
   ``replay_verdict_regression.json``, and the capture archive dir.

Regenerate the committed golden with ``--write-golden`` (captures the
wave on THIS box and stores spec + resolved tokens + sampling meta).
Last stdout line is a JSON verdict; exit 0 only when every check
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

GOLDEN = os.path.join(REPO, "tools", "golden", "replay_wave.json")
WAVE_SEED = 12
WAVE_N = 20

# the spec fields the drift guard pins (resolve fields — tokens,
# latencies — are measurements, not spec)
SPEC_FIELDS = ("rid", "arrival_s", "tenant", "priority",
               "deadline_ms", "prompt", "max_new", "eos")

NO_TIMING_GATES = {"e2e_p99_ratio": None, "ttft_p99_ratio": None,
                   "hop_share_delta": None}


def _wave():
    import fleet_replay as fr
    return fr.synth_wave(WAVE_SEED, WAVE_N, burst=4,
                         burst_gap_s=0.05)


def _spec(entries):
    return [{k: e.get(k) for k in SPEC_FIELDS} for e in entries]


def _capture_run(wave, out_dir):
    """Drive `wave` through a capture-armed fleet; returns
    (archive_entries, registry, checks_fragment)."""
    import fleet_replay as fr
    from paddle_tpu.observability.trace import report_all
    from paddle_tpu.observability.trafficrec import load_archive

    cap_dir = os.path.join(out_dir, "capture")
    router, engines, frozen = fr.build_fleet(wave,
                                             capture_dir=cap_dir)
    checks = {}
    try:
        _res, _wall, _map = fr.replay(router, wave, timeout_s=120.0)
        reg = router.registry
        checks["capture_all_requests"] = int(reg.get(
            "fleet_capture_requests_total").value) == len(wave)
        checks["capture_no_trace_missing"] = int(reg.get(
            "fleet_capture_trace_missing_total").value) == 0
        checks["capture_no_errors"] = int(reg.get(
            "fleet_capture_errors_total").value) == 0
        checks["capture_compiles_frozen"] = (
            [e.compile_counts() for e in engines] == frozen
            and router.compile_report()["unexpected_retraces"] == 0)
        reg.dump(os.path.join(out_dir, "metrics.json"),
                 extra={"recompile_report": report_all(),
                        "stage": "replay_smoke"})
    finally:
        router.close()
        for e in engines:
            e.close()
    entries, _meta, stats = load_archive(cap_dir)
    checks["archive_complete"] = (
        len(entries) == len(wave) and stats["unresolved"] == 0
        and stats["torn_drops"] == 0)
    return entries, stats, checks


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--write-golden", action="store_true",
                    help="capture the seeded wave on THIS box and "
                         "save it as the committed golden")
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "replay_smoke")
    os.makedirs(out_dir, exist_ok=True)
    os.environ.setdefault("PADDLE_TPU_FLIGHT_DIR", out_dir)

    import fleet_replay as fr
    from paddle_tpu.resilience import faults

    wave = _wave()

    if args.write_golden:
        entries, stats, checks = _capture_run(wave, out_dir)
        ok = all(checks.values())
        if ok:
            # wave_spec = the GENERATED schedule (the drift guard's
            # reference); entries = the CAPTURED archive (measured
            # arrival offsets + resolved tokens — golden replay input).
            # Through io/atomic: a ctrl-C mid-regen must cost this
            # regen, never the committed golden every future campaign
            # replays against.
            from paddle_tpu.io import atomic
            atomic.atomic_replace(
                GOLDEN,
                json.dumps({"format": 1,
                            "seed": WAVE_SEED, "n": WAVE_N,
                            "wave_spec": _spec(wave),
                            "entries": entries}, indent=1) + "\n")
        print(json.dumps({"ok": ok, "wrote_golden": GOLDEN if ok
                          else None, "checks": checks}))
        return 0 if ok else 1

    checks = {}

    # -- 1. wave-drift guard ----------------------------------------------
    try:
        with open(GOLDEN) as f:
            golden = json.load(f)
        committed = golden.get("entries") or []
        spec = golden.get("wave_spec") or []
    except (OSError, json.JSONDecodeError):
        committed, spec = [], []
    checks["wave_matches_committed_golden"] = bool(spec) and \
        _spec(wave) == spec

    # -- 2. capture the wave live -----------------------------------------
    live, stats, cap_checks = _capture_run(wave, out_dir)
    checks.update(cap_checks)

    # -- 3. committed-archive golden replay (token-exact, no new
    # traces; timing gates off — committed latencies are another
    # box's measurements) --------------------------------------------------
    if committed:
        v_gold, _ = fr.run_replay(
            committed, out_dir=os.path.join(out_dir, "committed"),
            golden=True, gates=NO_TIMING_GATES)
        checks["committed_golden_token_exact"] = bool(
            v_gold["golden"]["token_exact"]
            and v_gold["golden"]["compared"] == WAVE_N)
        checks["committed_golden_zero_new_traces"] = (
            v_gold["golden"]["compile_frozen"]
            and v_gold["golden"]["new_traces"] == 0
            and v_gold["golden"]["unexpected_retraces"] == 0)
        checks["committed_golden_ok"] = bool(v_gold["ok"])
    else:
        checks["committed_golden_token_exact"] = False
        checks["committed_golden_zero_new_traces"] = False
        checks["committed_golden_ok"] = False

    # -- 4. clean-wave gate proof (default gates incl. the 5%
    # per-hop attribution bar) ---------------------------------------------
    v_clean, _ = fr.run_replay(
        live, out_dir=os.path.join(out_dir, "clean"), golden=True)
    with open(os.path.join(out_dir, "replay_verdict.json"), "w") as f:
        json.dump(v_clean, f, indent=1)
    checks["clean_replay_ok"] = bool(v_clean["ok"])
    # vacuity guards: the clean pass must have genuinely compared
    checks["clean_replay_compared"] = (
        v_clean["golden"]["compared"] == WAVE_N
        and len(v_clean["attribution"]["hops"]) > 0)
    checks["clean_hop_deltas_within_5pct"] = (
        len(v_clean["attribution"]["hops"]) > 0
        and v_clean["attribution"]["max_share_delta"] <= 0.05)

    # -- 5. regression gate proof -----------------------------------------
    def arm():
        for name in ("r0", "r1"):
            faults.inject("replica_slow", count=10_000,
                          seconds=0.05, replica=name)

    try:
        v_reg, _ = fr.run_replay(
            live, out_dir=os.path.join(out_dir, "regression"),
            faults_arm=arm)
    finally:
        faults.clear()
    with open(os.path.join(out_dir,
                           "replay_verdict_regression.json"),
              "w") as f:
        json.dump(v_reg, f, indent=1)
    checks["regression_trips_gate"] = (not v_reg["ok"]) and any(
        f.get("gate") in ("e2e_p99_ratio", "ttft_p99_ratio")
        for f in v_reg["failures"])

    ok = all(checks.values())
    print(json.dumps({
        "ok": ok, "checks": checks,
        "clean_max_hop_delta":
            v_clean["attribution"]["max_share_delta"],
        "clean_ratios": v_clean["slo"]["ratios"],
        "regression_failures": v_reg["failures"],
        "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
