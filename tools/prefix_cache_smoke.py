"""prefix_cache_smoke — the campaign's CPU drill for copy-on-write
prefix caching (ISSUE 16 / round 19).

Shape (seeded, CPU-only, no tunnel window burned):

1. build a seeded SHARED-PREFIX wave: three base prompts (the "system
   prompt / few-shot template" stand-ins) each extended with short
   random tails — the traffic pattern the prefix cache exists for;
2. run the wave TWICE through a cache-ON engine and a cache-OFF
   control (same model, same sampling, both warmed on every prefill
   bucket AND the tail-prefill ladder before the clock starts);
3. invariants, asserted hard:
   - **token-exact**: every ON stream equals its OFF stream token for
     token across both waves (the hard invariant — a cache hit may
     change TTFT, never tokens);
   - **page hit rate ≥ floor** (default 0.5): cumulative page-level
     hit rate from the ON engine's health()["prefix_cache"] — wave 1
     hits within-wave (shared bases), wave 2 hits everything;
   - **TTFT p50 strictly better ON**: the ON engine's
     serve_ttft_seconds p50 below the OFF control's on the same wave
     (hits run a short bucketed tail prefill instead of the full
     ladder);
   - **zero new traces after warmup**: compile counts frozen across
     both waves with caching ON, zero unexpected retraces;
   - refcount conservation: after close() every page is back on the
     free list (shared pages included).
4. artifacts into $BENCH_TELEMETRY_DIR: ``metrics.json`` (the ON
   engine's registry + recompile report — the validate_stages
   contract), ``prefix_cache.json`` (both engines' health sections +
   per-wave stats).

Last stdout line is a JSON verdict; exit 0 only when every assertion
holds.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NEW_TOK = 8
BASE_LENS = (80, 110, 95)    # shared-template stand-ins — long
#                              enough that a full prefill (bucket 128)
#                              visibly outweighs a hit's tail prefill
#                              (bucket 16) even on the CPU drill
TAILS = 18                   # requests per wave
MAX_SEQ_LEN = 128            # gpt-tiny's max_position_embeddings
NUM_PAGES = 64               # pool sized so reclaim never starves the
#                              index (the default serving pool is
#                              deliberately tiny)


def build_wave(seed=0, vocab=256):
    """Seeded shared-prefix wave: each request is one of the three
    base prompts plus a short random tail — same generator as the
    engine test suite's, kept tool-local so the smoke stays runnable
    without pytest."""
    import numpy as np
    rng = np.random.default_rng(seed)
    bases = [rng.integers(1, vocab, (n,)).astype(np.int32)
             for n in BASE_LENS]
    return [np.concatenate([bases[i % len(bases)],
                            rng.integers(1, vocab,
                                         (3 + i % 7,)).astype(np.int32)])
            for i in range(TAILS)]


def run_engine(model, prompts, *, prefix_cache, waves=2):
    """One engine through ``waves`` passes of the wave; returns
    (tokens_per_wave, facts)."""
    from paddle_tpu.nlp.serving import ServingEngine
    eng = ServingEngine(model, max_slots=2, page_size=16,
                        max_seq_len=MAX_SEQ_LEN, steps_per_dispatch=4,
                        num_pages=NUM_PAGES,
                        prefix_cache=prefix_cache)
    eng.warmup(buckets=sorted({len(p) for p in prompts}), decode=True)
    frozen = eng.compile_counts()
    out = [eng.generate(prompts, max_new_tokens=NEW_TOK)
           for _ in range(int(waves))]
    h = eng.health()
    ttft = eng.registry.get("serve_ttft_seconds")
    facts = {
        "prefix_cache": h.get("prefix_cache"),
        "ttft_p50_s": ttft.quantile(0.5) if ttft.count else None,
        "ttft_p99_s": ttft.quantile(0.99) if ttft.count else None,
        "compile_frozen": eng.compile_counts() == frozen,
        "unexpected_retraces": eng.tracer.unexpected_retraces(),
        "registry": eng.registry,
    }
    usable = eng.num_pages - 1           # page 0 is the write sink
    eng.close()
    facts["pages_back_after_close"] = len(eng._free_pages) == usable
    return out, facts


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--waves", type=int, default=2)
    ap.add_argument("--hit-floor", type=float, default=0.5,
                    help="minimum cumulative page-level hit rate")
    args = ap.parse_args(argv)

    out_dir = os.environ.get("BENCH_TELEMETRY_DIR") or os.path.join(
        REPO, "campaign_out", "telemetry", "prefix_cache_smoke")
    os.makedirs(out_dir, exist_ok=True)

    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.observability.trace import report_all

    paddle.seed(0)
    model = GPTForCausalLM(_resolve_config("gpt-tiny"))
    model.eval()
    prompts = build_wave(args.seed)

    on_toks, on = run_engine(model, prompts, prefix_cache=True,
                             waves=args.waves)
    off_toks, off = run_engine(model, prompts, prefix_cache=False,
                               waves=args.waves)

    pc = on["prefix_cache"] or {}
    total = int(pc.get("total_pages") or 0)
    hit_rate = None if not total \
        else pc.get("hit_pages", 0) / total

    checks = {
        "token_exact_on_vs_off": on_toks == off_toks,
        "page_hit_rate_over_floor": (
            hit_rate is not None and hit_rate >= args.hit_floor),
        "ttft_p50_on_below_off": (
            on["ttft_p50_s"] is not None
            and off["ttft_p50_s"] is not None
            and on["ttft_p50_s"] < off["ttft_p50_s"]),
        "zero_new_traces_after_warmup": (
            on["compile_frozen"]
            and on["unexpected_retraces"] == 0),
        "pages_back_after_close": on["pages_back_after_close"],
        "off_control_cache_disabled": off["prefix_cache"] is None,
    }

    on["registry"].dump(os.path.join(out_dir, "metrics.json"),
                        extra={"recompile_report": report_all(),
                               "stage": "prefix_cache_smoke"})
    with open(os.path.join(out_dir, "prefix_cache.json"), "w") as f:
        json.dump({"on": pc,
                   "hit_rate": hit_rate,
                   "ttft_p50_on_s": on["ttft_p50_s"],
                   "ttft_p50_off_s": off["ttft_p50_s"],
                   "ttft_p99_on_s": on["ttft_p99_s"],
                   "ttft_p99_off_s": off["ttft_p99_s"]}, f, indent=1)

    ok = all(bool(v) for v in checks.values())
    print(json.dumps({
        "ok": ok, "checks": checks,
        "page_hit_rate": None if hit_rate is None
        else round(hit_rate, 4),
        "hit_floor": args.hit_floor,
        "hits": pc.get("hits"), "misses": pc.get("misses"),
        "cow_copies": pc.get("cow_copies"),
        "ttft_p50_on_s": on["ttft_p50_s"],
        "ttft_p50_off_s": off["ttft_p50_s"],
        "out_dir": out_dir}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
