"""Paged GQA flash-decode kernel (ops/pallas/flash_decode.py) vs the
jnp dense reference (nlp/paged_cache.paged_attention_ref), interpret
mode — the identical kernel/lowering path the TPU runs.

Covers: MHA and GQA head groupings, fp32/bf16/int8 cache dtypes (int8
with per-token f32 scale sidecars), ragged per-slot lengths including
zero (inactive slot -> zero row), trash-page routing, and the
write-path helpers the serving engine builds on.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.nlp import paged_cache as pc
from paddle_tpu.ops.pallas.flash_decode import paged_flash_decode


def _case(b=3, hkv=2, g=2, d=64, ps=16, p=9, mp=4, seed=0, dtype="float32"):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, hkv, g, d)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((hkv, p, ps, d)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((hkv, p, ps, d)), jnp.float32)
    pt = jnp.asarray(rng.integers(1, p, (b, mp)), jnp.int32)
    lens = jnp.asarray(rng.integers(0, mp * ps + 1, (b,)), jnp.int32)
    if dtype == "int8":
        kq, ks = pc.quantize_rows(kp)
        vq, vs = pc.quantize_rows(vp)
        return q, kq, vq, pt, lens, ks, vs
    dt = jnp.dtype(dtype)
    return q, kp.astype(dt), vp.astype(dt), pt, lens, None, None


def _both(q, kp, vp, pt, lens, ks, vs):
    ref = pc.paged_attention_ref(q, kp, vp, pt, lens,
                                 k_scale=ks, v_scale=vs)
    out = paged_flash_decode(q, kp, vp, pt, lens, k_scale=ks,
                             v_scale=vs, interpret=True)
    return np.asarray(ref, np.float32), np.asarray(out, np.float32)


class TestKernelVsReference:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
    def test_gqa(self, dtype):
        ref, out = _both(*_case(dtype=dtype))
        assert np.allclose(ref, out, atol=3e-5), \
            np.abs(ref - out).max()

    def test_mha_groups_1(self):
        ref, out = _both(*_case(hkv=4, g=1, seed=2))
        assert np.allclose(ref, out, atol=3e-5)

    def test_wide_group_pads_sublanes(self):
        # G=12 > the 8-sublane minimum: exercises the pad/unpad path
        ref, out = _both(*_case(hkv=1, g=12, seed=3))
        assert np.allclose(ref, out, atol=3e-5)

    def test_zero_len_slot_is_zero_row(self):
        q, kp, vp, pt, lens, ks, vs = _case(seed=4)
        lens = lens.at[1].set(0)
        ref, out = _both(q, kp, vp, pt, lens, ks, vs)
        assert np.allclose(out[1], 0.0)
        assert np.allclose(ref, out, atol=3e-5)

    def test_single_token_history(self):
        q, kp, vp, pt, lens, ks, vs = _case(seed=5)
        lens = jnp.ones_like(lens)
        ref, out = _both(q, kp, vp, pt, lens, ks, vs)
        assert np.allclose(ref, out, atol=3e-5)

    def test_trash_table_rows_ignored(self):
        """Entries past a slot's allocation point at the trash page —
        masked by lens, they must not perturb the output."""
        q, kp, vp, pt, lens, ks, vs = _case(seed=6)
        lens = jnp.asarray([10, 20, 16], jnp.int32)  # <= 2 pages each
        pt_trash = pt.at[:, 2:].set(pc.TRASH_PAGE)
        ref, out = _both(q, kp, vp, pt_trash, lens, ks, vs)
        ref2, out2 = _both(q, kp, vp, pt, lens, ks, vs)
        assert np.allclose(out, out2, atol=3e-5)
        assert np.allclose(ref, out, atol=3e-5)
        assert np.allclose(ref, ref2, atol=3e-5)


class TestWritePath:
    def test_token_write_lands_at_position(self):
        hkv, d, ps, p, b, mp = 2, 8, 8, 5, 2, 3
        kp, vp, ks, vs = pc.alloc_pages(p, ps, hkv, d, "float32")
        pt = np.array([[1, 2, 0], [3, 4, 0]], np.int32)
        pos = jnp.asarray([3, 9], jnp.int32)   # page 0-row 3 / page 1-row 1
        cache = pc.PagedLayerCache(kp, vp, jnp.asarray(pt), pos)
        k_new = jnp.arange(b * hkv * d, dtype=jnp.float32).reshape(
            b, hkv, d)
        kp2, vp2, _, _ = pc.write_token_kv(cache, k_new, k_new + 1.0,
                                           jnp.ones((b,), bool))
        np.testing.assert_allclose(np.asarray(kp2[:, 1, 3]),
                                   np.asarray(k_new[0]).swapaxes(0, 0))
        np.testing.assert_allclose(np.asarray(kp2[:, 4, 1]),
                                   np.asarray(k_new[1]))
        np.testing.assert_allclose(np.asarray(vp2[:, 4, 1]),
                                   np.asarray(k_new[1]) + 1.0)

    def test_prompt_write_blocks(self):
        hkv, d, ps = 1, 4, 8
        kp, vp, _, _ = pc.alloc_pages(4, ps, hkv, d, "float32")
        s_b = 16
        k_full = jnp.arange(s_b * hkv * d, dtype=jnp.float32).reshape(
            1, s_b, hkv, d)
        pages_vec = jnp.asarray([2, 3], jnp.int32)
        kp2, vp2, _, _ = pc.write_prompt_kv(kp, vp, None, None, k_full,
                                            k_full, pages_vec)
        got = np.asarray(kp2[0, 2])            # first page, head 0
        want = np.asarray(k_full[0, :ps, 0])
        np.testing.assert_allclose(got, want)
        got2 = np.asarray(kp2[0, 3])
        np.testing.assert_allclose(got2, np.asarray(k_full[0, ps:, 0]))

    def test_int8_quantize_roundtrip(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 3, 64)) * 5, jnp.float32)
        q, s = pc.quantize_rows(x)
        back = np.asarray(q, np.float32) * np.asarray(s)
        err = np.abs(back - np.asarray(x)).max()
        amax = np.abs(np.asarray(x)).max()
        assert err <= amax / 127.0 * 0.51 + 1e-6

    def test_quantize_zero_row_safe(self):
        q, s = pc.quantize_rows(jnp.zeros((2, 3, 8)))
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.isfinite(np.asarray(s)))
