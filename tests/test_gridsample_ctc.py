"""Torch-golden parity for grid_sample / affine_grid / ctc_loss —
previously implemented but never numerically verified (SURVEY marked
them gated).
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


@pytest.mark.parametrize("align", [True, False])
@pytest.mark.parametrize("mode", ["bilinear", "nearest"])
def test_grid_sample_matches_torch(align, mode):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 5, 7)).astype(np.float32)
    grid = (rng.random((2, 4, 6, 2)).astype(np.float32) * 2.4 - 1.2)
    ours = _np(F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                             mode=mode, padding_mode="zeros",
                             align_corners=align))
    ref = tF.grid_sample(torch.from_numpy(x), torch.from_numpy(grid),
                         mode=mode, padding_mode="zeros",
                         align_corners=align).numpy()
    if mode == "nearest":
        # ties at exactly .5 may round differently; compare off-tie only
        close = np.isclose(ours, ref, atol=1e-5)
        assert close.mean() > 0.97, close.mean()
    else:
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("align", [True, False])
def test_affine_grid_matches_torch(align):
    rng = np.random.default_rng(1)
    theta = rng.standard_normal((2, 2, 3)).astype(np.float32)
    ours = _np(F.affine_grid(paddle.to_tensor(theta), (2, 3, 4, 5),
                             align_corners=align))
    ref = tF.affine_grid(torch.from_numpy(theta), (2, 3, 4, 5),
                         align_corners=align).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


def test_affine_grid_then_sample_identity():
    """Identity theta reproduces the input (the STN smoke check)."""
    rng = np.random.default_rng(2)
    x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
    theta = np.asarray([[[1, 0, 0], [0, 1, 0]]], np.float32)
    grid = F.affine_grid(paddle.to_tensor(theta), (1, 2, 6, 6),
                         align_corners=True)
    out = _np(F.grid_sample(paddle.to_tensor(x), grid,
                            align_corners=True))
    np.testing.assert_allclose(out, x, rtol=1e-4, atol=1e-5)


def test_grid_sample_gradients_flow():
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((1, 2, 4, 4)), jnp.float32)
    grid = jnp.asarray(rng.random((1, 3, 3, 2)) * 1.6 - 0.8, jnp.float32)

    def loss(a, g):
        out = F.grid_sample(paddle.to_tensor(a), paddle.to_tensor(g))
        return jnp.sum(out._value ** 2)

    ga, gg = jax.grad(loss, argnums=(0, 1))(x, grid)
    assert float(jnp.abs(ga).max()) > 0
    assert float(jnp.abs(gg).max()) > 0


def _ctc_fixture(rng, b=3, t=12, c=6, lmax=4):
    logits = rng.standard_normal((t, b, c)).astype(np.float32)
    log_probs = torch.log_softmax(torch.from_numpy(logits), dim=-1)
    labels = rng.integers(1, c, (b, lmax)).astype(np.int64)
    in_len = np.asarray([t, t - 2, t - 1], np.int64)[:b]
    lab_len = np.asarray([lmax, lmax - 1, 2], np.int64)[:b]
    return log_probs, labels, in_len, lab_len


def test_ctc_loss_matches_torch_mean():
    rng = np.random.default_rng(4)
    log_probs, labels, in_len, lab_len = _ctc_fixture(rng)
    ref = tF.ctc_loss(log_probs, torch.from_numpy(labels),
                      torch.from_numpy(in_len), torch.from_numpy(lab_len),
                      blank=0, reduction="mean").numpy()
    ours = _np(F.ctc_loss(paddle.to_tensor(log_probs.numpy()),
                          paddle.to_tensor(labels),
                          paddle.to_tensor(in_len),
                          paddle.to_tensor(lab_len), blank=0,
                          reduction="mean"))
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_matches_torch_none_and_sum():
    rng = np.random.default_rng(5)
    log_probs, labels, in_len, lab_len = _ctc_fixture(rng)
    ref = tF.ctc_loss(log_probs, torch.from_numpy(labels),
                      torch.from_numpy(in_len), torch.from_numpy(lab_len),
                      blank=0, reduction="none").numpy()
    ours = _np(F.ctc_loss(paddle.to_tensor(log_probs.numpy()),
                          paddle.to_tensor(labels),
                          paddle.to_tensor(in_len),
                          paddle.to_tensor(lab_len), blank=0,
                          reduction="none"))
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)
    ref_s = tF.ctc_loss(log_probs, torch.from_numpy(labels),
                        torch.from_numpy(in_len),
                        torch.from_numpy(lab_len), blank=0,
                        reduction="sum").numpy()
    ours_s = _np(F.ctc_loss(paddle.to_tensor(log_probs.numpy()),
                            paddle.to_tensor(labels),
                            paddle.to_tensor(in_len),
                            paddle.to_tensor(lab_len), blank=0,
                            reduction="sum"))
    np.testing.assert_allclose(ours_s, ref_s, rtol=1e-4, atol=1e-3)


def test_ctc_layer_form():
    from paddle_tpu.nn import CTCLoss
    rng = np.random.default_rng(6)
    log_probs, labels, in_len, lab_len = _ctc_fixture(rng)
    loss = CTCLoss(blank=0)(paddle.to_tensor(log_probs.numpy()),
                            paddle.to_tensor(labels),
                            paddle.to_tensor(in_len),
                            paddle.to_tensor(lab_len))
    assert np.isfinite(float(_np(loss)))


@pytest.mark.parametrize("pad", ["border", "reflection"])
@pytest.mark.parametrize("align", [True, False])
def test_grid_sample_padding_modes_match_torch(pad, align):
    rng = np.random.default_rng(7)
    x = rng.standard_normal((2, 2, 5, 6)).astype(np.float32)
    grid = (rng.random((2, 4, 4, 2)).astype(np.float32) * 3.0 - 1.5)
    ours = _np(F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                             mode="bilinear", padding_mode=pad,
                             align_corners=align))
    ref = tF.grid_sample(torch.from_numpy(x), torch.from_numpy(grid),
                         mode="bilinear", padding_mode=pad,
                         align_corners=align).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)


def test_grid_sample_unknown_padding_rejected():
    with pytest.raises(ValueError, match="padding_mode"):
        F.grid_sample(paddle.to_tensor(np.zeros((1, 1, 2, 2), np.float32)),
                      paddle.to_tensor(np.zeros((1, 1, 1, 2), np.float32)),
                      padding_mode="wrap")
