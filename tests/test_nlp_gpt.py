"""GPT flagship tests (parity: PaddleNLP tests/transformers/gpt)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.mpu import shard_model
from paddle_tpu.nlp import (GPTConfig, GPTModel, GPTForCausalLM,
                            GPTPretrainingCriterion, GPT_CONFIGS)
from paddle_tpu.nn.layer import functional_call


def tiny():
    return GPTConfig(**GPT_CONFIGS["gpt-tiny"])


def test_forward_shape():
    m = GPTForCausalLM(tiny())
    m.eval()
    ids = paddle.to_tensor(np.arange(2 * 16).reshape(2, 16) % 256)
    logits = m(ids)
    assert logits.shape == [2, 16, 256]


def test_causality():
    """logits at position t must not depend on tokens > t."""
    m = GPTForCausalLM(tiny())
    m.eval()
    a = np.random.RandomState(0).randint(0, 256, (1, 12))
    b = a.copy()
    b[0, 8:] = (b[0, 8:] + 7) % 256  # perturb the future
    la = m(paddle.to_tensor(a)).numpy()
    lb = m(paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(la[0, :8], lb[0, :8], rtol=1e-4, atol=1e-4)
    assert np.abs(la[0, 8:] - lb[0, 8:]).max() > 1e-3


def test_cached_decode_matches_full_forward():
    m = GPTForCausalLM(tiny())
    m.eval()
    ids = np.random.RandomState(1).randint(0, 256, (2, 10))
    full = m(paddle.to_tensor(ids)).numpy()
    # prefill on first 9 tokens, then decode token 10 with the cache
    logits, cache = m(paddle.to_tensor(ids[:, :9]), use_cache=True)
    pos = paddle.to_tensor(np.full((2, 1), 9, dtype=np.int32))
    step, _ = m(paddle.to_tensor(ids[:, 9:10]), position_ids=pos, cache=cache)
    np.testing.assert_allclose(step.numpy()[:, 0], full[:, 9],
                               rtol=1e-4, atol=1e-4)


def test_generate():
    m = GPTForCausalLM(tiny())
    ids = paddle.to_tensor(np.array([[1, 2, 3]], dtype=np.int64))
    out = m.generate(ids, max_new_tokens=5)
    assert out.shape == [1, 8]
    out2 = m.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(out.numpy(), out2.numpy())  # greedy determinism


def test_pretraining_criterion():
    crit = GPTPretrainingCriterion()
    logits = np.random.RandomState(2).randn(2, 4, 16).astype(np.float32)
    labels = np.random.RandomState(3).randint(0, 16, (2, 4))
    mask = np.array([[1, 1, 0, 1], [1, 0, 1, 1]], dtype=np.float32)
    got = float(crit(paddle.to_tensor(logits), paddle.to_tensor(labels),
                     paddle.to_tensor(mask)))
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[..., 0]
    ce = lse - np.take_along_axis(logits, labels[..., None], -1)[..., 0]
    want = (ce * mask).sum() / mask.sum()
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_gspmd_dp_mp_matches_dense():
    """Sharded (dp=2, mp=4) jitted forward == dense single-device forward."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
    old = mesh_mod._global_mesh
    try:
        m = GPTForCausalLM(tiny())
        m.eval()
        ids = np.random.RandomState(4).randint(0, 256, (4, 16))
        dense = m(paddle.to_tensor(ids)).numpy()
        mesh_mod.set_mesh(mesh)
        shard_model(m, mesh)
        params, buffers = m.raw_state()

        @jax.jit
        def fwd(params, ids):
            out = functional_call(m, params, buffers, paddle.Tensor(ids))
            return out._value

        got = np.asarray(fwd(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, dense, rtol=2e-4, atol=2e-4)
    finally:
        mesh_mod._global_mesh = old


def test_grad_step_decreases_loss():
    """One fused train step on the tiny config lowers the LM loss."""
    m = GPTForCausalLM(tiny())
    crit = GPTPretrainingCriterion()
    m.train()
    ids = np.random.RandomState(5).randint(0, 256, (4, 16))
    inp, lab = ids[:, :-1], ids[:, 1:]
    params, buffers = m.raw_state()

    def loss_fn(p):
        logits = functional_call(m, p, buffers, paddle.Tensor(inp))
        return crit(logits, paddle.Tensor(lab))._value

    l0, g = jax.value_and_grad(loss_fn)(params)
    p1 = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg, params, g)
    l1 = loss_fn(p1)
    assert float(l1) < float(l0)


def test_shard_map_mp_loss_matches_dense():
    """Explicit shard_map TP: vocab-local logits + ParallelCrossEntropy
    must give the SAME loss as the dense model (regression: gathering
    logits before the parallel CE double-counted the partition function)."""
    from jax.sharding import Mesh
    from jax.experimental.shard_map import shard_map
    m = GPTForCausalLM(tiny())
    crit = GPTPretrainingCriterion()
    m.eval()
    ids = np.random.RandomState(6).randint(0, 256, (2, 16))
    inp, lab = ids[:, :-1], ids[:, 1:]
    dense_logits = m(paddle.to_tensor(inp))
    dense_loss = float(crit(dense_logits, paddle.to_tensor(lab)))

    mesh = Mesh(np.array(jax.devices()[:4]), ("mp",))
    params, buffers = m.raw_state()

    def step(inp, lab, params):
        logits = functional_call(m, params, buffers, paddle.Tensor(inp))
        return crit(logits, paddle.Tensor(lab))._value

    specs = {}
    for n, p in m.named_parameters():
        sp = getattr(p, "sharding_spec", None)
        specs[n] = sp if sp is not None else P()
    fn = shard_map(step, mesh=mesh, in_specs=(P(), P(), specs),
                   out_specs=P(), check_rep=False)
    got = float(jax.jit(fn)(inp, lab, params))
    np.testing.assert_allclose(got, dense_loss, rtol=1e-4)


def test_float_padding_mask_matches_bool_mask():
    """Regression: 0/1 int/float padding masks (tokenizer convention) must
    mask, not act as a +1 additive bias."""
    import jax.numpy as jnp
    from paddle_tpu.nlp.gpt import GPTModel, GPTConfig
    import paddle_tpu as paddle

    paddle.seed(0)
    m = GPTModel(GPTConfig(vocab_size=64, hidden_size=32,
                           num_hidden_layers=1, num_attention_heads=2,
                           max_position_embeddings=16,
                           hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0))
    m.eval()
    ids = paddle.to_tensor(np.arange(8, dtype=np.int32)[None, :] % 64)
    pad = np.array([[1, 1, 1, 1, 1, 0, 0, 0]])
    out_bool = m(ids, attention_mask=paddle.to_tensor(pad.astype(bool)))
    out_f32 = m(ids, attention_mask=paddle.to_tensor(pad.astype(np.float32)))
    out_i64 = m(ids, attention_mask=paddle.to_tensor(pad.astype(np.int64)))
    np.testing.assert_allclose(np.asarray(out_f32._value),
                               np.asarray(out_bool._value), atol=1e-6)
    np.testing.assert_allclose(np.asarray(out_i64._value),
                               np.asarray(out_bool._value), atol=1e-6)
