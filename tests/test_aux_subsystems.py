"""Aux subsystems: profiler, NaN checks, sharding validator, checkpoint
manager, utils (SURVEY §2.11)."""
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.tensor import Tensor


class TestProfiler:
    def test_step_timing_and_summary(self):
        from paddle_tpu.profiler import Profiler
        with Profiler() as p:
            for _ in range(3):
                time.sleep(0.01)
                p.step(num_samples=32)
        s = p.summary()
        assert "train_step" in s and p.steps == 3
        assert "samples/s" in s

    def test_record_event(self):
        from paddle_tpu.profiler import Profiler, RecordEvent
        p = Profiler().start()
        with RecordEvent("matmul", p):
            jnp.ones((64, 64)) @ jnp.ones((64, 64))
        p.stop()
        assert "matmul" in p.summary()


class TestCheckNumerics:
    def test_raises_on_nan(self):
        from paddle_tpu.amp.debugging import check_numerics
        bad = {"w": Tensor(jnp.array([1.0, float("nan")]))}
        with pytest.raises(FloatingPointError, match="NaN"):
            check_numerics(bad)

    def test_warn_mode(self):
        from paddle_tpu.amp.debugging import check_numerics, DebugMode
        with pytest.warns(UserWarning):
            check_numerics(Tensor(jnp.array([float("inf")])),
                           debug_mode=DebugMode.CHECK_NAN_INF)

    def test_clean_passes(self):
        from paddle_tpu.amp.debugging import check_numerics
        check_numerics({"a": jnp.ones((4,)), "b": [Tensor(jnp.zeros(2))]})

    def test_grad_spike_detector(self):
        from paddle_tpu.amp.debugging import GradNormSpikeDetector
        det = GradNormSpikeDetector(window=16, factor=5.0)
        g = {"w": jnp.ones((4,))}
        for _ in range(10):
            assert not det.check(g)
        assert det.check({"w": jnp.full((4,), 100.0)})


class TestShardingValidator:
    def _mesh(self):
        return Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))

    def test_good_spec(self):
        from paddle_tpu.distributed.validate import validate_spec
        validate_spec((8, 16), P("dp", "mp"), self._mesh())

    def test_unknown_axis(self):
        from paddle_tpu.distributed.validate import (validate_spec,
                                                     ShardingError)
        with pytest.raises(ShardingError, match="names axis"):
            validate_spec((8, 8), P("pp"), self._mesh())

    def test_indivisible(self):
        from paddle_tpu.distributed.validate import (validate_spec,
                                                     ShardingError)
        with pytest.raises(ShardingError, match="not divisible"):
            validate_spec((8, 6), P(None, "mp"), self._mesh())  # 6 % 4 != 0

    def test_duplicate_axis(self):
        from paddle_tpu.distributed.validate import (validate_spec,
                                                     ShardingError)
        with pytest.raises(ShardingError, match="twice"):
            validate_spec((8, 8), P("mp", "mp"), self._mesh())

    def test_validate_model(self):
        from paddle_tpu.distributed.validate import validate_model
        from paddle_tpu.distributed.fleet.mpu import ColumnParallelLinear
        m = ColumnParallelLinear(8, 16)
        assert validate_model(m, self._mesh())

    def test_placement_mismatch(self):
        from paddle_tpu.distributed.validate import (assert_same_placement,
                                                     ShardingError)
        mesh = self._mesh()
        a = {"w": jax.device_put(jnp.ones((8, 8)),
                                 NamedSharding(mesh, P("dp", None)))}
        b = {"w": jax.device_put(jnp.ones((8, 8)),
                                 NamedSharding(mesh, P(None, "mp")))}
        with pytest.raises(ShardingError, match="mismatch"):
            assert_same_placement(a, b)
        assert assert_same_placement(a, a)


class TestCheckpointManager:
    def _state(self, v):
        return {"model": {"w": jnp.full((4,), float(v))},
                "step": v, "lr": 0.1 * v}

    def test_save_restore_latest(self, tmp_path):
        from paddle_tpu.io import CheckpointManager
        mgr = CheckpointManager(tmp_path / "ck", keep_max=2)
        for s in (1, 2, 3):
            mgr.save(s, self._state(s))
        st = mgr.restore()
        assert st["step"] == 3
        np.testing.assert_array_equal(st["model"]["w"], np.full((4,), 3.0))

    def test_rolling_retention_keeps_best(self, tmp_path):
        from paddle_tpu.io import CheckpointManager
        mgr = CheckpointManager(tmp_path / "ck", keep_max=2)
        mgr.save(1, self._state(1), metric=0.9)   # best
        mgr.save(2, self._state(2), metric=0.5)
        mgr.save(3, self._state(3), metric=0.6)
        mgr.save(4, self._state(4), metric=0.7)
        steps = mgr.all_steps()
        assert 1 in steps, "best checkpoint must survive GC"
        assert mgr.best_step() == 1
        best = mgr.restore(best=True)
        assert best["step"] == 1

    def test_async_save(self, tmp_path):
        from paddle_tpu.io import CheckpointManager
        mgr = CheckpointManager(tmp_path / "ck", keep_max=3,
                                async_save=True)
        mgr.save(1, self._state(1))
        mgr.wait()
        assert mgr.restore()["step"] == 1

    def test_exact_resume_roundtrip(self, tmp_path):
        """params + opt state + rng resume exactly (SURVEY §2.11)."""
        from paddle_tpu.io import CheckpointManager
        from paddle_tpu.hapi.engine import Engine
        paddle.seed(0)
        def make():
            paddle.seed(0)
            net = paddle.nn.Linear(4, 4)
            opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
            return net, Engine(net, loss=paddle.nn.MSELoss(), optimizer=opt)
        net, eng = make()
        x = jnp.ones((2, 4)); y = jnp.zeros((2, 4))
        for _ in range(3):
            eng.train_batch([x], [y])
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(3, {"model": eng._params, "opt": eng.opt_state_dict()})
        loss_next, _ = eng.train_batch([x], [y])

        net2, eng2 = make()
        st = mgr.restore()
        eng2._params = jax.tree_util.tree_map(jnp.asarray, st["model"])
        eng2.load_opt_state_dict(jax.tree_util.tree_map(
            lambda a: jnp.asarray(a) if isinstance(a, np.ndarray) else a,
            st["opt"]))
        loss_resume, _ = eng2.train_batch([x], [y])
        np.testing.assert_allclose(float(loss_next), float(loss_resume),
                                   rtol=1e-6)


class TestUtils:
    def test_run_check(self, capsys):
        assert paddle.utils.run_check()

    def test_unique_name(self):
        un = paddle.utils.unique_name
        with un.guard():
            a = un.generate("fc")
            b = un.generate("fc")
        assert a != b and a.startswith("fc")

    def test_deprecated_warns(self):
        @paddle.utils.deprecated(update_to="new_fn", since="2.0")
        def old_fn():
            return 42
        with pytest.warns(DeprecationWarning):
            assert old_fn() == 42


class TestReviewRegressions:
    def test_record_event_measures_compute(self):
        from paddle_tpu.profiler import Profiler
        p = Profiler().start()
        f = jax.jit(lambda x: jnp.linalg.matrix_power(x, 64))
        x = jnp.eye(256) * 1.0001
        f(x).block_until_ready()  # compile outside the timer
        with p.record_event("big"):
            f(x)  # async dispatch; sync must still capture the compute
        with p.record_event("tiny"):
            pass
        big = p._events["big"].total
        tiny = p._events["tiny"].total
        assert big > tiny  # would be ~equal if sync were a no-op
        p.stop()

    def test_validate_tree_with_none_specs(self):
        from paddle_tpu.distributed.validate import validate_tree
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
        tree = {"w": jnp.ones((8, 8)), "b": jnp.ones((8,))}
        assert validate_tree(tree, mesh,
                             specs={"w": P(None, "mp"), "b": None})

    def test_checkpoint_async_error_surfaces(self, tmp_path):
        from paddle_tpu.io import CheckpointManager
        import threading
        mgr = CheckpointManager(tmp_path / "ck", async_save=True)
        mgr.save(1, {"bad": threading.Lock()})  # unpicklable payload
        with pytest.raises(RuntimeError, match="checkpoint save failed"):
            mgr.wait()

    def test_check_numerics_scalar_leaves(self):
        from paddle_tpu.amp.debugging import check_numerics
        with pytest.raises(FloatingPointError):
            check_numerics({"loss": float("nan")})
        check_numerics({"loss": 1.0, "n": 3})

    def test_spike_detector_bounded_history(self):
        from paddle_tpu.amp.debugging import GradNormSpikeDetector
        det = GradNormSpikeDetector(window=8)
        for _ in range(100):
            det.check({"w": jnp.ones((2,))})
        assert len(det._history) <= 8


class TestSecondReviewRegressions:
    def test_spike_detector_small_window(self):
        from paddle_tpu.amp.debugging import GradNormSpikeDetector
        det = GradNormSpikeDetector(window=4, factor=5.0)
        for _ in range(4):
            det.check({"w": jnp.ones((2,))})
        assert det.check({"w": jnp.full((2,), 1000.0)})

    def test_restore_best_without_metric_raises(self, tmp_path):
        from paddle_tpu.io import CheckpointManager
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(1, {"x": 1})
        with pytest.raises(ValueError, match="best=True"):
            mgr.restore(best=True)

    def test_record_event_excludes_prior_async_work(self):
        from paddle_tpu.profiler import Profiler
        p = Profiler().start()
        f = jax.jit(lambda x: jnp.linalg.matrix_power(x, 128))
        x = jnp.eye(256)
        f(x).block_until_ready()  # compile
        _ = f(x)  # async big work BEFORE the region
        with p.record_event("small"):
            pass
        small = p._events["small"].total
        with p.record_event("big"):
            f(x)
        big = p._events["big"].total
        assert big > small

    def test_root_linear_bias_spec_matches_weight(self):
        from paddle_tpu.distributed.auto_parallel import (plan_model,
                                                          Strategy)
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
        m = paddle.nn.Linear(16, 64)
        plan = plan_model(m, mesh, Strategy(min_shard_elems=1))
        assert tuple(plan["weight"]) == (None, "mp")
        assert tuple(plan["bias"]) == ("mp",)


class TestShardedCheckpoint:
    """VERDICT r1 #8: sharded save/restore via orbax — no full host
    gather; ZeRO-style sharded state round-trips onto its shardings."""

    def test_sharded_roundtrip_preserves_shardings(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from paddle_tpu.io.checkpoint import CheckpointManager

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        sh = NamedSharding(mesh, P("dp"))
        rep = NamedSharding(mesh, P())
        w = jax.device_put(jnp.arange(16.0).reshape(8, 2), sh)
        b = jax.device_put(jnp.ones(3), rep)
        state = {"model": {"w": w, "b": b}, "step": 7, "lr": 0.5}

        mgr = CheckpointManager(str(tmp_path / "ck"), sharded=True)
        mgr.save(1, state)

        target = {"model": {"w": jax.ShapeDtypeStruct((8, 2), w.dtype,
                                                      sharding=sh),
                            "b": jax.ShapeDtypeStruct((3,), b.dtype,
                                                      sharding=rep)},
                  "step": 7, "lr": 0.5}
        got = CheckpointManager(str(tmp_path / "ck"),
                                sharded=True).restore(target=target)
        assert got["step"] == 7 and got["lr"] == 0.5
        np.testing.assert_allclose(np.asarray(got["model"]["w"]),
                                   np.asarray(w))
        np.testing.assert_allclose(np.asarray(got["model"]["b"]),
                                   np.asarray(b))
        # arrays came back ON their shardings (placed, not host numpy)
        assert got["model"]["w"].sharding.is_equivalent_to(sh, 2)

    def test_sharded_restore_without_target(self, tmp_path):
        import jax.numpy as jnp
        import numpy as np
        from paddle_tpu.io.checkpoint import CheckpointManager
        mgr = CheckpointManager(str(tmp_path / "ck"), sharded=True)
        mgr.save(3, {"x": jnp.ones((4, 4)), "note": 11})
        got = mgr.restore()
        assert got["note"] == 11
        np.testing.assert_allclose(np.asarray(got["x"]), 1.0)
