"""API freeze (VERDICT r4 next #9): every name in the generated
surface snapshot (docs/api_surface.json, written by
tools/api_parity_report.py) must keep resolving. Removing or renaming
a public name is an API break and must be a deliberate act: regenerate
the snapshot in the same commit and say so. Additions don't fail —
the next regeneration picks them up."""
import importlib
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SNAP = os.path.join(REPO, "docs", "api_surface.json")


def _namespace(ns):
    try:
        return importlib.import_module(ns)
    except ModuleNotFoundError:
        parent, leaf = ns.rsplit(".", 1)
        return getattr(importlib.import_module(parent), leaf)


def test_frozen_surface_still_resolves():
    with open(SNAP) as f:
        snap = json.load(f)
    missing = []
    for ns, names in snap["surface"].items():
        try:
            mod = _namespace(ns)
        except Exception as e:
            missing.append(f"{ns} (namespace gone: {e!r})")
            continue
        for n in names:
            if not hasattr(mod, n):
                missing.append(f"{ns}.{n}")
    assert not missing, (
        f"{len(missing)} frozen public names no longer resolve "
        f"(API break — regenerate docs/api_surface.json deliberately "
        f"if intended): {missing[:20]}")


def test_snapshot_version_matches_package():
    import paddle_tpu
    with open(SNAP) as f:
        snap = json.load(f)
    assert snap["version"] == paddle_tpu.__version__, (
        "package version changed without regenerating the API "
        "snapshot: run python tools/api_parity_report.py")
