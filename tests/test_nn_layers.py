"""Layer behavior: shapes, modes, state_dict round trips (SURVEY §4)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def n(t):
    return np.asarray(t.numpy())


class TestLinear:
    def test_forward_layout(self):
        # reference layout: weight [in, out]
        l = nn.Linear(4, 3)
        assert l.weight.shape == [4, 3]
        x = paddle.ones([2, 4])
        out = l(x)
        expect = np.ones((2, 4)) @ n(l.weight) + n(l.bias)
        assert np.allclose(n(out), expect, rtol=1e-5)

    def test_no_bias(self):
        l = nn.Linear(4, 3, bias_attr=False)
        assert l.bias is None
        assert len(l.parameters()) == 1


class TestConv:
    def test_conv2d_shape_and_value(self):
        c = nn.Conv2D(2, 4, 3, padding=1)
        assert c.weight.shape == [4, 2, 3, 3]
        x = paddle.randn([1, 2, 8, 8])
        assert c(x).shape == [1, 4, 8, 8]
        # identity kernel check
        c2 = nn.Conv2D(1, 1, 1, bias_attr=False)
        c2.weight._value = c2.weight._value * 0 + 1
        xx = paddle.randn([1, 1, 5, 5])
        assert np.allclose(n(c2(xx)), n(xx))

    def test_stride_groups_dilation(self):
        c = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
        x = paddle.randn([2, 4, 16, 16])
        assert c(x).shape == [2, 8, 8, 8]
        c2 = nn.Conv2D(1, 1, 3, dilation=2)
        assert c2(paddle.randn([1, 1, 9, 9])).shape == [1, 1, 5, 5]

    def test_conv_transpose(self):
        ct = nn.Conv2DTranspose(3, 2, 4, stride=2, padding=1)
        x = paddle.randn([1, 3, 8, 8])
        assert ct(x).shape == [1, 2, 16, 16]

    def test_conv1d_3d(self):
        assert nn.Conv1D(2, 3, 3, padding=1)(
            paddle.randn([1, 2, 10])).shape == [1, 3, 10]
        assert nn.Conv3D(1, 2, 3, padding=1)(
            paddle.randn([1, 1, 4, 4, 4])).shape == [1, 2, 4, 4, 4]


class TestNorm:
    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = paddle.randn([4, 3, 5, 5]) * 2 + 1
        bn.train()
        out = bn(x)
        # normalized over N,H,W
        assert abs(float(out.mean())) < 1e-5
        assert 0.8 < float(out.std()) < 1.2
        # running stats moved toward batch stats
        assert not np.allclose(n(bn._mean), 0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = paddle.randn([2, 4, 8]) * 3 + 5
        out = n(ln(x))
        assert np.allclose(out.mean(-1), 0, atol=1e-5)
        assert np.allclose(out.std(-1), 1, atol=2e-2)

    def test_groupnorm_instancenorm_rmsnorm(self):
        assert nn.GroupNorm(2, 4)(paddle.randn([2, 4, 3, 3])).shape == [2, 4, 3, 3]
        assert nn.InstanceNorm2D(3)(paddle.randn([2, 3, 4, 4])).shape == [2, 3, 4, 4]
        rms = nn.RMSNorm(8)
        out = rms(paddle.randn([2, 8]))
        assert out.shape == [2, 8]


class TestPoolingActivation:
    def test_pools(self):
        x = paddle.randn([1, 2, 8, 8])
        assert nn.MaxPool2D(2, 2)(x).shape == [1, 2, 4, 4]
        assert nn.AvgPool2D(2, 2)(x).shape == [1, 2, 4, 4]
        assert nn.AdaptiveAvgPool2D((1, 1))(x).shape == [1, 2, 1, 1]
        # adaptive avg == mean
        assert np.allclose(n(nn.AdaptiveAvgPool2D(1)(x))[0, 0, 0, 0],
                           n(x)[0, 0].mean(), rtol=1e-5)

    def test_activations(self):
        x = paddle.to_tensor([-1.0, 0.0, 2.0])
        assert n(nn.ReLU()(x)).tolist() == [0.0, 0.0, 2.0]
        assert np.allclose(n(nn.Sigmoid()(x)), 1 / (1 + np.exp([1, 0, -2])),
                           rtol=1e-5)
        assert np.allclose(n(F.softmax(x)).sum(), 1.0, rtol=1e-6)
        assert np.allclose(n(F.gelu(paddle.to_tensor([1.0]))), 0.8413, atol=1e-3)
        assert n(F.relu6(paddle.to_tensor([8.0]))).tolist() == [6.0]


class TestEmbeddingDropout:
    def test_embedding(self):
        e = nn.Embedding(10, 4, padding_idx=0)
        idx = paddle.to_tensor([[1, 0, 2]])
        out = e(idx)
        assert out.shape == [1, 3, 4]
        assert np.allclose(n(out)[0, 1], 0)  # padding idx -> zeros

    def test_dropout_modes(self):
        paddle.seed(0)
        d = nn.Dropout(0.5)
        x = paddle.ones([1000])
        d.train()
        t = n(d(x))
        kept = t[t != 0]
        assert np.allclose(kept, 2.0)  # upscale_in_train
        d.eval()
        assert np.allclose(n(d(x)), 1.0)


class TestContainers:
    def test_sequential_layerlist_dict(self):
        s = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
        assert s(paddle.ones([1, 2])).shape == [1, 1]
        assert len(s) == 3
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(list(ll.parameters())) == 6
        ld = nn.LayerDict({"a": nn.Linear(2, 2)})
        assert "a" in ld

    def test_state_dict_roundtrip(self):
        m1 = nn.Sequential(nn.Linear(3, 4), nn.BatchNorm1D(4, data_format="NCL"))
        m2 = nn.Sequential(nn.Linear(3, 4), nn.BatchNorm1D(4, data_format="NCL"))
        sd = m1.state_dict()
        assert any("weight" in k for k in sd)
        assert any("_mean" in k for k in sd)  # buffers included
        m2.set_state_dict(sd)
        for (k1, v1), (k2, v2) in zip(m1.state_dict().items(),
                                      m2.state_dict().items()):
            assert k1 == k2 and np.allclose(n(v1), n(v2))

    def test_named_parameters_names(self):
        m = nn.Sequential(nn.Linear(2, 2))
        names = [k for k, _ in m.named_parameters()]
        assert names == ["0.weight", "0.bias"]
        # names assigned onto the params
        assert m[0].weight.name == "0.weight"

    def test_train_eval_recursive(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training


class TestLosses:
    def test_cross_entropy(self):
        logits = paddle.to_tensor([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
        labels = paddle.to_tensor([0, 1])
        l = F.cross_entropy(logits, labels)
        assert float(l) < 1e-3
        # soft label
        soft = paddle.to_tensor([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]])
        l2 = F.cross_entropy(logits, soft, soft_label=True)
        assert float(l2) < 1e-3
        # ignore index
        labels3 = paddle.to_tensor([0, -100])
        l3 = F.cross_entropy(logits, labels3, ignore_index=-100)
        assert float(l3) < 1e-3

    def test_mse_l1_bce(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([2.0, 4.0])
        assert float(F.mse_loss(a, b)) == 2.5
        assert float(F.l1_loss(a, b)) == 1.5
        p = paddle.to_tensor([0.5, 0.5])
        y = paddle.to_tensor([1.0, 0.0])
        assert np.allclose(float(F.binary_cross_entropy(p, y)),
                           -np.log(0.5), rtol=1e-4)
        z = paddle.to_tensor([0.0, 0.0])
        assert np.allclose(float(F.binary_cross_entropy_with_logits(z, y)),
                           -np.log(0.5), rtol=1e-4)

    def test_kl_smooth_l1(self):
        lp = F.log_softmax(paddle.to_tensor([[1.0, 2.0]]))
        tgt = F.softmax(paddle.to_tensor([[1.0, 2.0]]))
        assert abs(float(F.kl_div(lp, tgt))) < 1e-6
        assert float(F.smooth_l1_loss(paddle.to_tensor([0.0]),
                                      paddle.to_tensor([0.25]))) < 0.05


class TestTransformer:
    def test_mha_shapes(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 5, 16])
        assert mha(x, x, x).shape == [2, 5, 16]

    def test_encoder_decoder(self):
        enc_l = nn.TransformerEncoderLayer(16, 4, 32)
        enc = nn.TransformerEncoder(enc_l, 2)
        src = paddle.randn([2, 6, 16])
        mem = enc(src)
        assert mem.shape == [2, 6, 16]
        dec_l = nn.TransformerDecoderLayer(16, 4, 32)
        dec = nn.TransformerDecoder(dec_l, 2)
        tgt = paddle.randn([2, 3, 16])
        assert dec(tgt, mem).shape == [2, 3, 16]

    def test_causal_mask_effect(self):
        # with causal sdp attention, output at position 0 ignores future
        q = paddle.randn([1, 4, 2, 8])
        out_causal = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        assert out_causal.shape == [1, 4, 2, 8]


class TestRNN:
    def test_lstm_gru_shapes(self):
        lstm = nn.LSTM(8, 16, num_layers=2)
        x = paddle.randn([4, 5, 8])
        out, (h, c) = lstm(x)
        assert out.shape == [4, 5, 16]
        assert h.shape == [2, 4, 16] and c.shape == [2, 4, 16]
        gru = nn.GRU(8, 16, direction="bidirect")
        out2, h2 = gru(x)
        assert out2.shape == [4, 5, 32]

    def test_cells(self):
        cell = nn.LSTMCell(4, 8)
        h, (hh, cc) = cell(paddle.randn([2, 4]))
        assert h.shape == [2, 8]


class TestInitializers:
    def test_initializers(self):
        from paddle_tpu.nn import initializer as I
        w = nn.Linear(100, 50,
                      weight_attr=paddle.nn.ParamAttr(
                          initializer=I.Constant(3.0))).weight
        assert np.allclose(n(w), 3.0)
        paddle.seed(1)
        k = I.KaimingNormal()((1000,), np.float32)
        assert 0.02 < float(np.asarray(k).std()) < 0.05
        o = I.Orthogonal()((8, 8), np.float32)
        assert np.allclose(np.asarray(o) @ np.asarray(o).T, np.eye(8),
                           atol=1e-5)


class TestClip:
    def test_global_norm_clip(self):
        clip = nn.ClipGradByGlobalNorm(1.0)
        grads = {"a": np.full((4,), 3.0, dtype="float32")}
        out = clip.apply({k: paddle.to_tensor(v)._value
                          for k, v in grads.items()})
        assert np.allclose(np.linalg.norm(np.asarray(out["a"])), 1.0,
                           rtol=1e-4)

    def test_clip_value(self):
        clip = nn.ClipGradByValue(0.5)
        out = clip.apply({"a": paddle.to_tensor([2.0, -2.0])._value})
        assert np.asarray(out["a"]).tolist() == [0.5, -0.5]
