"""Round-4 regression tests for the round-3 advisor findings:

1. Model.load clears a pending gradient-accumulation window (a restored
   state invalidates grads computed against pre-load params).
2. quantize_for_serving's mp-axis guard only applies to the parallel
   Linear variants, not plain Linear subclasses.
3. repetition_penalty never penalizes pad_token_id (left-padded prompts
   and pad==eos configs must not be biased against termination).
4. _sround_bf16 keeps non-finite moments non-finite (inf must not
   truncate to NaN via noise-payload addition).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle


# ---------- 1. Model.load resets the accumulation window ----------

def _small_model():
    paddle.seed(7)
    net = paddle.nn.Sequential(paddle.nn.Linear(6, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 3))
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.AdamW(
        0.01, parameters=net.parameters()),
        loss=paddle.nn.CrossEntropyLoss())
    return m


def test_model_load_clears_pending_accum_window(tmp_path):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 6)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 3, (8,)).astype(np.int64))

    m = _small_model()
    eng = m._ensure_engine()
    eng.train_batch([x], [y])
    m.save(str(tmp_path / "ckpt"))

    # open a half-accumulated window, then restore the checkpoint
    eng.train_batch_accum([x], [y], apply_update=False)
    assert eng._micro_count == 1 and eng._acc_grads is not None
    m.load(str(tmp_path / "ckpt"))
    assert eng._micro_count == 0
    assert eng._acc_grads is None


# ---------- 2. mp-axis guard scope ----------

def test_plain_linear_subclass_not_blocked_by_mp_guard(monkeypatch):
    from paddle_tpu.nn import quant as quant_mod
    from paddle_tpu.nn.layers_common import Linear

    class MyLinear(Linear):          # plain subclass, no collective
        pass

    # simulate a live mp axis: old guard raised for ANY Linear subclass
    import paddle_tpu.distributed.fleet.mpu as mpu
    monkeypatch.setattr(mpu, "axis_bound", lambda name: True)

    paddle.seed(0)
    net = paddle.nn.Sequential(MyLinear(32, 32))
    n = quant_mod.quantize_for_serving(net, min_features=1)
    assert n == 1  # quantized, not ValueError


def test_parallel_linear_still_blocked_when_axis_live(monkeypatch):
    from paddle_tpu.nn import quant as quant_mod
    import paddle_tpu.distributed.fleet.mpu as mpu

    monkeypatch.setattr(mpu, "axis_bound", lambda name: True)
    col = mpu.ColumnParallelLinear.__new__(mpu.ColumnParallelLinear)
    # only need isinstance + the guard path; wrap in a container layer
    net = paddle.nn.Sequential()
    net._sub_layers["0"] = col
    with pytest.raises(ValueError, match="mp mesh axis is live"):
        quant_mod.quantize_for_serving(net, min_features=1)


# ---------- 3. repetition penalty excludes pad ----------

def test_seen_mask_excludes_pad_token():
    from paddle_tpu.nlp.generation import _seen_from_prompt
    ids = jnp.asarray([[0, 0, 0, 5, 9],    # left-padded with pad=0
                       [3, 0, 4, 4, 7]])
    seen = _seen_from_prompt(ids, 12, pad_token_id=0)
    assert not bool(seen[:, 0].any())       # pad column clear
    assert bool(seen[0, 5]) and bool(seen[0, 9]) and bool(seen[1, 3])


def test_finished_rows_do_not_penalize_eos_when_pad_eq_eos():
    """With pad==eos (the common GPT convention), a finished row keeps
    emitting pad; the seen-mask update must not mark it, or the eos
    logit of still-running rows sharing the batch would be fine — but
    the finished row itself (restarted contextually) would carry a
    permanent anti-eos bias. We check end-to-end: greedy decode with a
    strong repetition penalty still terminates at eos."""
    from paddle_tpu.nlp import GPTForCausalLM, GPTConfig
    from paddle_tpu.nlp.generation import generate
    paddle.seed(11)
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=64,
                    intermediate_size=32)
    m = GPTForCausalLM(cfg)
    ids = jnp.asarray(np.array([[1, 2, 3]], dtype=np.int64))
    out = generate(m, ids, max_new_tokens=6, temperature=0.0,
                   repetition_penalty=2.0, eos_token_id=0, pad_token_id=0)
    arr = np.asarray(out)
    assert arr.shape == (1, 9)  # runs; pad column never penalized


def test_repetition_penalty_with_pad_token_none():
    """pad_token_id=None (tokenizers without a pad token) must not break
    the seen-mask updates — an unguarded `.at[:, None].set(False)` would
    silently broadcast-clear the whole mask (None == newaxis)."""
    from paddle_tpu.nlp.generation import build_decode_fn
    from paddle_tpu.nlp import GPTForCausalLM, GPTConfig
    paddle.seed(13)
    cfg = GPTConfig(vocab_size=24, hidden_size=16, num_hidden_layers=1,
                    num_attention_heads=2, max_position_embeddings=32,
                    intermediate_size=32)
    m = GPTForCausalLM(cfg)
    params, buffers = m.raw_state()
    fn = build_decode_fn(m, max_new_tokens=4, temperature=0.0,
                         repetition_penalty=1.7, eos_token_id=None,
                         pad_token_id=None)
    ids = jnp.asarray(np.array([[2, 3, 4]], dtype=np.int64))
    out = np.asarray(fn(params, buffers, ids, jax.random.PRNGKey(0)))
    assert out.shape == (1, 7)


# ---------- 4. stochastic rounding non-finite guard ----------

def test_sround_bf16_preserves_inf_and_nan_sign():
    from paddle_tpu.optimizer.optimizer import _sround_bf16
    key = jax.random.PRNGKey(0)
    x = jnp.asarray([np.inf, -np.inf, np.nan, 1.5, -2.25], jnp.float32)
    out = np.asarray(_sround_bf16(x, key)).astype(np.float32)
    assert np.isposinf(out[0])
    assert np.isneginf(out[1])
    assert np.isnan(out[2])
    assert np.isfinite(out[3]) and np.isfinite(out[4])


def test_bf16_moment_state_survives_save_load(tmp_path):
    """Found while verifying the accum-window fix: np.savez round-trips
    ml_dtypes bfloat16 as void ('|V2'), so a bf16-moment checkpoint
    crashed on load. Moments must come back bit-exact as bf16."""
    paddle.seed(5)
    net = paddle.nn.Sequential(paddle.nn.Linear(6, 8), paddle.nn.Tanh(),
                               paddle.nn.Linear(8, 3))
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.AdamW(
        0.01, parameters=net.parameters(), moment_dtype="bfloat16"),
        loss=paddle.nn.CrossEntropyLoss())
    eng = m._ensure_engine()
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((8, 6)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 3, (8,)).astype(np.int64))
    eng.train_batch([x], [y])
    before = jax.tree_util.tree_leaves(eng._opt_state)
    m.save(str(tmp_path / "ck"))
    m.load(str(tmp_path / "ck"))
    after = jax.tree_util.tree_leaves(eng._opt_state)
    assert len(before) == len(after)
    for a, b in zip(before, after):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(
            np.asarray(a).astype(np.float32),
            np.asarray(b).astype(np.float32))
    eng.train_batch([x], [y])  # training continues post-load


def test_paddle_save_load_bf16_tensor_roundtrip(tmp_path):
    from paddle_tpu.serialization import save, load
    t = paddle.to_tensor(jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16))
    save({"w": t}, str(tmp_path / "x.pt"))
    back = load(str(tmp_path / "x.pt"))
    assert str(back["w"].dtype).endswith("bfloat16")
    np.testing.assert_array_equal(
        np.asarray(back["w"]._value).astype(np.float32),
        np.asarray(t._value).astype(np.float32))
    # 0-d: numpy view() promotes scalar user-defined dtypes to (1,) —
    # shape must be pinned through the round trip
    save(paddle.to_tensor(jnp.asarray(0.25, jnp.bfloat16)),
         str(tmp_path / "s.pt"))
    s = load(str(tmp_path / "s.pt"))
    assert s._value.shape == ()
    assert str(s._value.dtype) == "bfloat16"


def test_sround_bf16_still_unbiased_mean():
    from paddle_tpu.optimizer.optimizer import _sround_bf16
    x = jnp.full((4096,), 1.0 + 2 ** -10, jnp.float32)  # below bf16 cut
    keys = jax.random.split(jax.random.PRNGKey(1), 8)
    means = [np.asarray(_sround_bf16(x, k)).astype(np.float64).mean()
             for k in keys]
    np.testing.assert_allclose(np.mean(means), 1.0 + 2 ** -10, rtol=3e-4)
