"""Round-2 API sweep 3: cdist/matrix_exp/lu_unpack/ormqr + manip/stat
long tail."""
import numpy as np
import pytest

import paddle_tpu as paddle

t = paddle.to_tensor


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestLinalgLongtail:
    def setup_method(self, m):
        self.rng = np.random.default_rng(0)

    def test_cdist(self):
        a = self.rng.standard_normal((5, 3)).astype(np.float32)
        b = self.rng.standard_normal((4, 3)).astype(np.float32)
        ref2 = np.sqrt(((a[:, None] - b[None]) ** 2).sum(-1))
        assert np.allclose(_np(paddle.cdist(t(a), t(b))), ref2, atol=1e-4)
        assert np.allclose(_np(paddle.cdist(t(a), t(b), p=1.0)),
                           np.abs(a[:, None] - b[None]).sum(-1), atol=1e-5)
        assert np.allclose(
            _np(paddle.cdist(t(a), t(b), p=float("inf"))),
            np.abs(a[:, None] - b[None]).max(-1), atol=1e-5)

    def test_cdist_p0_and_constant_bins(self):
        # regression: p=0 crashed; constant input gave zero-width bins
        a = np.array([[1.0, 2.0, 3.0]], np.float32)
        b = np.array([[1.0, 0.0, 3.0], [1.0, 2.0, 3.0]], np.float32)
        h = _np(paddle.cdist(t(a), t(b), p=0.0))
        assert np.allclose(h, [[1.0, 0.0]])
        hb = _np(paddle.histogram_bin_edges(t(np.array([2.0, 2.0])),
                                            bins=4))
        assert hb[0] < hb[-1]  # expanded, not degenerate
        assert np.allclose(hb, np.histogram_bin_edges(
            np.array([2.0, 2.0]), bins=4))

    def test_cdist_default_mode_small_dim_exact(self):
        # regression: default if_necessary mode must keep small dims on
        # the exact path (no ||a||^2-cancellation)
        a = np.array([[1e4, 0.0], [1e4, 0.1]], np.float32)
        d = _np(paddle.cdist(t(a), t(a)))
        assert np.allclose(d[0, 1], 0.1, atol=1e-5)
        # exact path must also be grad-safe at coincident points
        x = t(a, stop_gradient=False)
        g = paddle.grad(paddle.cdist(x, x,
                        compute_mode="donot_use_mm_for_euclid_dist").sum(),
                        x)[0]
        assert np.isfinite(_np(g)).all()
        # row counts > 25 take the mm path and agree with the exact one
        rng = np.random.default_rng(1)
        big = rng.standard_normal((30, 8)).astype(np.float32)
        mm = _np(paddle.cdist(t(big), t(big)))
        exact = _np(paddle.cdist(t(big), t(big),
                    compute_mode="donot_use_mm_for_euclid_dist"))
        # fp32 cancellation noise (~1e-2 near zero) is inherent to the mm
        # formulation — the very reason the exact mode exists
        assert np.allclose(mm, exact, atol=5e-2)

    def test_cdist_donot_mm_and_grad_safety(self):
        # regression 1: donot_use_mm modes must take the exact path
        a = (np.array([[1e4, 0.0], [1e4, 0.1]], np.float32))
        exact = _np(paddle.cdist(t(a), t(a),
                                 compute_mode="donot_use_mm_for_euclid_dist"))
        assert np.allclose(exact[0, 1], 0.1, atol=1e-5)
        # regression 2: coincident points must backprop 0, not NaN
        x = t(np.array([[0.0, 0.0], [1.0, 1.0]], np.float32),
              stop_gradient=False)
        d = paddle.cdist(x, x)
        g = paddle.grad(d.sum(), x)[0]
        assert np.isfinite(_np(g)).all()

    def test_matrix_exp(self):
        import scipy.linalg
        m = self.rng.standard_normal((3, 3)).astype(np.float32) * 0.3
        assert np.allclose(_np(paddle.matrix_exp(t(m))),
                           scipy.linalg.expm(m), atol=1e-4)

    def test_lu_unpack_roundtrip(self):
        from paddle_tpu.tensor_ops.linalg import lu as plu
        M = self.rng.standard_normal((4, 4)).astype(np.float32)
        out = plu(t(M))
        LU, piv = _np(out[0]), _np(out[1])
        P, L, U = [_np(v) for v in paddle.lu_unpack(t(LU), t(piv))]
        assert np.allclose(P @ L @ U, M, atol=1e-4)
        assert np.allclose(np.tril(L, -1) + np.eye(4), L, atol=1e-6)
        assert np.allclose(np.triu(U), U, atol=1e-6)

    def test_ormqr(self):
        from scipy.linalg.lapack import sgeqrf
        M = self.rng.standard_normal((4, 4)).astype(np.float32)
        a, tau, _, _ = sgeqrf(M)
        other = self.rng.standard_normal((4, 2)).astype(np.float32)
        got = _np(paddle.ormqr(t(a), t(tau), t(other)))
        q = np.linalg.qr(M)[0]
        # Q @ other, up to the sign convention difference between lapack
        # and np.linalg.qr columns
        ref = q @ other
        assert got.shape == ref.shape
        col_match = np.allclose(np.abs(got), np.abs(ref), atol=1e-3)
        assert col_match
        # transpose=True gives Q^T @ other: Q^T Q = I check
        qt_q = _np(paddle.ormqr(t(a), t(tau),
                                paddle.ormqr(t(a), t(tau), t(other)),
                                transpose=True))
        assert np.allclose(qt_q, other, atol=1e-3)


class TestManipStatLongtail:
    def test_unflatten_index_fill(self):
        x = t(np.arange(12, dtype=np.float32).reshape(3, 4))
        assert tuple(paddle.unflatten(x, 1, [2, 2]).shape) == (3, 2, 2)
        assert tuple(paddle.unflatten(x, -1, [4, 1]).shape) == (3, 4, 1)
        fi = _np(paddle.index_fill(x, t(np.array([0, 2])), 0, -1.0))
        assert (fi[0] == -1).all() and (fi[2] == -1).all()
        assert (fi[1] == np.arange(4, 8)).all()

    def test_stacks_and_splits(self):
        x = t(np.arange(12, dtype=np.float32).reshape(3, 4))
        cs = _np(paddle.column_stack([t(np.ones(3, np.float32)),
                                      t(np.zeros(3, np.float32))]))
        assert cs.shape == (3, 2)
        rs = _np(paddle.row_stack([t(np.ones(4, np.float32)),
                                   t(np.zeros(4, np.float32))]))
        assert rs.shape == (2, 4)
        sp = paddle.tensor_split(t(np.arange(10, dtype=np.float32)), 3)
        assert [tuple(s.shape) for s in sp] == [(4,), (3,), (3,)]
        assert tuple(paddle.hsplit(x, 2)[0].shape) == (3, 2)
        assert tuple(paddle.vsplit(x, 3)[0].shape) == (1, 4)
        x3 = t(np.zeros((2, 3, 4), np.float32))
        assert tuple(paddle.dsplit(x3, 2)[0].shape) == (2, 3, 2)

    def test_slice_scatter(self):
        x = t(np.arange(12, dtype=np.float32).reshape(3, 4))
        ss = _np(paddle.slice_scatter(x, t(np.zeros((3, 2), np.float32)),
                                      [1], [1], [3], [1]))
        assert (ss[:, 1:3] == 0).all()
        assert (ss[:, 0] == [0, 4, 8]).all()

    def test_histogram_bin_edges_trapz(self):
        hb = _np(paddle.histogram_bin_edges(t(np.array([0.0, 1.0])),
                                            bins=4))
        assert np.allclose(hb, [0, 0.25, 0.5, 0.75, 1.0])
        hb2 = _np(paddle.histogram_bin_edges(t(np.array([5.0])), bins=2,
                                             min=1, max=3))
        assert np.allclose(hb2, [1, 2, 3])
        assert np.allclose(
            _np(paddle.trapz(t(np.array([0.0, 1.0, 2.0])))), 2.0)


def test_is_floating_point_is_complex_isin():
    import numpy as np
    import paddle_tpu as paddle
    assert paddle.is_floating_point(paddle.to_tensor(np.float32(1.0)))
    assert not paddle.is_floating_point(paddle.to_tensor(np.int64(1)))
    assert paddle.is_complex(paddle.to_tensor(np.complex64(1j)))
    assert not paddle.is_complex(paddle.to_tensor(np.float32(0.0)))
    got = paddle.isin(paddle.to_tensor(np.array([1, 2, 3, 4])),
                      paddle.to_tensor(np.array([2, 4])))
    np.testing.assert_array_equal(np.asarray(got.numpy()),
                                  [False, True, False, True])
    inv = paddle.isin(paddle.to_tensor(np.array([1, 2])),
                      paddle.to_tensor(np.array([2])), invert=True)
    np.testing.assert_array_equal(np.asarray(inv.numpy()), [True, False])
