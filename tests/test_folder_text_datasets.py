"""Folder image datasets + real text-format parsers
(ref: python/paddle/vision/datasets/folder.py,
python/paddle/text/datasets/{imdb,conll05,wmt16}.py).
"""
import gzip
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.vision.datasets import (DatasetFolder, ImageFolder,
                                        image_load, IMAGE_EXTENSIONS)
from paddle_tpu.text.datasets import Imdb, Conll05st, WMT16


# ---------------- fixtures ----------------

def _make_image_tree(root, classes=("cat", "dog"), n=3, size=8):
    from PIL import Image
    rng = np.random.default_rng(0)
    for ci, cls in enumerate(classes):
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(n):
            arr = np.full((size, size, 3), 40 * ci + i, np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i}.png"))
        # a non-image file that must be skipped
        with open(os.path.join(d, "notes.txt"), "w") as f:
            f.write("skip me")
    return root


# ---------------- DatasetFolder ----------------

def test_dataset_folder_classes_and_samples(tmp_path):
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root)
    assert ds.classes == ["cat", "dog"]
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    assert len(ds) == 6
    assert ds.targets == [0, 0, 0, 1, 1, 1]
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and img.dtype == np.uint8
    assert int(label) == 0
    img, label = ds[5]
    assert int(label) == 1


def test_dataset_folder_with_transform(tmp_path):
    from paddle_tpu.vision import transforms as T
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root, transform=T.Compose([T.Resize(4),
                                                  T.ToTensor()]))
    img, _ = ds[0]
    assert tuple(img.shape) == (3, 4, 4)     # CHW after ToTensor


def test_dataset_folder_is_valid_file(tmp_path):
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root, extensions=None,
                       is_valid_file=lambda p: p.endswith("img_0.png"))
    assert len(ds) == 2                      # one per class


def test_dataset_folder_both_filters_rejected(tmp_path):
    root = _make_image_tree(str(tmp_path / "train"))
    with pytest.raises(ValueError, match="exactly one"):
        DatasetFolder(root, extensions=(".png",),
                      is_valid_file=lambda p: True)


def test_wmt16_missing_mode_file_actionable(tmp_path):
    d = tmp_path / "corpus"
    os.makedirs(d)
    _write_parallel(str(d / "train"))
    with pytest.raises(ValueError, match="no 'dev' corpus"):
        WMT16(data_file=str(d), mode="dev")


def test_dataset_folder_empty_raises(tmp_path):
    os.makedirs(tmp_path / "empty" / "cls")
    with pytest.raises(RuntimeError, match="no valid files"):
        DatasetFolder(str(tmp_path / "empty"))
    with pytest.raises(RuntimeError, match="no class directories"):
        DatasetFolder(str(tmp_path / "empty" / "cls"))


def test_dataset_folder_in_dataloader(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.vision import transforms as T
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root, transform=T.ToTensor())
    loader = paddle.io.DataLoader(ds, batch_size=3, shuffle=False)
    xb, yb = next(iter(loader))
    assert tuple(xb.shape) == (3, 3, 8, 8)
    assert tuple(yb.shape) == (3,)


# ---------------- ImageFolder ----------------

def test_image_folder_flat_recursive(tmp_path):
    root = _make_image_tree(str(tmp_path / "imgs"))
    ds = ImageFolder(root)
    assert len(ds) == 6
    sample = ds[0]
    assert isinstance(sample, list) and len(sample) == 1
    assert sample[0].shape == (8, 8, 3)


def test_image_load_backends(tmp_path):
    from PIL import Image
    p = str(tmp_path / "x.png")
    Image.fromarray(np.zeros((5, 7, 3), np.uint8)).save(p)
    arr = image_load(p)
    assert arr.shape == (5, 7, 3) and arr.dtype == np.uint8
    pil = image_load(p, backend="pil")
    assert pil.size == (7, 5)


# ---------------- Imdb (aclImdb layout) ----------------

_DOCS = {
    ("train", "pos"): ["a great great movie", "great fine ending"],
    ("train", "neg"): ["a terrible terrible film", "boring bad plot"],
    ("test", "pos"): ["great story"],
    ("test", "neg"): ["awful pacing"],
}


def _make_aclimdb_dir(root):
    for (mode, sent), docs in _DOCS.items():
        d = os.path.join(root, mode, sent)
        os.makedirs(d, exist_ok=True)
        for i, doc in enumerate(docs):
            with open(os.path.join(d, f"{i}_7.txt"), "w") as f:
                f.write(doc)
    return root


def test_imdb_parses_directory(tmp_path):
    root = _make_aclimdb_dir(str(tmp_path / "aclImdb"))
    ds = Imdb(data_file=root, mode="train", cutoff=0)
    assert len(ds) == 4
    labels = sorted(int(ds[i][1]) for i in range(4))
    assert labels == [0, 0, 1, 1]
    # frequency-ordered dict: 'great' (3x) and 'terrible' (2x) precede
    # singletons; every doc maps to in-vocab ids
    assert ds.word_idx["great"] < ds.word_idx["boring"]
    unk = ds.word_idx["<unk>"]
    for i in range(4):
        assert (np.asarray(ds[i][0]) < unk).all()


def test_imdb_parses_tarball_and_cutoff(tmp_path):
    root = _make_aclimdb_dir(str(tmp_path / "aclImdb"))
    tar_path = str(tmp_path / "aclImdb_v1.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(root, arcname="aclImdb")
    ds = Imdb(data_file=tar_path, mode="train", cutoff=1)
    assert len(ds) == 4
    # cutoff=1 keeps only words with freq > 1: great(3), terrible(2), a(2)
    kept = set(ds.word_idx) - {"<unk>"}
    assert kept == {"great", "terrible", "a"}
    ds_test = Imdb(data_file=tar_path, mode="test", cutoff=0)
    assert len(ds_test) == 2


def test_imdb_synthetic_fallback_unchanged():
    ds = Imdb(mode="train", n_samples=10)
    x, y = ds[0]
    assert x.dtype == np.int64 and int(y) in (0, 1)


# ---------------- Conll05st (words + props column files) ----------------

_WORDS_FILE = """\
The
cat
chased
mice
.

Dogs
bark
.
"""

# props: col0 = predicate lemma ('-' elsewhere), one arg column per
# predicate with bracketed spans
_PROPS_FILE = """\
-\t(A0*
-\t*)
chase\t(V*)
-\t(A1*)
-\t*

-\t(A0*)
bark\t(V*)
-\t*
"""


def _write_conll(tmp_path, gz=False):
    wp = str(tmp_path / ("words.gz" if gz else "words"))
    pp = str(tmp_path / ("props.gz" if gz else "props"))
    if gz:
        with gzip.open(wp, "wt") as f:
            f.write(_WORDS_FILE)
        with gzip.open(pp, "wt") as f:
            f.write(_PROPS_FILE.replace("\\t", "\t"))
    else:
        with open(wp, "w") as f:
            f.write(_WORDS_FILE)
        with open(pp, "w") as f:
            f.write(_PROPS_FILE.replace("\\t", "\t"))
    return wp, pp


def test_conll05st_parses_column_format(tmp_path):
    wp, pp = _write_conll(tmp_path)
    ds = Conll05st(data_file=(wp, pp))
    assert len(ds) == 2                      # one predicate per sentence
    ids, pred, tags = ds[0]
    assert len(ids) == 5 and len(tags) == 5
    assert int(pred) == 2                    # 'chased' is the V span
    # BIO structure: A0 span covers 'The cat'
    tag_names = {v: k for k, v in ds.tag_idx.items()}
    decoded = [tag_names[int(t)] for t in np.asarray(tags)]
    assert decoded[0] == "B-A0" and decoded[1] == "I-A0"
    assert decoded[2] == "B-V"
    assert decoded[3] == "B-A1"
    ids2, pred2, tags2 = ds[1]
    assert len(ids2) == 3 and int(pred2) == 1


def test_conll05st_gz_and_mismatch(tmp_path):
    wp, pp = _write_conll(tmp_path, gz=True)
    ds = Conll05st(data_file=(wp, pp))
    assert len(ds) == 2
    # words/props length mismatch is a loud error
    bad = str(tmp_path / "short_words")
    with open(bad, "w") as f:
        f.write("Just\none\n")
    with pytest.raises(ValueError, match="sentence counts differ"):
        Conll05st(data_file=(bad, pp))


def test_conll05st_synthetic_fallback_unchanged():
    ds = Conll05st(n_samples=5)
    x, p, y = ds[0]
    assert x.dtype == np.int64 and y.dtype == np.int64


# ---------------- WMT16 (tab-separated parallel corpus) ----------------

_PARALLEL = [
    ("the cat sits", "die katze sitzt"),
    ("the dog runs", "der hund rennt"),
    ("a cat runs", "eine katze rennt"),
]


def _write_parallel(path):
    with open(path, "w") as f:
        for s, t in _PARALLEL:
            f.write(f"{s}\t{t}\n")


def test_wmt16_parses_tsv_file(tmp_path):
    p = str(tmp_path / "train")
    _write_parallel(p)
    ds = WMT16(data_file=p, mode="train", src_dict_size=50,
               trg_dict_size=50)
    assert len(ds) == 3
    src, trg_in, trg_next = ds[0]
    # special ids per the reference: <s>=0 <e>=1 <unk>=2
    assert ds.trg_dict["<s>"] == 0 and ds.trg_dict["<e>"] == 1
    assert int(trg_in[0]) == 0               # target starts with <s>
    assert int(trg_next[-1]) == 1            # and ends with <e>
    np.testing.assert_array_equal(trg_in[1:], trg_next[:-1])
    assert src.dtype == np.int64 and len(src) == 3


def test_wmt16_dict_size_cap_and_unk(tmp_path):
    p = str(tmp_path / "train")
    _write_parallel(p)
    ds = WMT16(data_file=p, mode="train", src_dict_size=4,
               trg_dict_size=4)
    assert len(ds.src_dict) == 4             # 3 specials + 1 real word
    # highest-frequency source word wins the single real slot
    assert "the" in ds.src_dict or "cat" in ds.src_dict
    src, _, _ = ds[1]
    assert (np.asarray(src) <= 3).all()      # everything else is <unk>

def test_wmt16_parses_directory_and_tarball(tmp_path):
    d = tmp_path / "corpus"
    os.makedirs(d)
    _write_parallel(str(d / "train"))
    ds = WMT16(data_file=str(d), mode="train")
    assert len(ds) == 3
    tar_path = str(tmp_path / "wmt16.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(str(d / "train"), arcname="wmt16/train")
    ds2 = WMT16(data_file=tar_path, mode="train")
    assert len(ds2) == 3
    s1, _, _ = ds[0]
    s2, _, _ = ds2[0]
    np.testing.assert_array_equal(s1, s2)


def test_wmt16_synthetic_fallback_unchanged():
    ds = WMT16(mode="train", n_samples=5)
    src, ti, tn = ds[0]
    assert src.dtype == np.int64


# ---------------- Flowers / VOC2012 ----------------

def test_flowers_parses_mat_release(tmp_path):
    import scipy.io
    from PIL import Image
    from paddle_tpu.vision.datasets import Flowers
    img_dir = tmp_path / "jpg"
    os.makedirs(img_dir)
    for i in range(1, 7):
        Image.fromarray(np.full((6, 6, 3), i * 10, np.uint8)).save(
            str(img_dir / f"image_{i:05d}.jpg"))
    labels = np.array([[1, 2, 3, 1, 2, 3]])       # 1-based
    scipy.io.savemat(str(tmp_path / "imagelabels.mat"),
                     {"labels": labels})
    scipy.io.savemat(str(tmp_path / "setid.mat"),
                     {"trnid": np.array([[1, 2, 3, 4]]),
                      "valid": np.array([[5]]),
                      "tstid": np.array([[6]])})
    ds = Flowers(data_file=(str(img_dir), str(tmp_path / "imagelabels.mat"),
                            str(tmp_path / "setid.mat")), mode="train")
    assert len(ds) == 4
    img, label = ds[0]
    assert img.shape == (6, 6, 3) and int(label) == 0   # 1-based -> 0
    assert len(Flowers(data_file=(str(img_dir),
                                  str(tmp_path / "imagelabels.mat"),
                                  str(tmp_path / "setid.mat")),
                       mode="test")) == 1


def test_voc2012_parses_devkit_layout(tmp_path):
    from PIL import Image
    from paddle_tpu.vision.datasets import VOC2012
    root = tmp_path / "VOC2012"
    os.makedirs(root / "ImageSets" / "Segmentation")
    os.makedirs(root / "JPEGImages")
    os.makedirs(root / "SegmentationClass")
    for name in ("2007_000001", "2007_000002"):
        Image.fromarray(np.zeros((5, 4, 3), np.uint8)).save(
            str(root / "JPEGImages" / f"{name}.jpg"))
        # real VOC masks are P-mode with class-id palette indices; an
        # L-mode png reads back identically (raw uint8 class ids)
        m = Image.fromarray(np.full((5, 4), 3, np.uint8), mode="L")
        m.save(str(root / "SegmentationClass" / f"{name}.png"))
    with open(root / "ImageSets" / "Segmentation" / "train.txt", "w") as f:
        f.write("2007_000001\n")
    with open(root / "ImageSets" / "Segmentation" / "val.txt", "w") as f:
        f.write("2007_000001\n2007_000002\n")
    tr = VOC2012(data_file=str(root), mode="train")
    va = VOC2012(data_file=str(root), mode="valid")
    assert len(tr) == 1 and len(va) == 2
    img, mask = tr[0]
    assert img.shape == (5, 4, 3)
    assert mask.shape == (5, 4) and int(mask[0, 0]) == 3


def test_flowers_voc_synthetic_fallback():
    from paddle_tpu.vision.datasets import Flowers, VOC2012
    f = Flowers()
    img, label = f[0]
    assert img.shape == (64, 64, 3) and 0 <= int(label) < 102
    v = VOC2012()
    img, mask = v[0]
    assert img.shape == (64, 64, 3) and mask.shape == (64, 64)


# ---------------- Imikolov / UCIHousing / WMT14 / Movielens ----------

def test_imikolov_parses_ptb(tmp_path):
    from paddle_tpu.text.datasets import Imikolov
    p = tmp_path / "ptb.train.txt"
    with open(p, "w") as f:
        f.write("the cat sat on the mat\nthe dog sat\n")
    ds = Imikolov(data_file=str(tmp_path), mode="train", window_size=3)
    # sentences are wrapped <s> ... <e> before windowing (reference
    # behavior): (6+2-2) + (3+2-2) = 9 windows
    assert len(ds) == 9
    ctx, nxt = ds[0]
    assert ctx.shape == (2,) and np.isscalar(int(nxt))
    assert int(ctx[0]) == ds.word_idx["<s>"]       # boundary n-gram
    assert ds.word_idx["the"] == 0                 # most frequent
    # a sentence shorter than the window still contributes via wrapping
    short = Imikolov(data_file=str(tmp_path / "ptb.train.txt"),
                     mode="train", window_size=5)
    assert len(short) == 5                         # (8-5+1) + (5-5+1)
    seq = Imikolov(data_file=str(p), mode="train", data_type="SEQ")
    x, y = seq[0]
    np.testing.assert_array_equal(x[1:], y[:-1])
    assert int(x[0]) == seq.word_idx["<s>"]
    assert int(y[-1]) == seq.word_idx["<e>"]


def test_ucihousing_parses_real_format(tmp_path):
    from paddle_tpu.text.datasets import UCIHousing
    rng = np.random.default_rng(0)
    rows = rng.random((10, 14)) * 10
    p = tmp_path / "housing.data"
    with open(p, "w") as f:
        for r in rows:
            f.write(" ".join(f"{v:.4f}" for v in r) + "\n")
    tr = UCIHousing(data_file=str(p), mode="train")
    te = UCIHousing(data_file=str(p), mode="test")
    assert len(tr) == 8 and len(te) == 2
    x, y = tr[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert x.min() >= 0.0 and x.max() <= 1.0       # min-max normalized


def test_wmt14_shares_parallel_format(tmp_path):
    from paddle_tpu.text.datasets import WMT14
    p = tmp_path / "train"
    _write_parallel(str(p))
    ds = WMT14(data_file=str(p), mode="train", dict_size=50)
    assert len(ds) == 3
    src, ti, tn = ds[0]
    assert int(ti[0]) == 0 and int(tn[-1]) == 1    # <s> ... <e>


def test_movielens_parses_ml1m(tmp_path):
    from paddle_tpu.text.datasets import Movielens
    d = tmp_path / "ml-1m"
    os.makedirs(d)
    with open(d / "users.dat", "w") as f:
        f.write("1::M::25::6::12345\n2::F::35::3::54321\n")
    with open(d / "movies.dat", "w") as f:
        f.write("10::Toy Story (1995)::Animation|Comedy\n"
                "20::Heat (1995)::Action|Crime\n")
    with open(d / "ratings.dat", "w") as f:
        f.write("1::10::5::978300760\n1::20::3::978300761\n"
                "2::10::4::978300762\n2::20::2::978300763\n")
    tr = Movielens(data_file=str(d), mode="train", test_ratio=0.0)
    assert len(tr) == 4
    u, g, a, j, m, cats, title, rating = tr[0]
    assert int(u) == 1 and int(g) == 1             # M -> 1
    assert int(a) == 2                             # age 25 -> bucket 2
    assert cats.shape == (18,) and cats.sum() >= 1
    assert title.shape == (8,) and title.max() > 0
    assert 1.0 <= float(rating) <= 5.0
    te = Movielens(data_file=str(d), mode="test", test_ratio=1.0)
    assert len(te) == 4


def test_text_synthetic_fallbacks_unchanged():
    from paddle_tpu.text.datasets import (Imikolov, UCIHousing, WMT14,
                                          Movielens)
    assert len(Imikolov(n_samples=10)) == 10
    assert UCIHousing(n_samples=20)[0][0].shape == (13,)
    assert len(WMT14(n_samples=5)) == 5
    assert len(Movielens(n_samples=6)) == 6


def test_flowers_reads_release_tarball(tmp_path):
    import scipy.io
    import tarfile as tarmod
    from PIL import Image
    from paddle_tpu.vision.datasets import Flowers
    img_dir = tmp_path / "jpg"
    os.makedirs(img_dir)
    for i in range(1, 4):
        Image.fromarray(np.full((6, 6, 3), i * 20, np.uint8)).save(
            str(img_dir / f"image_{i:05d}.jpg"))
    tgz = str(tmp_path / "102flowers.tgz")
    with tarmod.open(tgz, "w:gz") as tf:
        tf.add(str(img_dir), arcname="jpg")
    scipy.io.savemat(str(tmp_path / "imagelabels.mat"),
                     {"labels": np.array([[1, 2, 3]])})
    scipy.io.savemat(str(tmp_path / "setid.mat"),
                     {"trnid": np.array([[1, 2]]),
                      "valid": np.array([[3]]),
                      "tstid": np.array([[3]])})
    ds = Flowers(data_file=(tgz, str(tmp_path / "imagelabels.mat"),
                            str(tmp_path / "setid.mat")), mode="train")
    img, label = ds[1]
    assert img.shape == (6, 6, 3) and int(img[0, 0, 0]) == 40
    assert int(label) == 1


def test_movielens_split_is_order_independent(tmp_path):
    from paddle_tpu.text.datasets import Movielens

    def write(d, lines):
        os.makedirs(d, exist_ok=True)
        with open(d / "users.dat", "w") as f:
            f.write("1::M::25::6::x\n2::F::35::3::x\n")
        with open(d / "movies.dat", "w") as f:
            f.write("10::A (1990)::Drama\n20::B (1991)::Action\n")
        with open(d / "ratings.dat", "w") as f:
            f.writelines(lines)

    lines = ["1::10::5::1\n", "1::20::3::2\n", "2::10::4::3\n",
             "2::20::2::4\n"]
    write(tmp_path / "a", lines)
    write(tmp_path / "b", list(reversed(lines)))
    key = lambda s: (int(s[0]), int(s[4]))
    tr_a = {key(s) for s in Movielens(data_file=str(tmp_path / "a"),
                                      mode="train", test_ratio=0.5).samples}
    tr_b = {key(s) for s in Movielens(data_file=str(tmp_path / "b"),
                                      mode="train", test_ratio=0.5).samples}
    assert tr_a == tr_b                 # membership keyed on the pair


def test_functional_erase_affine_perspective():
    """r4: the deterministic functional forms behind the Random*
    transforms (ref: paddle.vision.transforms.erase/affine/perspective)."""
    from paddle_tpu.vision import transforms as T
    img = np.arange(5 * 6 * 3, dtype=np.uint8).reshape(5, 6, 3)
    e = T.erase(img, 1, 2, 2, 3, 7)
    assert (e[1:3, 2:5] == 7).all()
    assert (e[0] == img[0]).all()           # copy by default
    np.testing.assert_array_equal(T.affine(img, angle=0.0), img)
    corners = [(0, 0), (5, 0), (5, 4), (0, 4)]
    np.testing.assert_array_equal(
        T.perspective(img, corners, corners), img)
    # 180-degree rotation is an exact double flip about the center
    r = T.affine(img.astype(np.float32), angle=180.0)
    np.testing.assert_allclose(r, img[::-1, ::-1].astype(np.float32))
