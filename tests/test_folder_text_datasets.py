"""Folder image datasets + real text-format parsers
(ref: python/paddle/vision/datasets/folder.py,
python/paddle/text/datasets/{imdb,conll05,wmt16}.py).
"""
import gzip
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.vision.datasets import (DatasetFolder, ImageFolder,
                                        image_load, IMAGE_EXTENSIONS)
from paddle_tpu.text.datasets import Imdb, Conll05st, WMT16


# ---------------- fixtures ----------------

def _make_image_tree(root, classes=("cat", "dog"), n=3, size=8):
    from PIL import Image
    rng = np.random.default_rng(0)
    for ci, cls in enumerate(classes):
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(n):
            arr = np.full((size, size, 3), 40 * ci + i, np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img_{i}.png"))
        # a non-image file that must be skipped
        with open(os.path.join(d, "notes.txt"), "w") as f:
            f.write("skip me")
    return root


# ---------------- DatasetFolder ----------------

def test_dataset_folder_classes_and_samples(tmp_path):
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root)
    assert ds.classes == ["cat", "dog"]
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    assert len(ds) == 6
    assert ds.targets == [0, 0, 0, 1, 1, 1]
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and img.dtype == np.uint8
    assert int(label) == 0
    img, label = ds[5]
    assert int(label) == 1


def test_dataset_folder_with_transform(tmp_path):
    from paddle_tpu.vision import transforms as T
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root, transform=T.Compose([T.Resize(4),
                                                  T.ToTensor()]))
    img, _ = ds[0]
    assert tuple(img.shape) == (3, 4, 4)     # CHW after ToTensor


def test_dataset_folder_is_valid_file(tmp_path):
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root, extensions=None,
                       is_valid_file=lambda p: p.endswith("img_0.png"))
    assert len(ds) == 2                      # one per class


def test_dataset_folder_both_filters_rejected(tmp_path):
    root = _make_image_tree(str(tmp_path / "train"))
    with pytest.raises(ValueError, match="exactly one"):
        DatasetFolder(root, extensions=(".png",),
                      is_valid_file=lambda p: True)


def test_wmt16_missing_mode_file_actionable(tmp_path):
    d = tmp_path / "corpus"
    os.makedirs(d)
    _write_parallel(str(d / "train"))
    with pytest.raises(ValueError, match="no 'dev' corpus"):
        WMT16(data_file=str(d), mode="dev")


def test_dataset_folder_empty_raises(tmp_path):
    os.makedirs(tmp_path / "empty" / "cls")
    with pytest.raises(RuntimeError, match="no valid files"):
        DatasetFolder(str(tmp_path / "empty"))
    with pytest.raises(RuntimeError, match="no class directories"):
        DatasetFolder(str(tmp_path / "empty" / "cls"))


def test_dataset_folder_in_dataloader(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.vision import transforms as T
    root = _make_image_tree(str(tmp_path / "train"))
    ds = DatasetFolder(root, transform=T.ToTensor())
    loader = paddle.io.DataLoader(ds, batch_size=3, shuffle=False)
    xb, yb = next(iter(loader))
    assert tuple(xb.shape) == (3, 3, 8, 8)
    assert tuple(yb.shape) == (3,)


# ---------------- ImageFolder ----------------

def test_image_folder_flat_recursive(tmp_path):
    root = _make_image_tree(str(tmp_path / "imgs"))
    ds = ImageFolder(root)
    assert len(ds) == 6
    sample = ds[0]
    assert isinstance(sample, list) and len(sample) == 1
    assert sample[0].shape == (8, 8, 3)


def test_image_load_backends(tmp_path):
    from PIL import Image
    p = str(tmp_path / "x.png")
    Image.fromarray(np.zeros((5, 7, 3), np.uint8)).save(p)
    arr = image_load(p)
    assert arr.shape == (5, 7, 3) and arr.dtype == np.uint8
    pil = image_load(p, backend="pil")
    assert pil.size == (7, 5)


# ---------------- Imdb (aclImdb layout) ----------------

_DOCS = {
    ("train", "pos"): ["a great great movie", "great fine ending"],
    ("train", "neg"): ["a terrible terrible film", "boring bad plot"],
    ("test", "pos"): ["great story"],
    ("test", "neg"): ["awful pacing"],
}


def _make_aclimdb_dir(root):
    for (mode, sent), docs in _DOCS.items():
        d = os.path.join(root, mode, sent)
        os.makedirs(d, exist_ok=True)
        for i, doc in enumerate(docs):
            with open(os.path.join(d, f"{i}_7.txt"), "w") as f:
                f.write(doc)
    return root


def test_imdb_parses_directory(tmp_path):
    root = _make_aclimdb_dir(str(tmp_path / "aclImdb"))
    ds = Imdb(data_file=root, mode="train", cutoff=0)
    assert len(ds) == 4
    labels = sorted(int(ds[i][1]) for i in range(4))
    assert labels == [0, 0, 1, 1]
    # frequency-ordered dict: 'great' (3x) and 'terrible' (2x) precede
    # singletons; every doc maps to in-vocab ids
    assert ds.word_idx["great"] < ds.word_idx["boring"]
    unk = ds.word_idx["<unk>"]
    for i in range(4):
        assert (np.asarray(ds[i][0]) < unk).all()


def test_imdb_parses_tarball_and_cutoff(tmp_path):
    root = _make_aclimdb_dir(str(tmp_path / "aclImdb"))
    tar_path = str(tmp_path / "aclImdb_v1.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(root, arcname="aclImdb")
    ds = Imdb(data_file=tar_path, mode="train", cutoff=1)
    assert len(ds) == 4
    # cutoff=1 keeps only words with freq > 1: great(3), terrible(2), a(2)
    kept = set(ds.word_idx) - {"<unk>"}
    assert kept == {"great", "terrible", "a"}
    ds_test = Imdb(data_file=tar_path, mode="test", cutoff=0)
    assert len(ds_test) == 2


def test_imdb_synthetic_fallback_unchanged():
    ds = Imdb(mode="train", n_samples=10)
    x, y = ds[0]
    assert x.dtype == np.int64 and int(y) in (0, 1)


# ---------------- Conll05st (words + props column files) ----------------

_WORDS_FILE = """\
The
cat
chased
mice
.

Dogs
bark
.
"""

# props: col0 = predicate lemma ('-' elsewhere), one arg column per
# predicate with bracketed spans
_PROPS_FILE = """\
-\t(A0*
-\t*)
chase\t(V*)
-\t(A1*)
-\t*

-\t(A0*)
bark\t(V*)
-\t*
"""


def _write_conll(tmp_path, gz=False):
    wp = str(tmp_path / ("words.gz" if gz else "words"))
    pp = str(tmp_path / ("props.gz" if gz else "props"))
    if gz:
        with gzip.open(wp, "wt") as f:
            f.write(_WORDS_FILE)
        with gzip.open(pp, "wt") as f:
            f.write(_PROPS_FILE.replace("\\t", "\t"))
    else:
        with open(wp, "w") as f:
            f.write(_WORDS_FILE)
        with open(pp, "w") as f:
            f.write(_PROPS_FILE.replace("\\t", "\t"))
    return wp, pp


def test_conll05st_parses_column_format(tmp_path):
    wp, pp = _write_conll(tmp_path)
    ds = Conll05st(data_file=(wp, pp))
    assert len(ds) == 2                      # one predicate per sentence
    ids, pred, tags = ds[0]
    assert len(ids) == 5 and len(tags) == 5
    assert int(pred) == 2                    # 'chased' is the V span
    # BIO structure: A0 span covers 'The cat'
    tag_names = {v: k for k, v in ds.tag_idx.items()}
    decoded = [tag_names[int(t)] for t in np.asarray(tags)]
    assert decoded[0] == "B-A0" and decoded[1] == "I-A0"
    assert decoded[2] == "B-V"
    assert decoded[3] == "B-A1"
    ids2, pred2, tags2 = ds[1]
    assert len(ids2) == 3 and int(pred2) == 1


def test_conll05st_gz_and_mismatch(tmp_path):
    wp, pp = _write_conll(tmp_path, gz=True)
    ds = Conll05st(data_file=(wp, pp))
    assert len(ds) == 2
    # words/props length mismatch is a loud error
    bad = str(tmp_path / "short_words")
    with open(bad, "w") as f:
        f.write("Just\none\n")
    with pytest.raises(ValueError, match="sentence counts differ"):
        Conll05st(data_file=(bad, pp))


def test_conll05st_synthetic_fallback_unchanged():
    ds = Conll05st(n_samples=5)
    x, p, y = ds[0]
    assert x.dtype == np.int64 and y.dtype == np.int64


# ---------------- WMT16 (tab-separated parallel corpus) ----------------

_PARALLEL = [
    ("the cat sits", "die katze sitzt"),
    ("the dog runs", "der hund rennt"),
    ("a cat runs", "eine katze rennt"),
]


def _write_parallel(path):
    with open(path, "w") as f:
        for s, t in _PARALLEL:
            f.write(f"{s}\t{t}\n")


def test_wmt16_parses_tsv_file(tmp_path):
    p = str(tmp_path / "train")
    _write_parallel(p)
    ds = WMT16(data_file=p, mode="train", src_dict_size=50,
               trg_dict_size=50)
    assert len(ds) == 3
    src, trg_in, trg_next = ds[0]
    # special ids per the reference: <s>=0 <e>=1 <unk>=2
    assert ds.trg_dict["<s>"] == 0 and ds.trg_dict["<e>"] == 1
    assert int(trg_in[0]) == 0               # target starts with <s>
    assert int(trg_next[-1]) == 1            # and ends with <e>
    np.testing.assert_array_equal(trg_in[1:], trg_next[:-1])
    assert src.dtype == np.int64 and len(src) == 3


def test_wmt16_dict_size_cap_and_unk(tmp_path):
    p = str(tmp_path / "train")
    _write_parallel(p)
    ds = WMT16(data_file=p, mode="train", src_dict_size=4,
               trg_dict_size=4)
    assert len(ds.src_dict) == 4             # 3 specials + 1 real word
    # highest-frequency source word wins the single real slot
    assert "the" in ds.src_dict or "cat" in ds.src_dict
    src, _, _ = ds[1]
    assert (np.asarray(src) <= 3).all()      # everything else is <unk>

def test_wmt16_parses_directory_and_tarball(tmp_path):
    d = tmp_path / "corpus"
    os.makedirs(d)
    _write_parallel(str(d / "train"))
    ds = WMT16(data_file=str(d), mode="train")
    assert len(ds) == 3
    tar_path = str(tmp_path / "wmt16.tar.gz")
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(str(d / "train"), arcname="wmt16/train")
    ds2 = WMT16(data_file=tar_path, mode="train")
    assert len(ds2) == 3
    s1, _, _ = ds[0]
    s2, _, _ = ds2[0]
    np.testing.assert_array_equal(s1, s2)


def test_wmt16_synthetic_fallback_unchanged():
    ds = WMT16(mode="train", n_samples=5)
    src, ti, tn = ds[0]
    assert src.dtype == np.int64
