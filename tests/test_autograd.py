"""Eager autograd graph semantics (SURVEY §2.1 autograd surface)."""
import gc
import weakref

import numpy as np

import paddle_tpu as paddle


def test_simple_backward():
    x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_accumulate():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 3
    z = y * y  # dz/dx = 18x = 18
    z.backward()
    assert np.allclose(x.grad.numpy(), [18.0])
    # second backward accumulates into .grad
    z2 = (x * 2).sum()
    z2.backward()
    assert np.allclose(x.grad.numpy(), [20.0])


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    d = (x * 5).detach()
    out = (d * x).sum()
    out.backward()
    # only the direct x factor contributes: grad = d = 5
    assert np.allclose(x.grad.numpy(), [5.0])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._grad_node is None


def test_branching_graph():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 3
    b = x * 4
    out = (a * b).sum()  # 12x^2 -> d/dx = 24x = 48
    out.backward()
    assert np.allclose(x.grad.numpy(), [48.0])


def test_paddle_grad_api():
    x = paddle.to_tensor([3.0], stop_gradient=False)
    y = x ** 3
    (g,) = paddle.grad(y, x)
    assert np.allclose(g.numpy(), [27.0])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_matmul_grad():
    a = paddle.to_tensor(np.eye(2, dtype="float32"), stop_gradient=False)
    b = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    out = paddle.matmul(a, b).sum()
    out.backward()
    # d/dA sum(AB) = B^T summed over output = ones @ B^T
    assert np.allclose(a.grad.numpy(), np.ones((2, 2)) @ b.numpy().T)


def test_graph_freed_without_backward():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    out = (x * 2).sum()
    ref = weakref.ref(out._grad_node)
    del out
    gc.collect()
    assert ref() is None, "graph must be GC-freed when outputs are dropped"


def test_backward_frees_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    out = (x * 2).sum()
    out.backward()
    assert out._grad_node is None  # severed after backward


def test_retain_graph():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    out = (x * 2).sum()
    out.backward(retain_graph=True)
    x.clear_grad()
    out.backward()
    assert np.allclose(x.grad.numpy(), [2.0])


def test_nondiff_int_inputs():
    x = paddle.to_tensor([1, 2, 3])
    y = x + 1  # int op: no graph
    assert y._grad_node is None


def test_diamond_graph_grad():
    # loss = a + f(a): consumer ordering must be respected (regression)
    x = paddle.to_tensor([2.0], stop_gradient=False)
    a = x * 2
    b = paddle.exp(a)
    (a + b).sum().backward()
    expect = 2 + 2 * np.exp(4.0)
    assert np.allclose(x.grad.numpy(), [expect], rtol=1e-5)
    x2 = paddle.to_tensor([2.0], stop_gradient=False)
    a2 = x2 * 2
    (paddle.exp(a2) + a2).sum().backward()
    assert np.allclose(x2.grad.numpy(), [expect], rtol=1e-5)


def test_grad_unreachable_raises():
    from paddle_tpu import nn
    w = nn.Parameter(paddle.ones([2])._value)
    loss = paddle.ones([2]).sum()
    try:
        paddle.grad(loss, w)
        assert False, "expected ValueError"
    except ValueError:
        pass
    (g,) = paddle.grad(loss, w, allow_unused=True)
    assert g is None
