"""Optimizer numerics: hand-computed updates + convergence (SURVEY §4)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.optimizer import lr as lr_mod


def quad_param(v=None):
    p = nn.Parameter(paddle.to_tensor(v if v is not None else [2.0, -3.0])._value)
    return p


def test_sgd_exact():
    w = quad_param()
    opt = paddle.optimizer.SGD(0.1, parameters=[w])
    ((w * w).sum()).backward()
    opt.step()
    assert np.allclose(w.numpy(), [1.6, -2.4])


def test_momentum_exact():
    w = quad_param([1.0])
    opt = paddle.optimizer.Momentum(0.1, momentum=0.9, parameters=[w])
    (w * w).sum().backward()
    opt.step()            # v=2, w=1-0.2=0.8
    opt.clear_grad()
    assert np.allclose(w.numpy(), [0.8])
    (w * w).sum().backward()
    opt.step()            # v=0.9*2+1.6=3.4, w=0.8-0.34=0.46
    assert np.allclose(w.numpy(), [0.46], atol=1e-6)


def test_adam_exact_first_step():
    w = quad_param([1.0])
    opt = paddle.optimizer.Adam(0.001, parameters=[w])
    (w * w).sum().backward()
    opt.step()
    # first Adam step magnitude ~ lr regardless of grad scale
    assert np.allclose(w.numpy(), [1.0 - 0.001], atol=1e-6)


def test_adamw_decoupled_decay():
    w = quad_param([1.0])
    opt = paddle.optimizer.AdamW(0.001, weight_decay=0.5, parameters=[w])
    (w * 0).sum().backward()  # zero grad -> update is pure decay
    opt.step()
    assert np.allclose(w.numpy(), [1.0 - 0.001 * 0.5 * 1.0], atol=1e-6)


def test_convergence_all():
    for cls, kw, lr in [
        (paddle.optimizer.SGD, {}, 0.1),
        (paddle.optimizer.Momentum, {"momentum": 0.9}, 0.1),
        (paddle.optimizer.Adam, {}, 0.1),
        (paddle.optimizer.AdamW, {"weight_decay": 0.0}, 0.1),
        (paddle.optimizer.RMSProp, {}, 0.1),
        (paddle.optimizer.Adagrad, {}, 1.0),  # 1/sqrt(t) steps need big lr
        (paddle.optimizer.Adamax, {}, 0.1),
        (paddle.optimizer.Lamb, {"lamb_weight_decay": 0.0}, 0.1),
    ]:
        w = quad_param([5.0, -5.0])
        opt = cls(lr, parameters=[w], **kw)
        for _ in range(100):
            loss = (w * w).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float((w * w).sum()) < 0.3, f"{cls.__name__} did not converge"


def test_grad_clip_in_optimizer():
    w = quad_param([100.0])
    opt = paddle.optimizer.SGD(
        0.1, parameters=[w], grad_clip=nn.ClipGradByGlobalNorm(1.0))
    (w * w).sum().backward()
    opt.step()
    # grad 200 clipped to norm 1 -> step 0.1
    assert np.allclose(w.numpy(), [99.9], atol=1e-4)


def test_param_groups_lr_mult():
    w1 = quad_param([1.0])
    w2 = quad_param([1.0])
    opt = paddle.optimizer.SGD(0.1, parameters=[
        {"params": [w1]},
        {"params": [w2], "learning_rate": 0.1},  # 10x smaller
    ])
    ((w1 * w1).sum() + (w2 * w2).sum()).backward()
    opt.step()
    assert np.allclose(w1.numpy(), [0.8])
    assert np.allclose(w2.numpy(), [0.98])


def test_state_dict_roundtrip():
    w = quad_param([1.0])
    opt = paddle.optimizer.Adam(0.01, parameters=[w])
    for _ in range(3):
        (w * w).sum().backward()
        opt.step()
        opt.clear_grad()
    sd = opt.state_dict()
    w2 = quad_param([float(w.numpy()[0])])
    w2.name = w.name
    opt2 = paddle.optimizer.Adam(0.01, parameters=[w2])
    opt2.set_state_dict(sd)
    (w * w).sum().backward()
    opt.step()
    (w2 * w2).sum().backward()
    opt2.step()
    assert np.allclose(w.numpy(), w2.numpy(), atol=1e-7)


class TestLRSchedulers:
    def test_step_decay(self):
        s = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
        vals = []
        for _ in range(5):
            vals.append(round(s(), 5))
            s.step()
        assert vals == [0.1, 0.1, 0.05, 0.05, 0.025]

    def test_warmup(self):
        s = lr_mod.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
        first = s()
        for _ in range(6):
            s.step()
        assert first < 0.1 and abs(s() - 0.1) < 1e-9

    def test_cosine(self):
        s = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-9
        for _ in range(10):
            s.step()
        assert s() < 1e-6

    def test_noam(self):
        s = lr_mod.NoamDecay(d_model=512, warmup_steps=10, learning_rate=1.0)
        vals = [s()]
        for _ in range(20):
            s.step()
            vals.append(s())
        peak = max(vals)
        assert vals.index(peak) in (9, 10, 11)

    def test_reduce_on_plateau(self):
        s = lr_mod.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        s.step(1.0)
        s.step(1.0)
        s.step(1.0)  # no improvement for > patience
        assert s() == 0.05

    def test_scheduler_in_optimizer(self):
        w = quad_param([1.0])
        sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
        opt = paddle.optimizer.SGD(sched, parameters=[w])
        (w * w).sum().backward()
        opt.step()        # lr=0.1: 1 - 0.1*2 = 0.8
        opt.clear_grad()
        sched.step()
        (w * w).sum().backward()
        opt.step()        # lr=0.01: 0.8 - 0.01*1.6
        assert np.allclose(w.numpy(), [0.8 - 0.016], atol=1e-6)
