"""incubate.nn fused layers == unfused reference compositions."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import nn as inn


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestFusedLinear:
    def test_matches_linear(self):
        paddle.seed(0)
        fl = inn.FusedLinear(4, 3)
        x = paddle.to_tensor(
            np.random.default_rng(0).standard_normal((2, 4))
            .astype("float32"))
        ref = _np(x.numpy()) @ _np(fl.weight) + _np(fl.bias)
        assert np.allclose(_np(fl(x)), ref, atol=1e-5)

    def test_transpose_weight(self):
        paddle.seed(1)
        fl = inn.FusedLinear(4, 3, transpose_weight=True)
        assert tuple(fl.weight.shape) == (3, 4)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        ref = np.ones((2, 4)) @ _np(fl.weight).T + _np(fl.bias)
        assert np.allclose(_np(fl(x)), ref, atol=1e-5)


class TestFusedDropoutAdd:
    def test_eval_is_plain_add(self):
        fda = inn.FusedDropoutAdd(0.5)
        fda.eval()
        x = paddle.to_tensor(np.full((3,), 2.0, np.float32))
        y = paddle.to_tensor(np.full((3,), 1.0, np.float32))
        assert np.allclose(_np(fda(x, y)), 3.0)

    def test_train_drops(self):
        paddle.seed(3)
        fda = inn.FusedDropoutAdd(0.99)
        fda.train()
        x = paddle.to_tensor(np.full((1000,), 1.0, np.float32))
        y = paddle.to_tensor(np.zeros((1000,), np.float32))
        out = _np(fda(x, y))
        assert (out == 0).mean() > 0.9  # most dropped


class TestFusedMHA:
    def test_matches_unfused_attention(self):
        paddle.seed(4)
        d, h = 16, 4
        fmha = inn.FusedMultiHeadAttention(
            d, h, dropout_rate=0.0, attn_dropout_rate=0.0,
            normalize_before=True)
        fmha.eval()
        rng = np.random.default_rng(4)
        x = paddle.to_tensor(rng.standard_normal((2, 6, d))
                             .astype("float32"))
        out = fmha(x)
        assert tuple(out.shape) == (2, 6, d)

        # manual recomputation with the packed weights
        import jax.numpy as jnp
        xv = _np(x)
        mu = xv.mean(-1, keepdims=True)
        var = xv.var(-1, keepdims=True)
        xn = (xv - mu) / np.sqrt(var + 1e-5)
        xn = xn * _np(fmha.pre_ln_scale) + _np(fmha.pre_ln_bias)
        w = _np(fmha.qkv_weight)     # [3, H, D, E]
        b = _np(fmha.qkv_bias)       # [3, H, D]
        packed = np.einsum("bse,khde->bskhd", xn, w) + b[None, None]
        q, k, v = packed[:, :, 0], packed[:, :, 1], packed[:, :, 2]
        scale = 1.0 / np.sqrt(d // h)
        s = np.einsum("bqhd,bkhd->bhqk", q, k) * scale
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        att = np.einsum("bhqk,bkhd->bqhd", p, v).reshape(2, 6, d)
        ref = att @ _np(fmha.linear_weight) + _np(fmha.linear_bias) + xv
        assert np.allclose(_np(out), ref, atol=1e-4)

    def test_trains(self):
        paddle.seed(5)
        layer = inn.FusedTransformerEncoderLayer(16, 4, 32,
                                                 dropout_rate=0.0)
        layer.train()
        opt = paddle.optimizer.Adam(1e-3, parameters=layer.parameters())
        rng = np.random.default_rng(5)
        x = paddle.to_tensor(rng.standard_normal((2, 8, 16))
                             .astype("float32"))
        first = None
        for _ in range(5):
            out = layer(x)
            loss = (out ** 2).mean()
            first = first if first is not None else float(loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert float(loss) < first


class TestFusedFFN:
    def test_matches_unfused(self):
        paddle.seed(6)
        ffn = inn.FusedFeedForward(8, 16, dropout_rate=0.0,
                                   normalize_before=True)
        ffn.eval()
        rng = np.random.default_rng(6)
        x = paddle.to_tensor(rng.standard_normal((2, 3, 8))
                             .astype("float32"))
        xv = _np(x)
        mu = xv.mean(-1, keepdims=True)
        var = xv.var(-1, keepdims=True)
        xn = (xv - mu) / np.sqrt(var + 1e-5)
        xn = xn * _np(ffn.ln1_scale) + _np(ffn.ln1_bias)
        h = np.maximum(xn @ _np(ffn.linear1_weight) + _np(ffn.linear1_bias),
                       0)
        ref = h @ _np(ffn.linear2_weight) + _np(ffn.linear2_bias) + xv
        assert np.allclose(_np(ffn(x)), ref, atol=1e-4)


class TestFusedEdgeCases:
    def test_bias_attr_false(self):
        fl = inn.FusedLinear(4, 2, bias_attr=False)
        assert fl.bias is None
        x = paddle.to_tensor(np.ones((1, 4), np.float32))
        assert np.allclose(_np(fl(x)), np.ones((1, 4)) @ _np(fl.weight))
        mha = inn.FusedMultiHeadAttention(8, 2, dropout_rate=0.0,
                                          attn_dropout_rate=0.0,
                                          qkv_bias_attr=False,
                                          linear_bias_attr=False)
        mha.eval()
        out = mha(paddle.to_tensor(
            np.random.default_rng(0).standard_normal((1, 4, 8))
            .astype("float32")))
        assert tuple(out.shape) == (1, 4, 8)

    def test_unsupported_corners_raise(self):
        with pytest.raises(NotImplementedError):
            inn.FusedMultiHeadAttention(8, 2, need_weights=True)
        with pytest.raises(NotImplementedError):
            inn.FusedMultiHeadAttention(8, 2, kdim=4)
        mha = inn.FusedMultiHeadAttention(8, 2)
        with pytest.raises(NotImplementedError):
            mha(paddle.to_tensor(np.ones((1, 2, 8), np.float32)),
                cache="anything")

    def test_reference_state_dict_keys(self):
        mha = inn.FusedMultiHeadAttention(8, 2)
        keys = set(mha.state_dict().keys())
        assert {"qkv_weight", "qkv_bias", "linear_weight", "linear_bias",
                "pre_ln_scale", "pre_ln_bias", "ln_scale",
                "ln_bias"} <= keys
        ffn = inn.FusedFeedForward(8, 16)
        fkeys = set(ffn.state_dict().keys())
        assert {"linear1_weight", "linear1_bias", "linear2_weight",
                "linear2_bias", "ln1_scale", "ln1_bias", "ln2_scale",
                "ln2_bias"} <= fkeys
