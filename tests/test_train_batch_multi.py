"""Engine.train_batch_multi — K optimizer steps in one dispatch
(the public form of bench.py's --scan-steps construction; amortizes
per-dispatch latency on remote backends).

Defining property: EXACTLY equal to K sequential train_batch calls
(same rng folding, same counters, same updates).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine


def _make(lr=0.01):
    paddle.seed(3)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                               paddle.nn.Linear(16, 4))
    return net, Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                       optimizer=paddle.optimizer.AdamW(
                           lr, parameters=net.parameters()))


def _data(k=4, b=8):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((k, b, 8)).astype(np.float32)
    y = rng.integers(0, 4, (k, b)).astype(np.int64)
    return x, y


def test_multi_equals_sequential():
    x, y = _data()
    _, eng_a = _make()
    seq = [float(eng_a.train_batch([jnp.asarray(x[i])],
                                   [jnp.asarray(y[i])])[0])
           for i in range(4)]
    _, eng_b = _make()
    losses, _ = eng_b.train_batch_multi([jnp.asarray(x)], [jnp.asarray(y)])
    np.testing.assert_allclose(np.asarray(losses), seq, rtol=1e-6)
    for k in eng_a._params:
        np.testing.assert_allclose(np.asarray(eng_a._params[k]),
                                   np.asarray(eng_b._params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    assert eng_b._step == 4 and eng_b._opt_step == 4


def test_multi_then_single_continues_exactly():
    """Counters and rng line up so multi(4) + single == 5 singles."""
    x, y = _data(5)
    _, eng_a = _make()
    for i in range(5):
        last_a, _ = eng_a.train_batch([jnp.asarray(x[i])],
                                      [jnp.asarray(y[i])])
    _, eng_b = _make()
    eng_b.train_batch_multi([jnp.asarray(x[:4])], [jnp.asarray(y[:4])])
    last_b, _ = eng_b.train_batch([jnp.asarray(x[4])], [jnp.asarray(y[4])])
    np.testing.assert_allclose(float(last_b), float(last_a), rtol=1e-6)


def test_multi_lr_values_schedule_matches_sequential():
    x, y = _data(3)
    lrs = np.asarray([0.05, 0.02, 0.01], np.float32)
    # sequential reference: inject each lr before its step
    _, eng_a = _make(lr=1.0)
    for i in range(3):
        eng_a.optimizer._lr = float(lrs[i])
        eng_a.train_batch([jnp.asarray(x[i])], [jnp.asarray(y[i])])
    _, eng_b = _make(lr=1.0)
    losses, _ = eng_b.train_batch_multi([jnp.asarray(x)], [jnp.asarray(y)],
                                        lr_values=lrs)
    assert losses.shape == (3,)
    for k in eng_a._params:
        np.testing.assert_allclose(np.asarray(eng_a._params[k]),
                                   np.asarray(eng_b._params[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    with pytest.raises(ValueError, match="lr_values"):
        eng_b.train_batch_multi([jnp.asarray(x)], [jnp.asarray(y)],
                                lr_values=np.ones((2,), np.float32))


def test_multi_mismatched_k_fails_before_counters_move():
    x, y = _data(4)
    _, eng = _make()
    with pytest.raises(ValueError, match="disagree on K"):
        eng.train_batch_multi([jnp.asarray(x)], [jnp.asarray(y[:3])])
    assert eng._step == 0 and eng._opt_step == 0   # counters untouched


def test_multi_flushes_pending_accum_window():
    x, y = _data(2)
    _, eng = _make()
    eng.train_batch_accum([jnp.asarray(x[0])], [jnp.asarray(y[0])],
                          apply_update=False)
    assert eng._micro_count == 1
    eng.train_batch_multi([jnp.asarray(x)], [jnp.asarray(y)])
    assert eng._micro_count == 0


def test_multi_dp_sharded():
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    x, y = _data(3, b=16)
    paddle.seed(3)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                               paddle.nn.Linear(16, 4))
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.AdamW(
                     0.01, parameters=net.parameters()), mesh=mesh)
    losses, _ = eng.train_batch_multi([jnp.asarray(x)], [jnp.asarray(y)])
    assert losses.shape == (3,)
    # ragged stacked batch is a loud error
    with pytest.raises(ValueError, match="not divisible"):
        eng.train_batch_multi([jnp.asarray(x[:, :10])],
                              [jnp.asarray(y[:, :10])])
