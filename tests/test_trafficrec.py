"""Traffic capture plane (paddle_tpu/observability/trafficrec.py).

Pins the ISSUE-12 archive contracts (docs/observability.md "Traffic
capture & replay"):

- len+crc framed records through the journal's wire format; an
  archive truncated at ANY byte offset loads its prefix — never
  raises, never duplicates, drops at most the tail (fuzz ladder);
- bounded rotation: segments roll at ``segment_max_bytes`` and the
  ring keeps at most ``max_segments`` (capture can never fill a
  disk); finalized segments carry the io/atomic ``.complete`` marker;
- deterministic fractional-accumulator capture sampling, counted in
  ``fleet_capture_sampled_out_total`` — dropped is visible;
- every write is suppressed under ``introspecting()``;
- arrival+resolve fold into replayable entries (arrival order,
  rebased arrival offsets, meta records newest-wins).
"""
import json
import os

import pytest

from paddle_tpu.observability import introspect
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.trafficrec import (TrafficRecorder,
                                                 load_archive)


def _record_n(rec, n, resolve=True, start=0):
    refs = []
    for i in range(start, start + n):
        refs.append(rec.record_arrival(
            i, [1, 2, 3 + i], 8, eos=None, priority=i % 2,
            tenant=f"t{i % 3}", deadline_ms=None))
        if resolve:
            rec.record_resolve(
                i, "ok", [7, 8, 9 + i], tenant=f"t{i % 3}",
                replica="r0", e2e_s=0.5 + i, ttft_s=0.1,
                hops=[{"name": "replica_leg", "proc": "r0",
                       "dur_s": 0.4, "outcome": "ok"}])
    return refs


class TestArchiveRoundtrip:
    def test_capture_and_load(self, tmp_path):
        reg = MetricsRegistry()
        rec = TrafficRecorder(tmp_path, registry=reg)
        refs = _record_n(rec, 5)
        rec.note_meta(**{"sampling.r0": {"temperature": 0.0}})
        rec.record_arrival(99, [5], 4)  # meta flushes on this write
        rec.close()
        assert all(r is not None for r in refs)
        assert refs[0]["segment"] == "cap-000001.jsonl"
        entries, meta, stats = load_archive(tmp_path)
        assert [e["rid"] for e in entries] == [0, 1, 2, 3, 4, 99]
        assert stats["torn_drops"] == 0
        assert stats["unresolved"] == 1  # rid 99 never resolved
        e0 = entries[0]
        assert e0["prompt"] == [1, 2, 3]
        assert e0["tokens"] == [7, 8, 9]
        assert e0["status"] == "ok"
        assert e0["tenant"] == "t0"
        assert e0["hops"][0]["name"] == "replica_leg"
        assert e0["arrival_s"] == 0.0  # rebased to first arrival
        assert meta["sampling.r0"] == {"temperature": 0.0}
        assert int(reg.get("fleet_capture_requests_total").value) == 6
        assert int(reg.get("fleet_capture_errors_total").value) == 0

    def test_arrival_offsets_rebase(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        rec.record_arrival(0, [1], 4, t_pc=100.5)
        rec.record_arrival(1, [1], 4, t_pc=100.75)
        rec.close()
        entries, _, _ = load_archive(tmp_path)
        assert entries[0]["arrival_s"] == 0.0
        assert entries[1]["arrival_s"] == pytest.approx(0.25)

    def test_meta_newest_wins(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        rec.note_meta(k="old")
        rec.record_arrival(0, [1], 4)
        rec.note_meta(k="new")
        rec.record_arrival(1, [1], 4)
        rec.close()
        _, meta, _ = load_archive(tmp_path)
        assert meta["k"] == "new"


class TestTornTolerance:
    def test_truncate_at_every_offset(self, tmp_path):
        """The journal discipline: a copy truncated at ANY byte
        offset loads without raising, never duplicates a record, and
        loses at most the tail."""
        rec = TrafficRecorder(tmp_path)
        _record_n(rec, 3)
        rec.close()
        seg = os.path.join(tmp_path, "cap-000001.jsonl")
        data = open(seg, "rb").read()
        full, _, _ = load_archive(tmp_path)
        prev_rids = None
        for cut in range(len(data) + 1):
            with open(seg, "wb") as f:
                f.write(data[:cut])
            entries, _, stats = load_archive(tmp_path)
            rids = [e["rid"] for e in entries]
            assert rids == sorted(set(rids))  # never duplicated
            assert len(entries) <= len(full)
            if prev_rids is not None:
                # monotone: more bytes can only reveal more
                assert set(prev_rids) <= set(rids) or cut == 0
            prev_rids = rids
        assert prev_rids == [e["rid"] for e in full]

    def test_garbage_lines_resync(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        _record_n(rec, 2)
        rec.close()
        seg = os.path.join(tmp_path, "cap-000001.jsonl")
        data = open(seg, "rb").read()
        lines = data.split(b"\n")
        lines.insert(2, b"not a frame at all")
        with open(seg, "wb") as f:
            f.write(b"\n".join(lines))
        entries, _, stats = load_archive(tmp_path)
        assert [e["rid"] for e in entries] == [0, 1]
        assert stats["torn_drops"] == 1


class TestRotation:
    def test_segments_roll_and_ring_bounds(self, tmp_path):
        reg = MetricsRegistry()
        rec = TrafficRecorder(tmp_path, registry=reg,
                              segment_max_bytes=512, max_segments=3)
        _record_n(rec, 40)
        rec.close()
        segs = sorted(f for f in os.listdir(tmp_path)
                      if f.startswith("cap-")
                      and f.endswith(".jsonl"))
        assert 1 < len(segs) <= 3  # rotated AND bounded
        assert int(reg.get(
            "fleet_capture_rotations_total").value) > 0
        # finalized segments carry the io/atomic marker
        from paddle_tpu.io import atomic
        for seg in segs[:-1]:
            assert atomic.has_marker(os.path.join(tmp_path, seg))
        # the ring dropped the oldest — the survivors still load
        entries, _, stats = load_archive(tmp_path)
        assert stats["torn_drops"] == 0
        rids = [e["rid"] for e in entries]
        assert rids == sorted(rids)
        assert rids[-1] == 39  # newest survives

    def test_failed_rotation_never_raises(self, tmp_path):
        """Best-effort contract under the worst case: the archive
        directory vanishes mid-run, the next rotation cannot open a
        segment — capture dies QUIETLY (errors counted, writes
        dropped), never propagating into the submit path."""
        import shutil
        reg = MetricsRegistry()
        rec = TrafficRecorder(tmp_path / "cap", registry=reg,
                              segment_max_bytes=256)
        assert rec.record_arrival(0, [1] * 20, 8) is not None
        shutil.rmtree(tmp_path / "cap")
        # keep writing until rotation trips on the missing dir, then
        # beyond — every call must return None/record, never raise
        for i in range(1, 30):
            rec.record_arrival(i, [1] * 20, 8)
        assert rec.record_arrival(99, [1], 4) is None  # capture dead
        assert int(reg.get(
            "fleet_capture_errors_total").value) >= 1
        rec.close()  # idempotent on the dead recorder

    def test_meta_survives_transient_write_failure(self, tmp_path,
                                                   monkeypatch):
        """A transient I/O failure on the meta write must not drop
        the sampling params forever — the dirty flag clears only
        after the write lands, so the next append retries it."""
        rec = TrafficRecorder(tmp_path)
        rec.note_meta(k="v")
        real = TrafficRecorder._write_rec
        calls = {"n": 0}

        def flaky(self, rec_, fsync=False):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient")
            return real(self, rec_, fsync)

        monkeypatch.setattr(TrafficRecorder, "_write_rec", flaky)
        assert rec.record_arrival(0, [1], 4) is None  # meta write hit
        assert rec.record_arrival(1, [1], 4) is not None  # retried
        rec.close()
        _, meta, _ = load_archive(tmp_path)
        assert meta == {"k": "v"}

    def test_reopen_continues_numbering(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        _record_n(rec, 1)
        rec.close()
        rec2 = TrafficRecorder(tmp_path)
        _record_n(rec2, 1, start=10)
        rec2.close()
        entries, _, _ = load_archive(tmp_path)
        assert [e["rid"] for e in entries] == [0, 10]


class TestSamplingAndSuppression:
    def test_deterministic_fractional_sampling(self, tmp_path):
        reg = MetricsRegistry()
        rec = TrafficRecorder(tmp_path, registry=reg, sample=0.5)
        kept = [rec.admit() for _ in range(10)]
        assert kept == [False, True] * 5  # accumulator, no RNG
        assert rec.sampled_out == 5
        assert int(reg.get(
            "fleet_capture_sampled_out_total").value) == 5
        rec.close()

    def test_sample_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CAPTURE_SAMPLE", "0.25")
        rec = TrafficRecorder(tmp_path)
        assert rec.sample == 0.25
        rec.close()

    def test_suppressed_under_introspection(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        introspect._introspecting.on = True
        try:
            assert rec.admit() is False
            assert rec.record_arrival(0, [1], 4) is None
            assert rec.record_resolve(0, "ok", [1]) is None
        finally:
            introspect._introspecting.on = False
        rec.close()
        entries, _, stats = load_archive(tmp_path)
        assert entries == []

    def test_closed_recorder_drops(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        rec.close()
        assert rec.record_arrival(0, [1], 4) is None
        assert rec.admit() is False

    def test_nonfinite_floats_stay_valid_json(self, tmp_path):
        rec = TrafficRecorder(tmp_path)
        rec.record_resolve(0, "ok", [1], e2e_s=float("nan"),
                           ttft_s=float("inf"))
        rec.close()
        seg = os.path.join(tmp_path, "cap-000001.jsonl")
        for line in open(seg, "rb").read().split(b"\n"):
            if line:
                json.loads(line[18:])  # RFC-valid payload
