"""Flagship-level sequence/context parallelism: a GPT train step on a
dp x sp mesh with ring (and Ulysses) attention must equal the plain
GSPMD step numerically. ref parity: fleet sep_parallel /
RingFlashAttention route the same models through sequence sharding.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.mpu import shard_model
from paddle_tpu.distributed.mesh import set_mesh
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                GPTPretrainingCriterion)
from paddle_tpu.optimizer import AdamW

CFG = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
           num_attention_heads=4, max_position_embeddings=64,
           hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
           use_flash_attention=False)


def _mesh_dp_sp():
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    return Mesh(devs, ("dp", "sp"))


def _one_step(sp_mode, mesh, ids, labels):
    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(**CFG, sequence_parallel=sp_mode))
    model.train()
    shard_model(model, mesh)
    opt = AdamW(learning_rate=1e-3, parameters=model.parameters())
    eng = Engine(model, loss=GPTPretrainingCriterion(), optimizer=opt,
                 mesh=mesh)
    loss, _ = eng.train_batch([ids], [labels])
    p0 = next(iter(eng._params.values())) if isinstance(eng._params, dict) \
        else jax.tree_util.tree_leaves(eng._params)[0]
    return float(loss), np.asarray(p0)


@pytest.mark.parametrize("sp_mode", ["ring", "ulysses"])
def test_gpt_sp_train_step_matches_plain(sp_mode):
    mesh = _mesh_dp_sp()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (4, 32)), dtype=jnp.int32)
    labels = ids
    try:
        base_loss, base_p = _one_step("", mesh, ids, labels)
        sp_loss, sp_p = _one_step(sp_mode, mesh, ids, labels)
    finally:
        set_mesh(None)
    assert abs(base_loss - sp_loss) < 2e-4, (base_loss, sp_loss)
    np.testing.assert_allclose(sp_p, base_p, atol=2e-4, rtol=2e-4)


def test_gpt_sp_off_mesh_falls_back():
    # without an 'sp' axis the config flag must be a no-op (same program
    # as plain attention) — users can keep one config across topologies
    set_mesh(None)
    paddle.seed(3)
    m = GPTForCausalLM(GPTConfig(**CFG, sequence_parallel="ring"))
    m.eval()
    ids = jnp.ones((2, 16), dtype=jnp.int32)
    out = m(ids)
    assert out.shape == [2, 16, 128]


def test_sp_config_validation():
    with pytest.raises(ValueError):
        GPTConfig(**{**CFG, "attention_probs_dropout_prob": 0.1},
                  sequence_parallel="ring")
    with pytest.raises(ValueError):
        GPTConfig(**CFG, sequence_parallel="rings")
