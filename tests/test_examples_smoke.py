"""Examples stay runnable: fast ones execute end to end, slow ones at
least compile."""
import py_compile
import runpy
import sys

import pytest

EX = "examples"


def _run(script, argv=()):
    old = sys.argv
    sys.argv = [script, *argv]
    try:
        runpy.run_path(f"{EX}/{script}", run_name="__main__")
    finally:
        sys.argv = old


class TestExamples:
    def test_all_examples_compile(self):
        import glob
        scripts = glob.glob(f"{EX}/*.py")
        assert len(scripts) >= 5
        for s in scripts:
            py_compile.compile(s, doraise=True)

    def test_vae_runs(self, capsys):
        _run("vae_distribution.py")
        assert "final:" in capsys.readouterr().out

    def test_serve_generation_runs(self, capsys):
        _run("serve_generation.py")
        assert "served-model continuation correct: True" in \
            capsys.readouterr().out

    def test_quantize_runs(self, capsys):
        _run("quantize_qat.py")
        out = capsys.readouterr().out
        assert "int8 serving acc" in out

    def test_deploy_runs(self, capsys, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        import os
        import sys
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath("examples"))))
        old = sys.argv
        sys.argv = ["deploy_stablehlo.py"]
        try:
            runpy.run_path(os.path.join(os.path.dirname(__file__), "..",
                                        "examples", "deploy_stablehlo.py"),
                           run_name="__main__")
        finally:
            sys.argv = old
        assert "exported + reloaded" in capsys.readouterr().out

    @pytest.mark.slow
    def test_bert_runs(self, capsys):
        _run("finetune_bert.py")
        assert "epoch 2" in capsys.readouterr().out

    def test_dynamic_control_flow_runs(self, capsys):
        _run("dynamic_control_flow.py")
        out = capsys.readouterr().out
        assert "collatz(27) steps: 111" in out
        assert "un-lowerable pattern raises" in out

    @pytest.mark.slow
    def test_pointcloud_sparse_conv_runs(self, capsys):
        _run("pointcloud_sparse_conv.py")
        assert "accuracy on held-out clouds" in capsys.readouterr().out


class TestIoHelpers:
    def test_get_worker_info_none_in_main(self):
        # reference contract: None outside a worker process, so ported
        # `if info is None: iterate all` sharding guards degenerate right
        from paddle_tpu.io import get_worker_info
        assert get_worker_info() is None

    def test_default_convert_fn(self):
        import numpy as np
        from paddle_tpu.io import default_convert_fn
        out = default_convert_fn([1, {"a": 2.5}, (3,)])
        assert isinstance(out[0], np.ndarray)
        assert isinstance(out[1]["a"], np.ndarray)
        assert isinstance(out[2], tuple)
        import collections
        Point = collections.namedtuple("Point", "x y")
        p = default_convert_fn(Point(1, 2))
        assert isinstance(p, Point) and isinstance(p.x, np.ndarray)
