"""paddle.geometric — segment ops + message passing vs numpy/scipy goldens.

ref parity: python/paddle/geometric/math.py,
python/paddle/geometric/message_passing/send_recv.py.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric as G


def _np_segment(op, data, ids, n):
    out = np.zeros((n,) + data.shape[1:], data.dtype)
    for s in range(n):
        rows = data[ids == s]
        if len(rows) == 0:
            continue  # empty segments stay 0 (reference semantics)
        if op == "sum":
            out[s] = rows.sum(0)
        elif op == "mean":
            out[s] = rows.mean(0)
        elif op == "max":
            out[s] = rows.max(0)
        elif op == "min":
            out[s] = rows.min(0)
    return out


@pytest.mark.parametrize("op", ["sum", "mean", "max", "min"])
def test_segment_ops_vs_numpy(op):
    rng = np.random.default_rng(0)
    data = rng.standard_normal((12, 5)).astype(np.float32)
    ids = np.asarray([0, 0, 1, 1, 1, 3, 3, 5, 5, 5, 5, 6])  # 2,4 empty
    fn = getattr(G, f"segment_{op}")
    got = fn(paddle.to_tensor(data), paddle.to_tensor(ids)).numpy()
    want = _np_segment(op, data, ids, 7)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_segment_sum_grad():
    data = paddle.to_tensor(np.ones((4, 3), np.float32))
    data.stop_gradient = False
    ids = paddle.to_tensor(np.asarray([0, 0, 1, 1]))
    out = G.segment_sum(data, ids)
    out.sum().backward()
    np.testing.assert_allclose(data.grad.numpy(), np.ones((4, 3)), rtol=1e-6)


def test_segment_out_size_jit_static():
    """Under jit the row count must be static: out_size makes it so."""
    data = jnp.ones((6, 2), jnp.float32)
    ids = jnp.asarray([0, 1, 1, 2, 2, 2])

    @jax.jit
    def f(d):
        return G.segment_sum(d, ids, out_size=4)._value
    out = f(data)
    assert out.shape == (4, 2)
    np.testing.assert_allclose(np.asarray(out)[:, 0], [1, 2, 3, 0])


@pytest.mark.parametrize("reduce_op", ["sum", "mean", "max", "min"])
def test_send_u_recv(reduce_op):
    rng = np.random.default_rng(1)
    x = rng.standard_normal((5, 4)).astype(np.float32)
    src = np.asarray([0, 1, 2, 0, 4])
    dst = np.asarray([1, 1, 0, 3, 3])
    got = G.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                        paddle.to_tensor(dst), reduce_op=reduce_op).numpy()
    want = _np_segment_edges(x[src], dst, 5, reduce_op)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def _np_segment_edges(msg, dst, n, op):
    out = np.zeros((n,) + msg.shape[1:], msg.dtype)
    for s in range(n):
        rows = msg[dst == s]
        if len(rows) == 0:
            continue
        out[s] = {"sum": rows.sum(0), "mean": rows.mean(0),
                  "max": rows.max(0), "min": rows.min(0)}[op]
    return out


@pytest.mark.parametrize("message_op", ["add", "mul"])
def test_send_ue_recv(message_op):
    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 3)).astype(np.float32)
    e = rng.standard_normal((5, 3)).astype(np.float32)
    src = np.asarray([0, 1, 2, 3, 0])
    dst = np.asarray([1, 2, 2, 0, 0])
    got = G.send_ue_recv(paddle.to_tensor(x), paddle.to_tensor(e),
                         paddle.to_tensor(src), paddle.to_tensor(dst),
                         message_op=message_op, reduce_op="sum").numpy()
    msg = x[src] + e if message_op == "add" else x[src] * e
    want = _np_segment_edges(msg, dst, 4, "sum")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_send_uv():
    rng = np.random.default_rng(3)
    x = rng.standard_normal((4, 3)).astype(np.float32)
    y = rng.standard_normal((4, 3)).astype(np.float32)
    src = np.asarray([0, 1, 3])
    dst = np.asarray([2, 0, 1])
    got = G.send_uv(paddle.to_tensor(x), paddle.to_tensor(y),
                    paddle.to_tensor(src), paddle.to_tensor(dst),
                    message_op="add").numpy()
    np.testing.assert_allclose(got, x[src] + y[dst], rtol=1e-6)


def test_send_ue_recv_grads():
    x = paddle.to_tensor(np.ones((3, 2), np.float32))
    e = paddle.to_tensor(np.full((3, 2), 2.0, np.float32))
    x.stop_gradient = False
    e.stop_gradient = False
    src = paddle.to_tensor(np.asarray([0, 1, 2]))
    dst = paddle.to_tensor(np.asarray([0, 0, 1]))
    out = G.send_ue_recv(x, e, src, dst, message_op="mul", reduce_op="sum")
    out.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3, 2), 2.0))
    np.testing.assert_allclose(e.grad.numpy(), np.ones((3, 2)))


def test_colorjitter_present_and_runs():
    """VERDICT r2 weak #8: ColorJitter was an AttributeError."""
    from paddle_tpu.vision.transforms import ColorJitter
    t = ColorJitter(brightness=0.4, contrast=0.4, saturation=0.4, hue=0.2)
    img = np.random.default_rng(0).integers(
        0, 255, (16, 16, 3)).astype(np.uint8)
    out = t(img)
    assert np.asarray(out).shape == (16, 16, 3)
    # zero-strength jitter is identity
    t0 = ColorJitter()
    np.testing.assert_array_equal(np.asarray(t0(img)), img)
