"""Fused one-pass Adam/AdamW Pallas update: interpret-mode parity with
the optimizer's own jnp math (coupled + decoupled decay), optimizer-
level equivalence over multiple steps, and eligibility fallbacks."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.fused_adamw import (fused_adamw_supported,
                                               fused_adamw_update)


def _ref(p, m, v, g, lr, bc1, bc2, b1, b2, eps, wd, decoupled):
    g = g.astype(jnp.float32)
    if wd and not decoupled:
        g = g + wd * p
    m = b1 * m + (1 - b1) * g
    v = b2 * v + (1 - b2) * jnp.square(g)
    step = lr * (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if wd and decoupled:
        step = step + lr * wd * p
    return p - step, m, v


@pytest.mark.parametrize("decoupled,wd", [(False, 0.0), (False, 0.01),
                                          (True, 0.01)])
def test_kernel_matches_reference_math(decoupled, wd):
    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    shape = (300, 70)  # non-tiling size exercises the pad path
    p, g = (jax.random.normal(k, shape, jnp.float32) for k in ks[:2])
    m = jax.random.normal(ks[2], shape, jnp.float32) * 0.1
    v = jnp.abs(jax.random.normal(ks[3], shape, jnp.float32)) * 0.01
    args = dict(beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=wd,
                decoupled=decoupled)
    for step in (1, 2):
        bc1, bc2 = 1 - 0.9 ** step, 1 - 0.999 ** step
        pf, mf, vf = fused_adamw_update(p, m, v, g, 1e-3, bc1, bc2,
                                        interpret=True, **args)
        pr, mr, vr = _ref(p, m, v, g, 1e-3, bc1, bc2, 0.9, 0.999, 1e-8,
                          wd, decoupled)
        for a, b, name in ((pf, pr, "p"), (mf, mr, "m"), (vf, vr, "v")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6,
                                       err_msg=name)
        p, m, v = pf, mf, vf


def test_optimizer_level_parity():
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    def run(fused):
        paddle.seed(7)
        # 256x256 weight = 65536 elements >= the fused-size threshold
        net = nn.Sequential(nn.Linear(256, 256), nn.ReLU(),
                            nn.Linear(256, 8))
        net.train()
        eng = Engine(net, loss=nn.CrossEntropyLoss(),
                     optimizer=AdamW(learning_rate=1e-3,
                                     weight_decay=0.01,
                                     parameters=net.parameters(),
                                     fused_kernel=fused))
        rng = np.random.default_rng(0)
        for _ in range(3):
            x = jnp.asarray(rng.standard_normal((8, 256)), jnp.float32)
            y = jnp.asarray(rng.integers(0, 8, (8,)), jnp.int32)
            loss, _ = eng.train_batch([x], [y])
        return float(loss), [np.asarray(a) for a in
                             jax.tree_util.tree_leaves(eng._params)]

    base_loss, base_p = run(False)
    f_loss, f_p = run(True)
    assert abs(base_loss - f_loss) < 1e-5
    for i, (a, b) in enumerate(zip(base_p, f_p)):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5,
                                   err_msg=f"leaf {i}")


def test_ineligible_paths_fall_back():
    from paddle_tpu.optimizer import AdamW
    # bf16 moments (stochastic rounding) must keep the jnp path and run
    paddle.seed(1)
    import paddle_tpu.nn as nn
    net = nn.Linear(128, 128)
    net.train()
    opt = AdamW(learning_rate=1e-3, parameters=net.parameters(),
                moment_dtype="bfloat16", fused_kernel=True)
    from paddle_tpu.hapi.engine import Engine
    eng = Engine(net, loss=nn.MSELoss(), optimizer=opt)
    x = jnp.ones((4, 128), jnp.float32)
    loss, _ = eng.train_batch([x], [x])
    assert np.isfinite(float(loss))
    big32 = jnp.zeros((256, 256), jnp.float32)
    # restored bf16 moments must fall back even with big fp32 params
    assert not fused_adamw_supported(
        big32, jnp.zeros((256, 256), jnp.bfloat16), big32)
    # non-tiling sizes fall back (padding copies would defeat the
    # one-pass aliasing)
    assert not fused_adamw_supported(
        jnp.zeros((50257,), jnp.float32), jnp.zeros((50257,)),
        jnp.zeros((50257,)))
    assert fused_adamw_supported(big32, big32, big32)
