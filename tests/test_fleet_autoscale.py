"""Elastic fleet autoscaling + adaptive overload control (ISSUE 15).

Pins the contracts (docs/robustness.md "Elastic autoscaling &
overload control"):

- FleetAutoscaler: scale OUT on multi-window SLO burn / standing
  overload with a warm-boot adoption gate (a newcomer takes traffic
  only after a ``serving``+``warmed`` heartbeat, with zero new
  steady-state traces), scale IN on recovered budget + idle hold
  (hysteresis + per-direction cooldowns), drain → remove with zero
  lost or duplicated requests — token-exact, exactly-once by rid;
- adaptive overload control in FleetRouter: CoDel-style sojourn
  admission (head-of-line wait over target for a full interval sheds
  fail-fast in the tenant-fair order), the brownout ladder clamping
  the heaviest tenants' decode budgets first, ``degraded`` honestly
  visible in health();
- satellite regressions: a hedge leg on a retiring replica is
  cancelled before membership removal (never burns a draining slot
  into the stale-leg guard); a ``retiring`` replica is exempt from
  the supervisor's kill/respawn and half-open-trial paths
  (exactly-one-owner); autoscale decisions are journaled and
  recoverable across a router crash mid-scale-event; and
  ``tools/fleet_replay.py --knob autoscale.<param>`` scores a policy
  offline.

`pytest -m chaos` selects the chaos classes; the campaign's
fleet_chaos_smoke stage includes this file (the canary golden covers
the fleet_autoscale_*/fleet_brownout_*/overload counters) and the
autoscale_smoke stage runs the standalone drill.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.slo import SLObjective
from paddle_tpu.resilience import faults, preemption
from paddle_tpu.serving_fleet import (
    FleetAutoscaler, FleetRouter, FleetSupervisor, InprocReplica,
    RouterCrash)
from paddle_tpu.serving_fleet.journal import reconcile, replay

from test_fleet_proc import StubReplica, StubRouter

NEW_TOK = 8
WAVE_LENS = (5, 12, 17, 9, 12, 5, 17, 12, 9, 5, 12, 17,
             5, 9, 12, 17, 5, 12, 9, 17)

# tight SLOs + sub-second burn windows: the drills must see an alert
# within a CPU test's budget (SLOTracker semantics are pinned by
# test_fleet_tracing; here they are just the scale-out trigger)
SLOS = (SLObjective("ttft", "latency", target=0.99, threshold_s=0.05),
        SLObjective("e2e", "latency", target=0.99, threshold_s=2.0),
        SLObjective("availability", "availability", target=0.999))
# short 0.5s: alerts clear fast after recovery (alert = short AND
# long burning). long 8s: doubles as the SLI horizon, so the drill's
# end-of-run accounting assertions still see every event
WINDOWS = ({"short_s": 0.5, "long_s": 8.0, "burn": 1.0},)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    preemption.clear()
    yield
    faults.clear()
    preemption.clear()


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=200, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, vocab, (n,)).astype(np.int32)
            for n in lens]


@pytest.fixture(scope="module")
def wave(gpt_model):
    """(prompts, golden) — golden from an uninterrupted single
    engine: the token-exactness reference across scale events."""
    prompts = _prompts(WAVE_LENS)
    eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                        max_seq_len=64, steps_per_dispatch=4)
    refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
    eng.close()
    return prompts, refs


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    eng = ServingEngine(model, **d)
    eng.warmup(buckets=sorted(set(WAVE_LENS)), decode=True)
    return eng


def _counter(reg, name, **labels):
    c = reg.get(name, labels or None)
    return 0 if c is None else int(c.value)


def _register(router):
    import conftest
    conftest.fleet_stage_registries.append(router.registry)


def _elastic_fleet(model, register=True, router_kw=None,
                   autoscale_kw=None, n=1):
    """One-replica-plus-autoscaler fleet; spawn_fn builds warmed
    engines (appended to `engines` for cleanup)."""
    engines = []

    def build():
        eng = _engine(model)
        engines.append(eng)
        return eng

    reps = [InprocReplica(f"r{i}", build()) for i in range(n)]
    frozen = [e.compile_counts() for e in engines]
    rkw = dict(slos=SLOS, slo_windows=WINDOWS, history=True,
               history_interval_s=0.05)
    rkw.update(router_kw or {})
    router = FleetRouter(reps, **rkw)
    akw = dict(min_replicas=n, max_replicas=3,
               scale_out_cooldown_s=0.4, scale_in_cooldown_s=0.4,
               recovery_hold_s=0.6, boot_timeout_s=60.0,
               flap_window_s=0.05)
    akw.update(autoscale_kw or {})
    asc = FleetAutoscaler(router, lambda i: InprocReplica(
        f"as{i}", build()), **akw)
    if register:
        _register(router)
    return router, asc, engines, frozen


def _close(router, engines):
    router.close()
    for e in engines:
        e.close()


def _drive(router, asc, cond, timeout=60.0, results=None,
           events=None):
    deadline = time.monotonic() + timeout
    while not cond():
        router.step()
        ev = asc.poll()
        if events is not None:
            events.extend(ev)
        if results is not None:
            results.extend(router.results())
        assert time.monotonic() < deadline, "drill made no progress"
        time.sleep(0.002)


# -- adaptive overload control (router layer) ---------------------------


class TestOverloadControl:
    def test_sojourn_shed_tenant_fair_and_degraded_visible(
            self, gpt_model):
        """Standing head-of-line sojourn over target -> degraded;
        queued requests past the target shed fail-fast, heaviest
        tenant first within a priority band; degraded clears after
        the storm."""
        eng = _engine(gpt_model, max_slots=1)
        rep = InprocReplica("r0", eng)
        router = FleetRouter(
            [rep], slos=False, replica_queue_limit=1,
            overload_target_ms=80.0, overload_interval_s=0.08,
            brownout_step_s=60.0)
        try:
            # whale is pre-accounted heavy: the shed order must hit
            # it first inside the same priority band
            router.tenants.account("whale", tokens_in=10_000,
                                   requests=1)
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.05})):
                prompts = _prompts((5, 5, 5, 5, 5, 5), seed=3)
                rids = []
                for i, p in enumerate(prompts):
                    tenant = "whale" if i % 2 == 0 else "minnow"
                    rids.append(router.submit(p, NEW_TOK,
                                              tenant=tenant))
                res = []
                deadline = time.monotonic() + 30
                saw_degraded = False
                while len(res) < len(rids):
                    router.step()
                    saw_degraded = saw_degraded or router.degraded
                    res += router.results()
                    assert time.monotonic() < deadline
                    time.sleep(0.002)
            assert saw_degraded, "overload never became visible"
            assert router.health()["overload"]["target_s"] == 0.08
            shed = [r for r in res if r["status"] == "shed"]
            assert shed, "sojourn controller never shed"
            assert _counter(router.registry,
                            "fleet_overload_sheds_total") == len(shed)
            # tenant fairness: no minnow request sheds while a whale
            # request that was ALSO past the target stayed queued —
            # within the shed set, whales resolve before minnows
            shed_tenants = [r["tenant"] for r in shed]
            first_minnow = shed_tenants.index("minnow") \
                if "minnow" in shed_tenants else len(shed_tenants)
            assert all(t == "whale"
                       for t in shed_tenants[:first_minnow])
            # recovery: queue drained -> degraded clears
            deadline = time.monotonic() + 10
            while router.degraded:
                router.step()
                assert time.monotonic() < deadline
                time.sleep(0.002)
            assert router.health()["overload"]["degraded"] is False
        finally:
            _close(router, [eng])

    def test_brownout_clamps_heaviest_tenant_first(self, gpt_model):
        """The ladder climbs while degraded and DECAYS one rung per
        step after recovery (hysteresis): inside that decay window
        the heaviest tenant's decode budget is still clamped — its
        request resolves with exactly brownout_max_new tokens while a
        light tenant keeps the full budget."""
        eng = _engine(gpt_model, max_slots=1)
        rep = InprocReplica("r0", eng)
        router = FleetRouter(
            [rep], slos=False, replica_queue_limit=1,
            overload_target_ms=60.0, overload_interval_s=0.06,
            brownout_max_new=2, brownout_levels=1,
            brownout_step_s=2.0)
        try:
            router.tenants.account("whale", tokens_in=10_000,
                                   requests=1)
            prompts = _prompts((5, 5, 5, 5, 5, 5, 5, 5), seed=4)
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 40,
                                      "seconds": 0.05})):
                # saturate with enough filler that the head-of-line
                # wait stands past the interval -> degraded + level 1
                for p in prompts[:6]:
                    router.submit(p, NEW_TOK, priority=1)
                deadline = time.monotonic() + 30
                while router._brownout_level < 1:
                    router.step()
                    assert time.monotonic() < deadline
                    time.sleep(0.002)
                h = router.health()["overload"]
                assert h["brownout_level"] == 1
                assert h["clamped_tenants"] == ["whale"]
                # let the storm clear (sheds + drain) — the ladder
                # holds its rung for brownout_step_s after recovery
                deadline = time.monotonic() + 30
                while router.degraded or router._queue \
                        or router._outstanding().get("r0"):
                    router.step()
                    router.results()
                    assert time.monotonic() < deadline
                    time.sleep(0.002)
            assert router._brownout_level == 1, \
                "the ladder must decay with hysteresis, not a cliff"
            # inside the decay window: whale clamped, minnow not
            whale = router.submit(prompts[6], NEW_TOK,
                                  tenant="whale")
            minnow = router.submit(prompts[7], NEW_TOK,
                                   tenant="minnow")
            res = {}
            deadline = time.monotonic() + 30
            while not {whale, minnow} <= set(res):
                router.step()
                res.update({r["id"]: r for r in router.results()})
                assert time.monotonic() < deadline
                time.sleep(0.002)
            assert res[whale]["status"] == "ok"
            assert res[minnow]["status"] == "ok"
            assert len(res[whale]["tokens"]) == 2, \
                "whale budget not clamped to brownout_max_new"
            assert len(res[minnow]["tokens"]) == NEW_TOK, \
                "light tenant must keep its full budget"
            assert _counter(router.registry,
                            "fleet_brownout_clamped_total",
                            tenant="whale") == 1
            assert _counter(router.registry,
                            "fleet_brownout_clamped_total",
                            tenant="minnow") == 0
            # ladder fully decays once the step elapses
            deadline = time.monotonic() + 10
            while router._brownout_level > 0:
                router.step()
                assert time.monotonic() < deadline
                time.sleep(0.002)
            assert router.health()["overload"]["brownout_level"] == 0
        finally:
            _close(router, [eng])


# -- satellite 1: scale-in vs hedging race ------------------------------


class TestHedgeScaleInRace:
    def test_retire_cancels_inflight_hedge_leg(self, gpt_model, wave):
        """A hedge leg parked on the retiring replica is cancelled
        BEFORE the drain/removal — the primary resolves the request
        exactly once, no failover is counted for the hedge leg, and
        the replica removes cleanly."""
        prompts, refs = wave
        engines = [_engine(gpt_model) for _ in range(2)]
        reps = [InprocReplica(f"r{i}", e)
                for i, e in enumerate(engines)]
        router = FleetRouter(reps, slos=False, hedge_after_ms=30,
                             replica_queue_limit=4)
        try:
            # keep BOTH replicas slow so the hedge fires and both
            # legs are genuinely in flight at retire time
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.03}),
                    ("replica_slow", {"replica": "r1", "count": 1000,
                                      "seconds": 0.03})):
                rid = router.submit(prompts[0], NEW_TOK)
                deadline = time.monotonic() + 30
                p = router._pending[rid]
                while p.hedge is None:
                    router.step()
                    assert time.monotonic() < deadline, \
                        "hedge never fired"
                    time.sleep(0.002)
                victim = p.hedge
                primary = p.replica
                router.retire(victim)
                # the hedge leg is gone from the request state NOW —
                # nothing left to burn a draining slot
                assert p.hedge is None
                res = router.run_to_completion(timeout_s=60)
            assert [r["id"] for r in res] == [rid]
            assert res[0]["status"] == "ok"
            assert res[0]["tokens"] == refs[0]
            assert res[0]["replica"] == primary
            assert _counter(router.registry, "fleet_failovers_total",
                            replica=victim, reason="removed") == 0
            # the victim drains and removes cleanly
            deadline = time.monotonic() + 10
            while router.replicas[victim].alive:
                router.step()
                assert time.monotonic() < deadline
                time.sleep(0.002)
            router.remove_replica(victim)
            assert victim not in router.replicas
        finally:
            _close(router, engines)


# -- satellites 2+3: supervisor ownership -------------------------------


class TestSupervisorRetiring:
    def _sup(self, reps, **kw):
        router = StubRouter(reps)
        d = dict(seed=3, breaker_threshold=3, breaker_window_s=60.0,
                 breaker_cooldown_s=100.0, boot_timeout_s=5.0)
        d.update(kw)
        return FleetSupervisor(router, **d), router

    def test_retiring_replica_death_is_not_a_crash(self):
        """A retiring replica's death must NOT schedule a respawn —
        today's bug: watch() would resurrect a replica the autoscaler
        is scaling in."""
        rep = StubReplica("r0")
        sup, router = self._sup([rep])
        assert sup.mark_retiring("r0") == "serving"
        rep.die()
        assert sup.poll(now=1000.0) == []
        assert sup.poll(now=2000.0) == []
        assert rep.rejoins == 0
        h = sup.health()
        assert h["replicas"]["r0"]["phase"] == "retiring"
        assert h["retiring"] == ["r0"]
        # removal purges the state
        del router.replicas["r0"]
        sup.poll(now=3000.0)
        assert "r0" not in sup.health()["replicas"]

    def test_retiring_exempt_from_hb_timeout_kill(self):
        """The supervisor-side wedge detector must not kill a
        retiring replica that (expectedly) stopped heartbeating."""
        class StaleReplica(StubReplica):
            def scrape(self):
                snap = super().scrape()
                if snap:
                    snap["ts"] = 0.0   # ancient heartbeat
                return snap

        rep = StaleReplica("r0")
        sup, _router = self._sup([rep], heartbeat_timeout_s=1.0)
        sup.mark_retiring("r0")
        assert sup.poll(now=5000.0) == []
        assert rep.kills == 0 and rep.alive
        # control: without the mark the same staleness is a wedge
        rep2 = StaleReplica("r1")
        sup2, _ = self._sup([rep2], heartbeat_timeout_s=1.0)
        ev = sup2.poll(now=5000.0)
        assert ("r1", "down") in ev and rep2.kills == 1

    def test_half_open_trial_races_scale_in_exactly_one_owner(self):
        """Satellite 3: quarantined -> cooldown -> the half-open
        trial would fire, but the autoscaler retired the replica
        first — the supervisor must not re-arm/trial-boot it, and a
        retired NAME is never respawned."""
        rep = StubReplica("rbad", fail_incs=set(range(2, 50)))
        sup, router = self._sup([rep], breaker_threshold=1,
                                breaker_cooldown_s=10.0)
        t = 1000.0
        rep.die()
        ev = sup.poll(now=t)
        assert ("rbad", "quarantined") in ev
        assert rep.quarantined is True
        rejoins0 = rep.rejoins
        # the autoscaler claims ownership DURING the cooldown
        assert sup.mark_retiring("rbad") == "quarantined"
        assert rep.quarantined is False  # honest health: retiring,
        #                                   not phantom-quarantined
        # past the cooldown: no rearm, no trial boot
        assert sup.poll(now=t + 60.0) == []
        assert rep.rejoins == rejoins0
        assert sup.health()["replicas"]["rbad"]["phase"] == "retiring"
        # the router removes the name: purged, still never respawned
        del router.replicas["rbad"]
        assert sup.poll(now=t + 120.0) == []
        assert "rbad" not in sup.health()["replicas"]
        assert rep.rejoins == rejoins0


# -- autoscaler units ---------------------------------------------------


class TestAutoscalerUnits:
    def _stub_asc(self, monkeypatch=None, **kw):
        reps = [StubReplica("r0")]
        router = StubRouter(reps)
        router._lost = set()
        d = dict(registry=router.registry)
        d.update(kw)
        return FleetAutoscaler(router, lambda i: StubReplica(
            f"as{i}"), **d)

    def test_env_knob_defaults(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUTOSCALE_MIN", "2")
        monkeypatch.setenv("PADDLE_TPU_AUTOSCALE_MAX", "5")
        monkeypatch.setenv("PADDLE_TPU_AUTOSCALE_COOLDOWN_S", "7.5")
        monkeypatch.setenv("PADDLE_TPU_AUTOSCALE_HOLD_S", "9.0")
        asc = self._stub_asc()
        assert (asc.min_replicas, asc.max_replicas) == (2, 5)
        assert asc.scale_out_cooldown_s == 7.5
        assert asc.scale_in_cooldown_s == 22.5   # 3x by default
        assert asc.recovery_hold_s == 9.0
        # explicit args beat the env
        asc2 = self._stub_asc(min_replicas=1, max_replicas=3,
                              scale_out_cooldown_s=1.0,
                              recovery_hold_s=2.0)
        assert (asc2.min_replicas, asc2.max_replicas) == (1, 3)
        assert asc2.scale_out_cooldown_s == 1.0

    def test_bounds_validated(self):
        with pytest.raises(ValueError, match="min_replicas"):
            self._stub_asc(min_replicas=4, max_replicas=2)

    def test_flap_counter(self):
        asc = self._stub_asc(flap_window_s=10.0)
        reg = asc.registry
        assert _counter(reg, "fleet_autoscale_flaps_total") == 0
        asc._last_in_at = 100.0
        assert asc._flap_check(105.0, "out") is True
        assert _counter(reg, "fleet_autoscale_flaps_total") == 1
        assert asc._flap_check(200.0, "out") is False
        asc._last_out_at = 200.0
        assert asc._flap_check(205.0, "in") is True
        assert _counter(reg, "fleet_autoscale_flaps_total") == 2

    def test_boot_gate_and_timeout(self, gpt_model):
        """A spawned replica is adopted only on a serving+warmed
        heartbeat; an unwarmed one that never warms is killed at the
        boot deadline and the fleet is untouched."""
        eng = _engine(gpt_model)
        router = FleetRouter([InprocReplica("r0", eng)], slos=False)
        cold = []

        def spawn(i):
            e = ServingEngine(gpt_model, max_slots=2, page_size=16,
                              max_seq_len=64, steps_per_dispatch=4)
            cold.append(e)       # deliberately NOT warmed
            return InprocReplica(f"as{i}", e)

        asc = FleetAutoscaler(router, spawn, min_replicas=1,
                              max_replicas=2, boot_timeout_s=5.0,
                              scale_out_cooldown_s=0.0)
        try:
            t = time.monotonic()
            asc._start_scale_out(t, "slo_burn:test", [])
            assert asc.state == "booting"
            # heartbeats flow but warmed stays False -> no adoption
            deadline = time.monotonic() + 5
            while not asc._pending_rep.scrape():
                assert time.monotonic() < deadline
                time.sleep(0.01)
            assert asc.poll() == []
            assert asc.state == "booting"
            assert len(router.replicas) == 1
            # past the deadline: killed + counted, fleet untouched
            ev = asc.poll(now=t + 10.0)
            assert ev == [("boot_failed", "as0")]
            assert asc.state == "steady"
            assert len(router.replicas) == 1
            assert _counter(router.registry,
                            "fleet_autoscale_events_total",
                            direction="out",
                            reason="boot_timeout") == 1
            assert router.health()["autoscale"]["state"] == "steady"
        finally:
            _close(router, [eng] + cold)


# -- the elastic chaos drill --------------------------------------------


@pytest.mark.chaos
class TestElasticChaos:
    def test_burst_scaleout_recovery_scalein_token_exact(
            self, gpt_model, wave, tmp_path):
        """The acceptance drill: a seeded burst against a pinned-slow
        single replica fires the TTFT burn alert -> scale-out through
        the warm-boot gate (the newcomer takes traffic with zero new
        steady-state traces) -> the wave drains, budget recovers ->
        scale-in (hedge-safe drain -> remove, token-exact,
        exactly-once by rid vs the uninterrupted golden); decisions
        journaled; no SLO-accounting gap; zero flaps."""
        prompts, refs = wave
        jdir = os.path.join(str(tmp_path), "journal")
        router, asc, engines, frozen = _elastic_fleet(
            gpt_model, router_kw={"journal_dir": jdir,
                                  "overload_target_ms": 5000.0})
        try:
            faults.inject("replica_slow", replica="r0", count=50,
                          seconds=0.04)
            rids, results, events = [], [], []
            avail_snap = None
            t0 = time.monotonic()
            nxt = 0

            def done():
                return (nxt >= len(prompts)
                        and len(results) >= len(prompts)
                        and asc.state == "steady"
                        and len(router.replicas) == 1
                        and any(e[0] == "scaled_in" for e in events))

            deadline = time.monotonic() + 120
            while not done():
                now = time.monotonic() - t0
                while nxt < len(prompts) and now > nxt * 0.01:
                    rids.append(router.submit(prompts[nxt], NEW_TOK))
                    nxt += 1
                router.step()
                events += asc.poll()
                results += router.results()
                if avail_snap is None \
                        and len(results) >= len(prompts):
                    # accounting checked the moment the wave is fully
                    # resolved — the sliding SLO windows forget by
                    # design once events age past the horizon
                    avail_snap = router.slo.evaluate()["availability"]
                assert time.monotonic() < deadline, \
                    f"drill stalled: {events}, {len(results)}"
                time.sleep(0.002)
            faults.clear()
            # exactly-once, token-exact, nothing lost
            ids = [r["id"] for r in results]
            assert sorted(ids) == sorted(rids)
            assert len(ids) == len(set(ids))
            by_id = {r["id"]: r for r in results}
            for i, rid in enumerate(rids):
                assert by_id[rid]["status"] == "ok", by_id[rid]
                assert by_id[rid]["tokens"] == refs[i], \
                    f"rid {rid} not token-exact across scale events"
            # a scale-out passed the boot gate and TOOK TRAFFIC
            assert any(e[0] == "scaled_out" for e in events)
            spawned_names = [rep.name for rep, _fz in asc.spawned]
            assert spawned_names
            assert any(
                _counter(router.registry, "fleet_routed_total",
                         replica=n) > 0 for n in spawned_names), \
                "no spawned replica ever took traffic"
            # zero new steady-state traces: base engine vs warmup
            # snapshot, spawned engines vs their adoption snapshot
            assert engines[0].compile_counts() == frozen[0]
            for rep, fz in asc.spawned:
                assert fz is not None
                assert rep.engine.compile_counts() == fz, \
                    f"{rep.name} traced after its warm-boot gate"
            assert router.compile_report()["unexpected_retraces"] == 0
            # no SLO-accounting gap: every resolve across the scale
            # events was counted exactly once as ok (the registry is
            # the cumulative ledger; the sliding SLO windows forget
            # by design) and the availability objective never saw a
            # bad event
            assert avail_snap is not None
            assert avail_snap["bad"] == 0
            assert avail_snap["events"] > 0
            assert _counter(router.registry, "fleet_requests_total",
                            status="ok") == len(rids)
            for st in ("shed", "expired", "cancelled", "failed"):
                assert _counter(router.registry,
                                "fleet_requests_total",
                                status=st) == 0
            # decisions journaled + reconcilable
            records, _stats = replay(jdir)
            state = reconcile(records)
            kinds = [r["kind"] for r in state["autoscale"]]
            assert "scale_out" in kinds and "scale_in" in kinds
            # the controller never flapped
            assert _counter(router.registry,
                            "fleet_autoscale_flaps_total") == 0
        finally:
            faults.clear()
            _close(router, engines)

    def test_router_crash_mid_scale_event_recovers(
            self, gpt_model, wave, tmp_path):
        """Kill the router right after a scale-out was journaled and
        executed: the successor re-adopts the (now larger) fleet from
        the journal + live replicas, every request resolves exactly
        once token-exact, and the scale records survive replay."""
        prompts, refs = wave
        jdir = os.path.join(str(tmp_path), "journal")
        router, asc, engines, frozen = _elastic_fleet(
            gpt_model, router_kw={"journal_dir": jdir,
                                  "overload_target_ms": 5000.0})
        pre = []
        try:
            faults.inject("replica_slow", replica="r0", count=80,
                          seconds=0.04)
            rids = [router.submit(p, NEW_TOK) for p in prompts]
            events = []
            _drive(router, asc,
                   lambda: any(e[0] == "scaled_out" for e in events),
                   timeout=60.0, results=pre, events=events)
            # crash the control plane mid-scale-event (replicas live)
            faults.inject("router_crash")
            with pytest.raises(RouterCrash):
                deadline = time.monotonic() + 30
                while True:
                    router.step()
                    pre.extend(router.results())
                    assert time.monotonic() < deadline
            faults.clear()
            reps = list(router.replicas.values())
            r2 = FleetRouter.recover(jdir, reps, slos=SLOS,
                                     slo_windows=WINDOWS,
                                     overload_target_ms=5000.0)
            _register(r2)
            try:
                post = r2.run_to_completion(timeout_s=120)
                got = pre + post
                ids = [r["id"] for r in got]
                assert sorted(ids) == sorted(rids), \
                    "requests lost across the crash mid-scale-event"
                assert len(ids) == len(set(ids))
                by_id = {r["id"]: r for r in got}
                for i, rid in enumerate(rids):
                    assert by_id[rid]["status"] == "ok"
                    assert by_id[rid]["tokens"] == refs[i]
                # the journal still tells the scale story
                records, _stats = replay(jdir)
                state = reconcile(records)
                assert any(r["kind"] == "scale_out"
                           for r in state["autoscale"])
                assert r2.compile_report()[
                    "unexpected_retraces"] == 0
            finally:
                r2.close()
        finally:
            faults.clear()
            _close(router, engines)

    def test_replay_knob_scores_autoscale_policy(
            self, gpt_model, tmp_path):
        """tools/fleet_replay.py --knob autoscale.<param> arms an
        autoscaler over the replay fleet and the verdict scores the
        policy (events, flaps, final size) — the offline what-if
        loop."""
        import tools.fleet_replay as fr

        wave_entries = fr.synth_wave(7, 12, burst=6,
                                     burst_gap_s=0.02)
        knobs = ["autoscale.max_replicas=2",
                 "autoscale.min_replicas=1",
                 "autoscale.scale_out_cooldown_s=0.3",
                 "autoscale.recovery_hold_s=0.5",
                 "autoscale.flap_window_s=0.05",
                 "overload_target_ms=100",
                 "overload_interval_s=0.1"]
        verdict, _rep = fr.run_replay(
            wave_entries, out_dir=str(tmp_path), knob_pairs=knobs,
            replicas=1, timeout_s=120.0,
            faults_arm=lambda: faults.inject(
                "replica_slow", replica="r0", count=60,
                seconds=0.05))
        assert verdict["autoscale"] is not None
        assert verdict["autoscale"]["replicas_final"] >= 1
        evs = [e["event"] for e in verdict["autoscale"]["events"]]
        assert "scale_out_started" in evs, \
            f"policy never scaled under saturation: {evs}"
        assert isinstance(verdict["autoscale"]["flaps"], int)
        # the knob pairs are recorded in the verdict for provenance
        assert verdict["knobs"]["pairs"] == knobs
