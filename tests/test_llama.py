"""Llama family: numerics pinned against torch/transformers'
LlamaForCausalLM (RoPE half-split convention, GQA repeat layout,
SwiGLU, RMSNorm), plus training, generation, scan compose, and the
fused chunked head+CE on the untied head."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.llama import (LlamaConfig, LlamaForCausalLM,
                                  LlamaPretrainingCriterion)

TINY = dict(vocab_size=96, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=64, max_position_embeddings=64,
            use_flash_attention=False)


def _hf_model():
    import torch
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM as HFLlama
    torch.manual_seed(0)
    hf = HFLlama(HFConfig(
        vocab_size=96, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=64, max_position_embeddings=64,
        rms_norm_eps=1e-6, rope_theta=10000.0, attn_implementation="eager",
        tie_word_embeddings=False))
    hf.eval()
    return hf


def _port_weights(hf, model):
    """HF Linear stores [out, in]; ours stores [in, out] — transpose."""
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    m = {}
    m["llama.embed_tokens.weight"] = sd["model.embed_tokens.weight"]
    for i in range(2):
        src = f"model.layers.{i}"
        dst = f"llama.layers.{i}"
        for a, b in (("self_attn.q_proj", "self_attn.q_proj"),
                     ("self_attn.k_proj", "self_attn.k_proj"),
                     ("self_attn.v_proj", "self_attn.v_proj"),
                     ("self_attn.o_proj", "self_attn.o_proj"),
                     ("mlp.gate_proj", "mlp.gate_proj"),
                     ("mlp.up_proj", "mlp.up_proj"),
                     ("mlp.down_proj", "mlp.down_proj")):
            m[f"{dst}.{b}.weight"] = sd[f"{src}.{a}.weight"].T
        m[f"{dst}.input_layernorm.weight"] = \
            sd[f"{src}.input_layernorm.weight"]
        m[f"{dst}.post_attention_layernorm.weight"] = \
            sd[f"{src}.post_attention_layernorm.weight"]
    m["llama.norm.weight"] = sd["model.norm.weight"]
    m["lm_head.weight"] = sd["lm_head.weight"].T
    missing = set(model.state_dict()) - set(m)
    assert not missing, missing
    model.set_state_dict(m)


@pytest.fixture(scope="module")
def ported():
    hf = _hf_model()
    model = LlamaForCausalLM(LlamaConfig(**TINY))
    model.eval()
    _port_weights(hf, model)
    return hf, model


def test_logits_match_transformers(ported):
    import torch
    hf, model = ported
    ids = np.arange(24).reshape(2, 12) % 96
    with torch.no_grad():
        want = hf(torch.tensor(ids)).logits.numpy()
    got = np.asarray(model(jnp.asarray(ids, jnp.int32))._value)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_cached_decode_matches_full_forward(ported):
    _, model = ported
    ids = jnp.asarray(np.arange(16).reshape(1, 16) % 96, jnp.int32)
    out = model.generate(ids, max_new_tokens=6, temperature=0.0)
    assert out.shape == [1, 22]
    # greedy continuation must equal argmax of the full re-forward
    full = model(out[:, :-1])
    last = np.asarray(full._value)[0, -1]
    assert int(np.argmax(last)) == int(np.asarray(out._value)[0, -1])


def test_train_step_and_chunked_ce_parity():
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW

    def steps(chunked):
        paddle.seed(3)
        m = LlamaForCausalLM(LlamaConfig(**TINY, chunked_ce=chunked))
        m.train()
        eng = Engine(m, loss=LlamaPretrainingCriterion(),
                     optimizer=AdamW(learning_rate=1e-3,
                                     parameters=m.parameters()))
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(2):
            ids = jnp.asarray(rng.integers(0, 96, (2, 16)), jnp.int32)
            loss, _ = eng.train_batch([ids], [ids])
            losses.append(float(loss))
        return losses, jax.tree_util.tree_leaves(eng._params)

    base_l, base_p = steps(0)
    ch_l, ch_p = steps(8)
    assert np.isfinite(base_l).all()
    for a, b in zip(base_l, ch_l):
        assert abs(a - b) < 1e-4, (base_l, ch_l)
    for i, (a, b) in enumerate(zip(base_p, ch_p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4,
                                   err_msg=f"leaf {i}")


def test_scan_layers_matches_unrolled():
    paddle.seed(9)
    m = LlamaForCausalLM(LlamaConfig(**TINY))
    m.eval()
    ids = jnp.asarray(np.arange(16).reshape(1, 16) % 96, jnp.int32)
    want = np.asarray(m(ids)._value)

    from paddle_tpu.nn.scan_stack import stack_layer_state
    ms = LlamaForCausalLM(LlamaConfig(**TINY, scan_layers=True))
    ms.eval()
    state = {k: np.asarray(v._value) for k, v in m.state_dict().items()}
    ms.set_state_dict(stack_layer_state(state, 2,
                                        prefix="llama.layers."))
    got = np.asarray(ms(ids)._value)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_mha_decode_path():
    # groups==1 routes single-token decode through flash_decode's
    # valid-length path — greedy continuation must match a re-forward
    paddle.seed(2)
    m = LlamaForCausalLM(LlamaConfig(
        **{**TINY, "num_key_value_heads": 4}))
    m.eval()
    ids = jnp.asarray(np.arange(8)[None, :] % 96, jnp.int32)
    out = m.generate(ids, max_new_tokens=4, temperature=0.0)
    full = m(out[:, :-1])
    last = np.asarray(full._value)[0, -1]
    assert int(np.argmax(last)) == int(np.asarray(out._value)[0, -1])


def test_gqa_heads_validation():
    with pytest.raises(ValueError, match="multiple"):
        LlamaConfig(**{**TINY, "num_key_value_heads": 3})
