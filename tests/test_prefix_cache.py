"""Copy-on-write prefix caching + fleet-affinity routing (ISSUE 16).

Pins the round-19 contracts (docs/performance.md "Prefix caching"):

- THE invariant: a cache hit may change TTFT, never tokens — ON vs
  OFF streams are token-exact for GPT and Llama/GQA across greedy and
  top-k sampling and fp32/bf16/int8 KV dtypes (each axis covered on
  both models; the full cross product lives in the campaign's
  prefix_cache_smoke + bench serve rungs);
- fingerprint chain: rolling per-page-boundary digests, page-size
  domain-separated, final prompt position always private (COW is
  structural, not best-effort);
- PrefixIndex refcounts: pages return to the free list only at
  owners==0 AND rc==0, eviction never frees a slot-pinned page, and
  after close() every page is back on the free list — under churn,
  capacity eviction, and repeated waves;
- zero-recompile: a warmed engine serves hit AND miss admissions with
  frozen compile counts (the tail-prefill ladder traces at warmup);
- fleet: heartbeat fingerprint inventories feed a prefix_affinity
  placement term (weight 0 — the default — places exactly as before),
  fleet_prefix_* counters delta-fold engine stats (restart-safe),
  "placed" journal records carry the gain fingerprint, per-tenant
  hit-page accounting conserves, and crash-mid-wave failover stays
  token-exact with caching ON (the continuation re-fingerprints at
  the destination);
- replay: fleet_replay.prefix_stats predicts the committed golden
  wave's (independently random) hit rate as zero, and a genuinely
  shared wave as nonzero — the measure-before-build number.

`pytest -m chaos` selects the fleet classes; the campaign's
fleet_chaos_smoke stage runs exactly that (the router registries
registered here fold into the canary golden's fleet_prefix_* series).

Engine/warmup tracing dominates this module's wall time, so waves are
single-bucket (every prompt lands in prefill bucket 32, tail ladder
{16, 32}) and assertions share engines wherever the contracts allow.
"""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config as _gpt_cfg
from paddle_tpu.nlp.llama import LlamaForCausalLM, \
    _resolve_config as _llama_cfg
from paddle_tpu.nlp.paged_cache import PrefixIndex, prefix_fingerprints
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.resilience import faults
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica
from paddle_tpu.serving_fleet.journal import replay as journal_replay

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NEW_TOK = 6
PS = 16


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_gpt_cfg("gpt-tiny"))
    m.eval()
    return m


@pytest.fixture(scope="module")
def llama_model():
    paddle.seed(0)
    m = LlamaForCausalLM(_llama_cfg("llama-tiny"))
    m.eval()
    return m


def shared_wave(n=8, seed=0, vocab=256, base_lens=(24, 20)):
    """n requests over len(base_lens) shared "system prompt" bases,
    each with a short random tail — the traffic the cache exists for.
    Default lens keep every prompt inside prefill bucket 32."""
    rng = np.random.default_rng(seed)
    bases = [rng.integers(1, vocab, (L,)).astype(np.int32)
             for L in base_lens]
    return [np.concatenate([bases[i % len(bases)],
                            rng.integers(1, vocab,
                                         (3 + i % 5,)).astype(np.int32)])
            for i in range(n)]


def _engine(model, on=True, **kw):
    # num_pages=64: the default pool is deliberately tiny — hits need
    # room for the index to retain pages across admissions
    d = dict(max_slots=2, page_size=PS, max_seq_len=64,
             steps_per_dispatch=4, num_pages=64, prefix_cache=on)
    d.update(kw)
    return ServingEngine(model, **d)


def _run(model, on, prompts, waves=1, **kw):
    eng = _engine(model, on, **kw)
    eng.warmup(buckets=[len(p) for p in prompts], decode=True)
    out = [eng.generate(prompts, max_new_tokens=NEW_TOK)
           for _ in range(waves)]
    pc = (eng.health().get("prefix_cache") or {})
    eng.close()
    return out, pc, eng


def _counter(reg, name, **labels):
    c = reg.get(name, labels or None)
    return 0 if c is None else int(c.value)


# -- fingerprint chain (pure host hashing) -------------------------------


class TestPrefixFingerprints:
    def test_deterministic_rolling_chain(self):
        p = np.arange(100, 170).astype(np.int32)
        fps = prefix_fingerprints(p, PS)
        assert fps == prefix_fingerprints(p, PS)
        assert len(fps) == (len(p) - 1) // PS
        assert len(set(fps)) == len(fps)
        # rolling: a longer prompt's chain extends its prefix's chain
        assert prefix_fingerprints(p[:40], PS) == fps[:(40 - 1) // PS]

    def test_page_size_domain_separated(self):
        p = np.arange(64).astype(np.int32)
        assert set(prefix_fingerprints(p, 16)) \
            .isdisjoint(prefix_fingerprints(p, 32))

    def test_final_position_always_private(self):
        # a prompt that ends exactly on a page boundary must NOT
        # publish that page: its last position's forward pass samples
        # the first token, so the boundary is capped one short
        assert prefix_fingerprints(np.arange(PS), PS) == []
        assert len(prefix_fingerprints(np.arange(PS + 1), PS)) == 1
        assert prefix_fingerprints(np.arange(0), PS) == []

    def test_content_sensitivity(self):
        a = np.arange(40).astype(np.int32)
        b = a.copy()
        b[3] += 1   # first page differs -> whole chain differs
        fa, fb = prefix_fingerprints(a, PS), prefix_fingerprints(b, PS)
        assert all(x != y for x, y in zip(fa, fb))


# -- PrefixIndex refcount bookkeeping (no engine, no jax) ----------------


class TestPrefixIndex:
    def _fps(self, n, ps=4, seed=0):
        rng = np.random.default_rng(seed)
        return prefix_fingerprints(
            rng.integers(0, 99, (n,)).astype(np.int64), ps)

    def test_insert_match_acquire_release_evict_cycle(self):
        idx = PrefixIndex(4, min_pages=1, max_entries=8)
        fps = self._fps(13)                      # 3 boundaries
        adopted, freed = idx.insert(fps, [7, 8, 9], kv="sidecar")
        assert adopted == {7, 8, 9} and freed == []
        assert idx.entries == 3 and idx.owned_page_count == 3
        assert idx.adopted_pages == 3 and idx.covers(fps)
        # the donor pin blocks eviction until the slot releases
        assert idx.evict(3) == []
        idx.release([7, 8, 9])
        e, j = idx.match(fps)                    # longest boundary wins
        assert j == 3 and e.fp == fps[-1]
        assert idx.acquire(e) == [7, 8, 9] and idx.pinned(7)
        assert idx.evict(3) == []                # still pinned
        idx.release([7, 8, 9])
        got = idx.evict(3)
        assert sorted(got) == [7, 8, 9]
        assert idx.entries == 0 and idx.owned_page_count == 0
        assert idx.evictions == 3
        # re-registering the same chain adopts afresh (monotonic feed)
        idx.insert(fps, [1, 2, 3], kv="sidecar2", pin=False)
        assert idx.adopted_pages == 6

    def test_nested_boundaries_share_pages_and_kv(self):
        idx = PrefixIndex(4, min_pages=1, max_entries=8)
        fps = self._fps(13)
        sidecar = object()
        idx.insert(fps, [5, 6, 7], kv=sidecar, pin=False)
        ents = [idx.match(fps[:j + 1])[0] for j in range(3)]
        assert [len(e.pages) for e in ents] == [1, 2, 3]
        assert all(e.kv is sidecar for e in ents)
        # page 5 is covered by all three entries; evicting the deepest
        # entry must not free it
        assert idx._owners[5] == 3

    def test_min_pages_gates_short_prefixes(self):
        idx = PrefixIndex(4, min_pages=2, max_entries=8)
        fps = self._fps(13)
        idx.insert(fps, [1, 2, 3], kv=None, pin=False)
        assert idx.entries == 2                  # boundary 1 skipped
        assert idx.match(fps[:1]) is None
        assert idx.match(fps)[1] == 3

    def test_capacity_eviction_returns_freed_pages(self):
        idx = PrefixIndex(4, min_pages=1, max_entries=2)
        a = self._fps(9, seed=1)                 # 2 boundaries
        b = self._fps(9, seed=2)
        idx.insert(a, [1, 2], kv=None, pin=False)
        _, freed = idx.insert(b, [3, 4], kv=None, pin=False)
        # capacity 2: registering b's 2 boundaries evicted a's LRU
        # entries and handed their pages back to the caller
        assert idx.entries == 2
        assert set(freed) == {1, 2}
        assert idx.owned_pages == {3, 4}


# -- engine: the token-exactness invariant -------------------------------


# every sampler and every KV dtype covered on BOTH models (pairing,
# not cross product — each engine pays ~10s of warmup tracing, and
# the remaining combos ride prefix_cache_smoke + the bench rungs)
EXACT_CASES = [
    ("gpt", {}, None),
    ("gpt", dict(temperature=0.8, top_k=4, seed=11), "bfloat16"),
    ("gpt", dict(temperature=0.8, top_k=4, seed=11), "int8"),
    ("llama", {}, "int8"),
    ("llama", dict(temperature=0.8, top_k=4, seed=11), None),
    ("llama", {}, "bfloat16"),
]


class TestTokenExactness:
    @pytest.mark.parametrize(
        "which,sampler,cache_dtype", EXACT_CASES,
        ids=[f"{w}-{'topk' if s else 'greedy'}-{d or 'fp32'}"
             for w, s, d in EXACT_CASES])
    def test_on_vs_off_token_exact(self, which, sampler, cache_dtype,
                                   request):
        """Hits may never change tokens — only TTFT. Llama-tiny is the
        GQA coverage (kv_heads < heads)."""
        model = request.getfixturevalue(f"{which}_model")
        kw = dict(sampler)
        if cache_dtype:
            kw["cache_dtype"] = cache_dtype
        prompts = shared_wave()
        on, pc, _ = _run(model, True, prompts, **kw)
        off, _, _ = _run(model, False, prompts, **kw)
        assert on == off, "prefix-cache hits changed tokens"
        assert pc["hits"] > 0 and pc["hit_pages"] > 0, \
            "wave produced no hits — the exactness check was vacuous"

    def test_repeat_waves_identical_zero_recompile_cow_isolated(
            self, gpt_model):
        """Shared pages are immutable: if any hit wrote one, a later
        wave over the same prompts would diverge (two slots share an
        entry concurrently here — COW isolation). Also the no-new-
        traces contract with caching ON (hit + miss + extension paths
        all inside the warmed ladder), and refcount conservation:
        every page back on the free list after close()."""
        prompts = shared_wave()
        eng = _engine(gpt_model)
        eng.warmup(buckets=[len(p) for p in prompts], decode=True)
        frozen = eng.compile_counts()
        w1 = eng.generate(prompts, max_new_tokens=NEW_TOK)
        w2 = eng.generate(prompts, max_new_tokens=NEW_TOK)
        assert w1 == w2, "a hit mutated shared prefix state"
        assert eng.compile_counts() == frozen
        assert eng.tracer.unexpected_retraces() == 0
        pc = eng.health()["prefix_cache"]
        assert pc["hits"] >= len(prompts), "wave 2 must hit every time"
        assert pc["cow_copies"] > 0, "no private tail was materialized"
        eng.close()
        assert eng.free_page_count == eng.num_pages - 1, \
            "prefix refcounts leaked pages"


# -- engine: churn, telemetry, kill switch -------------------------------


class TestChurnAndTelemetry:
    def test_churn_eviction_occupancy_and_no_leaks(self, gpt_model):
        """Distinct waves through a capacity-starved index force LRU
        evictions mid-traffic; every page must still come back. The
        occupancy gauge is registered at 0 on a cold engine (DOC01
        catalogue contract) and tracks the index level."""
        eng = _engine(gpt_model, prefix_max_entries=3)
        g = eng.registry.get("prefix_cache_occupancy")
        assert g is not None and g.value == 0
        waves = [shared_wave(6, seed=s) for s in range(3)]
        lens = sorted({len(p) for w in waves for p in w})
        eng.warmup(buckets=lens, decode=True)
        for w in waves:
            eng.generate(w, max_new_tokens=NEW_TOK)
        pc = eng.health()["prefix_cache"]
        assert pc["evictions"] > 0, "capacity churn never evicted"
        assert pc["entries"] <= 3
        assert eng.registry.get("prefix_cache_occupancy").value > 0
        assert pc["fingerprints"] and pc["page_size"] == PS
        eng.close()
        assert eng.free_page_count == eng.num_pages - 1, \
            "prefix refcounts leaked pages under churn"

    def test_kill_switch_disables_cleanly(self, gpt_model, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PREFIX_CACHE", "0")
        eng = ServingEngine(gpt_model, max_slots=2, page_size=PS,
                            max_seq_len=64, steps_per_dispatch=4)
        assert eng.prefix is None
        assert eng.health().get("prefix_cache") is None
        eng.close()


# -- replay: the measure-before-build number -----------------------------


class TestReplayPrefixStats:
    def _stats(self, entries, **kw):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            from fleet_replay import prefix_stats
        finally:
            sys.path.pop(0)
        return prefix_stats(entries, **kw)

    def test_golden_wave_predicts_zero(self):
        """The committed replay wave's prompts are independently
        random — prefix_stats must predict a zero hit rate (which is
        also why the replay goldens stay byte-identical with caching
        ON by default)."""
        with open(os.path.join(REPO, "tools", "golden",
                               "replay_wave.json")) as f:
            entries = json.load(f)["entries"]
        assert len(entries) == 20
        for row in self._stats(entries).values():
            assert row["expected_hit_pages"] == 0
            assert row["requests"] == 20

    def test_shared_wave_predicts_hits_and_min_pages_gates(self):
        entries = [{"arrival_s": float(i), "prompt": p.tolist()}
                   for i, p in enumerate(shared_wave(8))]
        row = self._stats(entries, page_sizes=(PS,))[str(PS)]
        assert row["expected_hit_pages"] > 0
        assert 0.0 < row["expected_page_hit_rate"] <= 1.0
        assert row["expected_hit_requests"] >= 5     # all but seeds
        strict = self._stats(entries, page_sizes=(PS,),
                             min_pages=3)[str(PS)]
        assert strict["expected_hit_requests"] \
            <= row["expected_hit_requests"]


# -- fleet: affinity, counters, journal, failover (campaign chaos) -------


def _prefix_fleet(model, n=2, router_kw=None, jdir=None, **engine_kw):
    engines = [_engine(model, **engine_kw) for _ in range(n)]
    lens = sorted({len(p) for p in shared_wave(9)})
    for e in engines:
        e.warmup(buckets=lens, decode=True)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    kw = dict(router_kw or {})
    if jdir is not None:
        kw["journal_dir"] = str(jdir)
    router = FleetRouter(reps, **kw)
    # register for the session-end metrics.json export the campaign's
    # fleet canary gate diffs (conftest._fleet_stage_metrics_export) —
    # this is what makes fleet_prefix_* nonzero in the golden
    import conftest
    conftest.fleet_stage_registries.append(router.registry)
    return router, reps, engines, frozen


@pytest.mark.chaos
class TestFleetPrefix:
    def test_affinity_counters_tenancy_journal_and_zero_weight(
            self, gpt_model, tmp_path):
        """One fleet session, the full placement story: seed one
        replica with a base prefix, scrape, then place same-base
        requests with a dominant affinity weight — they must all land
        on the fingerprint holder; fleet_prefix_* counters fold off
        heartbeats (restart-reset-safe); per-tenant hit pages account;
        "placed" journal records carry the gain fingerprint; and with
        the weight dialed back to the default 0, a prefix-laden
        pending places exactly like no pending at all."""
        prompts = shared_wave(7, base_lens=(24,))
        router, reps, engines, frozen = _prefix_fleet(
            gpt_model, n=2, jdir=tmp_path / "journal",
            router_kw={"placement_weights": {"prefix_affinity": 1e6},
                       "replica_queue_limit": 16})
        try:
            router.generate(prompts[:1], max_new_tokens=NEW_TOK)
            router._scrape_all()
            holders = [name for name, (fs, ps) in router._fpsets.items()
                       if fs and ps == PS]
            assert len(holders) == 1
            holder = holders[0]
            before = _counter(router.registry, "fleet_routed_total",
                              replica=holder)
            rids = [router.submit(p, NEW_TOK, tenant="team-a")
                    for p in prompts[1:]]
            res = {r["id"]: r for r in router.run_to_completion()}
            assert all(res[i]["status"] == "ok" for i in rids)
            after = _counter(router.registry, "fleet_routed_total",
                             replica=holder)
            assert after - before == len(rids), \
                "affinity did not concentrate the shared prefix"
            router._scrape_all()
            reg = router.registry
            assert _counter(reg, "fleet_prefix_hits_total") > 0
            assert _counter(reg, "fleet_prefix_shared_pages_total") > 0
            assert _counter(reg, "fleet_prefix_cow_copies_total") > 0
            # per-tenant accounting: hit pages <= shareable pages
            pages = _counter(reg, "fleet_prefix_pages_total",
                             tenant="team-a")
            hitp = _counter(reg, "fleet_prefix_hit_pages_total",
                            tenant="team-a")
            assert pages > 0 and 0 < hitp <= pages
            # journal: placed records carry the prefix gain fingerprint
            records, _ = journal_replay(str(tmp_path / "journal"))
            placed = [r for r in records if r.get("kind") == "placed"]
            fps = [r.get("fingerprint") for r in placed
                   if r.get("fingerprint")]
            assert fps, "no placed record carried a fingerprint"
            assert prefix_fingerprints(prompts[1], PS)[-1] in fps
            # restart-reset fold: a stat that went BACKWARDS means a
            # respawn — fold the new absolute value, never a negative
            hits0 = _counter(reg, "fleet_prefix_hits_total")
            snap = {"page_size": PS,
                    "prefix_cache": {"fingerprints": ["ab" * 12],
                                     "hits": 2, "misses": 0,
                                     "adopted_pages": 0,
                                     "cow_copies": 0, "evictions": 0}}
            router._fold_prefix("zz", snap)      # fresh incarnation
            assert _counter(reg, "fleet_prefix_hits_total") \
                == hits0 + 2
            router._fold_prefix("zz", {"page_size": PS})
            assert "zz" not in router._fpsets    # inventory cleared
            # zero-weight kill path: affinity term skipped entirely —
            # identical pick with/without the pending, and its
            # fingerprint memo never even computes
            router.placement_weights["prefix_affinity"] = 0.0
            rid = router.submit(prompts[1], NEW_TOK)
            p = router._pending[rid]
            out = {name: 0 for name in router.replicas}
            assert router._pick_replica(out, pending=p) \
                == router._pick_replica(out, pending=None)
            assert p.prefix_fps is None, \
                "affinity memo computed despite weight 0"
            router.run_to_completion()
        finally:
            router.close()

    def test_failover_token_exact_with_caching_on(self, gpt_model):
        """Crash a replica mid-wave with caching ON everywhere: every
        request completes token-exact vs a cache-OFF golden (the
        failover continuation re-fingerprints at its destination),
        and compile counts stay frozen."""
        prompts = shared_wave(6)
        refs, _, _ = _run(gpt_model, False, prompts)
        router, reps, engines, frozen = _prefix_fleet(gpt_model, n=2)
        try:
            assert router.generate(prompts, max_new_tokens=NEW_TOK) \
                == refs[0]
            with faults.scenario(("replica_crash", {"replica": "r1"})):
                outs = router.generate(prompts, max_new_tokens=NEW_TOK)
            assert outs == refs[0], \
                "failover with caching ON must stay token-exact"
            assert reps[1].state == "dead"
            for i, eng in enumerate(engines):
                assert eng.compile_counts() == frozen[i]
            assert router.compile_report()["unexpected_retraces"] == 0
        finally:
            router.close()
