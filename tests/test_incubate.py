"""incubate optimizers: LookAhead / ModelAverage / EMA (ref:
python/paddle/incubate/optimizer tests)."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import LookAhead, ModelAverage, EMA
from paddle_tpu.incubate.ema import ema_init, ema_update


def _problem(seed=0):
    rng = np.random.RandomState(seed)
    x = paddle.to_tensor(rng.randn(32, 8).astype("float32"))
    y = paddle.to_tensor(rng.randn(32, 1).astype("float32"))
    paddle.seed(seed)
    net = paddle.nn.Linear(8, 1)
    return net, x, y


class TestLookAhead:
    def test_eager_training_decreases_loss(self):
        net, x, y = _problem()
        inner = paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=net.parameters())
        opt = LookAhead(inner, alpha=0.5, k=3)
        losses = []
        for _ in range(12):
            loss = paddle.nn.functional.mse_loss(net(x), y)
            losses.append(float(loss))
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert losses[-1] < losses[0]

    def test_functional_core_sync_semantics(self):
        opt = LookAhead(paddle.optimizer.SGD(learning_rate=1.0),
                        alpha=0.5, k=2)
        params = {"w": jnp.zeros(3)}
        state = opt.init_state(params)
        g = {"w": jnp.full(3, -1.0)}  # sgd: w += 1 each step
        # step 1: no sync -> fast=1, slow=0
        p1, state = opt.update(params, g, state, jnp.float32(1.0),
                               jnp.int32(1))
        assert np.allclose(np.asarray(p1["w"]), 1.0)
        # step 2: fast=2, sync -> slow=1, fast resets to slow
        p2, state = opt.update(p1, g, state, jnp.float32(1.0),
                               jnp.int32(2))
        assert np.allclose(np.asarray(p2["w"]), 1.0)
        assert np.allclose(np.asarray(state["slow"]["w"]), 1.0)

    def test_with_engine(self):
        from paddle_tpu.hapi.engine import Engine
        net, x, y = _problem(1)
        opt = LookAhead(paddle.optimizer.Adam(
            learning_rate=0.01, parameters=net.parameters()), k=2)
        eng = Engine(net, loss=paddle.nn.MSELoss(), optimizer=opt)
        losses = [float(eng.train_batch([x], [y])[0]) for _ in range(6)]
        assert losses[-1] < losses[0]


class TestModelAverage:
    def test_apply_restores(self):
        net, x, y = _problem(2)
        ma = ModelAverage(parameters=net.parameters(),
                          min_average_window=2, max_average_window=100)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for _ in range(5):
            loss = paddle.nn.functional.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.accumulate()
        before = np.asarray(net.weight)
        with ma.apply():
            averaged = np.asarray(net.weight)
            assert not np.allclose(averaged, before)
        after = np.asarray(net.weight)
        np.testing.assert_allclose(after, before)


class TestEMA:
    def test_tracks_params_and_restores(self):
        net, x, y = _problem(3)
        ema = EMA(parameters=net.parameters(), decay=0.5)
        w0 = np.asarray(net.weight).copy()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for _ in range(3):
            loss = paddle.nn.functional.mse_loss(net(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ema.update()
        live = np.asarray(net.weight)
        with ema.apply():
            shadow = np.asarray(net.weight)
            # shadow lags behind the live weights, between w0 and live
            assert not np.allclose(shadow, live)
        np.testing.assert_allclose(np.asarray(net.weight), live)

    def test_functional_update(self):
        ema = ema_init({"w": jnp.zeros(2)})
        ema = ema_update(ema, {"w": jnp.ones(2)}, decay=0.9)
        np.testing.assert_allclose(np.asarray(ema["w"]), 0.1, rtol=1e-5)
