"""MoE expert parallelism (SURVEY §2.6): gating, dense einsum path,
shard_map all-to-all path equivalence."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.moe import (
    MoELayer, moe_apply_dense, moe_apply_ep, top_k_gating)
from paddle_tpu.tensor import Tensor


def _params(e=8, d=16, h=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    return dict(
        gate_w=jax.random.normal(ks[0], (d, e)) * 0.5,
        w1=jax.random.normal(ks[1], (e, d, h)) * 0.1,
        b1=jnp.zeros((e, h)),
        w2=jax.random.normal(ks[2], (e, h, d)) * 0.1,
        b2=jnp.zeros((e, d)))


class TestGating:
    def test_top1_routes_to_argmax(self):
        logits = jnp.array([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
        dispatch, combine, aux = top_k_gating(logits, k=1, capacity=2)
        # token 0 -> expert 0 slot 0; token 1 -> expert 1 slot 0
        assert float(dispatch[0, 0, 0]) == 1.0
        assert float(dispatch[1, 1, 0]) == 1.0
        assert float(combine[0, 0, 0]) > 0.99

    def test_capacity_drops_overflow(self):
        logits = jnp.tile(jnp.array([[10.0, 0.0]]), (4, 1))  # all -> e0
        dispatch, _, _ = top_k_gating(logits, k=1, capacity=2)
        # only 2 of 4 tokens fit expert 0
        assert float(dispatch.sum()) == 2.0

    def test_top2_uses_two_experts(self):
        logits = jnp.array([[5.0, 4.9, -5.0, -5.0]])
        dispatch, combine, _ = top_k_gating(logits, k=2, capacity=2)
        assert float(dispatch[0, 0].sum()) == 1.0
        assert float(dispatch[0, 1].sum()) == 1.0

    def test_no_slot_collision(self):
        rng = jax.random.PRNGKey(0)
        logits = jax.random.normal(rng, (64, 4))
        dispatch, _, _ = top_k_gating(logits, k=2, capacity=64)
        # every (expert, slot) holds at most one token
        assert float(dispatch.sum(axis=0).max()) <= 1.0

    def test_aux_loss_balanced_is_one(self):
        # perfectly uniform router -> aux == 1 (Switch normalisation)
        logits = jnp.zeros((8, 4))
        _, _, aux = top_k_gating(logits, k=1, capacity=8)
        assert abs(float(aux) - 1.0) < 1e-5


class TestDensePath:
    def test_output_shape_and_grad(self):
        p = _params()
        x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))

        def loss(w1):
            y, aux = moe_apply_dense(x, p["gate_w"], w1, p["b1"], p["w2"],
                                     p["b2"])
            return (y ** 2).sum() + 0.01 * aux

        y, aux = moe_apply_dense(x, **p)
        assert y.shape == (32, 16) and np.isfinite(float(aux))
        g = jax.grad(loss)(p["w1"])
        assert float(jnp.abs(g).sum()) > 0


class TestExpertParallel:
    def test_ep_matches_dense(self):
        e, d, h = 8, 16, 32
        p = _params(e, d, h)
        x = jax.random.normal(jax.random.PRNGKey(2), (64, d))
        want, want_aux = moe_apply_dense(x, **p, k=2)

        mesh = Mesh(np.array(jax.devices()), ("ep",))
        from paddle_tpu.distributed.mesh import shard_map_compat
        fn = shard_map_compat(
            lambda x, gw, w1, b1, w2, b2: moe_apply_ep(
                x, gw, w1, b1, w2, b2, axis_name="ep", k=2),
            mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=(P("ep"), P()))
        got, got_aux = fn(x, p["gate_w"], p["w1"], p["b1"], p["w2"],
                          p["b2"])
        # aux is computed per-rank (local gating, like the reference), so
        # it differs from global-batch gating; both must be sane though
        assert 0.5 < float(got_aux) < float(e)
        assert got.shape == want.shape
        assert bool(jnp.isfinite(got).all())
        # outputs agree on tokens neither path dropped to capacity
        close = np.isclose(np.asarray(got), np.asarray(want),
                           atol=1e-4).all(axis=-1)
        assert close.mean() > 0.5, close.mean()

    @pytest.mark.slow
    def test_ep_singleton_equals_dense_exactly(self):
        """ep=1 mesh: the all-to-all path must reduce to the dense math."""
        e, d = 4, 8
        p = _params(e, d, 16)
        x = jax.random.normal(jax.random.PRNGKey(3), (16, d))
        want, _ = moe_apply_dense(x, **p, k=1)
        mesh = Mesh(np.array(jax.devices()[:1]), ("ep",))
        from paddle_tpu.distributed.mesh import shard_map_compat
        fn = shard_map_compat(
            lambda x, gw, w1, b1, w2, b2: moe_apply_ep(
                x, gw, w1, b1, w2, b2, axis_name="ep", k=1),
            mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=(P("ep"), P()))
        got, _ = fn(x, p["gate_w"], p["w1"], p["b1"], p["w2"], p["b2"])
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


class TestMoELayer:
    def test_layer_forward_and_aux(self):
        paddle.seed(0)
        layer = MoELayer(d_model=16, d_hidden=32, num_experts=4, top_k=2)
        x = Tensor(jax.random.normal(jax.random.PRNGKey(4), (2, 8, 16)))
        y = layer(x)
        assert tuple(y.shape) == (2, 8, 16)
        assert layer.aux_loss is not None

    def test_layer_trains(self):
        paddle.seed(0)
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=4, top_k=1)
        opt = paddle.optimizer.Adam(5e-3, parameters=layer.parameters())
        x = Tensor(jax.random.normal(jax.random.PRNGKey(5), (16, 8)))
        first = last = None
        for _ in range(30):
            y = layer(x)
            loss = (y ** 2).mean() + 0.01 * layer.aux_loss
            loss.backward()
            opt.step()
            opt.clear_grad()
            v = float(loss._value)
            first = first if first is not None else v
            last = v
        assert last < first

    def test_expert_weights_carry_ep_spec(self):
        layer = MoELayer(d_model=8, d_hidden=16, num_experts=8)
        assert tuple(layer.w1.sharding_spec) == ("ep", None, None)


def test_ep_capacity_is_per_rank():
    """Regression: ep path must not scale capacity by ep (redundant
    compute); per-rank formula matches GShard."""
    import math
    t_local, e, cf, k, ep = 64, 8, 1.25, 2, 8
    expect = max(1, int(math.ceil(t_local * cf * k / e)))
    assert expect == 20  # not 160 (= x ep)
