"""Pallas flash attention vs jnp reference, interpret mode on CPU
(parity: the reference's test_flash_attention.py vs naive softmax)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.attention import reference_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    b, s, h, d = 2, 256, 2, 64
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_uneven_kv_len():
    b, sq, sk, h, d = 1, 128, 384, 2, 64
    q = _rand((b, sq, h, d), 0)
    k = _rand((b, sk, h, d), 1)
    v = _rand((b, sk, h, d), 2)
    got = flash_attention(q, k, v, causal=False, interpret=True)
    want = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, s, h, d = 1, 256, 2, 64
    q, k, v = (_rand((b, s, h, d), 10 + i) for i in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=5e-3)


def test_flash_bf16():
    b, s, h, d = 1, 128, 2, 128
    q, k, v = (_rand((b, s, h, d), 20 + i).astype(jnp.bfloat16)
               for i in range(3))
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want, dtype=np.float32),
                               rtol=2e-2, atol=2e-2)


def test_flash_causal_uneven_matches_reference():
    """bottom-right aligned causal mask when sq != sk (decode/chunked
    prefill): must match the jnp reference's tril(k=sk-sq)."""
    b, sq, sk, h, d = 1, 128, 384, 2, 64
    q = _rand((b, sq, h, d), 30)
    k = _rand((b, sk, h, d), 31)
    v = _rand((b, sk, h, d), 32)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_rejects_unaligned_seq():
    q = _rand((1, 200, 2, 64), 40)
    with pytest.raises(ValueError):
        flash_attention(q, q, q, interpret=True)
