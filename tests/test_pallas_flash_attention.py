"""Pallas flash attention vs jnp reference, interpret mode on CPU
(parity: the reference's test_flash_attention.py vs naive softmax)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.attention import reference_attention
from paddle_tpu.ops.pallas.flash_attention import flash_attention


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    b, s, h, d = 2, 256, 2, 64
    q, k, v = (_rand((b, s, h, d), i) for i in range(3))
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_uneven_kv_len():
    b, sq, sk, h, d = 1, 128, 384, 2, 64
    q = _rand((b, sq, h, d), 0)
    k = _rand((b, sk, h, d), 1)
    v = _rand((b, sk, h, d), 2)
    got = flash_attention(q, k, v, causal=False, interpret=True)
    want = reference_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    b, s, h, d = 1, 256, 2, 64
    q, k, v = (_rand((b, s, h, d), 10 + i) for i in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-3, atol=5e-3)


def test_flash_bf16():
    b, s, h, d = 1, 128, 2, 128
    q, k, v = (_rand((b, s, h, d), 20 + i).astype(jnp.bfloat16)
               for i in range(3))
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want, dtype=np.float32),
                               rtol=2e-2, atol=2e-2)


def test_flash_causal_uneven_matches_reference():
    """bottom-right aligned causal mask when sq != sk (decode/chunked
    prefill): must match the jnp reference's tril(k=sk-sq)."""
    b, sq, sk, h, d = 1, 128, 384, 2, 64
    q = _rand((b, sq, h, d), 30)
    k = _rand((b, sk, h, d), 31)
    v = _rand((b, sk, h, d), 32)
    got = flash_attention(q, k, v, causal=True, interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_flash_rejects_unaligned_seq():
    q = _rand((1, 201, 2, 64), 40)  # not tileable into 8-row blocks
    with pytest.raises(ValueError):
        flash_attention(q, q, q, interpret=True)


class TestFlashMaskDropoutDecode:
    """r2 kernel completeness: kv-length padding masks, in-kernel dropout
    (fwd/bwd mask regeneration), flash decode (ref: flash_attn varlen +
    dropout paths in phi/kernels/gpu/flash_attn_kernel.cu)."""

    def _qkv(self, b=2, s=256, h=2, d=64):
        return tuple(_rand((b, s, h, d), 30 + i) for i in range(3))

    def _ref_masked(self, q, k, v, lens, causal=False):
        sq, sk = q.shape[1], k.shape[1]
        qh, kh, vh = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
        lg = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(q.shape[-1])
        m = jnp.arange(sk)[None, None, None, :] < lens[:, None, None, None]
        if causal:
            m = m & jnp.tril(jnp.ones((sq, sk), bool),
                             k=sk - sq)[None, None]
        p = jax.nn.softmax(jnp.where(m, lg, -jnp.inf), -1)
        p = jnp.where(jnp.isnan(p), 0.0, p)
        return jnp.swapaxes(jnp.einsum("bhqk,bhkd->bhqd", p, vh), 1, 2)

    def test_kv_lens_mask(self):
        q, k, v = self._qkv()
        lens = jnp.asarray([200, 128], jnp.int32)
        got = flash_attention(q, k, v, kv_lens=lens, interpret=True)
        want = self._ref_masked(q, k, v, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)

    def test_kv_lens_causal_grads(self):
        q, k, v = self._qkv(b=1, s=128)
        lens = jnp.asarray([100], jnp.int32)

        def lf(q, k, v):
            o = flash_attention(q, k, v, causal=True, kv_lens=lens,
                                interpret=True)
            return jnp.sum(o * jnp.cos(o))

        def lr(q, k, v):
            o = self._ref_masked(q, k, v, lens, causal=True)
            return jnp.sum(o * jnp.cos(o))
        gf = jax.grad(lf, (0, 1, 2))(q, k, v)
        gr = jax.grad(lr, (0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-3)

    def test_dropout_deterministic_and_mean_preserving(self):
        q, k, v = self._qkv(b=1, s=128)
        o1 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=7,
                             interpret=True)
        o2 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=7,
                             interpret=True)
        o3 = flash_attention(q, k, v, dropout_p=0.3, dropout_seed=8,
                             interpret=True)
        assert np.array_equal(np.asarray(o1), np.asarray(o2))
        assert not np.array_equal(np.asarray(o1), np.asarray(o3))
        # averaged over many seeds the dropout estimate approaches the
        # exact attention (unbiasedness of the 1/(1-p) scaling)
        acc = np.zeros(o1.shape, np.float64)
        n = 24
        for s in range(n):
            acc += np.asarray(flash_attention(
                q, k, v, dropout_p=0.3, dropout_seed=100 + s,
                interpret=True), np.float64)
        want = np.asarray(flash_attention(q, k, v, interpret=True))
        err = np.abs(acc / n - want).mean()
        assert err < 0.05, err

    def test_dropout_grad_matches_finite_difference(self):
        q, k, v = self._qkv(b=1, s=128, h=1)

        def loss(qq):
            o = flash_attention(qq, k, v, dropout_p=0.25, dropout_seed=9,
                                causal=True, interpret=True)
            return (o ** 2).sum()
        g = jax.grad(loss)(q)
        eps = 1e-2
        for (i, j) in [(5, 10), (100, 63)]:
            dq = np.zeros(q.shape, np.float32)
            dq[0, i, 0, j] = eps
            fd = (float(loss(q + dq)) - float(loss(q - dq))) / (2 * eps)
            rel = abs(fd - float(g[0, i, 0, j])) / max(1.0, abs(fd))
            assert rel < 0.02, (i, j, rel)

    def test_flash_decode_matches_reference(self):
        from paddle_tpu.ops.pallas.flash_attention import flash_decode
        q, k, v = self._qkv()
        qd = q[:, :1]
        lens = jnp.asarray([200, 128], jnp.int32)
        got = flash_decode(qd, k, v, lens, interpret=True)
        want = self._ref_masked(qd, k, v, lens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)
