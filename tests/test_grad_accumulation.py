"""Gradient accumulation (ref: fleet gradient_merge / hapi
accumulate_grad_batches — which was a silent no-op until r3).

Defining property: k accumulated microbatches of size m must produce the
SAME parameter update as one batch of size k*m (mean-loss semantics make
the averaged microbatch grads equal the big-batch grad).
"""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.hapi.engine import Engine


def _net():
    paddle.seed(3)
    return paddle.nn.Sequential(paddle.nn.Linear(8, 32), paddle.nn.Tanh(),
                                paddle.nn.Linear(32, 4))


def _data(n=32):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, 8)).astype(np.float32)
    y = rng.integers(0, 4, (n,)).astype(np.int64)
    return x, y


def _engine(net, lr=0.05):
    return Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                  optimizer=paddle.optimizer.AdamW(
                      lr, weight_decay=0.01, parameters=net.parameters()))


def _window_closed(eng):
    """A closed window means no pending micro-grads: either no
    accumulator at all, or one holding only zeros (apply_step returns it
    zeroed in-place so the next window reuses the donated buffer)."""
    if eng._micro_count != 0:
        return False
    if eng._acc_grads is None:
        return True
    return all(not np.asarray(l).any()
               for l in jax.tree_util.tree_leaves(eng._acc_grads))


def test_accum_k_micro_equals_one_big_batch():
    x, y = _data(32)
    # reference: one step on the full batch
    net_a = _net()
    eng_a = _engine(net_a)
    eng_a.train_batch([jnp.asarray(x)], [jnp.asarray(y)])
    # accumulation: 4 microbatches of 8, applied on the last
    net_b = _net()
    eng_b = _engine(net_b)
    for i in range(4):
        sl = slice(8 * i, 8 * (i + 1))
        loss, outs, applied = eng_b.train_batch_accum(
            [jnp.asarray(x[sl])], [jnp.asarray(y[sl])],
            apply_update=(i == 3))
        assert applied == (i == 3)
    for k in eng_a._params:
        np.testing.assert_allclose(
            np.asarray(eng_a._params[k]), np.asarray(eng_b._params[k]),
            rtol=2e-4, atol=2e-5, err_msg=k)


def test_accum_multiple_windows_trains():
    net = _net()
    eng = _engine(net, lr=0.02)
    x, y = _data(32)
    losses = []
    for epoch in range(8):
        for i in range(4):
            sl = slice(8 * i, 8 * (i + 1))
            loss, _, _ = eng.train_batch_accum(
                [jnp.asarray(x[sl])], [jnp.asarray(y[sl])],
                apply_update=(i == 3))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_fit_accumulate_grad_batches_no_longer_noop():
    """Model.fit(accumulate_grad_batches=k) must step the optimizer
    len(loader)/k times, not len(loader) times."""
    x, y = _data(32)
    net = _net()
    model = paddle.Model(net)
    sched = paddle.optimizer.lr.StepDecay(0.05, step_size=1, gamma=0.5)
    model.prepare(paddle.optimizer.AdamW(sched,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss())
    ds = paddle.io.TensorDataset([x, y])
    model.fit(ds, epochs=1, batch_size=8, shuffle=False, verbose=0,
              accumulate_grad_batches=4)
    # 4 microbatches -> exactly ONE lr-scheduler step
    assert sched.last_epoch == 1, sched.last_epoch


def test_accum_respects_grad_clip():
    net = _net()
    clip = paddle.nn.ClipGradByGlobalNorm(1e-8)  # crushes every update
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.SGD(
                     1.0, parameters=net.parameters(), grad_clip=clip))
    x, y = _data(8)
    before = {k: np.asarray(v).copy() for k, v in eng._params.items()}
    eng.train_batch_accum([jnp.asarray(x)], [jnp.asarray(y)],
                          apply_update=True)
    for k, v in eng._params.items():
        assert np.abs(np.asarray(v) - before[k]).max() < 1e-6, k


def test_fit_accum_flushes_tail_window():
    """A partial window at epoch end must be applied, not dropped: 4
    microbatches with k=3 -> 2 optimizer updates (3+1), not 1."""
    x, y = _data(32)
    net = _net()
    model = paddle.Model(net)
    sched = paddle.optimizer.lr.StepDecay(0.05, step_size=1, gamma=0.5)
    model.prepare(paddle.optimizer.AdamW(sched,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss())
    ds = paddle.io.TensorDataset([x, y])
    model.fit(ds, epochs=1, batch_size=8, shuffle=False, verbose=0,
              accumulate_grad_batches=3)
    assert sched.last_epoch == 2, sched.last_epoch
    eng = model._engine
    assert _window_closed(eng)


def test_accum_resume_preserves_opt_step(tmp_path):
    """Model.save/load keeps the optimizer-update counter: Adam's bias
    correction must not restart at step 1 with warm moments."""
    x, y = _data(16)
    net = _net()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.AdamW(0.01,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss())
    ds = paddle.io.TensorDataset([x, y])
    model.fit(ds, epochs=3, batch_size=8, shuffle=False, verbose=0,
              accumulate_grad_batches=2)
    saved_opt_step = model._engine._opt_step
    assert saved_opt_step == 3  # 2 micro -> 1 update per epoch
    model.save(str(tmp_path / "ck"))
    net2 = _net()
    m2 = paddle.Model(net2)
    m2.prepare(paddle.optimizer.AdamW(0.01, parameters=net2.parameters()),
               paddle.nn.CrossEntropyLoss())
    m2.load(str(tmp_path / "ck"))
    assert m2._engine._opt_step == saved_opt_step


def test_fit_accum_reports_metrics():
    from paddle_tpu.metric import Accuracy
    x, y = _data(16)
    net = _net()
    model = paddle.Model(net)
    model.prepare(paddle.optimizer.AdamW(0.01,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), Accuracy())
    out = model._train_batch_accum([paddle.to_tensor(x)],
                                   [paddle.to_tensor(y)], apply=True)
    assert isinstance(out, tuple) and len(out) == 2  # (loss, metrics)


def test_accum_with_zero2_sharding():
    """Accumulation composes with GroupSharded ZeRO-2: same losses as
    unsharded accumulation, and the fp32 accumulator stays dp-sharded
    (not replicated — the review-flagged memory hazard)."""
    from jax.sharding import Mesh, NamedSharding
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    x, y = _data(32)

    def run(sharded):
        net = _net()
        opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
        if sharded:
            net, opt, _ = group_sharded_parallel(net, opt, level="os_g",
                                                 mesh=mesh)
        eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                     optimizer=opt, mesh=mesh if sharded else None)
        losses = []
        for w in range(2):
            for i in range(2):
                sl = slice(16 * i, 16 * (i + 1))
                loss, _, _ = eng.train_batch_accum(
                    [jnp.asarray(x[sl])], [jnp.asarray(y[sl])],
                    apply_update=(i == 1))
            losses.append(float(loss))
        return losses, eng

    ref, _ = run(False)
    got, eng = run(True)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # mid-window accumulator leaves must carry a dp sharding
    for i in range(1):
        loss, _, _ = eng.train_batch_accum(
            [jnp.asarray(x[:16])], [jnp.asarray(y[:16])],
            apply_update=False)
    leaves = [l for l in jax.tree_util.tree_leaves(eng._acc_grads)
              if hasattr(l, "sharding") and l.ndim >= 1
              and max(l.shape) % 8 == 0]
    assert leaves
    assert any(isinstance(l.sharding, NamedSharding)
               and "dp" in jax.tree_util.tree_leaves(tuple(l.sharding.spec))
               for l in leaves), "accumulator not sharded over dp"


def test_mixed_fused_and_accum_paths():
    """Mixing train_batch with a pending accumulation window: the window
    flushes first (no stale-grad leak), and the optimizer update counter
    stays a true update count across both paths."""
    net = _net()
    eng = _engine(net)
    x, y = _data(16)
    eng.train_batch_accum([jnp.asarray(x[:8])], [jnp.asarray(y[:8])],
                          apply_update=False)
    assert eng._micro_count == 1
    eng.train_batch([jnp.asarray(x[8:])], [jnp.asarray(y[8:])])
    assert _window_closed(eng)
    # the path switch must DROP the accumulator, not retain it — a
    # param-size fp32 buffer pinned through fused-path training would
    # be pure overhead
    assert eng._acc_grads is None
    assert eng._opt_step == 2  # flush + fused update


def test_accum_no_unusable_donation_and_acc_aliased():
    """The accumulation programs must not leak param-size dead
    donations (r3 emitted 'Some donated buffers were not usable') and
    the microstep must alias the fp32 accumulator in place — at 1.3B an
    un-aliased accumulator is a 5+ GB copy per microbatch."""

    import warnings
    net = _net()
    eng = _engine(net)
    x, y = _data(16)
    with warnings.catch_warnings(record=True) as ws:
        warnings.simplefilter("always")
        for w in range(2):
            for i in range(2):
                sl = slice(8 * i, 8 * (i + 1))
                eng.train_batch_accum([jnp.asarray(x[sl])],
                                      [jnp.asarray(y[sl])],
                                      apply_update=(i == 1))
    bad = [w for w in ws if "donated buffers" in str(w.message)]
    assert not bad, [str(w.message) for w in bad]
    # HLO audit: every accumulator leaf is input-output aliased in the
    # grad microstep (no full-size accumulator copy in the program)
    n_acc = len(jax.tree_util.tree_leaves(eng._acc_grads))
    lowered = eng._grad_fn.lower(
        eng._params, eng._buffers, eng._acc_grads, np.int32(1),
        eng._rng_key, [jnp.asarray(x[:8])], [jnp.asarray(y[:8])])
    txt = lowered.compile().as_text()
    assert "input_output_alias" in txt, \
        "grad microstep has no input_output_alias map"
    n_alias = txt.count("may-alias") + txt.count("must-alias")
    assert n_alias >= n_acc, (n_alias, n_acc)
