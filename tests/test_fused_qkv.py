"""fused_qkv=True (one [h,3h] Megatron head-interleaved qkv matmul)
must be numerically identical to the separate projections, convert
checkpoints both ways, and compose with GSPMD tensor parallelism.

ref parity: the reference's fused_attention mp path fuses qkv the same
way on CUDA (paddle.incubate.nn.FusedMultiHeadAttention)."""
import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.autograd import no_grad
from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                GPTPretrainingCriterion, fuse_qkv_state,
                                split_qkv_state)
from paddle_tpu.tensor import Tensor

CFG = dict(vocab_size=89, hidden_size=32, num_hidden_layers=2,
           num_attention_heads=4, max_position_embeddings=32,
           hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
           use_flash_attention=False)


def _pair():
    paddle.seed(9)
    sep = GPTForCausalLM(GPTConfig(**CFG))
    fused = GPTForCausalLM(GPTConfig(**CFG, fused_qkv=True))
    sd = fuse_qkv_state({k: np.asarray(v._value)
                         for k, v in sep.state_dict().items()},
                        CFG["num_attention_heads"])
    fused.set_state_dict(sd)
    return sep, fused, sd


def test_forward_and_decode_match_separate():
    sep, fused, _ = _pair()
    sep.eval(), fused.eval()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 89, (2, 12)),
                      jnp.int32)
    with no_grad():
        o1, o2 = sep(Tensor(ids)), fused(Tensor(ids))
    a1 = (o1[0] if isinstance(o1, tuple) else o1)._value
    a2 = (o2[0] if isinstance(o2, tuple) else o2)._value
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a2),
                               rtol=2e-5, atol=2e-6)
    from paddle_tpu.nlp.generation import generate
    g1 = generate(sep, ids[:, :4], max_new_tokens=5, temperature=0.0)
    g2 = generate(fused, ids[:, :4], max_new_tokens=5, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(g1._value),
                                  np.asarray(g2._value))


def test_fuse_split_roundtrip():
    sep, _, sd = _pair()
    back = split_qkv_state(sd, CFG["num_attention_heads"])
    ref = {k: np.asarray(v._value) for k, v in sep.state_dict().items()}
    assert set(back) == set(ref)
    for k in ref:
        np.testing.assert_array_equal(back[k], ref[k])


def test_training_loss_matches_separate():
    from paddle_tpu.hapi.engine import Engine
    sep, fused, _ = _pair()
    # copy leaves: engine donation would delete buffers shared via the
    # conversion dict
    fused.set_state_dict({k: jnp.array(np.asarray(v._value))
                          for k, v in fused.state_dict().items()})
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 89, (2, 16)), jnp.int32)
    lbl = jnp.asarray(rng.integers(0, 89, (2, 16)), jnp.int32)
    losses = []
    for m in (sep, fused):
        m.train()
        eng = Engine(m, loss=GPTPretrainingCriterion(),
                     optimizer=paddle.optimizer.SGD(
                         0.05, parameters=m.parameters()))
        losses.append([float(eng.train_batch([ids], [lbl])[0])
                       for _ in range(2)])
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)


def test_fused_qkv_under_gspmd_mesh():
    """The interleaved layout must shard over mp and train (8-dev CPU
    mesh, dp x mp) — a contiguous head range per shard owns its q,k,v."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.mpu import shard_model
    from paddle_tpu.hapi.engine import Engine

    devs = jax.devices()
    if len(devs) < 4:
        import pytest
        pytest.skip("needs >=4 devices: environmental gate is conftest's "
                    "XLA_FLAGS --xla_force_host_platform_device_count=8 "
                    "(absent when run outside the tests/ conftest)")
    mesh = Mesh(np.array(devs[:4]).reshape(2, 2), ("dp", "mp"))
    paddle.seed(1)
    m = GPTForCausalLM(GPTConfig(**CFG, fused_qkv=True))
    m.train()
    shard_model(m, mesh)
    eng = Engine(m, loss=GPTPretrainingCriterion(),
                 optimizer=paddle.optimizer.AdamW(
                     1e-4, parameters=m.parameters()), mesh=mesh)
    ids = jnp.zeros((4, 16), jnp.int32)
    loss, _ = eng.train_batch([ids], [ids])
    assert np.isfinite(float(loss))


def test_conversion_refuses_wrong_format():
    import pytest
    with pytest.raises(ValueError, match="0 q/k/v trios"):
        fuse_qkv_state({"ln_f.weight": np.ones(4)}, 4)
    with pytest.raises(ValueError, match="scan_layers-stacked"):
        fuse_qkv_state({"gpt.h.attn__q_proj__weight": np.ones((2, 4, 4))},
                       4)
    with pytest.raises(ValueError, match="0 fused leaves"):
        split_qkv_state({"ln_f.weight": np.ones(4)}, 4)


def test_bert_ernie_fused_matches_separate():
    from paddle_tpu.nlp.bert import BertConfig, BertModel
    from paddle_tpu.nlp.ernie import ErnieConfig, ErnieModel

    for Model, Config in ((BertModel, BertConfig), (ErnieModel, ErnieConfig)):
        cfg = dict(vocab_size=67, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, max_position_embeddings=32,
                   hidden_dropout_prob=0.0,
                   attention_probs_dropout_prob=0.0,
                   use_flash_attention=False)
        paddle.seed(4)
        sep = Model(Config(**cfg))
        fused = Model(Config(**cfg, fused_qkv=True))
        fused.set_state_dict(fuse_qkv_state(
            {k: np.asarray(v._value) for k, v in sep.state_dict().items()},
            cfg["num_attention_heads"]))
        sep.eval(), fused.eval()
        ids = jnp.asarray(np.random.default_rng(2).integers(
            0, 67, (2, 10)), jnp.int32)
        with no_grad():
            s1, p1 = sep(Tensor(ids))
            s2, p2 = fused(Tensor(ids))
        np.testing.assert_allclose(np.asarray(s1._value),
                                   np.asarray(s2._value),
                                   rtol=2e-5, atol=2e-6,
                                   err_msg=Model.__name__)
        np.testing.assert_allclose(np.asarray(p1._value),
                                   np.asarray(p2._value),
                                   rtol=2e-5, atol=2e-6)


def test_fused_qkv_composes_with_scan_layers():
    """scan_layers + fused_qkv together (the 1.3B compile-size + launch
    -count combo) must match the unrolled fused model in training."""
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.nn.scan_stack import stack_layer_state

    paddle.seed(11)
    base = GPTForCausalLM(GPTConfig(**CFG, fused_qkv=True))
    both = GPTForCausalLM(GPTConfig(**CFG, fused_qkv=True,
                                    scan_layers=True, recompute=True))
    sd = stack_layer_state({k: np.asarray(v._value)
                            for k, v in base.state_dict().items()},
                           CFG["num_hidden_layers"], prefix="gpt.h.")
    both.set_state_dict(sd)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 89, (2, 16)), jnp.int32)
    lbl = jnp.asarray(rng.integers(0, 89, (2, 16)), jnp.int32)
    losses = []
    for m in (base, both):
        m.train()
        eng = Engine(m, loss=GPTPretrainingCriterion(),
                     optimizer=paddle.optimizer.SGD(
                         0.05, parameters=m.parameters()))
        losses.append([float(eng.train_batch([ids], [lbl])[0])
                       for _ in range(2)])
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)


def test_fused_qkv_through_pipeline_parallel():
    """dp x mp x pp with fused_qkv: the head-interleave must stay
    correct under shard_map tensor parallelism inside pipeline stages
    (a contiguous LOCAL head range owns its q,k,v)."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.mpu import shard_model
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.nlp.gpt import GPTForCausalLMPipe

    devs = jax.devices()
    if len(devs) < 4:
        import pytest
        pytest.skip("needs >=4 devices: environmental gate is conftest's "
                    "XLA_FLAGS --xla_force_host_platform_device_count=8 "
                    "(absent when run outside the tests/ conftest)")
    mesh = Mesh(np.array(devs[:4]).reshape(1, 2, 2), ("dp", "mp", "pp"))
    paddle.seed(2)
    pipe = GPTForCausalLMPipe(GPTConfig(**{**CFG, "vocab_size": 128,
                                           "max_position_embeddings": 64},
                                        fused_qkv=True),
                              mesh=mesh, n_micro=2)
    pipe.train()
    shard_model(pipe, mesh)
    eng = Engine(pipe, loss=GPTPretrainingCriterion(),
                 optimizer=paddle.optimizer.AdamW(
                     1e-4, parameters=pipe.parameters()), mesh=mesh)
    ids = jnp.zeros((2, 16), jnp.int32)
    with mesh:
        loss, _ = eng.train_batch([ids], [ids])
    assert np.isfinite(float(loss))
