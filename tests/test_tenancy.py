"""Per-tenant usage accounting (observability/tenancy.py), the
tenant= label threaded fleet → transport → engine, and the
PADDLE_TPU_TRACE_SAMPLE head-sampling knob (ISSUE 11).

Pinned contracts:

- SpaceSavingSketch: bounded cardinality, EXACT conservation of every
  accumulator through evictions (sum over entries == totals, always),
  guaranteed-tracked heavy hitters, stated error bounds;
- ServingEngine accounting: tagged requests accumulate tokens in/out,
  queue-wait and KV-page-seconds into engine.tenants and stamp them
  on their results; untagged requests cost nothing and stay
  result-shape compatible;
- fleet threading: tenant rides FleetRouter.submit through the
  transports into the engine; the router's per-tenant token totals
  sum EXACTLY to the fleet counters AND the resolved results;
  token-exactness and frozen compile counts hold with accounting on;
- /tenants endpoints (engine + router exporters) serve the report;
- shed order: within a priority band the heaviest tenant sheds first;
- trace sampling: deterministic keep-fraction, dropped trees counted
  (fleet_traces_sampled_out_total), never silent, and a sampled-out
  request still completes token-exactly.
"""
import json
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.observability.dtrace import TraceStore
from paddle_tpu.observability.metrics import get_registry
from paddle_tpu.observability.tenancy import SpaceSavingSketch, \
    TenantAccountant
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

NEW_TOK = 10


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32)
            for n in lens]


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(model, **d)


def _warm(eng):
    eng.generate(_prompts((5, 17), seed=7), max_new_tokens=4)
    eng.reset_counters()


def _fleet(model, n=2, router_kw=None, **engine_kw):
    engines = [_engine(model, **engine_kw) for _ in range(n)]
    for e in engines:
        _warm(e)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    router = FleetRouter(reps, **(router_kw or {}))
    return router, engines, frozen


class TestSpaceSavingSketch:
    def test_exact_below_capacity(self):
        sk = SpaceSavingSketch(capacity=8)
        for i in range(5):
            sk.add(f"t{i}", i + 1, tokens_out=i + 1)
        assert len(sk) == 5 and sk.evictions == 0
        assert sk.error_bound == 0
        assert sk.usage("t4") == 5 and sk.usage("t9") == 0
        assert [r["tenant"] for r in sk.top(2)] == ["t4", "t3"]

    def test_conservation_through_evictions(self):
        """The invariant the chaos wave rides: every accumulator's
        sum over sketch entries equals the exact total, whatever the
        eviction history."""
        rng = np.random.default_rng(0)
        sk = SpaceSavingSketch(capacity=4)
        totals = {"tokens_in": 0, "tokens_out": 0, "requests": 0}
        for _ in range(2000):
            t = f"t{rng.integers(0, 50)}"
            ti, to = int(rng.integers(1, 30)), int(rng.integers(1, 30))
            sk.add(t, ti + to, tokens_in=ti, tokens_out=to,
                   requests=1)
            totals["tokens_in"] += ti
            totals["tokens_out"] += to
            totals["requests"] += 1
        assert len(sk) == 4 and sk.evictions > 0
        for f, v in totals.items():
            assert sk.totals[f] == v
            assert sum(e[f] for e in sk._entries.values()) == v
        assert sk.total_weight == totals["tokens_in"] \
            + totals["tokens_out"]
        assert sk.error_bound > 0   # honesty: overestimates are stated

    def test_heavy_hitter_guaranteed_tracked(self):
        rng = np.random.default_rng(1)
        sk = SpaceSavingSketch(capacity=8)
        for i in range(3000):
            sk.add("whale", 10)          # ~55% of all weight
            sk.add(f"minnow{rng.integers(0, 200)}",
                   int(rng.integers(1, 9)))
        top = sk.top(1)[0]
        assert top["tenant"] == "whale"
        # space-saving bound: true count >= weight - err
        assert top["weight"] - top["err"] <= 30000 <= top["weight"]

    def test_unknown_field_raises(self):
        with pytest.raises(ValueError):
            SpaceSavingSketch(4).add("t", 1, typo_field=3)

    def test_accountant_report_and_none_tenant(self):
        acc = TenantAccountant(capacity=4)
        acc.account(None, tokens_out=5)          # skipped, not "None"
        acc.account("a", tokens_in=3, tokens_out=7, queue_wait_s=0.5,
                    kv_page_s=2.0, requests=1)
        rep = acc.report()
        assert rep["tracked"] == 1 and rep["exact_below_capacity"]
        assert rep["totals"]["tokens_out"] == 7
        assert rep["tenants"][0]["tenant"] == "a"
        assert acc.usage("a") == 10 and acc.usage(None) == 0


class TestEngineTenancy:
    def test_tagged_request_accounts_and_stamps_results(self,
                                                       gpt_model):
        eng = _engine(gpt_model)
        try:
            prompts = _prompts((5, 9))
            eng.submit(prompts[0], 6, tenant="acme")
            eng.submit(prompts[1], 6)            # untagged rides along
            res = {r["id"]: r for r in eng.run_to_completion()}
            tagged, untagged = res[0], res[1]
            assert tagged["tenant"] == "acme"
            assert tagged["kv_page_s"] > 0
            assert tagged["queue_wait_s"] >= 0
            assert "tenant" not in untagged      # shape-compatible
            rep = eng.tenants.report()
            assert rep["tracked"] == 1
            assert rep["totals"]["tokens_in"] == len(prompts[0])
            assert rep["totals"]["tokens_out"] == len(tagged["tokens"])
            assert rep["totals"]["kv_page_s"] > 0
            assert eng.health()["tenants_tracked"] == 1
        finally:
            eng.close()

    def test_engine_tenants_endpoint(self, gpt_model):
        eng = _engine(gpt_model)
        exp = eng.serve_metrics(port=0)
        try:
            eng.submit(_prompts((5,))[0], 4, tenant="acme")
            eng.run_to_completion()
            doc = json.loads(urllib.request.urlopen(
                exp.url + "/tenants", timeout=5).read())
            assert doc["tenants"][0]["tenant"] == "acme"
        finally:
            eng.close()

    def test_never_admitted_finish_accounts_queue_wait(self,
                                                       gpt_model):
        eng = _engine(gpt_model)
        try:
            rid = eng.submit(_prompts((5,))[0], 4, tenant="acme")
            assert eng.cancel(rid)
            res = eng.step()
            row = next(r for r in res if r["id"] == rid)
            assert row["status"] == "cancelled"
            assert row["kv_page_s"] == 0
            assert eng.tenants.report()["totals"]["requests"] == 1
        finally:
            eng.close()


@pytest.mark.chaos
class TestFleetTenancy:
    def test_tenant_totals_sum_exactly_to_fleet_totals(self,
                                                       gpt_model):
        """The acceptance invariant: sketch totals == fleet counters
        == resolved-result sums, with kv-page-seconds flowing up from
        the engines and compile counts frozen throughout."""
        router, engines, frozen = _fleet(gpt_model)
        try:
            prompts = _prompts((5, 12, 17, 9, 21, 14))
            tenants = ["a", "b", "a", "c", None, "b"]
            rids = [router.submit(p, NEW_TOK, tenant=t)
                    for p, t in zip(prompts, tenants)]
            res = {r["id"]: r for r in router.run_to_completion()}
            assert all(res[r]["status"] == "ok" for r in rids)
            # results carry their tenant back to the client
            assert [res[r]["tenant"] for r in rids] == tenants
            rep = router.tenants.report()
            by = {t["tenant"]: t for t in rep["tenants"]}
            assert set(by) == {"a", "b", "c", "anon"}
            out_total = sum(len(res[r]["tokens"]) for r in rids)
            in_total = sum(len(p) for p in prompts)
            reg = router.registry
            assert rep["totals"]["tokens_out"] == out_total \
                == int(reg.get("fleet_tokens_out_total").value)
            assert rep["totals"]["tokens_in"] == in_total \
                == int(reg.get("fleet_tokens_in_total").value)
            assert sum(t["tokens_out"] for t in rep["tenants"]) \
                == out_total
            # engine-side facts flowed up through the result plane
            assert rep["totals"]["kv_page_s"] > 0
            assert by["a"]["requests"] == 2 and by["anon"]["requests"] == 1
            # per-engine sketches saw only their tagged share
            eng_out = sum(e.tenants.report()["totals"]["tokens_out"]
                          for e in engines)
            assert eng_out == out_total - len(res[rids[4]]["tokens"])
            for i, e in enumerate(engines):
                assert e.compile_counts() == frozen[i]
            assert router.compile_report()["unexpected_retraces"] == 0
        finally:
            router.close()

    def test_router_tenants_endpoint_and_health(self, gpt_model):
        router, engines, frozen = _fleet(gpt_model)
        exp = router.serve_metrics(port=0)
        try:
            router.generate(_prompts((5, 9)), max_new_tokens=4)
            doc = json.loads(urllib.request.urlopen(
                exp.url + "/tenants", timeout=5).read())
            assert doc["totals"]["requests"] == 2
            assert doc["tenants"][0]["tenant"] == "anon"
            assert router.health()["tenants"] == {"tracked": 1}
        finally:
            router.close()

    def test_shed_prefers_heaviest_tenant_within_priority(
            self, gpt_model):
        """Saturate a 1-slot fleet after making 'whale' the dominant
        tenant: the overflow shed lands on whale's queued work before
        'shrimp's at the SAME priority."""
        router, engines, frozen = _fleet(
            gpt_model, n=1, max_slots=1,
            router_kw={"max_queue": 2, "replica_queue_limit": 2})
        try:
            # establish usage history: whale >> shrimp
            whale_rids = [router.submit(p, NEW_TOK, tenant="whale")
                          for p in _prompts((17, 21, 14))]
            router.run_to_completion()
            assert router.tenants.usage("whale") \
                > router.tenants.usage("shrimp")
            prompts = _prompts((5, 12, 17, 9, 21, 14))
            tenants = ["shrimp", "whale", "shrimp", "whale",
                       "shrimp", "whale"]
            rids = [router.submit(p, NEW_TOK, tenant=t)
                    for p, t in zip(prompts, tenants)]
            res = {r["id"]: r for r in router.run_to_completion()}
            shed = [r for r in rids if res[r]["status"] == "shed"]
            assert len(shed) == 2
            assert all(res[r]["tenant"] == "whale" for r in shed), \
                "the heavy tenant must shed before the light one"
            del whale_rids
        finally:
            router.close()


class TestTraceSampling:
    def test_deterministic_keep_fraction_and_counter(self):
        before = get_registry().get("fleet_traces_sampled_out_total")
        before = 0 if before is None else before.value
        store = TraceStore(sample=0.25)
        kept = sum(1 for _ in range(100)
                   if store.new_trace(rid=1) is not None)
        assert kept == 25
        assert store.sampled_out == 75
        after = get_registry().get("fleet_traces_sampled_out_total")
        assert after is not None and after.value - before == 75
        # sample=1.0 keeps everything and counts nothing
        full = TraceStore(sample=1.0)
        assert all(full.new_trace(rid=i) is not None
                   for i in range(10))
        assert full.sampled_out == 0

    def test_env_knob(self, monkeypatch):
        import paddle_tpu.observability.dtrace as dt
        monkeypatch.setattr(dt, "_default", None)
        monkeypatch.setenv("PADDLE_TPU_TRACE_SAMPLE", "0.5")
        store = dt.get_store()
        assert store.sample == 0.5
        monkeypatch.setattr(dt, "_default", None)
        monkeypatch.setenv("PADDLE_TPU_TRACE_SAMPLE", "junk")
        assert dt.get_store().sample == 1.0
        monkeypatch.setattr(dt, "_default", None)

    @pytest.mark.chaos
    def test_sampled_out_requests_still_token_exact(self, gpt_model):
        """sample=0.5 through a real fleet wave: every request
        completes with the right tokens; dropped trees are counted,
        kept ones still export; TTFT SLO simply skips the untraced."""
        store = TraceStore(sample=0.5)
        router, engines, frozen = _fleet(
            gpt_model, router_kw={"trace_store": store})
        try:
            eng = _engine(gpt_model)
            prompts = _prompts((5, 12, 17, 9))
            refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
            eng.close()
            outs = router.generate(prompts, max_new_tokens=NEW_TOK)
            assert outs == refs
            assert store.sampled_out == 2   # deterministic 1-in-2
            assert len(store.trace_ids()) == 2
            for i, e in enumerate(engines):
                assert e.compile_counts() == frozen[i]
        finally:
            router.close()


class TestProcFrameThreading:
    def test_submit_frame_carries_tenant(self, monkeypatch):
        """The Proc transport's wire frame carries the tenant label
        (no subprocess needed: capture the frame at the send seam)."""
        from paddle_tpu.serving_fleet.proc import ProcReplica
        rep = ProcReplica.__new__(ProcReplica)
        rep.name = "p0"
        import threading
        rep._out_lock = threading.Lock()
        rep._inflight = {}
        sent = []
        monkeypatch.setattr(ProcReplica, "_send",
                            lambda self, frame: sent.append(frame))
        rep.enqueue(("submit", 7, [1, 2, 3], 4, None, 0,
                     {"deadline_ms": None, "trace": None,
                      "tenant": "acme"}))
        assert sent[0]["tenant"] == "acme"
        rep.enqueue(("submit", 8, [1], 4, None, 0))
        assert sent[1]["tenant"] is None
