"""Engine builder the ProcReplica child processes use in tests.

File-loaded by ``proc_child.py`` via the spec's ``builder`` path —
NOT a test module (no ``test_`` prefix). The builder must be
deterministic per seed: the parent computes goldens on its own
identically-seeded engine, and the subprocess replica must generate
token-for-token the same streams for the chaos drills' token-exact
assertions to mean anything.
"""


def build_engine(seed=0, **kw):
    """gpt-tiny ServingEngine, seeded — the fleet chaos workhorse."""
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    from paddle_tpu.nlp.serving import ServingEngine

    paddle.seed(seed)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(m, **d)
