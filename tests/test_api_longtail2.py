"""Round-2 API sweep: long-tail math ops (vs scipy), Tensor convenience
methods, vision transforms."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import transforms as T


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


t = paddle.to_tensor


class TestMathLongtail:
    def test_special_vs_scipy(self):
        import scipy.special as sp
        assert np.allclose(_np(paddle.xlogy(t(0.0), t(0.0))), 0.0)
        assert np.allclose(_np(paddle.xlogy(t(2.0), t(3.0))),
                           2 * math.log(3), atol=1e-6)
        assert np.allclose(_np(paddle.igamma(t(2.0), t(1.0))),
                           sp.gammaincc(2.0, 1.0), atol=1e-6)
        assert np.allclose(_np(paddle.igammac(t(2.0), t(1.0))),
                           sp.gammainc(2.0, 1.0), atol=1e-6)
        assert np.allclose(_np(paddle.i0e(t(1.5))), sp.i0e(1.5), atol=1e-6)
        assert np.allclose(_np(paddle.nextafter(t(1.0), t(2.0))),
                           np.nextafter(np.float32(1), np.float32(2)))

    def test_combinatorics(self):
        c = _np(paddle.combinations(t([1.0, 2.0, 3.0]), 2))
        assert np.allclose(c, [[1, 2], [1, 3], [2, 3]])
        cr = _np(paddle.combinations(t([1.0, 2.0]), 2,
                                     with_replacement=True))
        assert np.allclose(cr, [[1, 1], [1, 2], [2, 2]])
        cp = _np(paddle.cartesian_prod(t([1.0, 2.0]), t([3.0, 4.0])))
        assert np.allclose(cp, [[1, 3], [1, 4], [2, 3], [2, 4]])

    def test_renorm_signbit_vdot(self):
        x = t(np.array([[3.0, 4.0], [6.0, 8.0]], np.float32))
        r = _np(paddle.renorm(x, 2.0, 0, 5.0))
        assert np.allclose(np.linalg.norm(r, axis=1), [5.0, 5.0])
        # rows under the bound untouched
        r2 = _np(paddle.renorm(x, 2.0, 0, 100.0))
        assert np.allclose(r2, _np(x))
        assert bool(_np(paddle.signbit(t(-1.0))))
        assert not bool(_np(paddle.signbit(t(1.0))))
        assert np.allclose(_np(paddle.vdot(t([1.0, 2.0]), t([3.0, 4.0]))),
                           11.0)
        assert not bool(_np(paddle.isreal(t(1j))).item()) \
            if hasattr(_np(paddle.isreal(t(1j))), "item") else True

    def test_tensor_method_binding(self):
        x = t([0.5])
        assert hasattr(x, "xlogy") and hasattr(x, "nextafter")
        assert np.allclose(_np(x.xlogy(t([2.0]))), 0.5 * math.log(2),
                           atol=1e-6)


class TestTensorConvenience:
    def test_sizes(self):
        x = t(np.zeros((2, 3), np.float32))
        assert x.element_size() == 4
        assert x.dim() == 2 and x.ndimension() == 2
        assert x.contiguous() is x
        assert x.is_contiguous()

    def test_cuda_alias(self):
        x = t([1.0]).cuda()
        assert np.allclose(_np(x), 1.0)

    def test_apply_(self):
        x = t(np.array([1.0, 2.0], np.float32))
        x.apply_(lambda v: v * 10)
        assert np.allclose(_np(x), [10.0, 20.0])
        y = t(np.array([1.0], np.float32))
        z = y.apply(lambda v: v + 1)
        assert np.allclose(_np(z), 2.0)
        assert np.allclose(_np(y), 1.0)  # original untouched


class TestTransformsLongtail:
    def setup_method(self, m):
        rng = np.random.default_rng(0)
        self.img = rng.integers(0, 256, (16, 16, 3)).astype(np.uint8)

    def test_grayscale(self):
        g = T.Grayscale(3)(self.img)
        assert g.shape == (16, 16, 3)
        assert np.allclose(g[..., 0], g[..., 1])
        ref = (self.img[..., 0] * 0.299 + self.img[..., 1] * 0.587
               + self.img[..., 2] * 0.114)
        assert np.abs(g[..., 0].astype(float) - ref).max() <= 1.0

    def test_rotate_identity_and_90(self):
        assert np.allclose(T.rotate(self.img, 0.0), self.img)
        r90 = T.rotate(self.img.astype(np.float32), 90.0)
        assert np.allclose(r90, np.rot90(self.img, -1).astype(np.float32))

    def test_hue_identity_and_range(self):
        assert np.abs(T.adjust_hue(self.img, 0.0).astype(int)
                      - self.img.astype(int)).max() <= 2
        h = T.HueTransform(0.4)(self.img)
        assert h.dtype == np.uint8 and h.shape == self.img.shape

    def test_random_transforms_preserve_shape(self):
        for tr in (T.RandomRotation(25), T.RandomErasing(prob=1.0),
                   T.SaturationTransform(0.5),
                   T.RandomAffine(10, translate=(0.1, 0.1),
                                  scale=(0.8, 1.2)),
                   T.RandomPerspective(prob=1.0)):
            out = tr(self.img)
            assert out.shape == self.img.shape, type(tr).__name__

    def test_erasing_erases(self):
        e = T.RandomErasing(prob=1.0, value=7)(self.img + 10)
        assert (e == 7).any()

    def test_to_pil(self):
        pil = T.ToPILImage()(self.img)
        assert pil.size == (16, 16)
        back = np.asarray(pil)
        assert np.allclose(back, self.img)

    def test_rotate_expand(self):
        # regression: expand=True was ignored
        out = T.rotate(self.img, 45.0, expand=True)
        assert out.shape[0] > 16 and out.shape[1] > 16
        # all original content present: mean magnitude preserved-ish
        assert out.max() == self.img.max()

    def test_affine_translation_fills_not_wraps(self):
        # regression: translation used np.roll (wraparound)
        img = np.full((16, 16, 3), 200, np.uint8)
        tr = T.RandomAffine(degrees=(0, 0), translate=(0.5, 0.5), fill=0)
        random_found_fill = False
        for _ in range(8):
            out = tr(img)
            if (out == 0).any():
                random_found_fill = True
                # no wraparound: every non-filled pixel is 200
                assert set(np.unique(out)) <= {0, 200}
        assert random_found_fill

    def test_affine_shear_applied(self):
        img = np.zeros((17, 17), np.float32)
        img[:, 8] = 1.0  # vertical line: shear about the center tilts it
        out = T.RandomAffine(degrees=(0, 0), shear=(30, 30))(img)
        assert not np.allclose(out, img)  # sheared, not ignored

    def test_erasing_random_value(self):
        # regression: value='random' crashed
        e = T.RandomErasing(prob=1.0, value="random")(self.img)
        assert e.shape == self.img.shape

    def test_fractional_pool_never_minus_inf(self):
        import paddle_tpu.nn as nn
        rng = np.random.default_rng(3)
        x = rng.standard_normal((1, 1, 9, 9)).astype(np.float32)
        # regression: u in the upper range made the last window empty
        for u in (0.3, 0.5, 0.7, 0.95):
            out = _np(nn.FractionalMaxPool2D(4, random_u=u)(
                paddle.to_tensor(x)))
            assert np.isfinite(out).all(), u

    def test_cartesian_prod_single_input_1d(self):
        out = _np(paddle.cartesian_prod(t([1.0, 2.0, 3.0])))
        assert out.shape == (3,)

    def test_renorm_negative_axis(self):
        # regression: negative axis computed one global norm
        x = t(np.array([[3.0, 4.0], [6.0, 8.0]], np.float32))
        r_pos = _np(paddle.renorm(x, 2.0, 1, 5.0))
        r_neg = _np(paddle.renorm(x, 2.0, -1, 5.0))
        assert np.allclose(r_pos, r_neg)
        assert np.allclose(np.linalg.norm(r_neg, axis=0),
                           np.minimum(np.linalg.norm(_np(x), axis=0), 5.0))

    def test_affine_four_element_shear_and_bilinear(self):
        img = np.zeros((17, 17), np.float32)
        img[:, 8] = 1.0
        out = T.RandomAffine(degrees=(0, 0), shear=[-20, 20, -20, 20],
                             interpolation="bilinear")(img)
        assert out.shape == img.shape
        p = T.RandomPerspective(prob=1.0, interpolation="bilinear")(
            np.random.default_rng(0).integers(0, 255, (16, 16, 3))
            .astype(np.uint8))
        assert p.shape == (16, 16, 3)
