"""paddle.autograd functional API (jacobian/hessian/jvp/vjp) +
set_global_initializer."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import jvp, vjp

t = paddle.to_tensor


def _np(x):
    return np.asarray(x.numpy() if hasattr(x, "numpy") else x)


class TestFunctionalAutograd:
    def test_jacobian_elementwise(self):
        x = t(np.array([1.0, 2.0, 3.0], np.float32))
        J = _np(paddle.jacobian(lambda v: v ** 2, x))
        assert np.allclose(J, np.diag([2.0, 4.0, 6.0]), atol=1e-5)

    def test_jacobian_matrix_fn(self):
        A = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        x = t(np.array([1.0, 1.0], np.float32))
        J = _np(paddle.jacobian(lambda v: t(A) @ v, x))
        assert np.allclose(J, A, atol=1e-5)

    def test_jacobian_multi_input(self):
        x = t(np.array([1.0, 2.0], np.float32))
        y = t(np.array([3.0, 4.0], np.float32))
        Jx, Jy = paddle.jacobian(lambda a, b: a * b, [x, y])
        assert np.allclose(_np(Jx), np.diag([3.0, 4.0]), atol=1e-5)
        assert np.allclose(_np(Jy), np.diag([1.0, 2.0]), atol=1e-5)

    def test_hessian(self):
        x = t(np.array([1.0, 2.0, 3.0], np.float32))
        H = _np(paddle.hessian(lambda v: (v ** 3).sum(), x))
        assert np.allclose(H, np.diag([6.0, 12.0, 18.0]), atol=1e-4)
        # quadratic form: H = A + A^T
        A = np.array([[2.0, 1.0], [0.0, 3.0]], np.float32)
        z = t(np.ones(2, np.float32))
        H2 = _np(paddle.hessian(lambda v: (v * (t(A) @ v)).sum(), z))
        assert np.allclose(H2, A + A.T, atol=1e-4)

    def test_incubate_namespace_and_flags(self):
        import paddle_tpu as paddle
        x = t(np.array([2.0], np.float32))
        out, g = paddle.incubate.autograd.vjp(lambda v: v * v, x)
        assert np.allclose(_np(g), [4.0])
        with pytest.raises(NotImplementedError):
            paddle.jacobian(lambda v: v, x, create_graph=True)

    def test_global_init_fires_for_named_paramattr(self):
        # regression: ParamAttr(name=...) without an initializer must
        # still pick up the global initializer
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.initializer import (Constant, ParamAttr,
                                               set_global_initializer)
        set_global_initializer(Constant(0.25))
        try:
            fc = nn.Linear(3, 2, weight_attr=ParamAttr(name="w"))
            assert np.allclose(_np(fc.weight), 0.25)
        finally:
            set_global_initializer(None, None)

    def test_vjp_multi_output_structure(self):
        # regression: list-output func with list cotangent crashed on a
        # pytree-structure mismatch
        x = t(np.array([1.0, 2.0], np.float32))
        out, g = vjp(lambda v: [v * v, v + 1],
                     x, [t(np.ones(2, np.float32)),
                         t(np.zeros(2, np.float32))])
        assert np.allclose(_np(g), [2.0, 4.0])

    def test_jvp_vjp(self):
        x = t(np.array([1.0, 2.0, 3.0], np.float32))
        out, tang = jvp(lambda v: v * v, x, t(np.ones(3, np.float32)))
        assert np.allclose(_np(out), [1.0, 4.0, 9.0])
        assert np.allclose(_np(tang), [2.0, 4.0, 6.0])
        out, grads = vjp(lambda v: v * v, x)
        assert np.allclose(_np(grads), [2.0, 4.0, 6.0])
        # custom cotangent
        _, g2 = vjp(lambda v: v * v, x, t(np.array([1.0, 0.0, 2.0],
                                                   np.float32)))
        assert np.allclose(_np(g2), [2.0, 0.0, 12.0])


class TestGlobalInitializer:
    def test_overrides_defaults_not_explicit(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.nn.initializer import (Constant, ParamAttr,
                                               set_global_initializer)
        set_global_initializer(Constant(0.5), Constant(-0.1))
        try:
            fc = nn.Linear(3, 2)
            assert np.allclose(_np(fc.weight), 0.5)
            assert np.allclose(_np(fc.bias), -0.1)
            # explicit attr wins over the global
            fc2 = nn.Linear(3, 2,
                            weight_attr=ParamAttr(initializer=Constant(9.0)))
            assert np.allclose(_np(fc2.weight), 9.0)
        finally:
            set_global_initializer(None, None)
        fc3 = nn.Linear(3, 2)
        assert not np.allclose(_np(fc3.weight), 0.5)
