"""Torch-golden spot checks for the pooling / conv-transpose / unfold
family (r4 audit after the interpolate divergence — these all passed,
pinned here so they stay that way). Note paddle's avg_pool default is
exclusive=True == torch count_include_pad=False.
"""
import numpy as np
import torch
import torch.nn.functional as tF

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F


def _np(t):
    return np.asarray(t.numpy())


def test_pool_family_matches_torch():
    x = np.random.default_rng(1).standard_normal(
        (2, 3, 9, 11)).astype(np.float32)
    xt, xr = paddle.to_tensor(x), torch.from_numpy(x)
    np.testing.assert_allclose(
        _np(F.max_pool2d(xt, 3, 2, 1)),
        tF.max_pool2d(xr, 3, 2, 1).numpy(), atol=1e-6)
    np.testing.assert_allclose(
        _np(F.max_pool2d(xt, 3, 2, 0, ceil_mode=True)),
        tF.max_pool2d(xr, 3, 2, 0, ceil_mode=True).numpy(), atol=1e-6)
    np.testing.assert_allclose(          # paddle default == exclude-pad
        _np(F.avg_pool2d(xt, 3, 2, 1)),
        tF.avg_pool2d(xr, 3, 2, 1, count_include_pad=False).numpy(),
        atol=1e-6)
    np.testing.assert_allclose(
        _np(F.avg_pool2d(xt, 3, 2, 1, exclusive=False)),
        tF.avg_pool2d(xr, 3, 2, 1, count_include_pad=True).numpy(),
        atol=1e-6)
    np.testing.assert_allclose(
        _np(F.lp_pool2d(xt, 2, 3, 2)),
        tF.lp_pool2d(xr, 2, 3, 2).numpy(), rtol=1e-5, atol=1e-6)


def test_adaptive_pools_match_torch_awkward_sizes():
    """The interpolate area bug lived in float window bounds; the
    adaptive pools use the same windows — pin the awkward sizes."""
    rng = np.random.default_rng(0)
    for in_sp, out_sp in [((21, 19), (19, 7)), ((25, 30), (11, 13))]:
        x = rng.standard_normal((2, 3) + in_sp).astype(np.float32)
        np.testing.assert_allclose(
            _np(F.adaptive_avg_pool2d(paddle.to_tensor(x), out_sp)),
            tF.adaptive_avg_pool2d(torch.from_numpy(x), out_sp).numpy(),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            _np(F.adaptive_max_pool2d(paddle.to_tensor(x), out_sp)),
            tF.adaptive_max_pool2d(torch.from_numpy(x), out_sp).numpy(),
            atol=1e-6)


def test_conv_transpose_matches_torch():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((2, 4, 7, 8)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)
    xt, xr = paddle.to_tensor(x), torch.from_numpy(x)
    wt, wr = paddle.to_tensor(w), torch.from_numpy(w)
    for kw in [dict(stride=2), dict(stride=2, padding=1),
               dict(stride=2, padding=1, output_padding=1),
               dict(dilation=2)]:
        np.testing.assert_allclose(
            _np(F.conv2d_transpose(xt, wt, **kw)),
            tF.conv_transpose2d(xr, wr, **kw).numpy(),
            rtol=1e-4, atol=1e-5, err_msg=str(kw))


def test_unfold_matches_torch():
    x = np.random.default_rng(3).standard_normal(
        (2, 4, 7, 8)).astype(np.float32)
    np.testing.assert_allclose(
        _np(F.unfold(paddle.to_tensor(x), 3, strides=2)),
        tF.unfold(torch.from_numpy(x), 3, stride=2).numpy(), atol=1e-6)
