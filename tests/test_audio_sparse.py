"""paddle.audio features vs closed forms; paddle.sparse subset vs dense."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio as A
from paddle_tpu import sparse as S


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestAudioFunctional:
    def test_windows(self):
        for name, ref in (("hann", np.hanning(33)[:-1]),
                          ("hamming", np.hamming(33)[:-1]),
                          ("blackman", np.blackman(33)[:-1])):
            w = _np(A.functional.get_window(name, 32))
            assert np.allclose(w, ref, atol=1e-6), name
        assert np.allclose(_np(A.functional.get_window("rect", 8)), 1.0)
        with pytest.raises(ValueError):
            A.functional.get_window("bogus", 8)

    def test_tuple_window_params_respected(self):
        # regression: ('kaiser', beta) dropped beta and used 12.0
        w5 = _np(A.functional.get_window(("kaiser", 5.0), 32))
        assert np.allclose(w5, np.kaiser(33, 5.0)[:-1], atol=1e-6)
        w12 = _np(A.functional.get_window(("kaiser", 12.0), 32))
        assert not np.allclose(w5, w12)
        g3 = _np(A.functional.get_window(("gaussian", 3.0), 16))
        k = np.arange(16) - 7.5
        assert np.allclose(g3, np.exp(-0.5 * (k / 3.0) ** 2), atol=1e-6)

    def test_mel_conversions_roundtrip(self):
        for htk in (False, True):
            f = np.array([0.0, 440.0, 1000.0, 4000.0, 8000.0])
            m = A.functional.hz_to_mel(f, htk)
            back = A.functional.mel_to_hz(m, htk)
            assert np.allclose(back, f, rtol=1e-4), htk
        # slaney scale is linear below 1 kHz
        assert abs(A.functional.hz_to_mel(500.0) - 7.5) < 1e-6

    def test_fbank_matrix(self):
        fb = _np(A.functional.compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        # every filter has some support
        assert (fb.sum(1) > 0).all()

    def test_power_to_db(self):
        x = paddle.to_tensor(np.array([1.0, 10.0, 100.0], np.float32))
        db = _np(A.functional.power_to_db(x, top_db=None))
        assert np.allclose(db, [0.0, 10.0, 20.0], atol=1e-5)

    def test_create_dct_orthonormal(self):
        d = _np(A.functional.create_dct(8, 8))
        # ortho-normalized type-II DCT basis: D^T D = I
        assert np.allclose(d.T @ d, np.eye(8), atol=1e-5)


class TestAudioFeatures:
    def test_spectrogram_parseval_tone(self):
        sr = 8000
        t = np.arange(sr, dtype=np.float32) / sr
        tone = np.sin(2 * np.pi * 1000 * t)[None]  # 1 kHz
        spec = A.Spectrogram(n_fft=256, hop_length=128)(
            paddle.to_tensor(tone))
        s = _np(spec)
        assert s.shape[1] == 129
        # spectral peak at bin 1000/ (8000/256) = 32
        assert np.argmax(s.mean(-1)[0]) == 32

    def test_mel_and_mfcc_shapes(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.standard_normal((2, 4000)).astype(np.float32))
        mel = A.MelSpectrogram(sr=8000, n_fft=256, n_mels=32, f_min=0.0)(x)
        assert tuple(mel.shape)[:2] == (2, 32)
        logmel = A.LogMelSpectrogram(sr=8000, n_fft=256, n_mels=32,
                                     f_min=0.0)(x)
        assert tuple(logmel.shape) == tuple(mel.shape)
        assert np.allclose(_np(logmel),
                           10 * np.log10(np.maximum(_np(mel), 1e-10)),
                           atol=1e-4)
        mfcc = A.MFCC(sr=8000, n_mfcc=13, n_fft=256, n_mels=32, f_min=0.0)(x)
        assert tuple(mfcc.shape)[:2] == (2, 13)

    def test_jit_and_grad(self):
        import jax
        layer = A.MelSpectrogram(sr=8000, n_fft=128, n_mels=16, f_min=0.0)
        x = paddle.to_tensor(
            np.random.default_rng(1).standard_normal(2000).astype(np.float32),
            stop_gradient=False)
        out = layer(x)
        g = paddle.grad(out.sum(), x)[0]
        assert np.all(np.isfinite(_np(g)))


class TestSparse:
    def test_coo_roundtrip(self):
        idx = np.array([[0, 1, 2], [1, 0, 2]])
        vals = np.array([1.0, 2.0, 3.0], np.float32)
        sp = S.sparse_coo_tensor(idx, vals, (3, 3))
        assert S.is_sparse_coo(sp)
        assert sp.nnz() == 3
        dense = _np(sp.to_dense())
        ref = np.zeros((3, 3), np.float32)
        ref[0, 1], ref[1, 0], ref[2, 2] = 1, 2, 3
        assert np.allclose(dense, ref)
        assert np.allclose(_np(sp.indices()), idx)
        assert np.allclose(_np(sp.values()), vals)

    def test_csr_roundtrip(self):
        crows = np.array([0, 1, 3, 3])
        cols = np.array([2, 0, 1])
        vals = np.array([5.0, 1.0, 2.0], np.float32)
        sp = S.sparse_csr_tensor(crows, cols, vals, (3, 3))
        assert S.is_sparse_csr(sp)
        ref = np.zeros((3, 3), np.float32)
        ref[0, 2], ref[1, 0], ref[1, 1] = 5, 1, 2
        assert np.allclose(_np(sp.to_dense()), ref)
        coo = sp.to_sparse_coo()
        assert S.is_sparse_coo(coo) or S.is_sparse(coo)

    def test_elementwise(self):
        idx = np.array([[0, 1], [1, 0]])
        sp = S.sparse_coo_tensor(idx, np.array([-1.0, 4.0], np.float32),
                                 (2, 2))
        assert np.allclose(_np(S.relu(sp).values()), [0.0, 4.0])
        assert np.allclose(_np(S.sqrt(S.abs(sp)).values()), [1.0, 2.0])
        sp2 = S.sparse_coo_tensor(idx, np.array([2.0, 2.0], np.float32),
                                  (2, 2))
        assert np.allclose(_np(S.add(sp, sp2).values()), [1.0, 6.0])
        assert np.allclose(_np(S.multiply(sp, sp2).values()), [-2.0, 8.0])

    def test_matmul_vs_dense(self):
        rng = np.random.default_rng(2)
        dense = rng.standard_normal((5, 4)).astype(np.float32)
        dense[np.abs(dense) < 0.8] = 0.0
        idx = np.stack(np.nonzero(dense), 0)
        sp = S.sparse_coo_tensor(idx, dense[tuple(idx)], dense.shape)
        y = rng.standard_normal((4, 3)).astype(np.float32)
        out = S.matmul(sp, paddle.to_tensor(y))
        assert np.allclose(_np(out), dense @ y, atol=1e-5)

    def test_matmul_grad_flows_to_dense(self):
        dense = np.array([[1.0, 0.0], [0.0, 2.0]], np.float32)
        idx = np.stack(np.nonzero(dense), 0)
        sp = S.sparse_coo_tensor(idx, dense[tuple(idx)], dense.shape)
        y = paddle.to_tensor(np.ones((2, 2), np.float32),
                             stop_gradient=False)
        out = S.matmul(sp, y)
        g = paddle.grad(out.sum(), y)[0]
        # d/dy sum(S y) = column sums of S broadcast
        assert np.allclose(_np(g), [[1.0, 1.0], [2.0, 2.0]])

    def test_masked_matmul(self):
        rng = np.random.default_rng(3)
        a = rng.standard_normal((4, 6)).astype(np.float32)
        b = rng.standard_normal((6, 4)).astype(np.float32)
        mask_idx = np.array([[0, 1, 3], [0, 2, 3]])
        mask = S.sparse_coo_tensor(mask_idx,
                                   np.ones(3, np.float32), (4, 4))
        out = S.masked_matmul(paddle.to_tensor(a), paddle.to_tensor(b), mask)
        full = a @ b
        assert np.allclose(_np(out.values()),
                           full[tuple(mask_idx)], atol=1e-5)

    def test_nn_relu_and_conv_constructible(self):
        idx = np.array([[0], [0]])
        sp = S.sparse_coo_tensor(idx, np.array([-3.0], np.float32), (1, 1))
        out = S.nn.ReLU()(sp)
        assert np.allclose(_np(out.values()), [0.0])
        # r4: the convs are real now (tests/test_sparse_conv.py); only
        # grouped convs remain gated
        assert S.nn.SubmConv3D(1, 1, 3).kernel_size == (3, 3, 3)
        with pytest.raises(NotImplementedError):
            S.nn.Conv3D(2, 2, 3, groups=2)
