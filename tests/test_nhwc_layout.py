"""NHWC-native conv stack: NCHW<->NHWC parity for conv/pool/BN, the
HWIO weight conversion, space-to-depth, and the full ResNet-50 forward
(+backward) in both layouts. The public API stays NCHW in/out — the
layout flips exactly once at the network boundary."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn.layer import functional_call
from paddle_tpu.tensor import Tensor
from paddle_tpu.vision.models import resnet50
from paddle_tpu.vision.models.resnet import space_to_depth


def _img(shape=(2, 3, 32, 32), seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).standard_normal(shape), jnp.float32)


def _fwd(m, x, train=False):
    params, buffers = m.raw_state()

    @jax.jit
    def f(p, b, a):
        if train:
            out, nb = functional_call(m, p, b, Tensor(a), mutable=True)
            return out._value, nb
        return functional_call(m, p, b, Tensor(a))._value
    return f(params, buffers, x)


# ---------------------------------------------------------------------------
# op-level parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("groups,stride,dilation,padding", [
    (1, 1, 1, 1), (2, 2, 1, [2, 1, 2, 1]), (4, 1, 2, 2)])
def test_conv2d_hwio_parity(groups, stride, dilation, padding):
    paddle.seed(0)
    c = nn.Conv2D(8, 16, 3, stride=stride, padding=padding,
                  dilation=dilation, groups=groups)
    x = Tensor(_img((2, 8, 12, 12)))
    ref = c(x)
    c.to_channels_last()
    assert c.weight._value.shape == (3, 3, 8 // groups, 16)
    out = c(x.transpose([0, 2, 3, 1]))
    np.testing.assert_allclose(
        np.asarray(ref._value),
        np.asarray(out.transpose([0, 3, 1, 2])._value), atol=1e-5)


def test_conv1d_hwio_parity():
    paddle.seed(0)
    c = nn.Conv1D(6, 10, 3, padding=1)
    x = Tensor(_img((2, 6, 16)))
    ref = c(x)
    c.to_channels_last()
    out = c(x.transpose([0, 2, 1]))
    np.testing.assert_allclose(
        np.asarray(ref._value),
        np.asarray(out.transpose([0, 2, 1])._value), atol=1e-5)


def test_transpose_conv_rejects_channels_last():
    c = nn.Conv2DTranspose(4, 4, 2)
    with pytest.raises(ValueError, match="transpose convs"):
        c.to_channels_last()


def test_pool_and_bn_parity():
    x = _img((2, 8, 10, 10))
    xt = jnp.transpose(x, (0, 2, 3, 1))
    mp = nn.MaxPool2D(3, stride=2, padding=1)
    mp_cl = nn.MaxPool2D(3, stride=2, padding=1, data_format="NHWC")
    np.testing.assert_allclose(
        np.asarray(mp(Tensor(x))._value),
        np.asarray(mp_cl(Tensor(xt)).transpose([0, 3, 1, 2])._value))
    ap = nn.AdaptiveAvgPool2D((1, 1))
    ap_cl = nn.AdaptiveAvgPool2D((1, 1), data_format="NHWC")
    np.testing.assert_allclose(
        np.asarray(ap(Tensor(x))._value),
        np.asarray(ap_cl(Tensor(xt)).transpose([0, 3, 1, 2])._value),
        atol=1e-6)
    paddle.seed(1)
    bn = nn.BatchNorm2D(8)
    paddle.seed(1)
    bn_cl = nn.BatchNorm2D(8, data_format="NHWC")
    for m in (bn, bn_cl):
        m.train()
    y1 = bn(Tensor(x))
    y2 = bn_cl(Tensor(xt))
    np.testing.assert_allclose(
        np.asarray(y1._value),
        np.asarray(y2.transpose([0, 3, 1, 2])._value), atol=1e-5)
    # train-mode running-stat updates identical across layouts
    np.testing.assert_allclose(np.asarray(bn._mean._value),
                               np.asarray(bn_cl._mean._value), atol=1e-6)
    np.testing.assert_allclose(np.asarray(bn._variance._value),
                               np.asarray(bn_cl._variance._value),
                               atol=1e-6)


def test_bn_rejects_bogus_data_format():
    with pytest.raises(ValueError, match="data_format"):
        nn.BatchNorm2D(4, data_format="HWCN")


def test_space_to_depth_layouts_agree():
    x = _img((2, 4, 8, 8))
    a = space_to_depth(Tensor(x), 2)
    b = space_to_depth(Tensor(jnp.transpose(x, (0, 2, 3, 1))), 2,
                       data_format="NHWC")
    np.testing.assert_allclose(
        np.asarray(a._value),
        np.asarray(b.transpose([0, 3, 1, 2])._value))


# ---------------------------------------------------------------------------
# ResNet-50 end to end
# ---------------------------------------------------------------------------

def test_resnet50_eval_forward_parity():
    x = _img()
    paddle.seed(0)
    m1 = resnet50(num_classes=8, layout="NCHW")
    paddle.seed(0)
    m2 = resnet50(num_classes=8, layout="NHWC")
    m1.eval()
    m2.eval()
    o1, o2 = _fwd(m1, x), _fwd(m2, x)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                               atol=2e-4, rtol=1e-4)


def test_resnet50_train_forward_parity_and_stats():
    # 48px keeps layer4's spatial extent >1 so train-mode BN stats are
    # well-conditioned (at 32px the 2-sample variance amplifies fp
    # reduction-order noise chaotically through 16 blocks)
    x = _img((2, 3, 48, 48))
    paddle.seed(0)
    m1 = resnet50(num_classes=8, layout="NCHW")
    paddle.seed(0)
    m2 = resnet50(num_classes=8, layout="NHWC", fused_bottleneck=True)
    m1.train()
    m2.train()
    o1, nb1 = _fwd(m1, x, train=True)
    o2, nb2 = _fwd(m2, x, train=True)
    scale = float(np.abs(np.asarray(o1)).max())
    np.testing.assert_allclose(np.asarray(o1) / scale,
                               np.asarray(o2) / scale, atol=2e-3)
    # running stats (incl. the Gram-trick conv3 path) match the NCHW
    # reference update
    for k in ("bn1._mean", "layer2.0.bn3._mean",
              "layer2.0.bn3._variance", "layer4.2.bn3._variance"):
        a, b = np.asarray(nb1[k]), np.asarray(nb2[k])
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-3,
                                   err_msg=k)


def test_resnet50_s2d_stem_nhwc_parity():
    x = _img()
    paddle.seed(0)
    m1 = resnet50(num_classes=8, s2d_stem=True, layout="NCHW")
    paddle.seed(0)
    m2 = resnet50(num_classes=8, s2d_stem=True, layout="NHWC")
    m1.eval()
    m2.eval()
    np.testing.assert_allclose(np.asarray(_fwd(m1, x)),
                               np.asarray(_fwd(m2, x)),
                               atol=2e-4, rtol=1e-4)


def test_convert_after_build_matches_native_nhwc():
    # the pretrained-checkpoint path: build NCHW, then convert in place
    x = _img()
    paddle.seed(0)
    m1 = resnet50(num_classes=8, layout="NCHW")
    m1.eval()
    ref = _fwd(m1, x)
    m1.convert_to_nhwc()
    assert m1._layout == "NHWC"
    np.testing.assert_allclose(np.asarray(ref), np.asarray(_fwd(m1, x)),
                               atol=2e-4, rtol=1e-4)
    m1._arm_fused_bottleneck()
    np.testing.assert_allclose(np.asarray(ref), np.asarray(_fwd(m1, x)),
                               atol=2e-4, rtol=1e-4)


def test_layout_flag_validation():
    with pytest.raises(ValueError, match="layout"):
        resnet50(num_classes=4, layout="NDHW")
    with pytest.raises(ValueError, match="NHWC"):
        resnet50(num_classes=4, layout="NCHW", fused_bottleneck=True)


@pytest.mark.slow
def test_resnet50_grads_parity_both_layouts():
    """Full fwd+bwd in train mode, NCHW vs NHWC+fused. Tolerance is
    relative-to-scale: train BN batch-stat normalization amplifies fp
    reduction-order differences through 16 blocks (~1e-2 relative is
    layout-change noise, not a wiring bug — the block-level test in
    test_fused_conv_bn_act pins 1e-6)."""
    import paddle_tpu.nn.functional as F
    x = _img((4, 3, 64, 64))
    y = jnp.asarray(np.random.default_rng(1).integers(0, 8, (4,)))
    paddle.seed(0)
    m1 = resnet50(num_classes=8, layout="NCHW")
    paddle.seed(0)
    m2 = resnet50(num_classes=8, layout="NHWC", fused_bottleneck=True)
    m1.train()
    m2.train()

    def grads(m):
        params, buffers = m.raw_state()

        @jax.jit
        def g(p, b, a, lbl):
            def loss_fn(pp):
                out = functional_call(m, pp, b, Tensor(a))
                return F.cross_entropy(out, Tensor(lbl))._value
            return jax.grad(loss_fn)(p)
        return g(params, buffers, x, y)

    g1, g2 = grads(m1), grads(m2)
    for k in ("conv1.weight", "layer1.0.conv1.weight",
              "layer3.0.conv3.weight", "layer3.0.bn3.weight",
              "fc.weight"):
        a, b = np.asarray(g1[k]), np.asarray(g2[k])
        if a.ndim == 4:
            a = a.transpose(2, 3, 1, 0)
        scale = max(1.0, np.abs(a).max())
        np.testing.assert_allclose(a / scale, b / scale, atol=2e-2,
                                   err_msg=k)
