"""paddle.device / version / rng-state / distributed group / amp caps."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestDevice:
    def test_enumeration(self):
        assert "cpu" in paddle.device.get_all_device_type()
        devs = paddle.device.get_available_device()
        assert len(devs) == paddle.device.device_count() >= 1

    def test_cuda_namespace(self):
        paddle.device.cuda.synchronize()
        paddle.device.cuda.empty_cache()
        assert paddle.device.cuda.memory_allocated() >= 0
        props = paddle.device.cuda.get_device_properties()
        assert "platform" in props

    def test_compiled_flags(self):
        assert paddle.device.is_compiled_with_cuda() is False


class TestRngState:
    def test_roundtrip(self):
        st = paddle.get_rng_state()
        a = np.asarray(paddle.rand([8]).numpy())
        _ = paddle.rand([8])  # advance further
        paddle.set_rng_state(st)
        b = np.asarray(paddle.rand([8]).numpy())
        assert np.allclose(a, b)

    def test_cuda_aliases(self):
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)


class TestDistributedShims:
    def test_group(self):
        g = paddle.distributed.get_group()
        assert g.nranks == 8  # the virtual CPU mesh
        assert paddle.distributed.destroy_process_group() is None

    def test_rpc_gate(self):
        with pytest.raises(NotImplementedError, match="Mesh"):
            paddle.distributed.rpc.init_rpc("worker0")


class TestVersionAmp:
    def test_version(self):
        assert paddle.version.full_version == paddle.__version__
        paddle.version.show()
        assert paddle.version.cuda() == "False"

    def test_amp_caps(self):
        assert paddle.amp.is_bfloat16_supported()
        assert paddle.amp.is_float16_supported()


class TestDistributedExtras:
    def test_object_collectives(self):
        import paddle_tpu.distributed as dist
        lst = []
        dist.all_gather_object(lst, {"k": 7})
        assert lst[0]["k"] == 7
        objs = ["a", "b"]
        assert dist.broadcast_object_list(objs) is objs

    def test_stream_namespace(self):
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        x = paddle.to_tensor(np.ones(4, np.float32))
        dist.stream.all_reduce(x)
        out = []
        dist.stream.all_gather(out, x)
        dist.stream.broadcast(x, 0)
        assert np.allclose(np.asarray(x.numpy()), 1.0)
