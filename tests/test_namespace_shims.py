"""paddle.device / version / rng-state / distributed group / amp caps."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestDevice:
    def test_enumeration(self):
        assert "cpu" in paddle.device.get_all_device_type()
        devs = paddle.device.get_available_device()
        assert len(devs) == paddle.device.device_count() >= 1

    def test_cuda_namespace(self):
        paddle.device.cuda.synchronize()
        paddle.device.cuda.empty_cache()
        assert paddle.device.cuda.memory_allocated() >= 0
        props = paddle.device.cuda.get_device_properties()
        assert "platform" in props

    def test_compiled_flags(self):
        assert paddle.device.is_compiled_with_cuda() is False


class TestRngState:
    def test_roundtrip(self):
        st = paddle.get_rng_state()
        a = np.asarray(paddle.rand([8]).numpy())
        _ = paddle.rand([8])  # advance further
        paddle.set_rng_state(st)
        b = np.asarray(paddle.rand([8]).numpy())
        assert np.allclose(a, b)

    def test_cuda_aliases(self):
        st = paddle.get_cuda_rng_state()
        paddle.set_cuda_rng_state(st)


class TestDistributedShims:
    def test_group(self):
        g = paddle.distributed.get_group()
        assert g.nranks == 8  # the virtual CPU mesh
        assert paddle.distributed.destroy_process_group() is None

    def test_rpc_gate(self):
        with pytest.raises(NotImplementedError, match="Mesh"):
            paddle.distributed.rpc.init_rpc("worker0")


class TestVersionAmp:
    def test_version(self):
        assert paddle.version.full_version == paddle.__version__
        paddle.version.show()
        assert paddle.version.cuda() == "False"

    def test_amp_caps(self):
        assert paddle.amp.is_bfloat16_supported()
        assert paddle.amp.is_float16_supported()


class TestDistributedExtras:
    def test_object_collectives(self):
        import paddle_tpu.distributed as dist
        lst = []
        dist.all_gather_object(lst, {"k": 7})
        assert lst[0]["k"] == 7
        objs = ["a", "b"]
        assert dist.broadcast_object_list(objs) is objs

    def test_stream_namespace(self):
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        x = paddle.to_tensor(np.ones(4, np.float32))
        dist.stream.all_reduce(x)
        out = []
        dist.stream.all_gather(out, x)
        dist.stream.broadcast(x, 0)
        assert np.allclose(np.asarray(x.numpy()), 1.0)


class TestJitLrCallbackExtras:
    def test_linear_lr(self):
        from paddle_tpu.optimizer.lr import LinearLR
        s = LinearLR(0.1, total_steps=4, start_factor=0.5)
        vals = [s()]
        for _ in range(4):
            s.step()
            vals.append(s())
        assert np.isclose(vals[0], 0.05)
        assert np.isclose(vals[-1], 0.1)
        # holds at end_factor past total_steps
        s.step()
        assert np.isclose(s(), 0.1)

    def test_enable_to_static_toggle(self):
        import jax

        @paddle.jit.to_static
        def f(x):
            return x * 2

        x = paddle.to_tensor(np.ones(2, np.float32))
        assert np.allclose(np.asarray(f(x).numpy()), 2.0)
        paddle.jit.enable_to_static(False)
        try:
            out = f(x)  # eager path
            assert np.allclose(np.asarray(out.numpy()), 2.0)
        finally:
            paddle.jit.enable_to_static(True)

    def test_wandb_callback_fallback_records_metrics(self, tmp_path):
        # regression: the fallback wrote an empty file and list-valued
        # logs (Model.fit's format) were dropped entirely
        import json
        import os
        from paddle_tpu.hapi.callbacks import WandbCallback
        cbk = WandbCallback(project="p", dir=str(tmp_path))
        assert cbk.model is None and cbk.params == {}  # base init ran
        cbk.on_train_begin({})
        cbk.on_train_batch_end(0, {"loss": [0.7]})
        cbk.on_epoch_end(0, {"loss": [0.5], "acc": 0.9})
        cbk.on_train_end({})
        path = os.path.join(str(tmp_path), "events.jsonl")
        recs = [json.loads(l) for l in open(path)]
        assert any(r.get("loss") == 0.7 for r in recs)
        assert any(r.get("event") == "epoch" and r.get("loss") == 0.5
                   and r.get("acc") == 0.9 for r in recs)

    def test_lazy_guard_gate(self):
        with pytest.raises(NotImplementedError, match="shard_model"):
            with paddle.LazyGuard():
                pass
