"""paddle.distribution parity tests — closed forms vs scipy/numpy,
sampling moments, KL registry, transforms, jit-compat."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestNormal:
    def test_log_prob_entropy_cdf(self):
        d = D.Normal(1.0, 2.0)
        x = 0.5
        ref = -((x - 1.0) ** 2) / 8 - math.log(2.0) - 0.5 * math.log(2 * math.pi)
        assert np.allclose(_np(d.log_prob(x)), ref, atol=1e-6)
        assert np.allclose(_np(d.entropy()),
                           0.5 + 0.5 * math.log(2 * math.pi) + math.log(2.0))
        assert np.allclose(_np(d.cdf(1.0)), 0.5, atol=1e-6)
        assert np.allclose(_np(d.icdf(d.cdf(x))), x, atol=1e-5)

    def test_sample_moments(self):
        paddle.seed(0)
        d = D.Normal(3.0, 0.5)
        s = _np(d.sample((20000,)))
        assert s.shape == (20000,)
        assert abs(s.mean() - 3.0) < 0.02
        assert abs(s.std() - 0.5) < 0.02

    def test_rsample_pathwise_grad(self):
        paddle.seed(0)
        loc = paddle.to_tensor(2.0, stop_gradient=False)

        def f(l):
            d = D.Normal(l, paddle.to_tensor(1.0))
            return (d.rsample((256,)) ** 2).mean()

        # E[(l+eps)^2] -> d/dl = 2l
        g = paddle.grad(f(loc), loc)[0]
        assert abs(float(g) - 4.0) < 0.3

    def test_kl(self):
        p, q = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        ref = (math.log(2.0) + (1 + 1) / (2 * 4) - 0.5)
        assert np.allclose(_np(D.kl_divergence(p, q)), ref, atol=1e-6)
        assert np.allclose(_np(p.kl_divergence(q)), ref, atol=1e-6)


class TestUniform:
    def test_basics(self):
        d = D.Uniform(1.0, 3.0)
        assert np.allclose(_np(d.mean), 2.0)
        assert np.allclose(_np(d.variance), 4.0 / 12)
        assert np.allclose(_np(d.log_prob(2.0)), -math.log(2.0))
        assert np.isneginf(_np(d.log_prob(3.5)))
        assert np.allclose(_np(d.entropy()), math.log(2.0))
        paddle.seed(1)
        s = _np(d.sample((4000,)))
        assert s.min() >= 1.0 and s.max() < 3.0
        assert abs(s.mean() - 2.0) < 0.05


class TestBetaGammaDirichlet:
    def test_beta(self):
        d = D.Beta(2.0, 3.0)
        assert np.allclose(_np(d.mean), 0.4)
        # B(2,3) = 1/12 → pdf(x) = 12 x (1-x)^2
        assert np.allclose(_np(d.prob(0.5)), 12 * 0.5 * 0.25, atol=1e-5)
        paddle.seed(2)
        s = _np(d.sample((8000,)))
        assert abs(s.mean() - 0.4) < 0.02

    def test_gamma(self):
        d = D.Gamma(3.0, 2.0)
        assert np.allclose(_np(d.mean), 1.5)
        assert np.allclose(_np(d.variance), 0.75)
        # pdf(x) = r^a x^(a-1) e^(-rx) / Γ(a)
        x = 1.2
        ref = (2.0 ** 3) * x ** 2 * math.exp(-2 * x) / math.gamma(3.0)
        assert np.allclose(_np(d.prob(x)), ref, atol=1e-5)
        assert np.allclose(_np(D.kl_divergence(d, d)), 0.0, atol=1e-6)

    def test_dirichlet(self):
        c = np.array([1.0, 2.0, 3.0], dtype=np.float32)
        d = D.Dirichlet(paddle.to_tensor(c))
        assert np.allclose(_np(d.mean), c / 6.0, atol=1e-6)
        paddle.seed(3)
        s = _np(d.sample((5000,)))
        assert s.shape == (5000, 3)
        assert np.allclose(s.sum(-1), 1.0, atol=1e-5)
        assert np.allclose(s.mean(0), c / 6.0, atol=0.02)
        assert np.allclose(_np(D.kl_divergence(d, d)), 0.0, atol=1e-5)


class TestLaplaceGumbelCauchyStudentT:
    def test_laplace(self):
        d = D.Laplace(0.0, 1.0)
        assert np.allclose(_np(d.log_prob(0.0)), -math.log(2.0))
        assert np.allclose(_np(d.cdf(0.0)), 0.5)
        assert np.allclose(_np(d.icdf(0.8)), -math.log(2 * 0.2), atol=1e-5)
        paddle.seed(4)
        s = _np(d.sample((20000,)))
        assert abs(s.mean()) < 0.05
        assert abs(s.var() - 2.0) < 0.15

    def test_gumbel(self):
        d = D.Gumbel(1.0, 2.0)
        euler = 0.5772156649
        assert np.allclose(_np(d.mean), 1.0 + 2.0 * euler, atol=1e-5)
        paddle.seed(5)
        s = _np(d.sample((20000,)))
        assert abs(s.mean() - (1 + 2 * euler)) < 0.1
        assert np.allclose(_np(D.kl_divergence(d, d)), 0.0, atol=1e-5)

    def test_gumbel_kl_different_locs(self):
        # regression: exponent sign + missing (pl-ql)/qs linear term.
        # reference value by numerical integration of p*log(p/q)
        p, q = D.Gumbel(1.0, 1.0), D.Gumbel(0.0, 1.0)
        xs = np.linspace(-8, 20, 200001)
        lp = -(xs - 1.0) - np.exp(-(xs - 1.0))
        lq = -xs - np.exp(-xs)
        ref = np.trapezoid(np.exp(lp) * (lp - lq), xs)
        assert np.allclose(_np(D.kl_divergence(p, q)), ref, atol=1e-4)
        p2, q2 = D.Gumbel(0.5, 2.0), D.Gumbel(-0.5, 1.5)
        lp2 = -np.log(2.0) - (xs - 0.5) / 2 - np.exp(-(xs - 0.5) / 2)
        lq2 = -np.log(1.5) - (xs + 0.5) / 1.5 - np.exp(-(xs + 0.5) / 1.5)
        ref2 = np.trapezoid(np.exp(lp2) * (lp2 - lq2), xs)
        assert np.allclose(_np(D.kl_divergence(p2, q2)), ref2, atol=1e-3)

    def test_cauchy(self):
        d = D.Cauchy(0.0, 1.0)
        assert np.allclose(_np(d.prob(0.0)), 1 / math.pi, atol=1e-6)
        assert np.allclose(_np(d.cdf(1.0)), 0.75, atol=1e-6)
        assert np.allclose(_np(d.entropy()), math.log(4 * math.pi), atol=1e-5)
        with pytest.raises(ValueError):
            d.mean

    def test_student_t(self):
        d = D.StudentT(5.0, 0.0, 1.0)
        assert np.allclose(_np(d.variance), 5.0 / 3.0, atol=1e-5)
        # t(0; df) = Γ((df+1)/2) / (sqrt(df π) Γ(df/2))
        ref = math.gamma(3.0) / (math.sqrt(5 * math.pi) * math.gamma(2.5))
        assert np.allclose(_np(d.prob(0.0)), ref, atol=1e-5)


class TestDiscrete:
    def test_bernoulli(self):
        d = D.Bernoulli(0.3)
        assert np.allclose(_np(d.log_prob(1.0)), math.log(0.3), atol=1e-6)
        assert np.allclose(_np(d.log_prob(0.0)), math.log(0.7), atol=1e-6)
        ent = -(0.3 * math.log(0.3) + 0.7 * math.log(0.7))
        assert np.allclose(_np(d.entropy()), ent, atol=1e-6)
        paddle.seed(6)
        s = _np(d.sample((20000,)))
        assert abs(s.mean() - 0.3) < 0.01
        q = D.Bernoulli(0.5)
        ref = 0.3 * math.log(0.3 / 0.5) + 0.7 * math.log(0.7 / 0.5)
        assert np.allclose(_np(D.kl_divergence(d, q)), ref, atol=1e-5)

    def test_categorical_reference_quirk(self):
        # scores normalized by sum (the reference's convention)
        d = D.Categorical(paddle.to_tensor([1.0, 2.0, 1.0]))
        assert np.allclose(_np(d.probs(paddle.to_tensor(1))), 0.5, atol=1e-6)
        paddle.seed(7)
        s = _np(d.sample((8000,)))
        frac1 = (s == 1).mean()
        assert abs(frac1 - 0.5) < 0.03
        ent = -(0.25 * math.log(0.25) * 2 + 0.5 * math.log(0.5))
        assert np.allclose(_np(d.entropy()), ent, atol=1e-5)

    def test_categorical_kl_and_from_logits(self):
        p = D.Categorical.from_logits(paddle.to_tensor([0.0, 0.0]))
        q = D.Categorical(paddle.to_tensor([1.0, 3.0]))
        ref = 0.5 * math.log(0.5 / 0.25) + 0.5 * math.log(0.5 / 0.75)
        assert np.allclose(_np(D.kl_divergence(p, q)), ref, atol=1e-5)

    def test_multinomial(self):
        d = D.Multinomial(10, paddle.to_tensor([0.2, 0.3, 0.5]))
        assert np.allclose(_np(d.mean), [2.0, 3.0, 5.0], atol=1e-5)
        paddle.seed(8)
        s = _np(d.sample((2000,)))
        assert s.shape == (2000, 3)
        assert np.allclose(s.sum(-1), 10.0)
        assert np.allclose(s.mean(0), [2, 3, 5], atol=0.2)
        # pmf of (2,3,5): 10!/(2!3!5!) 0.2^2 0.3^3 0.5^5
        coef = math.factorial(10) / (2 * 6 * 120)
        ref = math.log(coef * 0.2 ** 2 * 0.3 ** 3 * 0.5 ** 5)
        v = paddle.to_tensor([2.0, 3.0, 5.0])
        assert np.allclose(_np(d.log_prob(v)), ref, atol=1e-5)

    def test_geometric_poisson_binomial(self):
        g = D.Geometric(0.25)
        assert np.allclose(_np(g.mean), 3.0)
        assert np.allclose(_np(g.log_prob(2.0)),
                           math.log(0.75 ** 2 * 0.25), atol=1e-6)
        p = D.Poisson(4.0)
        assert np.allclose(_np(p.log_prob(3.0)),
                           math.log(math.exp(-4) * 4 ** 3 / 6), atol=1e-5)
        paddle.seed(9)
        s = _np(p.sample((10000,)))
        assert abs(s.mean() - 4.0) < 0.1
        b = D.Binomial(8, 0.5)
        assert np.allclose(_np(b.log_prob(4.0)),
                           math.log(70 / 256), atol=1e-5)
        ref_kl = 4.0 * (math.log(4.0 / 2.0)) - 4.0 + 2.0
        assert np.allclose(_np(D.kl_divergence(D.Poisson(4.0), D.Poisson(2.0))),
                           ref_kl, atol=1e-5)


class TestTransforms:
    def test_affine_exp_roundtrip(self):
        t = D.ChainTransform([D.AffineTransform(1.0, 2.0), D.ExpTransform()])
        x = paddle.to_tensor([0.1, -0.3, 0.7])
        y = t.forward(x)
        assert np.allclose(_np(t.inverse(y)), _np(x), atol=1e-6)
        # fldj = log|2| + (1 + 2x)
        ref = math.log(2.0) + (1 + 2 * _np(x))
        assert np.allclose(_np(t.forward_log_det_jacobian(x)), ref, atol=1e-5)

    def test_tanh_sigmoid_stable(self):
        for t in (D.TanhTransform(), D.SigmoidTransform()):
            x = paddle.to_tensor([-3.0, 0.0, 3.0])
            y = t.forward(x)
            assert np.allclose(_np(t.inverse(y)), _np(x), atol=1e-4)
            # fldj matches autodiff of forward
            import jax
            import jax.numpy as jnp
            g = jax.vmap(jax.grad(lambda v: t._forward(v)))(
                jnp.asarray(_np(x)))
            assert np.allclose(_np(t.forward_log_det_jacobian(x)),
                               np.log(np.abs(np.asarray(g))), atol=1e-5)

    def test_stick_breaking(self):
        t = D.StickBreakingTransform()
        x = paddle.to_tensor([0.3, -0.2, 0.5])
        y = _np(t.forward(x))
        assert y.shape == (4,)
        assert np.allclose(y.sum(), 1.0, atol=1e-6)
        assert (y > 0).all()
        assert np.allclose(_np(t.inverse(paddle.to_tensor(y))), _np(x),
                           atol=1e-5)

    def test_transformed_distribution_lognormal(self):
        paddle.seed(11)
        base = D.Normal(0.0, 0.25)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ln = D.LogNormal(0.0, 0.25)
        x = 1.3
        assert np.allclose(_np(td.log_prob(x)), _np(ln.log_prob(x)),
                           atol=1e-5)
        s = _np(td.sample((20000,)))
        assert abs(s.mean() - math.exp(0.25 ** 2 / 2)) < 0.02

    def test_transformed_event_rank_change(self):
        # regression: StickBreaking over a factored Normal must produce a
        # SCALAR log_prob (base reduced over the transform's domain event
        # dim), and event_shape must reflect the K-simplex output
        base = D.Normal(np.zeros(3, np.float32), np.ones(3, np.float32))
        td = D.TransformedDistribution(base, [D.StickBreakingTransform()])
        assert td.event_shape == (4,)
        paddle.seed(12)
        s = td.sample()
        assert tuple(s.shape) == (4,)
        lp = td.log_prob(s)
        assert _np(lp).shape == ()
        # numerical check vs change of variables computed by hand
        t = D.StickBreakingTransform()
        x = _np(t.inverse(s))
        base_lp = sum(-0.5 * x ** 2 - 0.5 * math.log(2 * math.pi))
        fldj = _np(t.forward_log_det_jacobian(paddle.to_tensor(x)))
        assert np.allclose(_np(lp), base_lp - fldj, atol=1e-5)

    def test_transformed_log_prob_grad_reaches_base_params(self):
        # regression: log_prob was one fused apply_op over `value`, so the
        # base distribution's params entered as constants and eager grads
        # never reached them
        loc = paddle.to_tensor(0.5, stop_gradient=False)
        td = D.TransformedDistribution(D.Normal(loc, paddle.to_tensor(1.0)),
                                       [D.ExpTransform()])
        g = paddle.grad(td.log_prob(2.0), loc)[0]
        # d/dloc log N(log 2; loc, 1) = (log 2 - loc)
        assert np.allclose(_np(g), math.log(2.0) - 0.5, atol=1e-5)

    def test_independent(self):
        d = D.Independent(D.Normal(np.zeros(3, np.float32),
                                   np.ones(3, np.float32)), 1)
        assert d.batch_shape == ()
        assert d.event_shape == (3,)
        lp = _np(d.log_prob(paddle.to_tensor([0.0, 0.0, 0.0])))
        assert np.allclose(lp, 3 * (-0.5 * math.log(2 * math.pi)), atol=1e-5)


class TestJitCompat:
    def test_log_prob_inside_jit(self):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(loc, x):
            d = D.Normal(loc, 1.0)
            return d.log_prob(x)._value

        out = f(jnp.float32(0.0), jnp.float32(1.0))
        assert np.allclose(np.asarray(out), -0.5 - 0.5 * math.log(2 * math.pi),
                           atol=1e-6)

    def test_rsample_in_traced_step(self):
        # sampling inside an rng_scope'd traced fn (Engine-style) works and
        # is a pure function of the scope key
        import jax
        from paddle_tpu import framework

        def step(key):
            with framework.rng_scope(key):
                return D.Normal(0.0, 1.0).rsample((4,))._value

        a = jax.jit(step)(jax.random.PRNGKey(0))
        b = jax.jit(step)(jax.random.PRNGKey(0))
        c = jax.jit(step)(jax.random.PRNGKey(1))
        assert np.allclose(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))
