"""GroupSharded (ZeRO 1/2/3) on the virtual 8-device mesh: numerics match
the unsharded engine; state is actually partitioned (SURVEY §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding

import paddle_tpu as paddle
from paddle_tpu.distributed.sharding import group_sharded_parallel
from paddle_tpu.hapi.engine import Engine


def _mesh():
    return Mesh(np.array(jax.devices()), ("dp",))


def _model():
    paddle.seed(7)
    return paddle.nn.Sequential(
        paddle.nn.Linear(16, 64), paddle.nn.ReLU(), paddle.nn.Linear(64, 8))


def _data(steps=4, batch=16):
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((steps, batch, 16)).astype(np.float32)
    ys = rng.integers(0, 8, (steps, batch)).astype(np.int64)
    return xs, ys


def _run(level, mesh):
    net = _model()
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    if level is not None:
        net, opt, _ = group_sharded_parallel(net, opt, level=level,
                                             mesh=mesh)
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt,
                 mesh=mesh)
    losses = []
    for x, y in zip(*_data()):
        loss, _ = eng.train_batch([jnp.asarray(x)], [jnp.asarray(y)])
        losses.append(float(loss))
    return losses, eng


@pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
def test_zero_levels_match_unsharded(level):
    mesh = _mesh()
    ref_losses, _ = _run(None, mesh)
    got_losses, eng = _run(level, mesh)
    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-4, atol=1e-4)
    # opt state moments must actually be partitioned over dp
    leaves = [l for l in jax.tree_util.tree_leaves(eng._opt_state)
              if hasattr(l, "sharding") and l.ndim >= 1
              and max(l.shape) % mesh.shape["dp"] == 0
              and max(l.shape) >= mesh.shape["dp"]]
    assert leaves, "no shardable opt-state leaves found"
    assert any(
        isinstance(l.sharding, NamedSharding)
        and "dp" in jax.tree_util.tree_leaves(tuple(l.sharding.spec))
        for l in leaves), "opt state not sharded over dp"


def test_stage3_params_sharded():
    mesh = _mesh()
    _, eng = _run("p_g_os", mesh)
    sharded = [k for k, v in eng._params.items()
               if isinstance(getattr(v, "sharding", None), NamedSharding)
               and "dp" in jax.tree_util.tree_leaves(tuple(v.sharding.spec))]
    assert sharded, "no parameters sharded over dp at stage 3"


def test_bad_level_raises():
    with pytest.raises(ValueError):
        group_sharded_parallel(_model(), paddle.optimizer.SGD(0.1),
                               level="zero9", mesh=_mesh())


def test_fleet_sharding_strategy_routes_to_group_sharded():
    import paddle_tpu.distributed.fleet as fleet
    strat = fleet.DistributedStrategy()
    strat.sharding = True
    strat.sharding_configs = {"stage": 2}
    fleet.fleet_obj.init(is_collective=True, strategy=strat)
    opt = paddle.optimizer.AdamW(1e-3, parameters=_model().parameters())
    opt = fleet.fleet_obj.distributed_optimizer(opt)
    assert opt._group_sharded.level == "os_g"


def test_eager_step_applies_sharding():
    """group_sharded_parallel must shard even in the eager
    loss.backward(); opt.step() flow (the reference's primary usage)."""
    mesh = _mesh()
    net = _model()
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    net, opt, _ = group_sharded_parallel(net, opt, level="os_g", mesh=mesh)
    x = paddle.to_tensor(np.random.default_rng(1).standard_normal(
        (16, 16)).astype(np.float32))
    y = paddle.to_tensor(np.arange(16) % 8)
    loss = paddle.nn.CrossEntropyLoss()(net(x), y)
    loss.backward()
    opt.step()
    leaves = [l for l in jax.tree_util.tree_leaves(opt._func_state)
              if hasattr(l, "sharding") and l.ndim >= 1]
    assert any(
        isinstance(l.sharding, NamedSharding)
        and "dp" in jax.tree_util.tree_leaves(tuple(l.sharding.spec))
        for l in leaves), "eager opt state not sharded over dp"


def test_resume_reapplies_sharding():
    """load_opt_state_dict must re-apply ZeRO placement (resume path)."""
    mesh = _mesh()
    _, eng = _run("p_g_os", mesh)
    saved = jax.device_get(eng.opt_state_dict())

    net = _model()
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    net, opt, _ = group_sharded_parallel(net, opt, level="p_g_os", mesh=mesh)
    eng2 = Engine(net, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt,
                  mesh=mesh)
    eng2.load_opt_state_dict(saved)
    x, y = (a[0] for a in _data())
    eng2.train_batch([jnp.asarray(x)], [jnp.asarray(y)])
    leaves = [l for l in jax.tree_util.tree_leaves(eng2._opt_state)
              if hasattr(l, "sharding") and l.ndim >= 1]
    assert any(
        isinstance(l.sharding, NamedSharding)
        and "dp" in jax.tree_util.tree_leaves(tuple(l.sharding.spec))
        for l in leaves), "resumed opt state not sharded over dp"


def test_save_group_sharded_model_writes_opt_state(tmp_path):
    from paddle_tpu.distributed.sharding import save_group_sharded_model
    mesh = _mesh()
    _, eng = _run("os", mesh)
    out = tmp_path / "ckpt"
    save_group_sharded_model(eng.network, str(out), optimizer=eng.optimizer)
    assert (tmp_path / "ckpt.pdparams").exists()
    assert (tmp_path / "ckpt.pdopt").exists()


def test_eval_batch_shards_over_dp():
    """VERDICT r2 weak #4: eval_batch must shard the batch over dp like
    train_batch does, so Model.evaluate keeps data parallelism."""
    from jax.sharding import PartitionSpec as P
    mesh = _mesh()
    net = _model()
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.AdamW(
                     1e-2, parameters=net.parameters()),
                 mesh=mesh)
    xs, ys = _data(steps=1)
    sharded = eng._shard_batch([jnp.asarray(xs[0])])
    assert isinstance(sharded[0].sharding, NamedSharding)
    assert tuple(sharded[0].sharding.spec) == ("dp",)
    # numerics: mesh eval == no-mesh eval
    loss_m, outs_m = eng.eval_batch([jnp.asarray(xs[0])],
                                    [jnp.asarray(ys[0])])
    net2 = _model()
    eng2 = Engine(net2, loss=paddle.nn.CrossEntropyLoss(),
                  optimizer=paddle.optimizer.AdamW(
                      1e-2, parameters=net2.parameters()))
    loss_s, outs_s = eng2.eval_batch([jnp.asarray(xs[0])],
                                     [jnp.asarray(ys[0])])
    np.testing.assert_allclose(float(loss_m), float(loss_s),
                               rtol=1e-5, atol=1e-6)
    # the eval output itself must come back dp-sharded (not replicated)
    out = outs_m[0] if isinstance(outs_m, (list, tuple)) else outs_m
    assert "dp" in jax.tree_util.tree_leaves(tuple(out.sharding.spec)) \
        or out.sharding.is_fully_replicated is False


def test_eval_batch_ragged_falls_back_replicated():
    """A final eval batch not divisible by dp must not crash — it runs
    replicated instead (review fix)."""
    mesh = _mesh()
    net = _model()
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.AdamW(
                     1e-2, parameters=net.parameters()),
                 mesh=mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((10, 16)), jnp.float32)  # 10 % 8 != 0
    y = jnp.asarray(rng.integers(0, 8, (10,)))
    loss, outs = eng.eval_batch([x], [y])
    assert np.isfinite(float(loss))


def test_train_batch_ragged_raises_loudly():
    """A non-dp-divisible TRAIN batch must fail with a clear error, not
    silently drop data parallelism (review fix)."""
    import pytest as _pytest
    mesh = _mesh()
    net = _model()
    eng = Engine(net, loss=paddle.nn.CrossEntropyLoss(),
                 optimizer=paddle.optimizer.AdamW(
                     1e-2, parameters=net.parameters()),
                 mesh=mesh)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((10, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (10,)))
    with _pytest.raises(ValueError, match="not divisible by the dp"):
        eng.train_batch([x], [y])
