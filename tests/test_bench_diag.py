"""The driver's one trusted artifact is bench.py's FINAL stdout line.

r1-r4 all recorded parsed:null; r4's cause was self-inflicted — the
probe-failure diagnostic embedded every prior campaign stage payload and
the line outgrew the driver's tail capture, truncating mid-JSON. These
tests pin the contract: on probe failure the final line is COMPACT
(bounded size), parses as JSON, carries value:null honestly, and points
at (not embeds) the full payload, which goes to a file.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


@pytest.fixture(scope="module")
def probe_fail_run(tmp_path_factory):
    env = dict(os.environ)
    # An unloadable backend makes the probe worker die fast and
    # deterministically (no tunnel dependence either way).
    env["JAX_PLATFORMS"] = "no_such_backend"
    env["BENCH_PROBE_TIMEOUT"] = "60"
    env["BENCH_WORK_TIMEOUT"] = "60"
    # CAMPAIGN_CHILD skips the chip-ownership preemption: this test must
    # never SIGKILL a real in-flight campaign stage.
    env["CAMPAIGN_CHILD"] = "1"
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=180)
    return proc


def _last_json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    assert lines, "bench.py printed nothing to stdout"
    return lines[-1]


def test_final_line_parses_and_is_compact(probe_fail_run):
    line = _last_json_line(probe_fail_run.stdout)
    # the r4 failure mode: a final line too large for the driver's
    # capture. 6000 bytes is bench.py's own belt-and-braces cap.
    assert len(line) <= 6000, f"final line is {len(line)} bytes"
    diag = json.loads(line)
    assert diag["value"] is None
    assert diag["metric"] == "gpt_pretrain_tokens_per_sec_per_chip"
    assert "error" in diag
    assert probe_fail_run.returncode == 2


def test_earlier_measurements_are_pointers_not_payload(probe_fail_run):
    diag = json.loads(_last_json_line(probe_fail_run.stdout))
    em = diag.get("earlier_session_measurements")
    if em is None:
        pytest.skip("no committed campaign summaries on this checkout")
    # pointers to artifacts, never embedded stage payloads
    assert "stages" not in em
    assert isinstance(em.get("artifacts"), list)
    for name, row in (em.get("headline_scalars") or {}).items():
        for v in row.values():
            assert not isinstance(v, (dict, list)), (
                f"{name} embeds a nested payload in the final line")
    full = em.get("full_diag")
    if full:
        with open(os.path.join(REPO, full)) as f:
            payload = json.load(f)
        assert "stages" in payload  # the real payload lives in the file


def test_every_stdout_json_line_parses(probe_fail_run):
    # incremental-flush contract: anything bench.py prints to stdout
    # that looks like JSON must BE JSON (the driver tails stdout)
    for ln in probe_fail_run.stdout.strip().splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            json.loads(ln)


def test_recompile_contaminated_decode_scalars_excluded(probe_fail_run):
    """VERDICT r5 weak #3: the r4 window's decode stages timed
    recompiles, not decode — their scalars must NOT ride in
    headline_scalars. They are named (with the reason) instead, so the
    artifact stays honest without looking like the stages never ran."""
    diag = json.loads(_last_json_line(probe_fail_run.stdout))
    em = diag.get("earlier_session_measurements")
    if em is None:
        pytest.skip("no committed campaign summaries on this checkout")
    for name, row in (em.get("headline_scalars") or {}).items():
        assert row.get("metric") != "gpt_decode_tokens_per_sec_per_chip", (
            f"{name} presents an invalidated decode scalar as a "
            "headline number")
    excl = em.get("excluded_decode_stages")
    if excl is not None:  # present whenever decode stages were parsed
        assert excl["stages"], "exclusion note without stage names"
        assert "recompile" in excl["reason"]
