"""The driver's one trusted artifact is bench.py's FINAL stdout line.

r1-r4 all recorded parsed:null; r4's cause was self-inflicted — the
probe-failure diagnostic embedded every prior campaign stage payload and
the line outgrew the driver's tail capture, truncating mid-JSON. These
tests pin the contract: on probe failure the final line is COMPACT
(bounded size), parses as JSON, carries value:null honestly, and points
at (not embeds) the full payload, which goes to a file.

NEVER-SKIP (VERDICT r5 #8): every test here runs on every checkout —
the campaign summaries the diagnostic reads come from a fixture dir
via BENCH_CAMPAIGN_DIR, not from whatever artifacts happen to be
committed.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


# one pre-memoization epoch (< bench.py's decode_valid_since cutoff)
# so the decode-exclusion branch is deterministically exercised
_OLD_WINDOW = 1785500000


@pytest.fixture(scope="module")
def probe_fail_run(tmp_path_factory):
    env = dict(os.environ)
    # An unloadable backend makes the probe worker die fast and
    # deterministically (no tunnel dependence either way).
    env["JAX_PLATFORMS"] = "no_such_backend"
    env["BENCH_PROBE_TIMEOUT"] = "60"
    env["BENCH_WORK_TIMEOUT"] = "60"
    # CAMPAIGN_CHILD skips the chip-ownership preemption: this test must
    # never SIGKILL a real in-flight campaign stage.
    env["CAMPAIGN_CHILD"] = "1"
    # NEVER-SKIP (VERDICT r5 #8): these tests used to depend on whatever
    # campaign summaries happened to be committed; a fixture campaign
    # dir (BENCH_CAMPAIGN_DIR) now guarantees the diagnostic's
    # earlier-measurements branch — one valid training scalar plus one
    # recompile-contaminated decode scalar — on every checkout. It also
    # keeps the run's bench_partial_* litter out of the real
    # campaign_out/.
    camp = tmp_path_factory.mktemp("campaign_fixture")
    with open(camp / f"summary_{_OLD_WINDOW}.json", "w") as f:
        json.dump({
            "_captured_at": {"epoch": _OLD_WINDOW},
            "bench_gpt": {"ok": True, "result": {
                "metric": "gpt_pretrain_tokens_per_sec_per_chip",
                "value": 32418.0, "unit": "tokens/s/chip",
                "vs_baseline": 9.26, "mfu": 0.4}},
            "bench_decode": {"ok": True, "result": {
                "metric": "gpt_decode_tokens_per_sec_per_chip",
                "value": 34.5, "unit": "tokens/s/chip",
                "vs_baseline": None}},
        }, f)
    env["BENCH_CAMPAIGN_DIR"] = str(camp)
    proc = subprocess.run(
        [sys.executable, BENCH], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=180)
    return proc


def _last_json_line(stdout):
    lines = [ln for ln in stdout.strip().splitlines() if ln.strip()]
    assert lines, "bench.py printed nothing to stdout"
    return lines[-1]


def test_final_line_parses_and_is_compact(probe_fail_run):
    line = _last_json_line(probe_fail_run.stdout)
    # the r4 failure mode: a final line too large for the driver's
    # capture. 6000 bytes is bench.py's own belt-and-braces cap.
    assert len(line) <= 6000, f"final line is {len(line)} bytes"
    diag = json.loads(line)
    assert diag["value"] is None
    assert diag["metric"] == "gpt_pretrain_tokens_per_sec_per_chip"
    assert "error" in diag
    assert probe_fail_run.returncode == 2


def test_earlier_measurements_are_pointers_not_payload(probe_fail_run):
    diag = json.loads(_last_json_line(probe_fail_run.stdout))
    # the fixture campaign dir guarantees this branch — never skipped
    em = diag["earlier_session_measurements"]
    # pointers to artifacts, never embedded stage payloads
    assert "stages" not in em
    assert isinstance(em.get("artifacts"), list)
    for name, row in (em.get("headline_scalars") or {}).items():
        for v in row.values():
            assert not isinstance(v, (dict, list)), (
                f"{name} embeds a nested payload in the final line")
    full = em.get("full_diag")
    if full:
        with open(os.path.join(REPO, full)) as f:
            payload = json.load(f)
        assert "stages" in payload  # the real payload lives in the file


def test_every_stdout_json_line_parses(probe_fail_run):
    # incremental-flush contract: anything bench.py prints to stdout
    # that looks like JSON must BE JSON (the driver tails stdout)
    for ln in probe_fail_run.stdout.strip().splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            json.loads(ln)


def test_recompile_contaminated_decode_scalars_excluded(probe_fail_run):
    """VERDICT r5 weak #3: the r4 window's decode stages timed
    recompiles, not decode — their scalars must NOT ride in
    headline_scalars. They are named (with the reason) instead, so the
    artifact stays honest without looking like the stages never ran."""
    diag = json.loads(_last_json_line(probe_fail_run.stdout))
    em = diag["earlier_session_measurements"]
    for name, row in (em.get("headline_scalars") or {}).items():
        assert row.get("metric") != "gpt_decode_tokens_per_sec_per_chip", (
            f"{name} presents an invalidated decode scalar as a "
            "headline number")
    # the fixture plants a pre-memoization decode stage, so the
    # exclusion note MUST be present and well-formed
    excl = em["excluded_decode_stages"]
    assert excl["stages"] == ["bench_decode"]
    assert "recompile" in excl["reason"]
    assert "bench_gpt" in (em.get("headline_scalars") or {}), (
        "the valid training scalar must still ride the final line")
