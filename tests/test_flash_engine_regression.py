"""Regression: the Pallas flash-attention kernel must be trainable through
the PRODUCTION path — F.scaled_dot_product_attention -> apply_op -> Engine's
jitted value_and_grad step.

Round 1 shipped with apply_op building a nested jax.vjp tape inside the
Engine's outer jax.grad trace; for jnp ops that was only compile bloat, but
for the custom_vjp Pallas kernel it crashed (_pallas_call_jvp_rule assert),
killing the TPU bench. On CPU the availability gate hid the bug because the
Pallas route is TPU-only. This test forces the gate on (the kernel then runs
in interpret mode on CPU, same trace/AD structure) and trains real Engine
steps.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.hapi.engine import Engine


@pytest.fixture
def force_flash(monkeypatch):
    import paddle_tpu.ops as ops_pkg
    import paddle_tpu.ops.attention as att

    def available(q_shape, k_shape, attn_mask, dropout_p):
        return attn_mask is None and not dropout_p and len(q_shape) == 4

    monkeypatch.setattr(att, "flash_attention_available", available)
    monkeypatch.setattr(ops_pkg, "flash_attention_available", available)


class TinyAttn(nn.Layer):
    def __init__(self, d_model=64, n_heads=2, seq=128):
        super().__init__()
        self.n_heads = n_heads
        self.qkv = nn.Linear(d_model, 3 * d_model)
        self.out = nn.Linear(d_model, d_model)
        self.head = nn.Linear(d_model, 1)

    def forward(self, x):
        import paddle_tpu.nn.functional as F
        b, s, d = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.n_heads,
                                   d // self.n_heads])
        q, k, v = (qkv[:, :, i] for i in range(3))
        o = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        o = o.reshape([b, s, d])
        return self.head(self.out(o)).mean(axis=[1, 2])


def test_engine_train_step_through_pallas_flash(force_flash):
    paddle.seed(0)
    net = TinyAttn()
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    eng = Engine(net, loss=nn.MSELoss(), optimizer=opt)
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(2, 128, 64).astype("float32"))
    y = paddle.to_tensor(rng.randn(2).astype("float32"))
    losses = [float(eng.train_batch([x], [y])[0]) for _ in range(4)]
    assert np.isfinite(losses).all()
    assert min(losses[1:]) < losses[0]


def test_eager_backward_through_pallas_flash(force_flash):
    """The eager tape path (outside any jax trace) must also differentiate
    the custom_vjp kernel."""
    import jax.numpy as jnp
    import paddle_tpu.nn.functional as F
    q = paddle.to_tensor(
        np.random.RandomState(1).randn(1, 128, 2, 64).astype("float32"),
        stop_gradient=False)
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    out.sum().backward()
    assert q.grad is not None
    assert bool(jnp.isfinite(q.grad._value).all())
