"""Fused residual-add + LayerNorm Pallas kernel: interpret-mode parity
vs the jnp reference (SURVEY §4 pallas test strategy), both outputs'
grads, the non-tiling fallback, and GPT integration (fused_ln=True ==
baseline through a train step)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.fused_ln import (_reference,
                                            fused_add_layer_norm)


def _inputs(shape=(4, 32, 64), dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    h = shape[-1]
    return (jax.random.normal(ks[0], shape, dtype),
            jax.random.normal(ks[1], shape, dtype),
            jax.random.normal(ks[2], (h,), dtype) * 0.1 + 1.0,
            jax.random.normal(ks[3], (h,), dtype) * 0.1)


@pytest.mark.parametrize("dtype,atol", [(jnp.float32, 1e-5),
                                        (jnp.bfloat16, 2e-2)])
def test_forward_parity(dtype, atol):
    x, r, g, b = _inputs(dtype=dtype)
    y, s = fused_add_layer_norm(x, r, g, b, 1e-5, 0, True)
    yr, sr = _reference(x, r, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32), atol=atol)
    np.testing.assert_allclose(np.asarray(s, np.float32),
                               np.asarray(sr, np.float32), atol=atol)


def test_grads_parity_both_outputs():
    x, r, g, b = _inputs()
    c1 = jax.random.normal(jax.random.PRNGKey(9), x.shape)
    c2 = jax.random.normal(jax.random.PRNGKey(10), x.shape)

    def loss_fused(x, r, g, b):
        y, s = fused_add_layer_norm(x, r, g, b, 1e-5, 0, True)
        return jnp.sum(y * c1) + jnp.sum(s * c2)

    def loss_ref(x, r, g, b):
        y, s = _reference(x, r, g, b, 1e-5)
        return jnp.sum(y * c1) + jnp.sum(s * c2)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, r, g, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, r, g, b)
    for a, bb, name in zip(gf, gr, "x r gamma beta".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=2e-4, rtol=2e-4, err_msg=name)


def test_non_tiling_rows_fall_back():
    # 7 rows can't tile to a multiple of 8 — must still be exact
    x, r, g, b = _inputs(shape=(7, 64))
    y, s = fused_add_layer_norm(x, r, g, b, 1e-5, 0, True)
    yr, sr = _reference(x, r, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-5)

    def loss(x, r, g, b):
        y, s = fused_add_layer_norm(x, r, g, b, 1e-5, 0, True)
        return jnp.sum(y * y) + jnp.sum(s)

    def loss_ref(x, r, g, b):
        y, s = _reference(x, r, g, b, 1e-5)
        return jnp.sum(y * y) + jnp.sum(s)

    gf = jax.grad(loss, argnums=(0, 2))(x, r, g, b)
    gr = jax.grad(loss_ref, argnums=(0, 2))(x, r, g, b)
    for a, bb in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=2e-4, rtol=2e-4)


def test_y_only_variant_matches_and_backprops():
    from paddle_tpu.ops.pallas.fused_ln import fused_add_layer_norm_y
    x, r, g, b = _inputs()
    y = fused_add_layer_norm_y(x, r, g, b, 1e-5, 0, True)
    yr, _ = _reference(x, r, g, b, 1e-5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr), atol=1e-5)

    def loss_y(x, r, g, b):
        return jnp.sum(jnp.square(
            fused_add_layer_norm_y(x, r, g, b, 1e-5, 0, True)))

    def loss_ref(x, r, g, b):
        return jnp.sum(jnp.square(_reference(x, r, g, b, 1e-5)[0]))

    gy = jax.grad(loss_y, argnums=(0, 1, 2, 3))(x, r, g, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, r, g, b)
    for a, bb, name in zip(gy, gr, "x r gamma beta".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   atol=2e-4, rtol=2e-4, err_msg=name)


def test_gpt_fused_ln_composes_with_scan_layers():
    # the kernel must trace inside the lax.scan body (1.3B runs
    # scan_layers=True; a fused-ln 1.3B A/B needs both together)
    import paddle_tpu as paddle
    from paddle_tpu.nlp.gpt import GPTConfig, GPTForCausalLM

    cfg = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
               num_attention_heads=4, max_position_embeddings=32,
               hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
               use_flash_attention=False)
    ids = jnp.asarray(np.arange(32).reshape(2, 16) % 128, jnp.int32)
    outs = {}
    for scan in (False, True):
        paddle.seed(4)
        m = GPTForCausalLM(GPTConfig(**cfg, fused_ln=True,
                                     scan_layers=scan))
        m.eval()
        outs[scan] = np.asarray(m(ids)._value)
    np.testing.assert_allclose(outs[True], outs[False], atol=2e-5,
                               rtol=2e-5)


def test_bert_fused_ln_matches_baseline():
    # post-LN: BOTH block sites fuse; forward must be bit-comparable
    import paddle_tpu as paddle
    from paddle_tpu.nlp.bert import BertModel, _resolve_config

    outs = {}
    for fused in (False, True):
        paddle.seed(8)
        m = BertModel(_resolve_config("bert-tiny", fused_ln=fused))
        m.eval()
        ids = jnp.asarray(np.arange(32).reshape(2, 16) % 512, jnp.int32)
        seq, pooled = m(ids)
        outs[fused] = np.asarray(seq._value)
    np.testing.assert_allclose(outs[True], outs[False], atol=2e-5,
                               rtol=2e-5)


def test_gpt_fused_ln_matches_baseline():
    import paddle_tpu as paddle
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.nlp.gpt import (GPTConfig, GPTForCausalLM,
                                    GPTPretrainingCriterion)
    from paddle_tpu.optimizer import AdamW

    cfg = dict(vocab_size=128, hidden_size=64, num_hidden_layers=2,
               num_attention_heads=4, max_position_embeddings=64,
               hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
               use_flash_attention=False)
    ids = jnp.asarray(np.arange(64).reshape(2, 32) % 128, jnp.int32)

    results = {}
    for fused in (False, True):
        paddle.seed(21)
        m = GPTForCausalLM(GPTConfig(**cfg, fused_ln=fused))
        m.train()
        eng = Engine(m, loss=GPTPretrainingCriterion(),
                     optimizer=AdamW(learning_rate=1e-3,
                                     parameters=m.parameters()))
        loss, _ = eng.train_batch([ids], [ids])
        p = jax.tree_util.tree_leaves(eng._params)[0]
        results[fused] = (float(loss), np.asarray(p))

    assert abs(results[True][0] - results[False][0]) < 1e-4
    np.testing.assert_allclose(results[True][1], results[False][1],
                               atol=2e-4, rtol=2e-4)
