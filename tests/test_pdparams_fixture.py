"""Binary-faithful .pdparams round trip (VERDICT r4 next #5).

No egress means no real published checkpoint; the honest substitute is
a fixture written in upstream's exact on-disk layout (ref:
python/paddle/framework/io.py paddle.save — a plain pickle of
{name: ndarray} for state-dict saves, and the older tensor-REBUILD
pickles whose values are GLOBAL calls like
paddle.framework.io._rebuild_tensor(ndarray, ...)). These tests
generate both byte layouts with the stdlib pickler alone — the
"rebuild" layout by installing a throwaway module named
paddle.framework.io so the pickler emits the same GLOBAL opcodes the
reference does — then pull BERT through from_pretrained and one
finetune step, including fused-qkv and scan-stacked layout conversion
both ways. If our reader or writer drifts from the upstream layout,
these fail.
"""
import pickle
import pickletools
import sys
import types

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.compat import load_pdparams, save_pdparams
from paddle_tpu.nlp.bert import BertForSequenceClassification, BertModel


def upstream_save_pdparams(state, path, layout="plain", protocol=2):
    """Emulate paddle.save's on-disk bytes for a state dict.

    layout='plain': pickle.dump({name: ndarray}) — what current
    paddle.save writes for state dicts (framework/io.py pickles the
    ndarray-converted dict directly).
    layout='rebuild': values serialize as calls to
    paddle.framework.io._rebuild_tensor(ndarray, stop_gradient) — the
    older tensor-wrapper save. The GLOBAL opcode stream is identical to
    upstream's because the pickler records module+qualname.
    """
    arrs = {k: np.asarray(v) for k, v in state.items()}
    if layout == "plain":
        with open(path, "wb") as f:
            pickle.dump(arrs, f, protocol=protocol)
        return
    assert layout == "rebuild"
    created = []
    try:
        for mname in ("paddle", "paddle.framework", "paddle.framework.io"):
            if mname not in sys.modules:
                sys.modules[mname] = types.ModuleType(mname)
                created.append(mname)

        def _rebuild_tensor(arr, stop_gradient=True):
            return arr
        _rebuild_tensor.__module__ = "paddle.framework.io"
        _rebuild_tensor.__qualname__ = "_rebuild_tensor"
        sys.modules["paddle.framework.io"]._rebuild_tensor = \
            _rebuild_tensor

        class _AsRebuild:
            def __init__(self, a):
                self.a = a

            def __reduce__(self):
                return (_rebuild_tensor, (self.a, True))

        with open(path, "wb") as f:
            pickle.dump({k: _AsRebuild(a) for k, a in arrs.items()}, f,
                        protocol=protocol)
    finally:
        for mname in created:
            del sys.modules[mname]


def _tiny_state():
    rng = np.random.default_rng(0)
    return {"linear.weight": rng.standard_normal((4, 3)).astype("float32"),
            "linear.bias": rng.standard_normal((3,)).astype("float32")}


def test_writer_matches_upstream_bytes(tmp_path):
    """save_pdparams must emit byte-for-byte what upstream paddle.save
    emits for the same state dict — the layout-drift tripwire."""
    state = _tiny_state()
    ours, ref = tmp_path / "ours.pdparams", tmp_path / "ref.pdparams"
    save_pdparams({k: paddle.to_tensor(v) for k, v in state.items()}, ours)
    upstream_save_pdparams(state, ref, layout="plain")
    assert ours.read_bytes() == ref.read_bytes()


def test_rebuild_layout_pickles_reference_globals(tmp_path):
    """The rebuild fixture must reference the reference framework's
    global by name — that's what makes it a faithful stand-in for an
    old checkpoint (and what exercises the compat passthrough)."""
    p = tmp_path / "old.pdparams"
    upstream_save_pdparams(_tiny_state(), p, layout="rebuild")
    ops = [(op.name, arg) for op, arg, _ in
           pickletools.genops(p.read_bytes())]
    globals_seen = [arg for name, arg in ops
                    if name in ("GLOBAL", "STACK_GLOBAL") and arg]
    assert any("paddle.framework.io" in str(g) for g in globals_seen), \
        globals_seen
    state = load_pdparams(p, return_numpy=True)
    np.testing.assert_array_equal(state["linear.weight"],
                                  _tiny_state()["linear.weight"])


@pytest.mark.parametrize("layout", ["plain", "rebuild"])
def test_bert_from_pretrained_roundtrip(tmp_path, layout):
    from paddle_tpu.nlp.bert import _resolve_config
    paddle.seed(11)
    src = BertForSequenceClassification(_resolve_config("bert-tiny"))
    state = {k: np.asarray(v._value) for k, v in src.state_dict().items()}
    p = tmp_path / f"bert_{layout}.pdparams"
    upstream_save_pdparams(state, p, layout=layout)

    model = BertForSequenceClassification.from_pretrained(
        "bert-tiny", pretrained_path=str(p))
    for k, v in model.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v._value), state[k], k)

    # one finetune step must run and move the loaded weights
    from paddle_tpu.hapi.engine import Engine
    from paddle_tpu.optimizer import AdamW
    import paddle_tpu.nn as nn
    model.train()
    eng = Engine(model, loss=nn.CrossEntropyLoss(),
                 optimizer=AdamW(learning_rate=1e-3,
                                 parameters=model.parameters()))
    ids = jnp.ones((2, 16), dtype=jnp.int32)
    labels = jnp.zeros((2,), dtype=jnp.int32)
    loss, _ = eng.train_batch([ids], [labels])
    assert np.isfinite(float(loss))


@pytest.mark.parametrize("variant", ["fused_qkv", "scan_layers",
                                     "fused_scan"])
def test_layout_conversion_on_load(tmp_path, variant):
    """A plain reference checkpoint loads into fused-qkv and/or
    scan-stacked models (forward parity pinned), and the converted
    model's state saves back into a file the PLAIN model can load —
    both directions through the .pdparams format."""
    paddle.seed(5)
    plain = BertModel.from_config_name("bert-tiny")
    plain.eval()
    state = {k: np.asarray(v._value)
             for k, v in plain.state_dict().items()}
    p = tmp_path / "plain.pdparams"
    upstream_save_pdparams(state, p, layout="plain")

    overrides = {"fused_qkv": variant in ("fused_qkv", "fused_scan"),
                 "scan_layers": variant in ("scan_layers", "fused_scan")}
    model = BertModel.from_pretrained("bert-tiny", pretrained_path=str(p),
                                      **overrides)
    model.eval()
    ids = jnp.asarray(np.arange(32).reshape(2, 16) % 512, dtype=jnp.int32)
    want_seq, want_pooled = plain(ids)
    got_seq, got_pooled = model(ids)
    np.testing.assert_allclose(np.asarray(got_seq._value),
                               np.asarray(want_seq._value), atol=2e-5,
                               rtol=2e-5)

    # reverse direction: converted state -> .pdparams -> plain model
    back = tmp_path / "converted.pdparams"
    save_pdparams(model.state_dict(), back)
    plain2 = BertModel.from_pretrained("bert-tiny",
                                       pretrained_path=str(back))
    plain2.eval()
    got2, _ = plain2(ids)
    np.testing.assert_allclose(np.asarray(got2._value),
                               np.asarray(want_seq._value), atol=2e-5,
                               rtol=2e-5)
