"""Tensor-parallel layer tests on the virtual 8-device CPU mesh.

Parity target: test/collective/fleet test_parallel_dygraph_mp_layers —
tp linear == dense linear, vocab-parallel embedding == dense embedding,
parallel CE == dense CE.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.mpu import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, shard_model, param_specs)
from paddle_tpu.nn.layer import functional_call


@pytest.fixture
def mp_mesh():
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
    old = mesh_mod._global_mesh
    mesh_mod.set_mesh(mesh)
    yield mesh
    mesh_mod._global_mesh = old


def test_column_row_gspmd_matches_dense(mp_mesh):
    """col(gather=False) -> row(parallel-in) under jit == dense 2-layer MLP."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 16).astype(np.float32)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)
    shard_model(col, mp_mesh)
    shard_model(row, mp_mesh)

    params = {**{f"c.{n}": p._value for n, p in col.named_parameters()},
              **{f"r.{n}": p._value for n, p in row.named_parameters()}}

    @jax.jit
    def fwd(params, x):
        cp = {n[2:]: v for n, v in params.items() if n.startswith("c.")}
        rp = {n[2:]: v for n, v in params.items() if n.startswith("r.")}
        h = functional_call(col, cp, {}, paddle.Tensor(x))
        y = functional_call(row, rp, {}, h)
        return y._value

    got = np.asarray(fwd(params, x))
    w1, b1 = np.asarray(col.weight), np.asarray(col.bias)
    w2, b2 = np.asarray(row.weight), np.asarray(row.bias)
    want = (x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_column_row_shard_map_matches_dense(mp_mesh):
    """Explicit shard_map path: local weight shards + psum == dense."""
    rng = np.random.RandomState(1)
    x = rng.randn(4, 16).astype(np.float32)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)
    w1 = np.asarray(col.weight)
    b1 = np.asarray(col.bias)
    w2 = np.asarray(row.weight)
    b2 = np.asarray(row.bias)

    def stage(x, w1, b1, w2, b2):
        h = functional_call(col, {"weight": w1, "bias": b1}, {},
                            paddle.Tensor(x))
        y = functional_call(row, {"weight": w2, "bias": b2}, {}, h)
        return y._value

    fn = shard_map(
        stage, mesh=mp_mesh,
        in_specs=(P(), P(None, "mp"), P("mp"), P("mp", None), P()),
        out_specs=P(),
        check_rep=False)
    got = np.asarray(jax.jit(fn)(x, w1, b1, w2, b2))
    want = (x @ w1 + b1) @ w2 + b2
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_vocab_parallel_embedding_shard_map(mp_mesh):
    vocab, dim = 64, 8
    emb = VocabParallelEmbedding(vocab, dim)
    w = np.asarray(emb.weight)
    ids = np.array([[0, 5, 63, 17], [33, 2, 48, 31]], dtype=np.int32)

    def stage(ids, w):
        out = functional_call(emb, {"weight": w}, {}, paddle.Tensor(ids))
        return out._value

    fn = shard_map(stage, mesh=mp_mesh,
                   in_specs=(P(), P("mp", None)), out_specs=P(),
                   check_rep=False)
    got = np.asarray(jax.jit(fn)(ids, w))
    np.testing.assert_allclose(got, w[ids], rtol=1e-6, atol=1e-6)


def test_parallel_cross_entropy_shard_map(mp_mesh):
    rng = np.random.RandomState(2)
    logits = rng.randn(4, 64).astype(np.float32)
    labels = np.array([3, 60, 17, 42], dtype=np.int32)
    ce = ParallelCrossEntropy()

    def stage(lg, lb):
        out = ce(paddle.Tensor(lg), paddle.Tensor(lb))
        return out._value

    fn = shard_map(stage, mesh=mp_mesh,
                   in_specs=(P(None, "mp"), P()), out_specs=P(),
                   check_rep=False)
    got = np.asarray(jax.jit(fn)(logits, labels))
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[:, 0]
    want = lse - logits[np.arange(4), labels]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_parallel_ce_dense_path_matches():
    logits = np.random.RandomState(3).randn(6, 33).astype(np.float32)
    labels = np.array([0, 5, 32, 7, 9, 11], dtype=np.int32)
    ce = ParallelCrossEntropy()
    got = np.asarray(ce(paddle.Tensor(logits), paddle.Tensor(labels)))
    m = logits.max(-1, keepdims=True)
    lse = np.log(np.exp(logits - m).sum(-1)) + m[:, 0]
    want = lse - logits[np.arange(6), labels]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_param_specs_and_shard_model_placement(mp_mesh):
    col = ColumnParallelLinear(16, 32, gather_output=False)
    shard_model(col, mp_mesh)
    specs = param_specs(col)
    assert specs["weight"] == P(None, "mp")
    sh = col.weight._value.sharding
    assert isinstance(sh, NamedSharding) and sh.spec == P(None, "mp")


def test_grad_through_tp_stack_matches_dense(mp_mesh):
    """value_and_grad through GSPMD tp layers == dense grads."""
    paddle.seed(4)  # pin layer init: fd-vs-grad tolerance depends on it
    rng = np.random.RandomState(4)
    x = rng.randn(8, 16).astype(np.float32)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)
    shard_model(col, mp_mesh)
    shard_model(row, mp_mesh)
    params = {"cw": col.weight._value, "cb": col.bias._value,
              "rw": row.weight._value, "rb": row.bias._value}

    @jax.jit
    def loss_fn(params, x):
        h = functional_call(col, {"weight": params["cw"],
                                  "bias": params["cb"]}, {},
                            paddle.Tensor(x))
        y = functional_call(row, {"weight": params["rw"],
                                  "bias": params["rb"]}, {}, h)
        return jnp.mean(y._value ** 2)

    g = jax.jit(jax.grad(loss_fn))(params, x)

    w1, b1 = np.asarray(col.weight), np.asarray(col.bias)
    w2, b2 = np.asarray(row.weight), np.asarray(row.bias)

    def dense_loss(w1):
        return jnp.mean(((x @ w1 + b1) @ w2 + b2) ** 2)

    ref = jax.grad(dense_loss)(jnp.asarray(w1))
    np.testing.assert_allclose(np.asarray(g["cw"]), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


class TestRNGStateTracker:
    def test_eager_streams_decorrelated_and_deterministic(self):
        from paddle_tpu.distributed.fleet.mpu import get_rng_state_tracker
        from paddle_tpu.framework import next_rng_key
        tr = get_rng_state_tracker()
        tr.reset()
        tr.add("global_seed", 100)
        tr.add("local_seed", 200)
        with tr.rng_state("global_seed"):
            g1 = next_rng_key()
        with tr.rng_state("local_seed"):
            l1 = next_rng_key()
        assert not np.array_equal(np.asarray(g1), np.asarray(l1))
        # re-adding the same seeds replays the same stream
        tr.add("global_seed", 100)
        with tr.rng_state("global_seed"):
            g1b = next_rng_key()
        assert np.array_equal(np.asarray(g1), np.asarray(g1b))

    def test_shard_map_local_stream_decorrelates_ranks(self):
        from paddle_tpu.distributed.fleet.mpu import get_rng_state_tracker
        from paddle_tpu.framework import next_rng_key, _rng_scope_ctx, RNGScope
        tr = get_rng_state_tracker()
        mesh = Mesh(np.array(jax.devices()[:4]), ("mp",))

        def draw(stream):
            def f():
                with _rng_scope_ctx(RNGScope(jax.random.PRNGKey(7))):
                    with tr.rng_state(stream):
                        k = next_rng_key()
                return jax.random.uniform(k, (1, 4))
            return shard_map(f, mesh=mesh, in_specs=(),
                             out_specs=P("mp"))()

        local = np.asarray(draw("local_seed"))    # [4, 4]
        glob = np.asarray(draw("global_seed"))
        # local stream: every rank draws a different row
        assert len({tuple(r) for r in local.round(6).tolist()}) == 4
        # global stream: identical rows on all ranks
        for r in glob[1:]:
            np.testing.assert_allclose(r, glob[0])


def test_fleet_ps_mode_gated():
    """SURVEY §2.6 descope: parameter-server mode raises a loud gate with
    a TPU migration recipe; the COLLECTIVE role_maker idiom still works."""
    import pytest as _pytest
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.base import PaddleCloudRoleMaker
    with _pytest.raises(NotImplementedError, match="parameter-server"):
        fleet.init(role_maker=PaddleCloudRoleMaker(is_collective=False))
    with _pytest.raises(NotImplementedError, match="VocabParallelEmbedding"):
        fleet.init(is_collective=False)
    # reference collective idiom must NOT be gated
    fleet.init(role_maker=PaddleCloudRoleMaker(is_collective=True))
