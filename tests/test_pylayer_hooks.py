"""paddle.autograd.PyLayer (eager tape + traced custom_vjp) and
Tensor.register_hook."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class DoubleBack(PyLayer):
    """y = tanh(x), but backward deliberately returns 2x the true grad so
    tests can tell the custom rule ran."""

    @staticmethod
    def forward(ctx, x):
        y = paddle.tanh(x)
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor()
        return dy * (1 - y * y) * 2.0


class TwoInOut(PyLayer):
    @staticmethod
    def forward(ctx, a, b):
        ctx.save_for_backward(a, b)
        return a * b, a + b

    @staticmethod
    def backward(ctx, d_mul, d_add):
        a, b = ctx.saved_tensor()
        return d_mul * b + d_add, d_mul * a + d_add


class TestPyLayerEager:
    def test_custom_backward_used(self):
        x = paddle.to_tensor(np.array([0.3, -0.7], np.float32),
                             stop_gradient=False)
        y = DoubleBack.apply(x)
        y.sum().backward()
        ref = (1 - np.tanh(_np(x)) ** 2) * 2.0
        assert np.allclose(_np(x.grad), ref, atol=1e-6)

    def test_multi_output(self):
        a = paddle.to_tensor(2.0, stop_gradient=False)
        b = paddle.to_tensor(3.0, stop_gradient=False)
        m, s = TwoInOut.apply(a, b)
        (m + s).backward()
        # d/da (ab + a + b) = b + 1 = 4; d/db = a + 1 = 3
        assert np.allclose(_np(a.grad), 4.0)
        assert np.allclose(_np(b.grad), 3.0)

    def test_ctx_attributes(self):
        class Scale(PyLayer):
            @staticmethod
            def forward(ctx, x, factor):
                ctx.factor = factor
                return x * factor

            @staticmethod
            def backward(ctx, dy):
                return dy * ctx.factor

        x = paddle.to_tensor(1.5, stop_gradient=False)
        y = Scale.apply(x, 4.0)
        y.backward()
        assert np.allclose(_np(x.grad), 4.0)

    def test_no_grad_inputs_passthrough(self):
        x = paddle.to_tensor(1.0)  # stop_gradient=True
        y = DoubleBack.apply(x)
        assert np.allclose(_np(y), np.tanh(1.0), atol=1e-6)

    def test_identity_passthrough_no_self_cycle(self):
        # regression: forward returning an input unchanged created a
        # self-cycle GradNode that the toposort silently dropped
        class Ident(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x

            @staticmethod
            def backward(ctx, dy):
                return dy * 3.0  # marker so we know this ran

        x = paddle.to_tensor(2.0, stop_gradient=False)
        h = x * 2.0        # upstream op that must also receive grads
        y = Ident.apply(h)
        y.backward()
        assert np.allclose(_np(x.grad), 6.0)  # 3 (custom) * 2 (upstream)


class TestPyLayerTraced:
    def test_inside_jax_grad(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.tensor import Tensor

        def loss(x):
            y = DoubleBack.apply(Tensor(x))
            return jnp.sum(y._value)

        x = jnp.asarray([0.3, -0.7], jnp.float32)
        g = jax.grad(loss)(x)
        ref = (1 - np.tanh(np.asarray(x)) ** 2) * 2.0
        assert np.allclose(np.asarray(g), ref, atol=1e-6)

    def test_inside_jit_grad(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.tensor import Tensor

        @jax.jit
        def gf(x):
            return jax.grad(
                lambda v: jnp.sum(DoubleBack.apply(Tensor(v))._value))(x)

        x = jnp.asarray([0.1, 0.9], jnp.float32)
        ref = (1 - np.tanh(np.asarray(x)) ** 2) * 2.0
        assert np.allclose(np.asarray(gf(x)), ref, atol=1e-6)

    def test_in_layer_through_engine_step(self):
        # a Layer whose forward uses a PyLayer, trained one Engine step
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi.engine import Engine

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                return DoubleBack.apply(self.fc(x))

        paddle.seed(0)
        net = Net()
        eng = Engine(net, loss=paddle.nn.MSELoss(),
                     optimizer=paddle.optimizer.SGD(
                         0.1, parameters=net.parameters()))
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        y = paddle.to_tensor(np.zeros((2, 2), np.float32))
        w0 = _np(net.fc.weight).copy()
        loss, _ = eng.train_batch([x], [y])
        assert np.isfinite(float(loss))
        assert not np.allclose(_np(net.fc.weight), w0)  # stepped


class TestRegisterHook:
    def test_hook_scales_leaf_grad(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        x.register_hook(lambda g: g * 10.0)
        (x * 3.0).sum().backward()
        assert np.allclose(_np(x.grad), [30.0, 30.0])

    def test_hook_none_return_keeps_grad(self):
        seen = []
        x = paddle.to_tensor(2.0, stop_gradient=False)
        x.register_hook(lambda g: seen.append(_np(g)))
        (x ** 2).backward()
        assert np.allclose(_np(x.grad), 4.0)
        assert len(seen) == 1 and np.allclose(seen[0], 4.0)

    def test_hook_on_intermediate_affects_propagation(self):
        x = paddle.to_tensor(3.0, stop_gradient=False)
        h = x * 2.0        # dh/dx = 2
        h.register_hook(lambda g: g * 5.0)
        (h * 4.0).backward()  # dL/dh = 4 -> hook -> 20 -> dL/dx = 40
        assert np.allclose(_np(x.grad), 40.0)

    def test_hook_accumulated_before_firing(self):
        # diamond: two consumers of h; hook must see the SUM (6), not fire
        # per-edge
        seen = []
        x = paddle.to_tensor(1.0, stop_gradient=False)
        h = x * 1.0

        def hook(g):
            seen.append(float(_np(g)))
            return g

        h.register_hook(hook)
        (h * 2.0 + h * 4.0).backward()
        assert seen == [6.0]
        assert np.allclose(_np(x.grad), 6.0)

    def test_remove_handle(self):
        x = paddle.to_tensor(1.0, stop_gradient=False)
        handle = x.register_hook(lambda g: g * 100.0)
        handle.remove()
        (x * 2.0).backward()
        assert np.allclose(_np(x.grad), 2.0)

    def test_remove_is_idempotent_and_keyed(self):
        # regression: double-remove of one handle must not delete another
        # registration of the same callable
        x = paddle.to_tensor(1.0, stop_gradient=False)
        fn = lambda g: g * 10.0  # noqa: E731
        h1 = x.register_hook(fn)
        x.register_hook(fn)
        h1.remove()
        h1.remove()
        (x * 1.0).backward()
        assert np.allclose(_np(x.grad), 10.0)  # second registration fires

    def test_stale_handle_cannot_alias_new_registration(self):
        # regression: ids were max+1, so remove+register reused an id and a
        # stale handle's second remove() killed the new hook
        x = paddle.to_tensor(1.0, stop_gradient=False)
        x.register_hook(lambda g: g)          # id a
        h2 = x.register_hook(lambda g: g)     # id b
        h2.remove()
        x.register_hook(lambda g: g * 10.0)   # new id, must not equal b
        h2.remove()  # stale second remove
        (x * 1.0).backward()
        assert np.allclose(_np(x.grad), 10.0)

    def test_deepcopy_does_not_share_hooks(self):
        import copy
        x = paddle.to_tensor(1.0, stop_gradient=False)
        x.register_hook(lambda g: g * 3.0)
        y = copy.deepcopy(x)
        y.register_hook(lambda g: g * 7.0)
        (x * 1.0).backward()
        assert np.allclose(_np(x.grad), 3.0)  # y's hook did not fire on x

    def test_traced_backward_arity_mismatch_raises(self):
        # regression: traced path silently zero-padded missing grads
        import jax
        import jax.numpy as jnp
        from paddle_tpu.tensor import Tensor

        class Bad(PyLayer):
            @staticmethod
            def forward(ctx, a, b):
                return a * b

            @staticmethod
            def backward(ctx, dy):
                return dy  # WRONG: one grad for two inputs

        def loss(a, b):
            return jnp.sum(Bad.apply(Tensor(a), Tensor(b))._value)

        with pytest.raises(ValueError, match="returned 1 grads"):
            jax.grad(loss, argnums=(0, 1))(jnp.float32(3.0), jnp.float32(2.0))

    def test_register_on_stopped_tensor_raises(self):
        x = paddle.to_tensor(1.0)
        with pytest.raises(RuntimeError):
            x.register_hook(lambda g: g)
