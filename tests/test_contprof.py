"""Continuous profiling plane (ISSUE 22; observability/contprof.py).

Pins the round-22 contracts (docs/observability.md "Continuous
profiling"):

- serving-phase markers are GIL-atomic thread-local tags: set/clear,
  cross-thread reads, and re-entrant nesting (journal inside placement
  restores placement on exit);
- the sampler attributes a busy thread's stacks to its marked phase
  and SKIPS threads inside an ``introspecting()`` AOT replay;
- caps are never silent: the stack-trie node bound keeps the sample's
  weight at the deepest existing node and counts the truncation, and
  the overhead EWMA deterministically halves Hz above the 1% cap
  (floor at min_hz, every step counted) — ``_note_duty`` is exercised
  directly, no real sampling needed;
- folded persistence is torn-tolerant at EVERY byte offset (a crash
  mid-write loses at most the tail line, never raises) and the
  flamegraph HTML's embedded JSON parses back out even when a frame
  label contains ``</script>``;
- profile ON leaves an engine's compile counts frozen, serves
  ``/profile`` over the live exporter (which then self-times in
  ``exporter_scrape_seconds``), and rides health(); a never-armed
  engine creates NO profiler and registers NO profile_* series;
- the router delta-folds heartbeat digests into fleet_profile_*
  (restart-reset-safe, the _fold_spec idiom) and rolls hotspots up in
  health()["profile"]; fleet_top renders the HOST% column off it;
- span-ring overflow is counted and exported (export_chrome metadata);
- tools/profile_diff.py gates share drift in BOTH directions and
  fails vacuous comparisons;
- Profiler.export_flamegraph bridges to the active continuous
  profiler, falling back to a regions-only flame.
"""
import importlib
import json
import os
import re
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.observability import contprof
from paddle_tpu.observability.contprof import ContinuousProfiler
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.spans import SpanRecorder, export_chrome
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

_SCRIPT_RE = re.compile(
    r'<script id="profile-data" type="application/json">(.*?)</script>',
    re.S)


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


# -- phase markers ---------------------------------------------------------


class TestPhaseMarkers:
    def test_set_clear_and_default(self):
        assert contprof.current_phase() is None
        contprof.set_phase("decode")
        try:
            assert contprof.current_phase() == "decode"
        finally:
            contprof.set_phase(None)
        assert contprof.current_phase() is None

    def test_context_reentrant_restores_outer(self):
        with contprof.phase("placement"):
            assert contprof.current_phase() == "placement"
            with contprof.phase("journal"):
                assert contprof.current_phase() == "journal"
            # the journal append inside placement goes BACK to
            # placement, not to unmarked
            assert contprof.current_phase() == "placement"
        assert contprof.current_phase() is None

    def test_cross_thread_read_by_tid(self):
        ready = threading.Event()
        release = threading.Event()
        tid_box = []

        def worker():
            tid_box.append(threading.get_ident())
            with contprof.phase("spec_verify"):
                ready.set()
                release.wait(5.0)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        assert ready.wait(5.0)
        try:
            # the sampler's exact read path: marker of ANOTHER thread
            assert contprof.current_phase(tid_box[0]) == "spec_verify"
        finally:
            release.set()
            t.join(5.0)
        assert contprof.current_phase(tid_box[0]) is None


# -- live sampler ----------------------------------------------------------


def _busy(stop, phase_name):
    with contprof.phase(phase_name):
        while not stop.is_set():
            sum(i * i for i in range(200))


class TestSampler:
    def test_busy_thread_attributed_to_phase(self):
        pr = ContinuousProfiler(hz=200.0, name="t-sampler").start()
        stop = threading.Event()
        t = threading.Thread(target=_busy, args=(stop, "decode"),
                             daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if pr.digest()["phases"].get("decode", 0) >= 3:
                    break
                time.sleep(0.02)
        finally:
            stop.set()
            t.join(5.0)
            pr.stop()
        dg = pr.digest()
        assert dg["phases"].get("decode", 0) >= 3
        # the digest carries a per-phase leaf table for decode
        assert dg["top"]["decode"]
        # and the folded stacks are phase-rooted and walk through the
        # busy-loop's frame (the LEAF is often its inner genexpr — the
        # trie holds the whole stack)
        assert any(k.startswith("phase:decode;") and "_busy" in k
                   for k in pr.fold())

    def test_introspecting_thread_suppressed(self):
        from paddle_tpu.observability import introspect
        stop = threading.Event()
        tid_box = []

        def worker():
            tid_box.append(threading.get_ident())
            _busy(stop, "introtest")

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while not tid_box:
            time.sleep(0.005)
        # publish the worker as an AOT-replay thread the way
        # introspecting() does BEFORE any sampling starts — the
        # sampler must skip it entirely
        introspect._introspecting_threads.add(tid_box[0])
        pr = ContinuousProfiler(hz=200.0, name="t-intro").start()
        try:
            time.sleep(0.25)
            assert pr.digest()["phases"].get("introtest", 0) == 0
        finally:
            introspect._introspecting_threads.discard(tid_box[0])
            stop.set()
            t.join(5.0)
            pr.stop()


# -- caps: trie bound + overhead backoff (deterministic, no threads) -------


class TestCapsNeverSilent:
    def test_trie_node_bound_counts_drops(self):
        reg = MetricsRegistry()
        pr = ContinuousProfiler(hz=19.0, registry=reg, name="t-bound",
                                max_nodes=8)
        with pr._lock:
            for i in range(50):
                pr._insert("decode", (f"m.f{i}", f"m.g{i}"))
        assert pr.dropped > 0
        assert int(reg.get("profile_samples_dropped_total").value) \
            == pr.dropped
        # truncation keeps the weight at the deepest existing node:
        # every insert still lands somewhere
        assert sum(pr.fold().values()) == 50

    def test_overhead_backoff_halves_to_floor(self):
        reg = MetricsRegistry()
        pr = ContinuousProfiler(hz=16.0, registry=reg, name="t-duty",
                                overhead_cap=0.01, min_hz=1.0)
        period = 1.0 / 16.0
        # one full-period sample seeds the EWMA at ratio 1.0 — way
        # over the 1% cap: Hz halves, the ratio is halved with it
        pr._note_duty(period)
        assert pr.hz == 8.0
        assert pr.backoffs == 1
        assert pr.overhead_ratio == pytest.approx(0.5)
        assert reg.get("profile_hz").value == 8.0
        # keep feeding saturated samples: the ladder walks down but
        # NEVER below min_hz, and every step is counted
        for _ in range(32):
            pr._note_duty(1.0)
        assert pr.hz == 1.0
        assert pr.backoffs == 4          # 16 -> 8 -> 4 -> 2 -> 1
        assert int(reg.get("profile_backoffs_total").value) == 4
        b = pr.backoffs
        pr._note_duty(1.0)
        assert pr.hz == 1.0 and pr.backoffs == b
        # cheap samples decay the EWMA back under the cap
        for _ in range(200):
            pr._note_duty(0.0)
        assert pr.overhead_ratio < pr.overhead_cap

    def test_duty_gauge_tracks_ewma(self):
        reg = MetricsRegistry()
        pr = ContinuousProfiler(hz=16.0, registry=reg, name="t-g",
                                overhead_cap=0.5)
        pr._note_duty(0.25 / 16.0)       # ratio 0.25, under the cap
        assert reg.get("profile_overhead_ratio").value \
            == pytest.approx(pr.overhead_ratio)
        assert pr.backoffs == 0 and pr.hz == 16.0


# -- folded persistence ----------------------------------------------------


def _populated(name="t-fold"):
    pr = ContinuousProfiler(hz=19.0, name=name)
    with pr._lock:
        pr._insert("decode", ("mod.outer", "mod.inner"))
        pr._insert("decode", ("mod.outer", "mod.inner"))
        pr._insert("decode", ("mod.outer",))
        pr._insert("prefill_32", ("mod.prefill",))
        pr._insert("idle", ())
        pr.samples = 5
    return pr


class TestFoldedPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        pr = _populated()
        p = str(tmp_path / "a.folded")
        pr.save(p)
        loaded = load_full = contprof.load_folded(p)
        assert loaded == pr.fold()
        assert load_full["phase:decode;mod.outer;mod.inner"] == 2

    def test_torn_file_tolerated_at_every_byte(self, tmp_path):
        pr = _populated()
        p = str(tmp_path / "a.folded")
        pr.save(p)
        with open(p, "rb") as f:
            data = f.read()
        full = contprof.load_folded(p)
        torn = str(tmp_path / "torn.folded")
        for cut in range(len(data) + 1):
            with open(torn, "wb") as f:
                f.write(data[:cut])
            got = contprof.load_folded(torn)   # must never raise
            for stack, w in got.items():
                assert stack in full
                assert 0 < w <= full[stack]
        # missing file is an empty profile, not an exception
        assert contprof.load_folded(str(tmp_path / "nope")) == {}

    def test_fold_shares_sum_to_one(self):
        folded = _populated().fold()
        phases, frames = contprof.fold_shares(folded)
        assert sum(phases.values()) == pytest.approx(1.0)
        assert sum(frames.values()) == pytest.approx(1.0)
        assert phases["decode"] == pytest.approx(3 / 5)
        # a pre-phase-tag profile reads as idle, not a crash
        ph2, _ = contprof.fold_shares({"mod.f;mod.g": 4})
        assert ph2 == {"idle": pytest.approx(1.0)}

    def test_windowed_fold_uses_recent_ring(self):
        pr = _populated()
        key = ("phase:decode", "mod.outer")
        now = 1000.0
        pr._recent.append((now - 120.0, key))   # outside the window
        pr._recent.append((now - 10.0, key))
        pr._recent.append((now - 5.0, key))
        win = pr.fold(window_s=60.0, now=now)
        assert win == {"phase:decode;mod.outer": 2}


# -- flamegraph ------------------------------------------------------------


class TestFlamegraph:
    def test_embedded_json_roundtrips_with_script_escape(self, tmp_path):
        pr = _populated(name="t-flame")
        with pr._lock:
            # the label that would end the <script> block early if the
            # payload weren't escaped
            pr._insert("idle", ("evil</script>frame",))
        p = str(tmp_path / "flame.html")
        assert pr.flamegraph_html(p, title="t") == p
        with open(p, "r", encoding="utf-8") as f:
            html = f.read()
        m = _SCRIPT_RE.search(html)
        assert m, "embedded profile JSON block missing"
        doc = json.loads(m.group(1))
        assert doc["folded"] == pr.fold()
        assert any("evil</script>frame" in k for k in doc["folded"])
        # path=None returns the HTML text instead of writing
        assert _SCRIPT_RE.search(pr.flamegraph_html())


# -- active-profiler registry ----------------------------------------------


class TestActiveRegistry:
    def test_current_profile_attaches_and_clears(self):
        assert contprof.active_profiler() is None
        assert contprof.current_profile() is None
        pr = ContinuousProfiler(hz=50.0, name="t-active").start()
        try:
            assert contprof.active_profiler() is pr
            rep = contprof.current_profile(window_s=5.0)
            assert rep is not None and "folded" in rep \
                and rep["name"] == "t-active"
        finally:
            pr.stop()
        assert contprof.active_profiler() is None
        assert contprof.current_profile() is None


# -- tools/profile_diff.py -------------------------------------------------


def _write_folded(path, rows):
    with open(path, "w", encoding="utf-8") as f:
        f.write("# contprof folded v1 name=test hz=19\n")
        for stack, w in rows.items():
            f.write(f"{stack} {w}\n")
    return str(path)


class TestProfileDiff:
    @pytest.fixture(scope="class")
    def pd(self):
        return importlib.import_module("profile_diff")

    def test_gate_trips_on_growth_and_collapse(self, pd, tmp_path,
                                               capsys):
        a = _write_folded(tmp_path / "a.folded",
                          {"phase:decode;m.f": 50, "phase:idle;m.w": 50})
        b = _write_folded(tmp_path / "b.folded",
                          {"phase:decode;m.f": 80, "phase:idle;m.w": 20})
        # A vs A: no drift, gate quiet
        assert pd.main([a, a, "--fail-on", "phase:decode>+5%",
                        "--quiet"]) == 0
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["ok"] and not rep["vacuous"]
        # +30pp decode growth trips >
        assert pd.main([a, b, "--fail-on", "phase:decode>+5%",
                        "--quiet"]) == 1
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["failures"][0]["delta_pp"] == pytest.approx(30.0)
        # the same motion reads as idle COLLAPSE through a < gate
        assert pd.main([a, b, "--fail-on", "phase:idle<10%",
                        "--quiet"]) == 1
        # frame gates ride the leaf-frame table
        assert pd.main([a, b, "--fail-on", "frame:m.f>+5%",
                        "--quiet"]) == 1

    def test_missing_key_reads_as_zero(self, pd, tmp_path, capsys):
        a = _write_folded(tmp_path / "a2.folded", {"phase:idle;m.w": 10})
        b = _write_folded(tmp_path / "b2.folded",
                          {"phase:idle;m.w": 5,
                           "phase:spec_verify;m.v": 5})
        # a brand-new phase DOES trip a > gate (0% -> 50%)
        assert pd.main([a, b, "--fail-on", "phase:spec_verify>+20%",
                        "--quiet"]) == 1
        capsys.readouterr()

    def test_vacuous_comparison_fails(self, pd, tmp_path, capsys):
        e = _write_folded(tmp_path / "e.folded", {})
        assert pd.main([e, e, "--quiet"]) == 1
        rep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rep["vacuous"] and not rep["ok"]

    def test_bad_spec_rejected(self, pd):
        with pytest.raises(Exception):
            pd.parse_spec("decode>+5%")     # missing phase:/frame: kind


# -- engine integration ----------------------------------------------------


class TestEngineIntegration:
    def test_profiled_engine_frozen_compiles_and_endpoints(self,
                                                           gpt_model):
        prompts = _prompts((12, 14, 10, 13))
        eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                            max_seq_len=64, steps_per_dispatch=4,
                            profile=True, profile_hz=97.0)
        try:
            assert eng.profiler is not None
            assert eng.registry.get("profile_samples_total") is not None
            eng.warmup(buckets=[len(p) for p in prompts], decode=True)
            frozen = eng.compile_counts()
            # deterministic phase witness: watch the dispatch thread's
            # marker while generate() runs (immune to sampler Hz)
            observed = set()
            main_tid = threading.get_ident()
            stop = threading.Event()

            def watch():
                while not stop.is_set():
                    ph = contprof.current_phase(main_tid)
                    if ph:
                        observed.add(ph)
                    time.sleep(0.001)

            w = threading.Thread(target=watch, daemon=True)
            w.start()
            try:
                outs = eng.generate(prompts, max_new_tokens=8)
            finally:
                stop.set()
                w.join(5.0)
            assert len(outs) == len(prompts)
            # THE contract: profiling ON never touches compilation
            assert eng.compile_counts() == frozen
            assert "decode" in observed
            assert any(p.startswith("prefill_") for p in observed)
            h = eng.health()
            assert h["profile"]["hz"] > 0
            assert set(h["profile"]) >= {"samples", "phases", "top"}
            # live endpoints: /profile renders, then /metrics carries
            # the exporter's own scrape timing for that render
            import urllib.request
            ex = eng.serve_metrics(port=0)
            base = f"http://127.0.0.1:{ex.port}"
            with urllib.request.urlopen(base + "/profile?window=60",
                                        timeout=10) as r:
                prof = json.loads(r.read().decode("utf-8"))
            assert prof["name"] == "engine" and "folded" in prof
            assert prof["window_s"] == 60.0
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode("utf-8")
            assert "exporter_scrape_seconds" in text
            assert "profile_hz" in text
            pr = eng.profiler
        finally:
            eng.close()
        assert not pr.running

    def test_dormant_engine_has_no_profiler(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=64)
        try:
            assert eng.profiler is None
            assert eng.registry.get("profile_samples_total") is None
            assert eng.registry.get("profile_overhead_ratio") is None
            assert "profile" not in eng.health()
        finally:
            eng.close()


# -- span-ring overflow accounting -----------------------------------------


class TestSpansEviction:
    def test_overflow_counted_and_exported(self, tmp_path):
        rec = SpanRecorder(name="t-ring", maxlen=4)
        t0 = rec.now()
        for i in range(10):
            rec.add(f"s{i}", t0, t0 + 0.001)
        assert rec.evicted == 6
        p = str(tmp_path / "trace.json")
        export_chrome(p, [rec])
        with open(p, "r", encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["metadata"]["evicted_spans"]["t-ring"] == 6


# -- fleet rollup ----------------------------------------------------------


def _digest_snap(samples=10, dropped=1, backoffs=2, decode=6, idle=4):
    return {"profile": {
        "samples": samples, "dropped": dropped, "backoffs": backoffs,
        "overhead_ratio": 0.001, "hz": 19.0,
        "phases": {"decode": decode, "idle": idle},
        "top": {"decode": [["m.decode_step", decode]],
                "idle": [["m.wait", idle]]}}}


class TestFleetRollup:
    def test_fold_restart_tolerance_and_health(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=64)
        router = FleetRouter([InprocReplica("r0", eng)])
        try:
            reg = router.registry

            def c(name):
                m = reg.get(name)
                return 0 if m is None else int(m.value)

            router._fold_profile("r0", _digest_snap(samples=10))
            assert c("fleet_profile_samples_total") == 10
            assert c("fleet_profile_samples_dropped_total") == 1
            assert c("fleet_profile_backoffs_total") == 2
            # monotonic growth folds the delta only
            router._fold_profile("r0", _digest_snap(samples=14))
            assert c("fleet_profile_samples_total") == 14
            # a BACKWARDS value means the replica restarted: fold the
            # new absolute, never a negative delta
            router._fold_profile("r0", _digest_snap(samples=5))
            assert c("fleet_profile_samples_total") == 19
            h = router.health()["profile"]
            assert h["phases"]["decode"] == 6
            assert "m.decode_step" in h["top"]
            assert h["replicas"]["r0"]["host_pct"] \
                == pytest.approx(60.0)
            # a heartbeat with no profile section clears the inventory;
            # dormant router + no digests -> rollup reads None
            router._fold_profile("r0", {})
            assert "r0" not in router._profile_digests
            assert router.profiler is None
            assert router.health()["profile"] is None
            assert "r0" not in router._profile_seen
        finally:
            router.close()
            eng.close()

    def test_armed_router_samples_its_own_loop(self, gpt_model):
        eng = ServingEngine(gpt_model, max_slots=1, page_size=16,
                            max_seq_len=64)
        router = FleetRouter([InprocReplica("r0", eng)],
                             profile=True, profile_hz=97.0)
        try:
            assert router.profiler is not None \
                and router.profiler.running
            h = router.health()["profile"]
            assert h["router"]["hz"] > 0
            pr = router.profiler
        finally:
            router.close()
            eng.close()
        assert not pr.running


# -- fleet_top HOST% column ------------------------------------------------


class TestFleetTopHostPct:
    def test_render_host_pct_from_profile_rollup(self, tmp_path):
        ft = importlib.import_module("fleet_top")
        reg = MetricsRegistry()
        reg.counter("fleet_tokens_out_total").inc(10)
        from paddle_tpu.observability.history import HistoryStore
        hs = HistoryStore(reg, interval_s=1.0)
        for i in range(5):
            hs.scrape(now=1_700_000_000.0 + i)
        hs.save(str(tmp_path / "history_snapshot.json"))
        with open(tmp_path / "health.json", "w") as f:
            json.dump({
                "queue_depth": 0, "pending": 0, "lost": [],
                "replicas": {
                    "r0": {"state": "serving", "incarnation": 1,
                           "queued": 0, "running": 0, "free_pages": 9,
                           "scrape_age_s": 0.01, "lost": False,
                           "quarantined": False},
                    "r1": {"state": "serving", "incarnation": 1,
                           "queued": 0, "running": 0, "free_pages": 9,
                           "scrape_age_s": 0.01, "lost": False,
                           "quarantined": False}},
                "profile": {
                    "phases": {"decode": 6, "idle": 4},
                    "top": {"m.decode_step": 6},
                    "replicas": {"r0": {"host_pct": 42.5,
                                        "samples": 10}}}}, f)
        frame = ft.collect_snapshot(str(tmp_path))
        text = ft.render(frame)
        assert "HOST%" in text
        assert "42.5" in text      # r0 rolls up a duty figure
        # r1 has no profiler armed: renders "-", never crashes
        r1_line = [ln for ln in text.splitlines()
                   if ln.strip().startswith("r1")][0]
        assert " - " in r1_line


# -- Profiler.export_flamegraph bridge -------------------------------------


class TestProfilerBridge:
    def test_bridge_uses_active_continuous_profiler(self, tmp_path):
        from paddle_tpu.profiler import Profiler
        pr = ContinuousProfiler(hz=50.0, name="t-bridge").start()
        try:
            p = Profiler(registry=False)
            out = p.export_flamegraph(str(tmp_path / "live.html"))
            with open(out, "r", encoding="utf-8") as f:
                doc = json.loads(_SCRIPT_RE.search(f.read()).group(1))
            assert doc["name"] == "t-bridge"
        finally:
            pr.stop()

    def test_regions_fallback_without_active_profiler(self, tmp_path,
                                                      monkeypatch):
        from paddle_tpu.profiler import Profiler
        monkeypatch.setattr(contprof, "active_profiler", lambda: None)
        p = Profiler(registry=False)
        with p.record_event("my_region", sync=False):
            time.sleep(0.002)
        out = p.export_flamegraph(str(tmp_path / "regions.html"))
        with open(out, "r", encoding="utf-8") as f:
            doc = json.loads(_SCRIPT_RE.search(f.read()).group(1))
        assert doc["name"] == "regions"
        assert "region:my_region" in doc["folded"]
