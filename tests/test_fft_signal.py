"""paddle.fft / paddle.signal parity vs numpy + torch-style istft identity."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft as pfft
from paddle_tpu import signal as psignal


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


class TestFFT:
    def setup_method(self, m):
        self.rng = np.random.default_rng(0)

    def test_fft_ifft_roundtrip(self):
        x = self.rng.standard_normal(32).astype(np.float32)
        y = pfft.fft(paddle.to_tensor(x))
        assert np.allclose(_np(y), np.fft.fft(x), atol=1e-4)
        back = pfft.ifft(y)
        assert np.allclose(_np(back).real, x, atol=1e-5)

    def test_norm_modes(self):
        x = self.rng.standard_normal(16).astype(np.float32)
        for norm in ("backward", "ortho", "forward"):
            y = pfft.fft(paddle.to_tensor(x), norm=norm)
            assert np.allclose(_np(y), np.fft.fft(x, norm=norm), atol=1e-4)
        with pytest.raises(ValueError):
            pfft.fft(paddle.to_tensor(x), norm="bogus")

    def test_rfft_irfft(self):
        x = self.rng.standard_normal(30).astype(np.float32)
        y = pfft.rfft(paddle.to_tensor(x))
        assert y.shape[-1] == 16
        assert np.allclose(_np(y), np.fft.rfft(x), atol=1e-4)
        assert np.allclose(_np(pfft.irfft(y, n=30)), x, atol=1e-5)

    def test_hfft_ihfft(self):
        x = self.rng.standard_normal(17).astype(np.float32) \
            + 1j * self.rng.standard_normal(17).astype(np.float32)
        x = x.astype(np.complex64)
        x[0] = x[0].real  # hermitian-compatible DC
        assert np.allclose(_np(pfft.hfft(paddle.to_tensor(x))),
                           np.fft.hfft(x), atol=1e-3)
        r = self.rng.standard_normal(32).astype(np.float32)
        assert np.allclose(_np(pfft.ihfft(paddle.to_tensor(r))),
                           np.fft.ihfft(r), atol=1e-5)

    def test_2d_nd(self):
        x = self.rng.standard_normal((8, 12)).astype(np.float32)
        assert np.allclose(_np(pfft.fft2(paddle.to_tensor(x))),
                           np.fft.fft2(x), atol=1e-3)
        assert np.allclose(_np(pfft.rfft2(paddle.to_tensor(x))),
                           np.fft.rfft2(x), atol=1e-3)
        x3 = self.rng.standard_normal((4, 6, 10)).astype(np.float32)
        assert np.allclose(_np(pfft.fftn(paddle.to_tensor(x3))),
                           np.fft.fftn(x3), atol=1e-3)
        assert np.allclose(
            _np(pfft.irfftn(pfft.rfftn(paddle.to_tensor(x3)), s=x3.shape)),
            x3, atol=1e-4)

    def test_hfftn_ihfftn_match_scipy(self):
        # regression: leading axes used ifftn/fftn+conj instead of
        # fftn/ifftn
        import scipy.fft as sfft
        x = (self.rng.standard_normal((6, 5))
             + 1j * self.rng.standard_normal((6, 5))).astype(np.complex64)
        assert np.allclose(_np(pfft.hfft2(paddle.to_tensor(x))),
                           sfft.hfft2(x), atol=1e-3)
        r = self.rng.standard_normal((6, 8)).astype(np.float32)
        assert np.allclose(_np(pfft.ihfft2(paddle.to_tensor(r))),
                           sfft.ihfft2(r), atol=1e-5)
        x3 = (self.rng.standard_normal((3, 4, 5))
              + 1j * self.rng.standard_normal((3, 4, 5))).astype(np.complex64)
        assert np.allclose(_np(pfft.hfftn(paddle.to_tensor(x3))),
                           sfft.hfftn(x3), atol=1e-3)
        r3 = self.rng.standard_normal((3, 4, 8)).astype(np.float32)
        assert np.allclose(_np(pfft.ihfftn(paddle.to_tensor(r3))),
                           sfft.ihfftn(r3), atol=1e-5)
        # regression: s given with axes=None — scipy defaults to the LAST
        # len(s) axes
        assert np.allclose(_np(pfft.hfftn(paddle.to_tensor(x3), s=(4, 8))),
                           sfft.hfftn(x3, s=(4, 8)), atol=1e-3)

    def test_freq_shift(self):
        assert np.allclose(_np(pfft.fftfreq(10, 0.1)), np.fft.fftfreq(10, 0.1))
        assert np.allclose(_np(pfft.rfftfreq(10, 0.1)),
                           np.fft.rfftfreq(10, 0.1))
        x = self.rng.standard_normal((4, 5)).astype(np.float32)
        assert np.allclose(_np(pfft.fftshift(paddle.to_tensor(x))),
                           np.fft.fftshift(x))
        assert np.allclose(
            _np(pfft.ifftshift(pfft.fftshift(paddle.to_tensor(x)))), x)

    def test_grad_through_rfft(self):
        x = paddle.to_tensor(
            self.rng.standard_normal(16).astype(np.float32),
            stop_gradient=False)
        y = pfft.rfft(x)
        # |rfft(x)|^2 summed = parseval-ish; grad exists and is finite
        mag = (y.real() ** 2 + y.imag() ** 2).sum() if hasattr(y, "real") \
            else None
        if mag is None:
            import jax.numpy as jnp
            from paddle_tpu.autograd import apply_op
            mag = apply_op(lambda a: jnp.sum(jnp.abs(a) ** 2), y)
        g = paddle.grad(mag, x)[0]
        assert np.all(np.isfinite(_np(g)))


class TestSignal:
    def setup_method(self, m):
        self.rng = np.random.default_rng(1)

    def test_frame_shape_and_content(self):
        x = np.arange(10, dtype=np.float32)
        f = psignal.frame(paddle.to_tensor(x), frame_length=4, hop_length=2)
        assert tuple(f.shape) == (4, 4)
        ref = np.stack([x[i * 2:i * 2 + 4] for i in range(4)], -1)
        assert np.allclose(_np(f), ref)

    def test_frame_batched(self):
        x = self.rng.standard_normal((3, 20)).astype(np.float32)
        f = psignal.frame(paddle.to_tensor(x), 5, 3)
        assert tuple(f.shape) == (3, 5, 6)

    def test_overlap_add_inverts_nonoverlapping(self):
        x = self.rng.standard_normal((2, 12)).astype(np.float32)
        f = psignal.frame(paddle.to_tensor(x), 4, 4)
        back = psignal.overlap_add(f, 4)
        assert np.allclose(_np(back), x, atol=1e-6)

    def test_frame_axis0_reference_layout(self):
        # regression: axis=0 must give [num_frames, frame_length, ...]
        x = np.arange(10, dtype=np.float32)
        f = psignal.frame(paddle.to_tensor(x), 4, 2, axis=0)
        assert tuple(f.shape) == (4, 4)
        ref = np.stack([x[i * 2:i * 2 + 4] for i in range(4)], 0)
        assert np.allclose(_np(f), ref)
        # batched: [num, fl, B]
        xb = self.rng.standard_normal((20, 3)).astype(np.float32)
        fb = psignal.frame(paddle.to_tensor(xb), 5, 3, axis=0)
        assert tuple(fb.shape) == (6, 5, 3)
        # overlap_add round-trips the axis=0 layout
        f0 = psignal.frame(paddle.to_tensor(xb[:12]), 4, 4, axis=0)
        back = psignal.overlap_add(f0, 4, axis=0)
        assert np.allclose(_np(back), xb[:12], atol=1e-6)

    def test_stft_matches_manual_dft(self):
        x = self.rng.standard_normal((1, 64)).astype(np.float32)
        n_fft, hop = 16, 8
        w = np.hanning(n_fft + 1)[:-1].astype(np.float32)
        s = psignal.stft(paddle.to_tensor(x), n_fft, hop,
                         window=paddle.to_tensor(w), center=False)
        # manual reference
        frames = np.stack([x[0, i * hop:i * hop + n_fft] * w
                           for i in range((64 - n_fft) // hop + 1)], -1)
        ref = np.fft.rfft(frames, axis=0)
        assert tuple(s.shape) == (1, n_fft // 2 + 1, frames.shape[-1])
        assert np.allclose(_np(s)[0], ref, atol=1e-4)

    def test_stft_istft_roundtrip(self):
        x = self.rng.standard_normal((2, 256)).astype(np.float32)
        n_fft, hop = 64, 16
        w = np.hanning(n_fft + 1)[:-1].astype(np.float32)
        s = psignal.stft(paddle.to_tensor(x), n_fft, hop,
                         window=paddle.to_tensor(w))
        back = psignal.istft(s, n_fft, hop, window=paddle.to_tensor(w),
                             length=256)
        assert np.allclose(_np(back), x, atol=1e-4)

    def test_istft_return_complex(self):
        # regression: return_complex under onesided crashed on a shape
        # mismatch; now validated, and the onesided=False path round-trips
        x = self.rng.standard_normal((2, 256)).astype(np.float32)
        n_fft, hop = 64, 16
        w = np.hanning(n_fft + 1)[:-1].astype(np.float32)
        s1 = psignal.stft(paddle.to_tensor(x), n_fft, hop,
                          window=paddle.to_tensor(w))
        with pytest.raises(ValueError):
            psignal.istft(s1, n_fft, hop, window=paddle.to_tensor(w),
                          return_complex=True)
        s2 = psignal.stft(paddle.to_tensor(x), n_fft, hop,
                          window=paddle.to_tensor(w), onesided=False)
        back = psignal.istft(s2, n_fft, hop, window=paddle.to_tensor(w),
                             onesided=False, return_complex=True, length=256)
        b = _np(back)
        assert np.iscomplexobj(b)
        assert np.allclose(b.real, x, atol=1e-4)
        assert np.allclose(b.imag, 0.0, atol=1e-4)
