"""tools/fleet_top.py — the fleet htop (satellite of ISSUE 12).

Covers both data paths the tool ships:

- ``--snapshot`` offline mode rendered against the COMMITTED history
  archive (tools/golden/history_clean_wave.json) — the artifact every
  history_smoke run regenerates its claims from, so the offline
  renderer must keep reading it;
- the live-poll path against a stub exporter serving canned
  /healthz, /history, /tenants and /requests docs — collect_live
  must survive partial deployments (endpoints missing) and render
  the replica/tenant/recent-request tables.
"""
import importlib
import json
import os
import shutil
import sys

import pytest

from paddle_tpu.observability.exporter import MetricsExporter
from paddle_tpu.observability.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
ft = importlib.import_module("fleet_top")

GOLDEN_HISTORY = os.path.join(REPO, "tools", "golden",
                              "history_clean_wave.json")

HEALTH = {"queue_depth": 1, "pending": 2, "lost": [],
          "slo": {"alerting": ["ttft"]},
          "anomaly": {"alerting": []},
          "replicas": {
              "r0": {"state": "serving", "incarnation": 1,
                     "queued": 0, "running": 1, "free_pages": 6,
                     "scrape_age_s": 0.02, "lost": False,
                     "quarantined": False},
              "r1": {"state": "drained", "incarnation": 3,
                     "queued": 0, "running": 0, "free_pages": 7,
                     "scrape_age_s": 1.5, "lost": True,
                     "quarantined": True}},
          "overload": {"degraded": True, "brownout_level": 1,
                       "clamped_tenants": ["acme"], "target_s": 2.0,
                       "degraded_for_s": 1.2},
          "autoscale": {"state": "retiring", "replicas": 2,
                        "min": 1, "max": 4, "booting": "as1",
                        "retiring": "r1",
                        "last_decision": {"event": "scale_in_started",
                                          "replica": "r1", "t": 12.0},
                        "events": 3}}

TENANTS = {"tracked": 2, "capacity": 8, "evictions": 0,
           "error_bound": 0,
           "totals": {"tokens_in": 30, "tokens_out": 64,
                      "queue_wait_s": 0.2, "kv_page_s": 2.0,
                      "requests": 8},
           "tenants": [
               {"tenant": "acme", "weight": 70, "err": 0,
                "tokens_in": 20, "tokens_out": 50,
                "queue_wait_s": 0.1, "kv_page_s": 1.5,
                "requests": 5},
               {"tenant": "anon", "weight": 24, "err": 0,
                "tokens_in": 10, "tokens_out": 14,
                "queue_wait_s": 0.1, "kv_page_s": 0.5,
                "requests": 3}]}

REQUESTS = {"capture": {"dir": "/tmp/cap", "sample": 1.0},
            "requests": [
                {"rid": 4, "tenant": "acme", "status": "ok",
                 "ttft_s": 0.011, "e2e_s": 0.034, "replica": "r0",
                 "failovers": 0, "hedged": False,
                 "archive": {"segment": "cap-000001.jsonl",
                             "offset": 1234}, "ts": 0.0},
                {"rid": 5, "tenant": None, "status": "shed",
                 "ttft_s": None, "e2e_s": 0.002, "replica": None,
                 "failovers": 0, "hedged": False, "archive": None,
                 "ts": 0.0}]}


@pytest.fixture()
def stub_exporter():
    exp = MetricsExporter(
        registry=MetricsRegistry(), port=0,
        health_fn=lambda: HEALTH,
        history_fn=lambda params: {"value": 2.5}
        if params.get("series") else {"series": []},
        tenants_fn=lambda: TENANTS,
        requests_fn=lambda key: REQUESTS if key is None else None)
    yield exp
    exp.close()


class TestOfflineSnapshot:
    def test_committed_archive_renders(self, tmp_path):
        """--snapshot offline mode against the COMMITTED clean-wave
        history archive: the frame carries real history-derived
        rates and the renderer stays total on it."""
        shutil.copy(GOLDEN_HISTORY,
                    tmp_path / "history_snapshot.json")
        frame = ft.collect_snapshot(str(tmp_path))
        assert frame["ts"] is not None
        rates = frame["rates"]
        # the committed clean wave really served traffic
        assert rates["tok_s"] is not None and rates["tok_s"] > 0
        assert rates["ttft_p99_s"] is not None
        text = ft.render(frame)
        assert "tok/s" in text and "fleet_top" in text

    def test_main_snapshot_mode(self, tmp_path, capsys):
        shutil.copy(GOLDEN_HISTORY,
                    tmp_path / "history_snapshot.json")
        with open(tmp_path / "health.json", "w") as f:
            json.dump(HEALTH, f)
        with open(tmp_path / "tenants.json", "w") as f:
            json.dump(TENANTS, f)
        with open(tmp_path / "requests.json", "w") as f:
            json.dump(REQUESTS, f)
        rc = ft.main(["--snapshot", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "r0" in out and "acme" in out
        assert "cap-000001.jsonl@1234" in out

    def test_snapshot_without_sidecars(self, tmp_path):
        """health/tenants/requests sidecars are optional — a bare
        archive still renders (post-mortem dirs are often partial)."""
        shutil.copy(GOLDEN_HISTORY,
                    tmp_path / "history_snapshot.json")
        frame = ft.collect_snapshot(str(tmp_path))
        assert frame["health"] is None
        assert frame["requests"] is None
        assert "fleet_top" in ft.render(frame)


class TestLivePoll:
    def test_collect_live_full_stack(self, stub_exporter):
        frame = ft.collect_live(stub_exporter.url)
        assert frame["health"]["queue_depth"] == 1
        assert frame["tenants"]["tracked"] == 2
        assert frame["requests"]["requests"][0]["rid"] == 4
        # /history rollups answered by the stub
        assert frame["rates"]["req_s"] == 2.5
        assert frame["rates"]["ttft_p99_s"] == 2.5

    def test_render_live_frame(self, stub_exporter):
        text = ft.render(ft.collect_live(stub_exporter.url))
        # replica table with flags (lost + quarantined -> LQ)
        assert "r1" in text and "LQ" in text
        assert "serving" in text and "drained" in text
        # tenant table
        assert "acme" in text
        # recent-requests table with the archive locator
        assert "RECENT REQUESTS" in text
        assert "cap-000001.jsonl@1234" in text
        assert "shed" in text
        # SLO alert surfaced
        assert "ttft" in text

    def test_render_autoscaler_panel(self, stub_exporter):
        """The AUTOSCALER panel (ISSUE 15 satellite): controller
        state + bounds, degraded/brownout with the clamp set, last
        decision, and per-replica roles incl. the booting newcomer
        and the retiring victim."""
        text = ft.render(ft.collect_live(stub_exporter.url))
        assert "AUTOSCALER" in text
        assert "state=retiring" in text and "[1..4]" in text
        assert "degraded=yes" in text and "brownout=L1" in text
        assert "clamped=acme" in text
        assert "last: scale_in_started" in text
        assert "r1=retiring" in text and "as1=booting" in text
        assert "r0=serving" in text

    def test_main_live_once(self, stub_exporter, capsys):
        rc = ft.main(["--url", stub_exporter.url, "--once"])
        assert rc == 0
        assert "fleet_top" in capsys.readouterr().out

    def test_live_survives_missing_endpoints(self):
        """A router without tenancy/history/capture still renders —
        collect_live degrades per endpoint, never dies."""
        exp = MetricsExporter(registry=MetricsRegistry(), port=0,
                              health_fn=lambda: HEALTH)
        try:
            frame = ft.collect_live(exp.url)
            assert frame["tenants"] is None
            assert frame["requests"] is None
            assert frame["rates"]["req_s"] is None
            assert "r0" in ft.render(frame)
        finally:
            exp.close()

    def test_url_and_snapshot_mutually_exclusive(self):
        with pytest.raises(SystemExit):
            ft.main([])
        with pytest.raises(SystemExit):
            ft.main(["--url", "http://x", "--snapshot", "/tmp"])
