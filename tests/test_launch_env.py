"""launch_mod multi-host env wiring — mocked env + mocked
jax.distributed.initialize (VERDICT r2 next #9: the one distributed file
with zero tests).

ref parity: python/paddle/distributed/launch env conventions
(PADDLE_MASTER / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID).
"""
import pytest

from paddle_tpu.distributed import launch_mod


def test_parse_env_single():
    assert launch_mod.parse_env({}) == {"mode": "single"}


def test_parse_env_explicit_ours():
    cfg = launch_mod.parse_env({
        "PADDLE_TPU_COORDINATOR": "10.0.0.1:1234",
        "PADDLE_TPU_NUM_PROCESSES": "4",
        "PADDLE_TPU_PROCESS_ID": "2",
    })
    assert cfg == {"mode": "explicit",
                   "coordinator_address": "10.0.0.1:1234",
                   "num_processes": 4, "process_id": 2}


def test_parse_env_reference_names():
    """Reference scripts exporting PADDLE_MASTER etc. work unchanged."""
    cfg = launch_mod.parse_env({
        "PADDLE_MASTER": "host0:8090",
        "PADDLE_TRAINERS_NUM": "16",
        "PADDLE_TRAINER_ID": "7",
    })
    assert cfg == {"mode": "explicit", "coordinator_address": "host0:8090",
                   "num_processes": 16, "process_id": 7}


def test_parse_env_ours_wins_over_reference():
    cfg = launch_mod.parse_env({
        "PADDLE_TPU_COORDINATOR": "a:1", "PADDLE_MASTER": "b:2",
        "PADDLE_TPU_NUM_PROCESSES": "2", "PADDLE_TRAINERS_NUM": "8",
        "PADDLE_TPU_PROCESS_ID": "1", "PADDLE_TRAINER_ID": "5",
    })
    assert cfg["coordinator_address"] == "a:1"
    assert cfg["num_processes"] == 2
    assert cfg["process_id"] == 1


def test_parse_env_tpu_pod_metadata():
    assert launch_mod.parse_env(
        {"TPU_WORKER_HOSTNAMES": "w0,w1"})["mode"] == "tpu_pod"
    assert launch_mod.parse_env(
        {"MEGASCALE_COORDINATOR_ADDRESS": "c:99"})["mode"] == "tpu_pod"


def test_parse_env_defaults():
    cfg = launch_mod.parse_env({"PADDLE_TPU_COORDINATOR": "c:1"})
    assert cfg["num_processes"] == 1 and cfg["process_id"] == 0


@pytest.mark.parametrize("bad", [
    {"PADDLE_TPU_COORDINATOR": "c:1", "PADDLE_TPU_NUM_PROCESSES": "x"},
    {"PADDLE_TPU_COORDINATOR": "c:1", "PADDLE_TPU_PROCESS_ID": "three"},
])
def test_parse_env_malformed_ints(bad):
    with pytest.raises(ValueError, match="malformed"):
        launch_mod.parse_env(bad)


def test_parse_env_pid_out_of_range():
    with pytest.raises(ValueError, match="out of range"):
        launch_mod.parse_env({"PADDLE_TPU_COORDINATOR": "c:1",
                              "PADDLE_TPU_NUM_PROCESSES": "4",
                              "PADDLE_TPU_PROCESS_ID": "4"})


def test_launch_calls_initialize(monkeypatch):
    import jax
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setenv("PADDLE_TPU_COORDINATOR", "coord:7777")
    monkeypatch.setenv("PADDLE_TPU_NUM_PROCESSES", "2")
    monkeypatch.setenv("PADDLE_TPU_PROCESS_ID", "1")
    got = launch_mod.launch(lambda a, b: a + b, args=(1, 2))
    assert got == 3
    assert calls == [{"coordinator_address": "coord:7777",
                      "num_processes": 2, "process_id": 1}]


def test_launch_single_skips_initialize(monkeypatch):
    import jax

    def boom(**kw):
        raise AssertionError("initialize must not be called single-host")
    monkeypatch.setattr(jax.distributed, "initialize", boom)
    for v in ("PADDLE_TPU_COORDINATOR", "PADDLE_MASTER",
              "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(v, raising=False)
    assert launch_mod.launch(lambda: 42) == 42


def test_launch_tpu_pod_autodetect(monkeypatch):
    import jax
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.delenv("PADDLE_TPU_COORDINATOR", raising=False)
    monkeypatch.delenv("PADDLE_MASTER", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1,w2,w3")
    launch_mod.launch()
    assert calls == [{}]


def test_parse_env_no_family_mixing():
    """Stale reference-family exports must not leak into a PADDLE_TPU_*
    launch (a mixed world-size hangs initialize)."""
    cfg = launch_mod.parse_env({
        "PADDLE_TPU_COORDINATOR": "c:1",
        "PADDLE_TRAINERS_NUM": "8", "PADDLE_TRAINER_ID": "3",
    })
    assert cfg["num_processes"] == 1 and cfg["process_id"] == 0
