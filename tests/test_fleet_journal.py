"""Write-ahead journal units (paddle_tpu/serving_fleet/journal.py).

Pure host-side — no models, no jax arrays — so the whole disk-fault
surface drills in milliseconds:

- record framing: length-prefix + crc32, compact JSON payload;
- segment rotation: atomic write-then-rename + COMPLETE-marker
  (the shared io/atomic discipline), compaction drops old segments;
- torn-tail-tolerant replay: the FUZZ satellite truncates the journal
  at EVERY byte offset of the final record and asserts replay never
  crashes, never resurrects a duplicate, and drops at most the tail;
- reconcile(): per-rid lifecycle folding (accepted → placed →
  delivered → resolved → retired, failovers, snapshots);
- the three disk-fault seams (journal_torn_write / journal_io_error /
  journal_slow_fsync) and their metrics.
"""
import json
import os
import shutil
import time

import pytest

from paddle_tpu.io import atomic
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.resilience import faults
from paddle_tpu.serving_fleet.journal import (
    Journal, JournalCrash, JournalError, reconcile, replay)


def _mk(tmp_path, name="j", **kw):
    return Journal(os.path.join(tmp_path, name), **kw)


def _lifecycle(j, rid=0, tokens=(7, 8, 9)):
    j.append("accepted", rid=rid, prompt=[1, 2, 3], max_new=5,
             eos=None, priority=0, deadline_epoch=None,
             submitted_epoch=round(time.time(), 6))
    j.append("placed", rid=rid, replica="r0")
    j.append("delivered", rid=rid, tokens=list(tokens[:2]))
    j.append("resolved", result={"id": rid, "tokens": list(tokens),
                                 "status": "ok", "replica": "r0",
                                 "failovers": 0, "hedged": False})


class TestJournalCore:
    def test_append_replay_roundtrip(self, tmp_path):
        j = _mk(tmp_path)
        _lifecycle(j, rid=0)
        j.append("retired", rids=[0])
        recs, stats = replay(j.dir)
        assert stats["torn_tail_drops"] == 0
        kinds = [r["kind"] for r in recs]
        assert kinds == ["header", "accepted", "placed", "delivered",
                         "resolved", "retired"]
        st = reconcile(recs)
        assert st["retired"] == {0}
        assert st["requests"] == {}      # retired = compacted away
        assert st["next_rid"] == 1
        j.close()

    def test_reconcile_lifecycle_states(self, tmp_path):
        j = _mk(tmp_path)
        _lifecycle(j, rid=0)                      # resolved, unretired
        j.append("accepted", rid=1, prompt=[4, 5], max_new=3, eos=2,
                 priority=7, deadline_epoch=123.0,
                 submitted_epoch=100.0)
        j.append("placed", rid=1, replica="r1", prefix=0)
        j.append("delivered", rid=1, tokens=[9])
        j.append("failover", rid=1, replica="r1", reason="crash")
        j.append("accepted", rid=2, prompt=[6], max_new=4, eos=None,
                 priority=0, deadline_epoch=None, submitted_epoch=None)
        st = reconcile(replay(j.dir)[0])
        assert st["requests"][0]["resolved"]["tokens"] == [7, 8, 9]
        e1 = st["requests"][1]
        assert e1["resolved"] is None
        assert e1["replica"] is None         # failover cleared it
        assert e1["placed_prefix"] is None   # ...and its anchor
        assert e1["failovers"] == 1
        assert e1["delivered"] == [9]
        assert e1["priority"] == 7 and e1["eos"] == 2
        assert e1["deadline_epoch"] == 123.0
        e2 = st["requests"][2]
        assert e2["replica"] is None and e2["delivered"] == []
        assert st["next_rid"] == 3
        j.close()

    def test_resolved_after_retired_never_resurrects(self, tmp_path):
        """A backlog-flushed `resolved` record can land AFTER the
        rid's `retired` record in the segment — replay must not
        resurrect the rid (its result was already handed out;
        restoring it would deliver it twice across a crash)."""
        j = _mk(tmp_path)
        _lifecycle(j, rid=0)
        j.append("retired", rids=[0])
        j.append("resolved", result={"id": 0, "tokens": [7, 8, 9],
                                     "status": "ok", "replica": "r0",
                                     "failovers": 0, "hedged": False})
        j.append("placed", rid=0, replica="r1", prefix=0)
        st = reconcile(replay(j.dir)[0])
        assert st["retired"] == {0}
        assert st["requests"] == {}, \
            "retired rids must stay retired, whatever replays later"
        j.close()

    def test_delivered_keeps_longest_prefix(self, tmp_path):
        j = _mk(tmp_path)
        j.append("accepted", rid=0, prompt=[1], max_new=8, eos=None,
                 priority=0, deadline_epoch=None, submitted_epoch=None)
        j.append("delivered", rid=0, tokens=[5, 6, 7])
        j.append("delivered", rid=0, tokens=[5])   # stale, shorter
        st = reconcile(replay(j.dir)[0])
        assert st["requests"][0]["delivered"] == [5, 6, 7]
        j.close()

    def test_rotation_compacts_and_is_marked(self, tmp_path):
        j = _mk(tmp_path)
        _lifecycle(j, rid=0)
        j.append("retired", rids=[0])
        j.append("accepted", rid=1, prompt=[4], max_new=2, eos=None,
                 priority=0, deadline_epoch=None, submitted_epoch=None)
        snap = [{"kind": "snap_req", "rid": 1, "prompt": [4],
                 "max_new": 2, "eos": None, "priority": 0,
                 "deadline_epoch": None, "submitted_epoch": None,
                 "delivered": [], "replica": None, "failovers": 0}]
        j.rotate(snap, next_rid=2)
        names = sorted(os.listdir(j.dir))
        assert names == ["wal-000002.jsonl", "wal-000002.jsonl.complete"]
        assert atomic.has_marker(j.active_path)
        marker = json.load(open(atomic.marker_path(j.active_path)))
        assert marker["segment"] == 2 and marker["records"] == 1
        # appends continue into the rotated segment; replay sees
        # snapshot + tail, old rids only via next_rid
        j.append("placed", rid=1, replica="r1")
        st = reconcile(replay(j.dir)[0])
        assert sorted(st["requests"]) == [1]
        assert st["requests"][1]["replica"] == "r1"
        assert st["next_rid"] == 2
        j.close()

    def test_needs_rotation_threshold(self, tmp_path):
        j = _mk(tmp_path, segment_max_bytes=256)
        assert not j.needs_rotation
        for i in range(8):
            j.append("accepted", rid=i, prompt=[1] * 8, max_new=4,
                     eos=None, priority=0, deadline_epoch=None,
                     submitted_epoch=None)
        assert j.needs_rotation
        j.rotate([], next_rid=8)
        assert not j.needs_rotation
        j.close()

    def test_seal_marks_clean_shutdown(self, tmp_path):
        j = _mk(tmp_path, fsync_every=64)   # leave an unsynced tail
        _lifecycle(j, rid=0)
        assert not replay(j.dir)[1]["sealed"]
        j.seal()
        j.seal()   # idempotent
        recs, stats = replay(j.dir)
        assert stats["sealed"] and reconcile(recs)["sealed"]
        # appends inside the grace window stay legal after the seal
        j.append("retired", rids=[0])
        assert reconcile(replay(j.dir)[0])["retired"] == {0}
        j.close()

    def test_replay_empty_and_missing_dir(self, tmp_path):
        recs, stats = replay(os.path.join(tmp_path, "nope"))
        assert recs == [] and stats["replay_records"] == 0
        j = _mk(tmp_path)          # header only
        recs, stats = replay(j.dir)
        assert [r["kind"] for r in recs] == ["header"]
        assert reconcile(recs)["requests"] == {}
        j.close()


class TestJournalFaultSeams:
    def test_torn_write_tears_record_and_kills_journal(self, tmp_path):
        reg = MetricsRegistry()
        j = _mk(tmp_path, registry=reg)
        with faults.scenario(("journal_torn_write", {"step": 3})):
            _lifecycle_gen = [
                lambda: j.append("accepted", rid=0, prompt=[1],
                                 max_new=2, eos=None, priority=0,
                                 deadline_epoch=None,
                                 submitted_epoch=None),
                lambda: j.append("placed", rid=0, replica="r0"),
            ]
            for fn in _lifecycle_gen:
                fn()
            with pytest.raises(JournalCrash):
                j.append("delivered", rid=0, tokens=[5])
            # the journal is dead — every later write refuses, exactly
            # like the process that died mid-append
            with pytest.raises(JournalCrash):
                j.append("retired", rids=[0])
        recs, stats = replay(j.dir)
        assert stats["torn_tail_drops"] == 1
        assert [r["kind"] for r in recs] == ["header", "accepted",
                                             "placed"]
        st = reconcile(recs)
        assert st["requests"][0]["delivered"] == []   # torn record gone
        j.close()

    def test_reopen_over_torn_tail_repairs_newline(self, tmp_path):
        """A successor journal opened over a torn segment must
        terminate the torn line before appending — otherwise its
        first record concatenates onto the torn bytes and is silently
        unreplayable (an acked-but-unjournaled hole if the successor
        dies again before compacting)."""
        j = _mk(tmp_path)
        with faults.scenario(("journal_torn_write", {"step": 2})):
            j.append("accepted", rid=0, prompt=[1], max_new=4,
                     eos=None, priority=0, deadline_epoch=None,
                     submitted_epoch=None)
            with pytest.raises(JournalCrash):
                j.append("placed", rid=0, replica="r0")
        j2 = Journal(j.dir)          # the successor incarnation
        j2.append("placed", rid=0, replica="r1")
        recs, stats = replay(j.dir)
        assert stats["torn_tail_drops"] == 1
        assert [r.get("replica") for r in recs
                if r["kind"] == "placed"] == ["r1"], \
            "the post-repair record must replay"
        assert reconcile(recs)["requests"][0]["replica"] == "r1"
        j2.close()

    def test_io_error_raises_with_nothing_written(self, tmp_path):
        reg = MetricsRegistry()
        j = _mk(tmp_path, registry=reg)
        with faults.scenario(("journal_io_error", {"step": 2})):
            j.append("accepted", rid=0, prompt=[1], max_new=2,
                     eos=None, priority=0, deadline_epoch=None,
                     submitted_epoch=None)
            with pytest.raises(JournalError):
                j.append("placed", rid=0, replica="r0")
            j.append("placed", rid=0, replica="r1")  # disk recovered
        recs, _ = replay(j.dir)
        assert [r.get("replica") for r in recs
                if r["kind"] == "placed"] == ["r1"]
        assert reg.get("fleet_journal_errors_total").value == 1
        # the failed append is NOT counted — nothing was written
        assert reg.get("fleet_journal_appends_total").value == 2
        j.close()

    def test_slow_fsync_stalls_never_corrupts(self, tmp_path):
        j = _mk(tmp_path)
        with faults.scenario(("journal_slow_fsync",
                              {"seconds": 0.05})):
            t0 = time.monotonic()
            j.append("accepted", rid=0, prompt=[1], max_new=2,
                     eos=None, priority=0, deadline_epoch=None,
                     submitted_epoch=None)
            assert time.monotonic() - t0 >= 0.05
        recs, stats = replay(j.dir)
        assert stats["torn_tail_drops"] == 0
        assert recs[-1]["kind"] == "accepted"
        j.close()

    def test_metrics_catalogue(self, tmp_path):
        reg = MetricsRegistry()
        j = _mk(tmp_path, registry=reg)
        _lifecycle(j, rid=0)
        j.rotate([], next_rid=1)
        for name in ("appends", "bytes", "fsyncs", "rotations"):
            c = reg.get(f"fleet_journal_{name}_total")
            assert c is not None and c.value > 0, name
        for name in ("errors", "replay_records", "torn_tail_drops"):
            assert reg.get(f"fleet_journal_{name}_total") is not None
        j.close()


class TestTornTailFuzz:
    """Satellite: truncate the journal at EVERY byte offset of the
    final record; recovery must never crash, never duplicate a
    result, and drop at most the torn tail."""

    def _build(self, tmp_path):
        j = _mk(tmp_path, name="fuzz")
        _lifecycle(j, rid=0)                       # resolved
        j.append("accepted", rid=1, prompt=[4, 5], max_new=6,
                 eos=None, priority=1, deadline_epoch=None,
                 submitted_epoch=None)
        j.append("placed", rid=1, replica="r1")
        j.append("delivered", rid=1, tokens=[8, 9])
        # the FINAL record: a second resolution — the fuzz tears it
        # at every byte, which must never resurrect rid 0's result or
        # invent a partial rid-1 result
        j.append("resolved", result={"id": 1, "tokens": [8, 9, 10],
                                     "status": "ok", "replica": "r1",
                                     "failovers": 0, "hedged": False})
        j.close()
        return j.dir

    def test_truncate_every_byte_of_final_record(self, tmp_path):
        src = self._build(tmp_path)
        seg = os.path.join(src, "wal-000001.jsonl")
        data = open(seg, "rb").read()
        # strip the final frame; keep its byte count for the sweep
        body = data[:-1].rsplit(b"\n", 1)[0] + b"\n"
        final_len = len(data) - len(body)
        assert final_len > 20
        full = reconcile(replay(src)[0])
        assert full["requests"][1]["resolved"] is not None
        work = os.path.join(tmp_path, "cut")
        for cut in range(final_len + 1):
            shutil.rmtree(work, ignore_errors=True)
            shutil.copytree(src, work)
            with open(os.path.join(work, "wal-000001.jsonl"),
                      "r+b") as f:
                f.truncate(len(body) + cut)
            recs, stats = replay(work)       # never crashes
            st = reconcile(recs)
            # at most the torn tail is dropped — every earlier record
            # survives intact
            assert stats["torn_tail_drops"] <= 1, cut
            assert stats["replay_records"] >= 8, cut
            assert st["requests"][0]["resolved"]["tokens"] \
                == [7, 8, 9], cut
            e1 = st["requests"][1]
            assert e1["delivered"] == [8, 9], cut
            # the torn final record either fully survives (cut at the
            # very end) or is fully dropped — never a partial result,
            # never a duplicate
            if e1["resolved"] is not None:
                assert e1["resolved"] == \
                    full["requests"][1]["resolved"], cut
            else:
                # tail dropped: the request stays unresolved with its
                # journaled placement — recovery resubmits it
                assert e1["replica"] == "r1", cut
            # recovery state is a per-rid map by construction: no rid
            # can resolve twice out of a reconcile
            assert sorted(st["requests"]) == [0, 1], cut

    def test_mid_file_garbage_resyncs_at_newline(self, tmp_path):
        src = self._build(tmp_path)
        seg = os.path.join(src, "wal-000001.jsonl")
        lines = open(seg, "rb").read().split(b"\n")
        lines[2] = lines[2][: len(lines[2]) // 2]   # corrupt ONE line
        open(seg, "wb").write(b"\n".join(lines))
        recs, stats = replay(src)
        assert stats["torn_tail_drops"] == 1
        # every other record still parses — replay resynced
        assert stats["replay_records"] == 8
