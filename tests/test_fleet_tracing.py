"""Fleet-wide distributed tracing, latency attribution, SLO burn rate
(paddle_tpu/observability/{dtrace,slo}.py + serving_fleet wiring).

Pins the ISSUE-8 contracts (docs/observability.md "Distributed
tracing & SLOs"):

- every fleet request yields ONE causally-linked span tree covering
  placement wait, transport, and each replica leg's queue/prefill/
  decode — and its hop-by-hop attribution sums to the measured
  end-to-end wall time within tolerance;
- a crash-mid-decode failover keeps BOTH replica legs in the same
  tree (the lost leg annotated ``failover_source``, the continuation
  carrying the prefix-dedup boundary) and still attributes within
  tolerance;
- a hedged request's losing leg stays in the tree as
  ``outcome=cancelled``;
- the cross-replica Perfetto merge is valid traceEvents JSON with a
  router lane, one lane per replica, and monotonic per-lane spans;
- burn-rate alerts fire on an injected deadline-miss storm and clear
  after recovery, scrapeable as ``fleet_slo_*`` gauges;
- the flight recorder dumps on fleet failover / shed storm / router
  exception with the fleet registry + victim trace tree attached;
- store hygiene: eviction drops WHOLE trees (never an interior
  node), emission is suppressed under ``introspecting()``, exports
  stay RFC-valid under NaN/Inf — and fleet compile counts stay
  frozen with tracing enabled.

`pytest -m chaos` selects the chaos classes; the campaign's
fleet_chaos_smoke stage runs them together with test_fleet_serving.
"""
import json
import time
from urllib.request import urlopen

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.observability import dtrace as dtrace_mod
from paddle_tpu.observability.dtrace import TraceStore, hop
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.slo import SLObjective, SLOTracker
from paddle_tpu.observability.spans import SpanRecorder
from paddle_tpu.resilience import faults
from paddle_tpu.serving_fleet import FleetRouter, InprocReplica

NEW_TOK = 10
WAVE_LENS = (5, 12, 17, 9)


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


@pytest.fixture(scope="module")
def wave(gpt_model):
    prompts = _prompts(WAVE_LENS)
    eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                        max_seq_len=64, steps_per_dispatch=4)
    refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
    eng.close()
    return prompts, refs


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(model, **d)


def _warm(eng):
    eng.generate(_prompts((5, 17), seed=7), max_new_tokens=4)
    eng.reset_counters()


def _fleet(model, n=3, router_kw=None, **engine_kw):
    # fresh global trace store per fleet: the engines record into the
    # process-global store, so the router must share it
    dtrace_mod.get_store().clear()
    engines = [_engine(model, **engine_kw) for _ in range(n)]
    for e in engines:
        _warm(e)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e) for i, e in enumerate(engines)]
    router = FleetRouter(reps, **(router_kw or {}))
    import conftest
    conftest.fleet_stage_registries.append(router.registry)
    return router, reps, engines, frozen


def _assert_frozen(engines, frozen, router):
    for i, eng in enumerate(engines):
        assert eng.compile_counts() == frozen[i], \
            f"replica {i} compiled something with tracing on"
    assert router.compile_report()["unexpected_retraces"] == 0


def _legs(report):
    return [h for h in report["attribution"]["hops"]
            if h["name"] == "replica_leg"]


# -- trace store units ---------------------------------------------------


class TestTraceStore:
    def test_whole_tree_eviction_never_orphans(self):
        s = TraceStore(max_traces=3)
        ctxs = []
        for i in range(8):
            ctx = s.new_trace(rid=i)
            leg = s.start_span(ctx, "leg", proc="r0")
            s.add_span(leg, "queue", dtrace_mod.now())
            s.end_span(leg, outcome="ok")
            s.end_span(ctx, outcome="ok")
            ctxs.append(ctx)
        assert len(s.trace_ids()) == 3
        # only the NEWEST whole trees survive; every surviving span's
        # parent is present (no interior-node eviction)
        for tid in s.trace_ids():
            spans = s.spans(tid)
            ids = {sp["id"] for sp in spans}
            assert all(sp["parent"] is None or sp["parent"] in ids
                       for sp in spans)
            assert s.tree(tid)["root"]["name"] == "request"
        assert s.tree(ctxs[0]["trace_id"]) is None  # oldest: whole
        #                                             tree gone

    def test_truncation_drops_new_spans_not_interior_nodes(self):
        s = TraceStore(max_spans_per_trace=3)
        ctx = s.new_trace(rid=1)
        leg = s.start_span(ctx, "leg", proc="r0")
        assert s.add_span(leg, "queue", dtrace_mod.now()) is not None
        # cap reached: new spans are refused, the tree stays intact
        assert s.add_span(leg, "prefill_16", dtrace_mod.now()) is None
        assert s.start_span(ctx, "leg2", proc="r1") is None
        t = s.tree(ctx["trace_id"])
        assert t["truncated"]
        ids = {sp["id"] for sp in s.spans(ctx["trace_id"])}
        assert all(sp["parent"] is None or sp["parent"] in ids
                   for sp in s.spans(ctx["trace_id"]))

    def test_hop_budget_exhausts_to_none(self):
        s = TraceStore()
        ctx = s.new_trace(hops=2)
        h1 = hop(ctx)
        h2 = hop(h1)
        assert h1["hops"] == 1 and h2["hops"] == 0
        assert hop(h2) is None
        assert hop(None) is None

    def test_suppressed_under_introspection(self):
        from paddle_tpu.observability import introspect
        s = TraceStore()
        rec = SpanRecorder()
        introspect._introspecting.on = True
        try:
            assert s.new_trace(rid=1) is None
            assert rec.add("x", dtrace_mod.now()) is None
            assert rec.instant("y") is None
        finally:
            introspect._introspecting.on = False
        assert s.trace_ids() == []
        assert rec.events() == []
        # and emission works again once the flag drops
        assert s.new_trace(rid=1) is not None
        assert rec.add("x", dtrace_mod.now()) is not None

    def test_export_rfc_valid_under_nan_inf(self, tmp_path):
        s = TraceStore()
        ctx = s.new_trace(rid=1)
        s.add_span(ctx, "queue", dtrace_mod.now(),
                   args={"bad": float("nan"), "worse": float("inf")})
        s.end_span(ctx, outcome="ok")
        path = s.export_chrome(str(tmp_path / "t.json"))
        doc = json.load(open(path))  # bare NaN tokens would raise
        assert doc["traceEvents"]

    def test_serial_sum_excludes_only_hedge_losers(self):
        """A client-CANCELLED leg is real serial work and stays in
        hops_sum_s; only hedge_loser-annotated legs (which overlap
        the winner by construction) are excluded."""
        s = TraceStore()
        ctx = s.new_trace(rid=1, t0=100.0)
        a = s.start_span(ctx, "replica_leg", proc="r0", t0=100.0)
        s.end_span(a, t1=102.0, outcome="cancelled")
        b = s.start_span(ctx, "replica_leg", proc="r1", t0=100.5,
                         args={"hedge_loser": True})
        s.end_span(b, t1=101.5, outcome="cancelled")
        s.end_span(ctx, t1=102.0, outcome="cancelled")
        att = s.attribution(ctx["trace_id"])
        assert att["hops_sum_s"] == pytest.approx(2.0)
        assert att["within_tolerance"]

    def test_summaries_one_pass_index(self):
        s = TraceStore()
        ctx = s.new_trace(rid=9, t0=10.0)
        s.end_span(ctx, t1=10.5, outcome="ok")
        (row,) = s.summaries()
        assert row["rid"] == 9 and row["outcome"] == "ok"
        assert row["e2e_s"] == pytest.approx(0.5)
        assert row["spans"] == 1 and not row["truncated"]

    def test_end_span_first_close_wins(self):
        s = TraceStore()
        ctx = s.new_trace(rid=1)
        leg = s.start_span(ctx, "leg", proc="r0")
        s.end_span(leg, outcome="cancelled")
        s.end_span(leg, outcome="ok")  # late result: must not rewrite
        spans = {sp["name"]: sp for sp in s.spans(ctx["trace_id"])}
        assert spans["leg"]["outcome"] == "cancelled"


# -- SLO units -----------------------------------------------------------


class TestSLOTracker:
    def _tracker(self, reg=None):
        return SLOTracker(
            [SLObjective("e2e", "latency", target=0.9, threshold_s=1.0),
             SLObjective("availability", "availability", target=0.9)],
            windows=[{"short_s": 1.0, "long_s": 5.0, "burn": 2.0}],
            registry=reg)

    def test_alert_fires_on_storm_and_clears_after_recovery(self):
        reg = MetricsRegistry()
        tr = self._tracker(reg)
        for i in range(20):
            tr.record_latency("e2e", 5.0, now=10.0 + i * 0.01)
        rep = tr.evaluate(now=10.3)
        assert rep["e2e"]["alert"]
        assert reg.get("fleet_slo_alert", {"slo": "e2e"}).value == 1
        for i in range(50):
            tr.record_latency("e2e", 0.1, now=12.0 + i * 0.01)
        rep = tr.evaluate(now=16.0)  # short window clean -> clears
        assert not rep["e2e"]["alert"]
        assert reg.get("fleet_slo_alert", {"slo": "e2e"}).value == 0

    def test_no_traffic_burns_nothing(self):
        tr = self._tracker()
        rep = tr.evaluate(now=100.0)
        assert rep["e2e"]["sli"] is None
        assert not rep["e2e"]["alert"]

    def test_availability_classification(self):
        tr = self._tracker()
        # all inside the 5s retention horizon at evaluate time
        for i in range(9):
            tr.record_event("availability", good=True,
                            now=55.5 + i * 0.5)
        tr.record_event("availability", good=False, now=59.9)
        rep = tr.evaluate(now=60.0)
        assert rep["availability"]["events"] == 10
        assert rep["availability"]["bad"] == 1
        assert rep["availability"]["sli"] == pytest.approx(0.9)

    def test_validation(self):
        with pytest.raises(ValueError, match="threshold_s"):
            SLObjective("x", "latency")
        with pytest.raises(ValueError, match="latency | availability"):
            SLObjective("x", "nope", threshold_s=1.0)
        with pytest.raises(ValueError, match="duplicate"):
            SLOTracker([SLObjective("a", "availability", target=0.9),
                        SLObjective("a", "availability", target=0.9)])


# -- fleet chaos (campaign stage: fleet_chaos_smoke) ---------------------


@pytest.mark.chaos
class TestFleetTracingChaos:
    def test_clean_wave_attribution_and_endpoints(self, gpt_model,
                                                  wave):
        """Every request of a clean wave yields one span tree whose
        hops cover e2e within tolerance; /traces, /report and
        /healthz answer with the new payloads; compile counts stay
        frozen with tracing enabled."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(gpt_model, n=2)
        exp = router.serve_metrics(port=0)
        try:
            rids = [router.submit(p, NEW_TOK) for p in prompts]
            res = {r["id"]: r for r in router.run_to_completion()}
            assert [res[i]["tokens"] for i in rids] == refs
            for rid in rids:
                assert res[rid]["trace_id"]
                rep = router.trace_report(rid)
                att = rep["attribution"]
                assert att["within_tolerance"], att
                assert att["e2e_s"] == pytest.approx(
                    res[rid]["age_s"], rel=0.2, abs=0.05)
                names = [h["name"] for h in att["hops"]]
                assert "placement_wait" in names
                legs = _legs(rep)
                assert len(legs) == 1 and legs[0]["outcome"] == "ok"
                kid_names = [k["name"] for k in legs[0]["children"]]
                assert "queue" in kid_names
                assert any(k.startswith("prefill_")
                           for k in kid_names)
                assert "decode" in kid_names
                assert "transport_submit" in kid_names
                # serial hops sum to e2e within the 5% tolerance
                assert abs(att["hops_sum_s"] - att["e2e_s"]) \
                    <= 0.05 * att["e2e_s"] + 0.01
            # live endpoints
            idx = json.loads(urlopen(f"{exp.url}/traces",
                                     timeout=5).read().decode())
            assert {t["rid"] for t in idx["traces"]} >= set(rids)
            one = json.loads(urlopen(f"{exp.url}/traces/{rids[0]}",
                                     timeout=5).read().decode())
            assert one["trace"]["root"]["name"] == "request"
            report = json.loads(urlopen(f"{exp.url}/report",
                                        timeout=5).read().decode())
            assert report["fleet_compile_report"][
                "unexpected_retraces"] == 0
            health = json.loads(urlopen(f"{exp.url}/healthz",
                                        timeout=5).read().decode())
            assert "slo" in health
            metrics = urlopen(f"{exp.url}/metrics",
                              timeout=5).read().decode()
            assert "fleet_slo_alert" in metrics
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_crash_failover_one_trace_two_legs(self, gpt_model, wave,
                                               tmp_path, monkeypatch):
        """THE acceptance drill: a crash-mid-decode failover produces
        ONE trace with two causally-linked replica legs (lost leg
        ``failover_source`` with the harvested prefix, continuation
        carrying the prefix-dedup boundary), attribution still sums
        to e2e within tolerance, the merged Perfetto timeline carries
        a router lane + per-replica lanes with monotonic spans, and
        the flight recorder dumped the failover with the victim's
        tree."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        from paddle_tpu.observability import flightrec
        flightrec.get_recorder().clear()
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(gpt_model)
        try:
            with faults.scenario(("replica_crash", {"replica": "r1"})):
                rids = [router.submit(p, NEW_TOK) for p in prompts]
                res = {r["id"]: r for r in router.run_to_completion()}
            assert [res[i]["tokens"] for i in rids] == refs
            victims = [rid for rid in rids if res[rid]["failovers"]]
            assert victims, "the crash must have cost someone a leg"
            for rid in victims:
                rep = router.trace_report(rid)
                legs = _legs(rep)
                assert len(legs) >= 2, \
                    "failover must leave both legs in ONE tree"
                lost = [h for h in legs
                        if h["outcome"] == "failover_source"]
                assert lost and lost[0]["proc"] == "r1"
                cont = [h for h in legs if h["args"].get("failover_of")]
                assert cont, "continuation leg must be in the tree"
                for h in cont:
                    assert ("prefix_dedup" in h["args"]) == \
                        (h["args"].get("prefix_tokens", 0) > 0)
                att = rep["attribution"]
                assert att["within_tolerance"], att
                assert abs(att["hops_sum_s"] - att["e2e_s"]) \
                    <= 0.05 * att["e2e_s"] + 0.01
            _assert_frozen(engines, frozen, router)
            # merged Perfetto timeline: router + both replica lanes,
            # valid traceEvents JSON, monotonic per-lane spans
            path = router.export_timeline(str(tmp_path / "fleet.json"))
            doc = json.load(open(path))
            procs = {e["args"]["name"] for e in doc["traceEvents"]
                     if e.get("name") == "process_name"}
            assert "router" in procs
            assert {"r0", "r1"} & procs == {"r0", "r1"}
            lanes = {}
            for e in doc["traceEvents"]:
                if e.get("ph") == "X":
                    assert e["dur"] >= 0
                    lanes.setdefault((e["pid"], e["tid"]),
                                     []).append(e["ts"])
            assert lanes
            for ts in lanes.values():
                assert ts == sorted(ts), "per-lane spans must be " \
                    "time-ordered"
            # flight recorder: the failover dumped with the victim's
            # trace tree + the fleet registry snapshot
            dumps = sorted(tmp_path.glob("flight_fleet_failover*.json"))
            assert dumps, "failover must trigger a flight dump"
            dump = json.load(open(dumps[0]))
            assert dump["reason"] == "fleet_failover"
            assert dump["failover_reason"] == "crash"
            assert dump["replica"] == "r1"
            assert isinstance(dump["fleet_registry"], dict)
            assert dump["victim_trace"]["root"]["name"] == "request"
        finally:
            router.close()

    def test_hedge_loser_leg_cancelled_in_tree(self, gpt_model, wave):
        """The losing hedge leg stays in the trace, annotated
        outcome=cancelled (hedge_loser) — the winner reads ok."""
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=2,
            router_kw={"hedge_after_ms": 60, "wedge_timeout_s": 30.0})
        try:
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.05})):
                rid = router.submit(prompts[0], NEW_TOK)
                (result,) = router.run_to_completion()
            assert result["tokens"] == refs[0] and result["hedged"]
            rep = router.trace_report(rid)
            legs = _legs(rep)
            assert len(legs) == 2
            by_outcome = {h["outcome"]: h for h in legs}
            assert by_outcome["cancelled"]["args"].get("hedge_loser")
            assert by_outcome["cancelled"]["proc"] == "r0"
            assert by_outcome["ok"]["proc"] == "r1"
            assert by_outcome["ok"]["args"].get("hedge")
            # the cancelled leg is excluded from the serial sum but
            # counted in interval coverage — tolerance still holds
            assert rep["attribution"]["within_tolerance"]
            _assert_frozen(engines, frozen, router)
        finally:
            router.close()

    def test_burn_alert_fires_on_deadline_storm_and_clears(
            self, gpt_model, wave, tmp_path, monkeypatch):
        """An injected deadline-miss storm lights the availability
        burn alert (gauges + health rollup); clean traffic after the
        short window clears it. Piggybacks the router-exception
        flight-dump check on the same fleet."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        prompts, refs = wave
        slos = (SLObjective("availability", "availability",
                            target=0.9),)
        windows = ({"short_s": 0.5, "long_s": 3.0, "burn": 1.0},)
        router, reps, engines, frozen = _fleet(
            gpt_model, n=1,
            router_kw={"slos": slos, "slo_windows": windows})
        try:
            with faults.scenario(
                    ("replica_slow", {"replica": "r0", "count": 1000,
                                      "seconds": 0.05})):
                for p in prompts:
                    router.submit(p, NEW_TOK, deadline_ms=1)
                res = router.run_to_completion()
            assert {r["status"] for r in res} == {"expired"}
            assert router._slo_state["availability"]["alert"]
            assert router.health()["slo"]["alerting"] \
                == ["availability"]
            g = router.registry.get("fleet_slo_alert",
                                    {"slo": "availability"})
            assert g is not None and g.value == 1
            # recovery: wait out the short window, serve clean
            time.sleep(0.6)
            assert router.generate(prompts[:2],
                                   max_new_tokens=NEW_TOK) == refs[:2]
            assert not router._slo_state["availability"]["alert"]
            assert router.health()["slo"]["alerting"] == []
            assert g.value == 0
            _assert_frozen(engines, frozen, router)
            # router-loop exception -> flight dump, then error
            monkeypatch.setattr(
                router, "_hedge",
                lambda: (_ for _ in ()).throw(RuntimeError("boom")))
            with pytest.raises(RuntimeError, match="boom"):
                router.step()
            dumps = sorted(
                tmp_path.glob("flight_fleet_router_exception*.json"))
            assert dumps and json.load(open(dumps[0]))["error"]
        finally:
            router.close()

    def test_shed_storm_flight_dump(self, gpt_model, wave, tmp_path,
                                    monkeypatch):
        """Sheds past the threshold inside the window dump ONE
        shed-storm flight record carrying a victim trace tree."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        prompts, refs = wave
        router, reps, engines, frozen = _fleet(
            gpt_model, n=1, max_slots=1,
            router_kw={"max_queue": 1, "replica_queue_limit": 1,
                       "shed_storm_threshold": 2,
                       "shed_storm_window_s": 0.5})
        try:
            rids = [router.submit(p, NEW_TOK)
                    for p in prompts + prompts]
            res = {r["id"]: r for r in router.run_to_completion()}
            shed = [r for r in rids if res[r]["status"] == "shed"]
            assert len(shed) >= 2
            dumps = sorted(
                tmp_path.glob("flight_fleet_shed_storm*.json"))
            assert len(dumps) == 1, "one storm -> one dump"
            doc = json.load(open(dumps[0]))
            assert doc["shed_in_window"] >= 2
            assert doc["victim_trace"]["root"]["args"]["priority"] == 0
            # a shed request's trace still tiles e2e: its router-queue
            # wait is a hop, not unattributed time
            rep = router.trace_report(shed[0])
            att = rep["attribution"]
            assert att["outcome"] == "shed"
            assert any(h["name"] == "router_queue"
                       for h in att["hops"])
            assert att["within_tolerance"], att
            # re-arm: a SECOND storm after the window drains dumps
            # again (regression: the armed flag used to stay down
            # when the next storm's first batch already met the
            # threshold)
            time.sleep(0.6)
            router._note_shed_storm(shed[:2])
            dumps = sorted(
                tmp_path.glob("flight_fleet_shed_storm*.json"))
            assert len(dumps) == 2, "post-drain storm must re-dump"
        finally:
            router.close()
