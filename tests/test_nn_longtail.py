"""Long-tail nn layers/losses vs torch reference numerics (torch-cpu is in
the image; torch and the reference share these ops' definitions)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip(
    "torch",
    reason="environmental gate: torch-cpu (baked into the image) is the "
           "reference implementation these numerics pin against")


def _np(t):
    return np.asarray(t.numpy() if hasattr(t, "numpy") else t)


def _tt(x):
    return torch.tensor(x)


class TestLossesVsTorch:
    def setup_method(self, m):
        self.rng = np.random.default_rng(0)

    def test_gaussian_nll(self):
        x = self.rng.standard_normal((8, 4)).astype(np.float32)
        y = self.rng.standard_normal((8, 4)).astype(np.float32)
        var = (self.rng.random((8, 4)).astype(np.float32) + 0.1)
        for full in (False, True):
            got = _np(F.gaussian_nll_loss(
                paddle.to_tensor(x), paddle.to_tensor(y),
                paddle.to_tensor(var), full=full))
            ref = torch.nn.functional.gaussian_nll_loss(
                _tt(x), _tt(y), _tt(var), full=full).numpy()
            assert np.allclose(got, ref, atol=1e-5), full

    def test_soft_margin(self):
        x = self.rng.standard_normal((10,)).astype(np.float32)
        y = np.where(self.rng.random(10) > 0.5, 1.0, -1.0).astype(np.float32)
        got = _np(F.soft_margin_loss(paddle.to_tensor(x),
                                     paddle.to_tensor(y)))
        ref = torch.nn.functional.soft_margin_loss(_tt(x), _tt(y)).numpy()
        assert np.allclose(got, ref, atol=1e-6)

    def test_multi_label_soft_margin(self):
        x = self.rng.standard_normal((6, 5)).astype(np.float32)
        y = (self.rng.random((6, 5)) > 0.5).astype(np.float32)
        got = _np(F.multi_label_soft_margin_loss(
            paddle.to_tensor(x), paddle.to_tensor(y)))
        ref = torch.nn.functional.multilabel_soft_margin_loss(
            _tt(x), _tt(y)).numpy()
        assert np.allclose(got, ref, atol=1e-6)

    def test_multi_margin(self):
        x = self.rng.standard_normal((6, 5)).astype(np.float32)
        y = self.rng.integers(0, 5, 6)
        got = _np(F.multi_margin_loss(paddle.to_tensor(x),
                                      paddle.to_tensor(y)))
        ref = torch.nn.functional.multi_margin_loss(
            _tt(x), torch.tensor(y, dtype=torch.long)).numpy()
        assert np.allclose(got, ref, atol=1e-6)

    def test_triplet_with_distance(self):
        a = self.rng.standard_normal((6, 8)).astype(np.float32)
        p = self.rng.standard_normal((6, 8)).astype(np.float32)
        n = self.rng.standard_normal((6, 8)).astype(np.float32)
        for swap in (False, True):
            got = _np(F.triplet_margin_with_distance_loss(
                paddle.to_tensor(a), paddle.to_tensor(p),
                paddle.to_tensor(n), swap=swap))
            ref = torch.nn.functional.triplet_margin_with_distance_loss(
                _tt(a), _tt(p), _tt(n), swap=swap).numpy()
            assert np.allclose(got, ref, atol=1e-5), swap


class TestLayersVsTorch:
    def setup_method(self, m):
        self.rng = np.random.default_rng(1)

    def test_bilinear(self):
        paddle.seed(0)
        layer = nn.Bilinear(4, 5, 3)
        x1 = self.rng.standard_normal((6, 4)).astype(np.float32)
        x2 = self.rng.standard_normal((6, 5)).astype(np.float32)
        got = _np(layer(paddle.to_tensor(x1), paddle.to_tensor(x2)))
        w = _np(layer.weight)
        b = _np(layer.bias)
        ref = np.einsum("bi,oij,bj->bo", x1, w, x2) + b
        assert np.allclose(got, ref, atol=1e-5)

    def test_softmax2d_logsigmoid(self):
        x = self.rng.standard_normal((2, 3, 4, 4)).astype(np.float32)
        got = _np(nn.Softmax2D()(paddle.to_tensor(x)))
        ref = torch.nn.Softmax2d()(_tt(x)).numpy()
        assert np.allclose(got, ref, atol=1e-6)
        got2 = _np(nn.LogSigmoid()(paddle.to_tensor(x)))
        ref2 = torch.nn.LogSigmoid()(_tt(x)).numpy()
        assert np.allclose(got2, ref2, atol=1e-6)

    def test_zeropad2d(self):
        x = self.rng.standard_normal((1, 2, 3, 3)).astype(np.float32)
        got = _np(F.zeropad2d(paddle.to_tensor(x), [1, 2, 0, 1]))
        ref = torch.nn.functional.pad(_tt(x), (1, 2, 0, 1)).numpy()
        assert np.allclose(got, ref)

    def test_feature_alpha_dropout(self):
        paddle.seed(2)
        layer = nn.FeatureAlphaDropout(0.5)
        layer.train()
        x = paddle.to_tensor(np.ones((4, 8, 3, 3), np.float32))
        out = _np(layer(x))
        # whole channels share one value (dropped or kept)
        per_chan = out.reshape(4, 8, -1)
        assert np.allclose(per_chan.std(-1), 0.0, atol=1e-6)
        assert len(np.unique(per_chan[:, :, 0].round(4))) == 2
        layer.eval()
        assert np.allclose(_np(layer(x)), 1.0)

    def test_max_unpool2d_roundtrip(self):
        x = self.rng.standard_normal((1, 2, 8, 8)).astype(np.float32)
        pooled, idx = F.max_pool2d(paddle.to_tensor(x), 2, 2,
                                   return_mask=True)
        up = F.max_unpool2d(pooled, idx, 2, 2)
        ref_p, ref_i = torch.nn.functional.max_pool2d(
            _tt(x), 2, 2, return_indices=True)
        ref = torch.nn.functional.max_unpool2d(ref_p, ref_i, 2, 2).numpy()
        assert np.allclose(_np(up), ref, atol=1e-6)

    def test_fractional_max_pool(self):
        x = self.rng.standard_normal((1, 2, 9, 9)).astype(np.float32)
        layer = nn.FractionalMaxPool2D(4, random_u=0.5)
        out = _np(layer(paddle.to_tensor(x)))
        assert out.shape == (1, 2, 4, 4)
        # every output is a max over a window -> must appear in the input
        for v in out.ravel():
            assert np.any(np.isclose(x, v))

    def test_fractional_max_pool_mask_and_kernel(self):
        # regression: return_mask/kernel_size were silently ignored
        x = self.rng.standard_normal((1, 1, 9, 9)).astype(np.float32)
        layer = nn.FractionalMaxPool2D(4, kernel_size=2, random_u=0.3,
                                       return_mask=True)
        out, mask = layer(paddle.to_tensor(x))
        o, m = _np(out), _np(mask)
        assert o.shape == (1, 1, 4, 4) and m.shape == (1, 1, 4, 4)
        # the mask indexes the flat input and recovers the output values
        flat = x.reshape(1, 1, -1)
        picked = np.take_along_axis(flat, m.reshape(1, 1, -1).astype(int),
                                    -1).reshape(o.shape)
        assert np.allclose(picked, o)

    def test_max_unpool2d_overlapping_windows(self):
        # regression: stride < kernel duplicated scatter indices; the
        # unpool must write v once, not k*v
        x = np.zeros((1, 1, 3, 3), np.float32)
        x[0, 0, 1, 1] = 7.0  # max of all four 2x2 windows
        pooled, idx = F.max_pool2d(paddle.to_tensor(x), 2, 1,
                                   return_mask=True)
        up = _np(F.max_unpool2d(pooled, idx, 2, 1))
        ref_p, ref_i = torch.nn.functional.max_pool2d(
            _tt(x), 2, 1, return_indices=True)
        ref = torch.nn.functional.max_unpool2d(ref_p, ref_i, 2, 1).numpy()
        assert np.allclose(up, ref)
        assert up[0, 0, 1, 1] == 7.0  # not 28.0

    def test_adaptive_log_softmax(self):
        paddle.seed(3)
        layer = nn.AdaptiveLogSoftmaxWithLoss(16, 20, cutoffs=[5, 10])
        x = paddle.to_tensor(
            self.rng.standard_normal((6, 16)).astype(np.float32))
        y = paddle.to_tensor(self.rng.integers(0, 20, 6))
        out, loss = layer(x, y)
        lp = _np(layer.log_prob(x))
        assert lp.shape == (6, 20)
        # rows are log-distributions
        assert np.allclose(np.exp(lp).sum(-1), 1.0, atol=1e-4)
        assert np.allclose(_np(out),
                           lp[np.arange(6), _np(y).astype(int)], atol=1e-5)
        assert np.isclose(float(loss), -_np(out).mean(), atol=1e-6)
        # trains
        g = paddle.grad(loss, layer.head_weight)[0]
        assert np.isfinite(_np(g)).all()


class TestFunctionalMirrors:
    def test_bilinear_functional(self):
        rng = np.random.default_rng(7)
        x1 = rng.standard_normal((4, 3)).astype(np.float32)
        x2 = rng.standard_normal((4, 5)).astype(np.float32)
        w = rng.standard_normal((2, 3, 5)).astype(np.float32)
        b = rng.standard_normal(2).astype(np.float32)
        got = _np(F.bilinear(paddle.to_tensor(x1), paddle.to_tensor(x2),
                             paddle.to_tensor(w), paddle.to_tensor(b)))
        ref = torch.nn.functional.bilinear(
            _tt(x1), _tt(x2), _tt(w), _tt(b)).numpy()
        assert np.allclose(got, ref, atol=1e-4)

    def test_fractional_pool_functional(self):
        rng = np.random.default_rng(8)
        img = paddle.to_tensor(
            rng.standard_normal((1, 2, 9, 9)).astype(np.float32))
        out, mask = F.fractional_max_pool2d(img, 4, random_u=0.4,
                                            return_mask=True)
        assert tuple(out.shape) == (1, 2, 4, 4)
        assert tuple(mask.shape) == (1, 2, 4, 4)

    def test_feature_alpha_dropout_functional(self):
        paddle.seed(9)
        x = paddle.to_tensor(np.ones((2, 8, 3, 3), np.float32))
        out = _np(F.feature_alpha_dropout(x, 0.5, training=True))
        per_chan = out.reshape(2, 8, -1)
        assert np.allclose(per_chan.std(-1), 0.0, atol=1e-6)
        assert np.allclose(
            _np(F.feature_alpha_dropout(x, 0.5, training=False)), 1.0)

    def test_npair_loss_reference_reg_scaling(self):
        # regression: reg divided by 2 instead of the reference's *0.25;
        # with identical logits across the batch the CE term is constant
        # log(B) for one class... use the closed single-sample form:
        ones = paddle.to_tensor(np.ones((1, 1), np.float32))
        y = paddle.to_tensor(np.array([0]))
        l = float(F.npair_loss(ones, ones, y, l2_reg=0.002))
        # CE = 0 (single row softmax), reg = 0.002*0.25*(1+1) = 0.001
        assert np.isclose(l, 0.001, atol=1e-6)

    def test_npair_loss(self):
        rng = np.random.default_rng(10)
        a = paddle.to_tensor(rng.standard_normal((6, 8)).astype(np.float32),
                             stop_gradient=False)
        p = paddle.to_tensor(rng.standard_normal((6, 8)).astype(np.float32))
        y = paddle.to_tensor(np.array([0, 0, 1, 1, 2, 2]))
        l = F.npair_loss(a, p, y)
        assert np.isfinite(float(l))
        g = paddle.grad(l, a)[0]
        assert np.isfinite(_np(g)).all()
        # perfectly separated similarities should give lower loss than
        # anti-separated ones
        emb = np.eye(6, 8, dtype=np.float32) * 10
        good = float(F.npair_loss(paddle.to_tensor(emb),
                                  paddle.to_tensor(emb), y))
        bad = float(F.npair_loss(paddle.to_tensor(emb),
                                 paddle.to_tensor(-emb), y))
        assert good < bad
