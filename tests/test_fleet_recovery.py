"""Durable fleet state: router crash recovery drills.

Pins the round-13 contracts (docs/robustness.md "Router durability &
recovery"): a FleetRouter journaling to a write-ahead log can die at
ANY control round — crash seam (``router_crash``), SIGTERM preemption,
torn journal write, transient disk errors — and a successor built by
``FleetRouter.recover(journal_dir, replicas)``:

- re-adopts the still-live replicas (scrape + retained result plane +
  carcass export_inflight) with ZERO new compiles on their engines;
- continuation-resubmits every unresolved request with the journaled
  delivered prefix deduped — the combined pre-crash + post-recovery
  output is TOKEN-EXACT vs an uninterrupted single-router golden;
- delivers every result EXACTLY ONCE across the crash (no rid
  resolved twice, restored unpopped results re-delivered once,
  retired rids never resurrected).

`pytest -m chaos` selects the chaos classes; the campaign's
fleet_recovery_smoke stage runs exactly that (and fleet_chaos_smoke
includes this file so the fleet canary golden covers the
fleet_journal_* counters).
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
from paddle_tpu.nlp.serving import ServingEngine
from paddle_tpu.resilience import faults, preemption
from paddle_tpu.serving_fleet import (
    FleetRouter, InprocReplica, JournalError, RouterCrash)
from paddle_tpu.serving_fleet.journal import JournalCrash, reconcile, \
    replay

NEW_TOK = 10
WAVE_LENS = (5, 12, 17, 9, 21, 14)


@pytest.fixture(autouse=True)
def _clean_faults_and_preemption():
    """The crash drills arm global faults outside scenario() blocks
    (the router must die OUTSIDE a with-body to mimic a process
    crash) — never leak them, or a preemption flag, into the next
    test."""
    faults.clear()
    preemption.clear()
    yield
    faults.clear()
    preemption.clear()


@pytest.fixture(scope="module")
def gpt_model():
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


def _prompts(lens, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (n,)).astype(np.int32) for n in lens]


@pytest.fixture(scope="module")
def wave(gpt_model):
    """(prompts, golden) — golden from an uninterrupted single
    replica, the token-exactness reference for every drill."""
    prompts = _prompts(WAVE_LENS)
    eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                        max_seq_len=64, steps_per_dispatch=4)
    refs = eng.generate(prompts, max_new_tokens=NEW_TOK)
    eng.close()
    return prompts, refs


def _engine(model, **kw):
    d = dict(max_slots=2, page_size=16, max_seq_len=64,
             steps_per_dispatch=4)
    d.update(kw)
    return ServingEngine(model, **d)


def _warm(eng):
    eng.generate(_prompts((5, 17), seed=7), max_new_tokens=4)
    eng.reset_counters()


def _fleet(model, tmp_path, n=3, router_kw=None, replica_kw=None,
           **engine_kw):
    engines = [_engine(model, **engine_kw) for _ in range(n)]
    for e in engines:
        _warm(e)
    frozen = [e.compile_counts() for e in engines]
    reps = [InprocReplica(f"r{i}", e, **(replica_kw or {}))
            for i, e in enumerate(engines)]
    jdir = os.path.join(tmp_path, "journal")
    router = FleetRouter(reps, journal_dir=jdir, **(router_kw or {}))
    _register(router)
    return router, reps, engines, frozen, jdir


def _register(router):
    """Session-end metrics export for the campaign's fleet canary
    gate (conftest._fleet_stage_metrics_export) — the recovery
    drills' fleet_journal_* counters ride the same golden."""
    import conftest
    conftest.fleet_stage_registries.append(router.registry)


def _drive_until(router, cond, timeout=60.0, results=None):
    """Step the router until cond() or a crash propagates."""
    deadline = time.monotonic() + timeout
    while not cond():
        router.step()
        if results is not None:
            results.extend(router.results())
        assert time.monotonic() < deadline, "drill made no progress"
        time.sleep(0.002)


def _crash(router, results):
    """Arm the crash seam and step until the router dies mid-round,
    exactly like a process crash: NO close(), the replicas keep
    running under a dead control plane."""
    faults.inject("router_crash")
    with pytest.raises(RouterCrash):
        deadline = time.monotonic() + 30
        while True:
            router.step()
            results.extend(router.results())
            assert time.monotonic() < deadline
    assert not faults.armed("router_crash")


def _assert_exactly_once_token_exact(rids, refs, pre, post,
                                     statuses=("ok",)):
    got = pre + post
    ids = [r["id"] for r in got]
    assert len(ids) == len(set(ids)), \
        f"a rid was delivered twice across the crash: {sorted(ids)}"
    assert sorted(ids) == sorted(rids), \
        f"requests lost across the crash: {sorted(set(rids) - set(ids))}"
    by_id = {r["id"]: r for r in got}
    for i, rid in enumerate(rids):
        assert by_id[rid]["status"] in statuses, by_id[rid]
        assert by_id[rid]["tokens"] == refs[i], \
            f"rid {rid} not token-exact across the crash"


def _assert_frozen(engines, frozen, router):
    for i, eng in enumerate(engines):
        assert eng.compile_counts() == frozen[i], \
            f"replica {i} compiled something across the recovery"
    assert router.compile_report()["unexpected_retraces"] == 0


def _ok_total(*routers):
    total = 0
    for r in routers:
        c = r.registry.get("fleet_requests_total", {"status": "ok"})
        total += 0 if c is None else int(c.value)
    return total


# -- journal-at-the-router units (no crash needed) -----------------------


class TestRouterJournalUnits:
    def test_submit_rejected_when_admission_append_fails(
            self, gpt_model, wave, tmp_path):
        """Write-ahead admission: a submit whose `accepted` record
        cannot be made durable raises — the caller KNOWS the request
        was never accepted, and the fleet state stays consistent."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=1)
        try:
            with faults.scenario(("journal_io_error", {"step": 2})):
                rid0 = router.submit(prompts[0], NEW_TOK)
                with pytest.raises(JournalError):
                    router.submit(prompts[1], NEW_TOK)
                rid2 = router.submit(prompts[2], NEW_TOK)
            res = {r["id"]: r for r in router.run_to_completion()}
            assert sorted(res) == [rid0, rid2]
            assert res[rid0]["tokens"] == refs[0]
            assert res[rid2]["tokens"] == refs[2]
            # the rejected rid was journaled nowhere and never ran
            st = reconcile(replay(jdir)[0])
            assert 1 not in st["requests"] and 1 not in st["retired"]
        finally:
            router.close()

    def test_results_withheld_until_retirement_is_durable(
            self, gpt_model, wave, tmp_path):
        """A transient disk failure on the `retired` append WITHHOLDS
        the pop (returns []) instead of handing over results whose
        retirement is not durable — handing them over un-retired
        would re-deliver them after a crash."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=1)
        try:
            rids = [router.submit(p, NEW_TOK) for p in prompts[:2]]
            deadline = time.monotonic() + 60
            while any(not p.done for p in router._pending.values()):
                router.step()
                assert time.monotonic() < deadline
                time.sleep(0.002)
            faults.inject("journal_io_error")   # next append fails
            assert router.results() == [], \
                "un-retired results must be withheld"
            faults.clear()
            res = {r["id"]: r for r in router.results()}
            assert sorted(res) == rids
            assert [res[i]["tokens"] for i in rids] == refs[:2]
            assert router.results() == []
            st = reconcile(replay(jdir)[0])
            assert st["retired"] == set(rids)
        finally:
            router.close()

    def test_lifecycle_is_journaled_and_retired(self, gpt_model, wave,
                                                tmp_path):
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=2)
        try:
            rids = [router.submit(p, NEW_TOK) for p in prompts[:3]]
            router.run_to_completion()
            st = reconcile(replay(jdir)[0])
            assert st["retired"] == set(rids)
            assert st["requests"] == {}
            assert st["next_rid"] == max(rids) + 1
            reg = router.registry
            assert reg.get("fleet_journal_appends_total").value > 0
            assert reg.get("fleet_journal_fsyncs_total").value > 0
            assert reg.get("fleet_journal_bytes_total").value > 0
        finally:
            router.close()


# -- chaos drills (campaign stage: fleet_recovery_smoke) -----------------


@pytest.mark.chaos
class TestRouterRecoveryChaos:
    def test_router_crash_recovery_token_exact_exactly_once(
            self, gpt_model, wave, tmp_path, monkeypatch):
        """THE acceptance drill: kill the router mid-wave with results
        already delivered, some resolved-but-unpopped, some mid-decode
        on live replicas, some still queued. The successor re-adopts
        the SAME replicas and the combined output is token-exact and
        exactly-once, with frozen compile counts and a parseable
        fleet_router_recovery flight dump."""
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path)
        faults.clear()
        pre = []
        rids = [router.submit(p, NEW_TOK) for p in prompts[:4]]
        # progress until ≥2 results reached the client, then accept
        # two MORE requests the dead router can never place — the
        # crash provably lands mid-wave: delivered + in-flight +
        # journaled-but-never-placed, all at once
        _drive_until(router, lambda: len(pre) >= 2, results=pre)
        rids += [router.submit(p, NEW_TOK) for p in prompts[4:]]
        _crash(router, pre)
        assert any(not p.done for p in router._pending.values()), \
            "drill must crash with work still in flight"
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=90)
            _assert_exactly_once_token_exact(rids, refs, pre, post)
            _assert_frozen(engines, frozen, r2)
            # no resolution was double-counted fleet-wide either
            assert _ok_total(router, r2) == len(prompts)
            reg = r2.registry
            assert reg.get(
                "fleet_journal_replay_records_total").value > 0
            assert reg.get(
                "fleet_journal_recovered_requests_total").value > 0
            dumps = [f for f in os.listdir(tmp_path)
                     if f.startswith("flight_fleet_router_recovery")]
            assert dumps, "recovery must leave a flight record"
            doc = json.load(open(os.path.join(tmp_path, dumps[0])))
            assert doc["reason"] == "fleet_router_recovery"
            assert doc["replay"]["replay_records"] > 0
            assert doc["reinstated"], "dump must name the survivors"
        finally:
            r2.close()

    def test_sigterm_preemption_seals_journal_and_recovers(
            self, gpt_model, wave, tmp_path):
        """Process-level SIGTERM: the replicas drain through the
        preemption seam (round-11 behavior) and the router now ALSO
        seals the journal — so the next incarnation recovers the
        bounced backlog instead of inheriting a torn tail. In-flight
        work finishes token-exactly on the draining replicas; queued
        work bounces, is journaled with its delivered watermark, and
        completes after recovery."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=2, max_slots=1,
            router_kw={"replica_queue_limit": 3})
        pre = []
        try:
            rids = [router.submit(p, NEW_TOK) for p in prompts]
            _drive_until(
                router,
                lambda: any(p.placed_at
                            for p in router._pending.values()),
                results=pre)
            preemption.request()
            # grace window: replicas drain; router seals + keeps
            # collecting until every worker parked
            _drive_until(
                router,
                lambda: all(not rp.alive for rp in reps),
                results=pre, timeout=90)
            assert router._journal.sealed, \
                "preemption must seal the journal, not just drain"
            for _ in range(3):          # settle the last bounces
                router.step()
                pre.extend(router.results())
            assert replay(jdir)[1]["sealed"]
            assert all(rp.state == "drained" for rp in reps)
        finally:
            preemption.clear()
        # successor: rejoin the parked replicas, finish the backlog
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=90)
            _assert_exactly_once_token_exact(rids, refs, pre, post)
            _assert_frozen(engines, frozen, r2)
            assert all(rp.state == "serving" for rp in reps)
        finally:
            r2.close()

    def test_torn_write_crash_recovery(self, gpt_model, wave,
                                       tmp_path):
        """journal_torn_write mid-wave: the append tears and the
        router dies AT that write (JournalCrash). Replay drops
        exactly the torn record; the successor reconciles the rest
        against the live replicas — still token-exact, still
        exactly-once."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path)
        pre = []
        rids = [router.submit(p, NEW_TOK) for p in prompts]
        # appends 1-6 are the admissions; tear a mid-wave lifecycle
        # record (placed/delivered/resolved — whichever lands 10th)
        faults.clear()
        faults.inject("journal_torn_write", step=10)
        with pytest.raises(JournalCrash):
            deadline = time.monotonic() + 60
            while True:
                router.step()
                pre.extend(router.results())
                assert time.monotonic() < deadline
                time.sleep(0.002)
        faults.clear()
        stats = replay(jdir)[1]
        assert stats["torn_tail_drops"] == 1
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=90)
            _assert_exactly_once_token_exact(rids, refs, pre, post)
            _assert_frozen(engines, frozen, r2)
            assert _ok_total(router, r2) == len(prompts)
            assert r2.registry.get(
                "fleet_journal_torn_tail_drops_total").value == 1
        finally:
            r2.close()

    def test_io_error_faults_then_crash_recovery(self, gpt_model,
                                                 wave, tmp_path):
        """Transient disk errors on lifecycle appends: the live
        router parks them in the retry backlog (results stay unacked
        at their replicas until durable) and keeps serving; a crash
        on top still recovers token-exact and exactly-once."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path)
        pre = []
        rids = [router.submit(p, NEW_TOK) for p in prompts[:4]]
        # admissions are appends 1-4; the storm window [6, 8) lands on
        # placement/lifecycle records — the live router must absorb
        # both failures (retry backlog) and keep serving
        faults.clear()
        faults.inject("journal_io_error", step=6, count=2)
        _drive_until(router, lambda: len(pre) >= 2, results=pre)
        assert router.registry.get(
            "fleet_journal_errors_total").value == 2
        rids += [router.submit(p, NEW_TOK) for p in prompts[4:]]
        _crash(router, pre)
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=90)
            _assert_exactly_once_token_exact(rids, refs, pre, post)
            _assert_frozen(engines, frozen, r2)
            assert _ok_total(router, r2) == len(prompts)
        finally:
            r2.close()

    def test_drain_backlog_race_with_router_kill(self, gpt_model,
                                                 wave, tmp_path):
        """Satellite: drain_to_completion under a pinned replica_slow
        seam racing a router kill. r0 is slow and draining with a
        backlog; the router dies mid-drain. Recovery must NOT
        double-place the drained backlog — every rid resolves exactly
        once, token-exact."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=2, max_slots=1,
            router_kw={"replica_queue_limit": 3})
        pre = []
        with faults.scenario(
                ("replica_slow", {"replica": "r0", "count": 1000,
                                  "seconds": 0.02})):
            rids = [router.submit(p, NEW_TOK) for p in prompts]
            _drive_until(
                router,
                lambda: any(p.replica == "r0" and p.placed_at
                            for p in router._pending.values()),
                results=pre)
            router.drain("r0")
            # let the drain begin bouncing/finishing, then kill the
            # router in the middle of the re-placement churn
            _drive_until(
                router,
                lambda: (not reps[0].alive
                         or router.registry.get(
                             "fleet_requeued_total").value > 0),
                results=pre, timeout=90)
            _crash(router, pre)
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=120)
            _assert_exactly_once_token_exact(rids, refs, pre, post)
            _assert_frozen(engines, frozen, r2)
            assert _ok_total(router, r2) == len(prompts)
        finally:
            r2.close()

    def test_cancel_intent_survives_router_crash(self, gpt_model,
                                                 wave, tmp_path):
        """A client cancel journaled before the crash is honored by
        the successor: the request resolves cancelled with its
        partial tokens instead of being resurrected into a full
        decode the client never wanted."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=2)
        pre = []
        rids = [router.submit(p, NEW_TOK) for p in prompts[:3]]
        _drive_until(
            router,
            lambda: any(p.placed_at and not p.done
                        for p in router._pending.values()),
            results=pre)
        victim = next(rid for rid in rids
                      if router._pending[rid].placed_at
                      and not router._pending[rid].done)
        # keep the victim's replica slow so the cancel provably races
        # ahead of completion, then cancel and crash immediately
        faults.inject("replica_slow",
                      replica=router._pending[victim].replica,
                      count=1000, seconds=0.02)
        router.cancel(victim)
        _crash(router, pre)
        faults.clear()
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=90)
            allres = {r["id"]: r for r in pre + post}
            assert sorted(allres) == sorted(rids)
            assert len(pre) + len(post) == len(rids)
            assert allres[victim]["status"] == "cancelled", \
                "recovery must honor the journaled cancel"
            got = allres[victim]["tokens"]
            assert got == refs[rids.index(victim)][:len(got)], \
                "cancelled partials must still be a golden prefix"
            for rid in rids:
                if rid != victim:
                    assert allres[rid]["status"] == "ok"
                    assert allres[rid]["tokens"] \
                        == refs[rids.index(rid)]
            _assert_frozen(engines, frozen, r2)
        finally:
            r2.close()

    def test_recovery_restores_unpopped_results_exactly_once(
            self, gpt_model, wave, tmp_path):
        """Results resolved before the crash but never popped are
        journaled: the successor re-delivers them ONCE, and rids the
        dead router already handed out (journaled `retired`) are
        never resurrected."""
        prompts, refs = wave
        router, reps, engines, frozen, jdir = _fleet(
            gpt_model, tmp_path, n=2)
        rids = [router.submit(p, NEW_TOK) for p in prompts[:4]]
        # resolve everything, pop HALF (journals their retirement)
        deadline = time.monotonic() + 60
        while any(not p.done for p in router._pending.values()):
            router.step()
            assert time.monotonic() < deadline
            time.sleep(0.002)
        popped = router.results()     # all four delivered + retired
        assert sorted(r["id"] for r in popped) == rids
        # submit two more; resolve them; crash BEFORE popping
        rids2 = [router.submit(p, NEW_TOK) for p in prompts[4:6]]
        deadline = time.monotonic() + 60
        while any(not p.done for p in router._pending.values()):
            router.step()
            assert time.monotonic() < deadline
            time.sleep(0.002)
        pre = []
        _crash(router, pre)
        assert not pre, "nothing was popped after the second wave"
        r2 = FleetRouter.recover(jdir, reps)
        _register(r2)
        try:
            post = r2.run_to_completion(timeout_s=60)
            # exactly the unpopped wave comes back — once
            assert sorted(r["id"] for r in post) == rids2
            by_id = {r["id"]: r for r in post}
            for i, rid in enumerate(rids2):
                assert by_id[rid]["tokens"] == refs[4 + i]
            # popping again yields nothing (retired stays retired)
            assert r2.results() == []
            _assert_frozen(engines, frozen, r2)
        finally:
            r2.close()
