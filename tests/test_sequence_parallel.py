"""Ring attention and Ulysses all-to-all sequence parallelism vs vanilla
attention on the virtual 8-device CPU mesh (SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from paddle_tpu.distributed.fleet.sequence_parallel import (
    ring_attention_spmd, ulysses_attention_spmd)
from paddle_tpu.ops.attention import reference_attention


def _mesh():
    return Mesh(np.array(jax.devices()), ("sp",))


def _qkv(b=2, s=64, h=8, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype=jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    q, k, v = _qkv()
    want = reference_attention(q, k, v, causal=causal)
    got = ring_attention_spmd(q, k, v, _mesh(), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    q, k, v = _qkv()
    want = reference_attention(q, k, v, causal=causal)
    got = ulysses_attention_spmd(q, k, v, _mesh(), causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grads_match_reference():
    q, k, v = _qkv(b=1, s=32, h=2, d=8)
    mesh = _mesh()

    def loss_ring(q, k, v):
        o = ring_attention_spmd(q, k, v, mesh, causal=True)
        return (o * o).sum()

    def loss_ref(q, k, v):
        o = reference_attention(q, k, v, causal=True)
        return (o * o).sum()

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_ring_attention_jits_under_mesh():
    q, k, v = _qkv(b=1, s=64, h=2, d=8)
    mesh = _mesh()
    f = jax.jit(lambda q, k, v: ring_attention_spmd(q, k, v, mesh,
                                                    causal=True))
    out = f(q, k, v)
    assert out.shape == q.shape and bool(jnp.isfinite(out).all())
