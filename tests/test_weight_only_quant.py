"""paddle.nn.quant weight-only serving path (ref:
python/paddle/nn/quant/quantized_linear.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn.quant import (WeightOnlyLinear, llm_int8_linear,
                                 weight_dequantize, weight_only_linear,
                                 weight_quantize)


def _w(k=64, n=32, seed=0):
    return np.random.default_rng(seed).standard_normal((k, n)).astype(
        np.float32)


@pytest.mark.parametrize("algo,bits", [("weight_only_int8", 127),
                                       ("weight_only_int4", 7)])
def test_quant_dequant_roundtrip_error_bound(algo, bits):
    w = _w()
    q, s = weight_quantize(paddle.to_tensor(w), algo)
    back = weight_dequantize(q, s, algo).numpy()
    # absmax per channel / bits is the max quantization step
    step = np.abs(w).max(0) / bits
    assert (np.abs(back - w) <= step / 2 + 1e-6).all()


def test_int4_packing_halves_rows():
    w = _w(64, 32)
    q8, _ = weight_quantize(paddle.to_tensor(w), "weight_only_int8")
    q4, _ = weight_quantize(paddle.to_tensor(w), "weight_only_int4")
    assert q8.shape == [64, 32] and q4.shape == [32, 32]
    assert q4.numpy().dtype == np.int8


def test_int4_odd_k_rejected():
    with pytest.raises(ValueError, match="even K"):
        weight_quantize(paddle.to_tensor(_w(63, 8)), "weight_only_int4")


@pytest.mark.parametrize("dtype,rtol", [("int8", 2e-2), ("int4", 2e-1)])
def test_weight_only_linear_close_to_fp(dtype, rtol):
    w = _w()
    x = np.random.default_rng(1).standard_normal((4, 64)).astype(np.float32)
    bias = np.random.default_rng(2).standard_normal(32).astype(np.float32)
    algo = f"weight_only_{dtype}"
    q, s = weight_quantize(paddle.to_tensor(w), algo)
    y = weight_only_linear(paddle.to_tensor(x), q,
                           paddle.to_tensor(bias), s, dtype).numpy()
    ref = x @ w + bias
    assert np.abs(y - ref).max() / np.abs(ref).max() < rtol


def test_llm_int8_linear_accurate_without_outliers():
    """Real LLM.int8(): per-row int8 activation quantization + int8x8
    matmul. On well-behaved activations the result tracks the fp
    reference within combined int8 quantization error."""
    w = _w()
    x = np.random.default_rng(3).standard_normal((4, 64)).astype(np.float32)
    q, s = weight_quantize(paddle.to_tensor(w), "weight_only_int8")
    y = llm_int8_linear(paddle.to_tensor(x), q, None, s).numpy()
    ref = x @ w
    assert np.abs(y - ref).max() / np.abs(ref).max() < 0.05


def test_llm_int8_outlier_decomposition_recovers_accuracy():
    """The point of the algorithm: activations with systematic outlier
    channels destroy plain int8 quantization (the outlier dominates the
    per-row scale); the decomposition runs those features at full
    precision. threshold=inf disables it — error must drop sharply when
    it is on."""
    rng = np.random.default_rng(4)
    w = (rng.standard_normal((64, 32)) * 0.1).astype(np.float32)
    x = rng.standard_normal((4, 64)).astype(np.float32)
    x[:, 7] *= 60.0                        # a classic outlier channel
    q, s = weight_quantize(paddle.to_tensor(w), "weight_only_int8")
    ref = x @ (np.asarray(q.numpy(), np.float32) * np.asarray(s.numpy())[None, :])
    y_on = llm_int8_linear(paddle.to_tensor(x), q, None, s,
                           threshold=6.0).numpy()
    y_off = llm_int8_linear(paddle.to_tensor(x), q, None, s,
                            threshold=1e9).numpy()
    err_on = np.abs(y_on - ref).max()
    err_off = np.abs(y_off - ref).max()
    assert err_on < err_off / 4, (err_on, err_off)
    assert err_on / np.abs(ref).max() < 0.05


def test_llm_int8_ste_gradient_and_shapes():
    """Straight-through gradients (the dequant-matmul jacobian — the
    round/int-cast path would otherwise zero the tangent), 1-D inputs
    keep their rank, and bf16 inputs stay bf16."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn.quant import _llm_int8_mm
    w = (_w() * 0.1).astype(np.float32)
    q, s = weight_quantize(paddle.to_tensor(w), "weight_only_int8")
    wq, ws = q._value, s._value
    x = np.random.default_rng(7).standard_normal((4, 64)).astype(np.float32)

    g = jax.grad(lambda a: jnp.sum(_llm_int8_mm(a, wq, ws, 6.0) ** 2))(
        jnp.asarray(x))
    w_f = np.asarray(wq, np.float32) * np.asarray(ws)[None, :]
    ref_g = 2 * (x @ w_f) @ w_f.T
    assert np.abs(np.asarray(g)).max() > 0          # not silently zero
    assert np.abs(np.asarray(g) - ref_g).max() / np.abs(ref_g).max() < 0.02

    assert _llm_int8_mm(jnp.asarray(x[0]), wq, ws, 6.0).shape == (32,)
    assert _llm_int8_mm(jnp.asarray(x, jnp.bfloat16), wq, ws,
                        6.0).dtype == jnp.bfloat16

    xt = paddle.to_tensor(x, stop_gradient=False)
    out = llm_int8_linear(xt, q, None, s)
    out.sum().backward()
    assert np.abs(xt.grad.numpy()).max() > 0


def test_llm_int8_linear_bias_and_jit():
    import jax
    w = _w()
    x = np.random.default_rng(5).standard_normal((2, 64)).astype(np.float32)
    b = np.random.default_rng(6).standard_normal((32,)).astype(np.float32)
    q, s = weight_quantize(paddle.to_tensor(w), "weight_only_int8")

    @jax.jit
    def f(a):
        return llm_int8_linear(paddle.to_tensor(a), q,
                               paddle.to_tensor(b), s)._value

    y = np.asarray(f(x))
    ref = x @ w + b
    assert np.abs(y - ref).max() / np.abs(ref).max() < 0.06


@pytest.mark.parametrize("dtype", ["int8", "int4"])
def test_weight_only_module_from_linear(dtype):
    paddle.seed(0)
    lin = paddle.nn.Linear(64, 32)
    m = WeightOnlyLinear.from_linear(lin, weight_dtype=dtype)
    x = paddle.to_tensor(
        np.random.default_rng(4).standard_normal((3, 64)).astype(np.float32))
    ref = lin(x).numpy()
    got = m(x).numpy()
    tol = 3e-2 if dtype == "int8" else 3e-1
    assert np.abs(got - ref).max() / max(np.abs(ref).max(), 1e-6) < tol
    # weights really stored int8 (half the rows when int4-packed)
    assert m.qweight.numpy().dtype == np.int8
    rows = 32 if dtype == "int4" else 64
    assert m.qweight.shape == [rows, 32]


def test_weight_only_linear_state_dict_roundtrip():
    paddle.seed(0)
    lin = paddle.nn.Linear(16, 8)
    m = WeightOnlyLinear.from_linear(lin, weight_dtype="int8")
    sd = m.state_dict()
    m2 = WeightOnlyLinear(16, 8, weight_dtype="int8")
    m2.set_state_dict(sd)
    x = paddle.to_tensor(np.ones((2, 16), np.float32))
    np.testing.assert_allclose(m(x).numpy(), m2(x).numpy())


def test_quantize_for_serving_gpt_decode():
    """Convert a whole GPT for serving: logits stay close and the jitted
    KV-cache decode still runs on the converted model."""
    from paddle_tpu.nlp import GPTForCausalLM, GPTConfig
    from paddle_tpu.nlp.generation import generate
    from paddle_tpu.nn.quant import quantize_for_serving
    paddle.seed(0)
    cfg = dict(vocab_size=97, hidden_size=32, num_hidden_layers=2,
               num_attention_heads=4, max_position_embeddings=64,
               hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
               use_flash_attention=False)
    m = GPTForCausalLM(GPTConfig(**cfg))
    m.eval()
    ids = paddle.to_tensor(np.asarray([[5, 17, 3, 42]], np.int32))
    ref = m(ids)
    ref = (ref[0] if isinstance(ref, tuple) else ref).numpy()
    n = quantize_for_serving(m, weight_dtype="int8")
    assert n >= 2 * 4, n  # qkv/out/fc1/fc2 per block at least
    got = m(ids)
    got = (got[0] if isinstance(got, tuple) else got).numpy()
    rel = np.abs(got - ref).max() / np.abs(ref).max()
    assert rel < 0.1, rel
    out = generate(m, ids, max_new_tokens=4, temperature=0.0)
    assert np.asarray(out._value).shape == (1, 8)


def test_quantize_for_serving_counts_and_idempotent():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 4))
    from paddle_tpu.nn.quant import quantize_for_serving
    assert quantize_for_serving(net) == 2
    assert quantize_for_serving(net) == 0  # already converted
