"""`import paddle_tpu` must not touch any device: a wedged remote backend
(observed 2026-07-30) must not be able to hang the import, and array-free
users shouldn't pay backend init."""
import subprocess
import sys


def test_import_performs_no_device_ops():
    code = (
        "import jax\n"
        "import jax._src.xla_bridge as xb\n"
        "def boom(*a, **k):\n"
        "    raise RuntimeError('DEVICE TOUCHED AT IMPORT')\n"
        "xb.backends = boom\n"
        "import paddle_tpu\n"
        "print('CLEAN')\n")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=240, cwd=".")
    assert "CLEAN" in r.stdout, r.stderr[-2000:]
