"""BERT/ERNIE model families + tokenizers (SURVEY §2.9)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

import paddle_tpu as paddle
from paddle_tpu.nlp import (
    BertConfig, BertModel, BertForPretraining, BertPretrainingCriterion,
    BertForSequenceClassification, BertForQuestionAnswering,
    ErnieModel, ErnieForSequenceClassification,
    BertTokenizer, GPTTokenizer)
from paddle_tpu.nlp.bert import BertForMaskedLM
from paddle_tpu.tensor import Tensor


def _tiny_cfg(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, max_position_embeddings=64,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
                use_flash_attention=False)
    base.update(kw)
    return base


def _ids(b=2, s=16, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    return Tensor(jnp.asarray(rng.integers(0, vocab, (b, s)),
                              dtype=jnp.int32))


class TestBert:
    def test_forward_shapes(self):
        paddle.seed(0)
        m = BertModel(BertConfig(**_tiny_cfg()))
        m.eval()
        seq, pooled = m(_ids())
        assert tuple(seq.shape) == (2, 16, 32)
        assert tuple(pooled.shape) == (2, 32)

    def test_padding_mask_changes_output(self):
        paddle.seed(0)
        m = BertModel(BertConfig(**_tiny_cfg()))
        m.eval()
        ids = _ids()
        pad = np.ones((2, 16), np.float32)
        pad[:, 10:] = 0
        out_m, _ = m(ids, attention_mask=Tensor(jnp.asarray(pad)))
        out_f, _ = m(ids)
        # masked positions must change the attended output
        assert not np.allclose(np.asarray(out_m._value[:, :10]),
                               np.asarray(out_f._value[:, :10]), atol=1e-6)

    def test_pretraining_loss_and_grads(self):
        paddle.seed(0)
        m = BertForPretraining(BertConfig(**_tiny_cfg()))
        crit = BertPretrainingCriterion()
        ids = _ids()
        labels = _ids(seed=1)
        nsp = Tensor(jnp.asarray([0, 1]))
        scores, rel = m(ids)
        assert tuple(scores.shape) == (2, 16, 128) and tuple(rel.shape) == (2, 2)
        loss = crit(scores, rel, labels, nsp)
        loss.backward()
        emb = m.bert.embeddings.word_embeddings.weight
        assert emb.grad is not None
        assert bool(jnp.isfinite(loss._value))

    def test_mlm_head_tied_to_embedding(self):
        paddle.seed(0)
        m = BertForMaskedLM(BertConfig(**_tiny_cfg()))
        assert m.cls._tied is m.bert.embeddings.word_embeddings.weight

    def test_heads(self):
        paddle.seed(0)
        cfg = BertConfig(**_tiny_cfg())
        cls_logits = BertForSequenceClassification(cfg, num_labels=3)(_ids())
        assert tuple(cls_logits.shape) == (2, 3)
        start, end = BertForQuestionAnswering(BertConfig(**_tiny_cfg()))(
            _ids())
        assert tuple(start.shape) == (2, 16) and tuple(end.shape) == (2, 16)

    def test_trains_end_to_end(self):
        from paddle_tpu.hapi.engine import Engine
        paddle.seed(0)
        m = BertForSequenceClassification(
            BertConfig(**_tiny_cfg()), num_labels=2)
        opt = paddle.optimizer.AdamW(2e-3, parameters=m.parameters())
        eng = Engine(m, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(0, 128, (8, 16)), dtype=jnp.int32)
        y = jnp.asarray(ids[:, 0] % 2)  # learnable from first token
        losses = [float(eng.train_batch([ids], [y])[0]) for _ in range(50)]
        assert losses[-1] < losses[0] * 0.2, losses[::10]


class TestErnie:
    def test_forward_and_task_ids(self):
        paddle.seed(0)
        m = ErnieModel(**_tiny_cfg(task_type_vocab_size=3, use_task_id=True))
        m.eval()
        ids = _ids()
        seq, pooled = m(ids)
        task = Tensor(jnp.ones((2, 16), dtype=jnp.int32))
        seq2, _ = m(ids, task_type_ids=task)
        assert tuple(seq.shape) == (2, 16, 32)
        assert not np.allclose(np.asarray(seq._value),
                               np.asarray(seq2._value), atol=1e-6)

    def test_seq_classification(self):
        paddle.seed(0)
        m = ErnieForSequenceClassification(num_labels=4, **_tiny_cfg())
        assert tuple(m(_ids()).shape) == (2, 4)

    def test_tensor_parallel_matches_dense(self):
        from paddle_tpu.distributed.fleet.mpu import shard_model
        from paddle_tpu.distributed import mesh as mesh_mod
        paddle.seed(3)
        m = ErnieModel(**_tiny_cfg())
        m.eval()
        ids = _ids()
        want = m(ids)[0]
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "mp"))
        old = mesh_mod._global_mesh
        try:
            shard_model(m, mesh)
            got = m(ids)[0]
        finally:
            mesh_mod._global_mesh = old
        np.testing.assert_allclose(np.asarray(got._value),
                                   np.asarray(want._value),
                                   atol=2e-5, rtol=2e-5)


class TestTokenizers:
    CORPUS = ["the quick brown fox jumps over the lazy dog",
              "pack my box with five dozen liquor jugs",
              "the five boxing wizards jump quickly"]

    def test_bert_tokenizer_roundtrip(self):
        tok = BertTokenizer.from_corpus(self.CORPUS, vocab_size=200)
        enc = tok("The quick fox!", max_length=16, padding=True)
        assert len(enc["input_ids"]) == 16
        assert enc["input_ids"][0] == tok.vocab["[CLS]"]
        assert sum(enc["attention_mask"]) < 16
        assert "quick" in tok.decode(enc["input_ids"])

    def test_bert_tokenizer_pairs(self):
        tok = BertTokenizer.from_corpus(self.CORPUS, vocab_size=200)
        enc = tok("the quick fox", "the lazy dog", max_length=12,
                  padding=True)
        assert len(enc["input_ids"]) == 12
        assert 1 in enc["token_type_ids"]

    def test_bert_wordpiece_subwords(self):
        tok = BertTokenizer({"[UNK]": 0, "un": 1, "##able": 2, "able": 3})
        assert tok.tokenize("unable") == ["un", "##able"]
        assert tok.tokenize("zzz") == ["[UNK]"]

    def test_gpt_bpe_roundtrip(self):
        tok = GPTTokenizer.train(self.CORPUS, vocab_size=400)
        text = "the quick dog"
        assert tok.decode(tok.encode(text)) == text
        # BPE actually merges: fewer tokens than characters
        assert len(tok.encode(text)) < len(text)


class TestReviewRegressions:
    def test_mlm_masked_mean_uses_valid_count(self):
        """MLM loss must normalise by non-ignored positions, not b*s."""
        paddle.seed(0)
        crit = BertPretrainingCriterion()
        rng = np.random.default_rng(0)
        scores = Tensor(jnp.asarray(
            rng.standard_normal((2, 8, 32)), dtype=jnp.float32))
        labels = np.full((2, 8), -100, np.int64)
        labels[:, :2] = rng.integers(0, 32, (2, 2))  # only 4 of 16 valid
        rel = Tensor(jnp.zeros((2, 2), dtype=jnp.float32))
        loss = crit(scores, rel, Tensor(jnp.asarray(labels)))
        # hand-computed masked mean
        lp = jax.nn.log_softmax(scores._value.astype(jnp.float32), -1)
        want = -np.mean([lp[b, s, labels[b, s]]
                         for b in range(2) for s in range(2)])
        np.testing.assert_allclose(float(loss._value), want, rtol=1e-5)

    def test_tokenizer_tiny_max_length_no_crash(self):
        tok = BertTokenizer.from_corpus(["a b c"], vocab_size=50)
        enc = tok("a b c", "a b", max_length=2, padding=True)
        assert len(enc["input_ids"]) >= 2  # no IndexError

    def test_ernie_heads_share_bert_implementation(self):
        from paddle_tpu.nlp import ErniePretrainingCriterion
        assert issubclass(ErnieForSequenceClassification,
                          BertForSequenceClassification)
        assert issubclass(ErniePretrainingCriterion,
                          BertPretrainingCriterion)
        m = ErnieForSequenceClassification(num_labels=2, **_tiny_cfg())
        assert hasattr(m, "ernie")  # reference attribute name preserved
        assert any(k.startswith("ernie.") for k in m.state_dict())
