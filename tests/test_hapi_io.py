"""hapi Model / Engine / DataLoader / metrics / serialization (SURVEY §4)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.io import (BatchSampler, DataLoader, Dataset,
                           DistributedBatchSampler, TensorDataset,
                           random_split)
from paddle_tpu.metric import Accuracy, Auc, Precision, Recall


def make_ds(n=128, din=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, din).astype("float32")
    w = np.random.RandomState(99).randn(din, classes).astype("float32")
    ys = (xs @ w).argmax(1).astype("int64")
    return TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)]), xs, ys


class TestDataLoader:
    def test_batching(self):
        ds, xs, ys = make_ds(100)
        dl = DataLoader(ds, batch_size=32)
        batches = list(dl)
        assert len(batches) == 4
        assert batches[0][0].shape == [32, 8]
        assert batches[-1][0].shape == [4, 8]

    def test_drop_last_shuffle(self):
        ds, _, _ = make_ds(100)
        dl = DataLoader(ds, batch_size=32, drop_last=True, shuffle=True)
        assert len(list(dl)) == 3

    def test_num_workers_prefetch(self):
        ds, xs, _ = make_ds(64)
        dl = DataLoader(ds, batch_size=16, num_workers=2)
        total = sum(int(b[0].shape[0]) for b in dl)
        assert total == 64

    def test_samplers(self):
        ds, _, _ = make_ds(10)
        bs = BatchSampler(dataset=ds, batch_size=3)
        assert len(bs) == 4
        dbs = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
        idxs = [i for b in dbs for i in b]
        assert len(idxs) == 5  # half the (padded) dataset

    def test_random_split_concat(self):
        ds, _, _ = make_ds(10)
        a, b = random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3
        from paddle_tpu.io import ConcatDataset
        c = ConcatDataset([a, b])
        assert len(c) == 10


class TestModelFit:
    def test_fit_evaluate_predict(self, tmp_path):
        paddle.seed(1234)  # init/shuffle must not depend on test order
        ds, xs, ys = make_ds(128)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.Adam(0.01, parameters=net.parameters()),
                      nn.CrossEntropyLoss(), Accuracy())
        model.fit(ds, epochs=15, batch_size=32, verbose=0)
        res = model.evaluate(ds, batch_size=64, verbose=0)
        assert res["acc"] > 0.9
        preds = model.predict(ds, batch_size=64, stack_outputs=True)
        assert np.asarray(preds[0]).shape == (128, 4)

    def test_save_load_resume(self, tmp_path):
        ds, _, _ = make_ds(64)
        def build():
            net = nn.Sequential(nn.Linear(8, 4))
            m = paddle.Model(net)
            m.prepare(paddle.optimizer.Adam(0.01, parameters=net.parameters()),
                      nn.CrossEntropyLoss(), Accuracy())
            return m
        m1 = build()
        m1.fit(ds, epochs=2, batch_size=32, verbose=0)
        path = os.path.join(tmp_path, "ck")
        m1.save(path)
        m2 = build()
        m2.load(path)
        r1 = m1.evaluate(ds, batch_size=64, verbose=0)
        r2 = m2.evaluate(ds, batch_size=64, verbose=0)
        assert np.allclose(r1["loss"], r2["loss"], atol=1e-6)
        # optimizer state resumed
        assert m2._engine._step == m1._engine._step

    def test_callbacks_early_stop(self):
        ds, _, _ = make_ds(64)
        net = nn.Sequential(nn.Linear(8, 4))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.Adam(0.01, parameters=net.parameters()),
                      nn.CrossEntropyLoss())
        from paddle_tpu.hapi.callbacks import EarlyStopping
        es = EarlyStopping(monitor="loss", patience=0, mode="min")
        model.fit(ds, eval_data=ds, epochs=3, batch_size=32, verbose=0,
                  callbacks=[es])
        # ran without error; stop flag may or may not be set
        assert model._engine._step > 0

    def test_engine_bn_buffer_update(self):
        net = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4, data_format="NCL"))
        ds = TensorDataset([paddle.to_tensor(np.random.randn(32, 4).astype("float32")),
                            paddle.to_tensor(np.random.randn(32, 4).astype("float32"))])
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.SGD(0.01, parameters=net.parameters()),
                      nn.MSELoss())
        before = net[1]._mean.numpy().copy()
        model.fit(ds, epochs=1, batch_size=16, verbose=0)
        after = net[1]._mean.numpy()
        assert not np.allclose(before, after), "running mean must update under jit"


class TestMetrics:
    def test_accuracy_topk(self):
        m = Accuracy(topk=(1, 2))
        pred = paddle.to_tensor([[0.1, 0.9, 0.0], [0.8, 0.05, 0.15]])
        lab = paddle.to_tensor([[1], [2]])
        m.update(m.compute(pred, lab))
        top1, top2 = m.accumulate()
        assert top1 == 0.5 and top2 == 1.0

    def test_precision_recall(self):
        p = Precision()
        r = Recall()
        pred = paddle.to_tensor([0.9, 0.8, 0.2, 0.6])
        lab = paddle.to_tensor([1, 0, 1, 1])
        p.update(pred, lab)
        r.update(pred, lab)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc(self):
        auc = Auc()
        pred = paddle.to_tensor([[0.9, 0.1], [0.1, 0.9]])[:, 1]
        auc.update(paddle.to_tensor([0.1, 0.9]), paddle.to_tensor([0, 1]))
        assert auc.accumulate() == 1.0


class TestSerialization:
    def test_nested_roundtrip(self, tmp_path):
        obj = {"a": paddle.to_tensor([1.0, 2.0]),
               "b": [paddle.to_tensor([3]), {"c": 4.5}],
               "d": "hello", "e": (1, 2)}
        p = os.path.join(tmp_path, "blob.pd")
        paddle.save(obj, p)
        back = paddle.load(p)
        assert np.allclose(back["a"].numpy(), [1.0, 2.0])
        assert back["b"][1]["c"] == 4.5
        assert back["d"] == "hello" and back["e"] == (1, 2)

    def test_layer_state_dict_file(self, tmp_path):
        net = nn.Linear(3, 2)
        p = os.path.join(tmp_path, "w.pd")
        paddle.save(net.state_dict(), p)
        net2 = nn.Linear(3, 2)
        net2.set_state_dict(paddle.load(p))
        assert np.allclose(net.weight.numpy(), net2.weight.numpy())


class TestAmp:
    def test_gradscaler_semantics(self):
        from paddle_tpu.amp import GradScaler
        s = GradScaler(init_loss_scaling=8.0, incr_every_n_steps=2,
                       decr_every_n_nan_or_inf=1)
        w = nn.Parameter(paddle.to_tensor([1.0])._value)
        opt = paddle.optimizer.SGD(0.1, parameters=[w])
        loss = (w * w).sum()
        scaled = s.scale(loss)
        assert float(scaled) == float(loss) * 8.0
        scaled.backward()
        s.minimize(opt, scaled)
        # grad 2*8=16 unscaled to 2 -> w = 1-0.2
        assert np.allclose(w.numpy(), [0.8], atol=1e-6)

    def test_scaler_skips_inf(self):
        from paddle_tpu.amp import GradScaler
        s = GradScaler(init_loss_scaling=4.0, decr_every_n_nan_or_inf=1)
        w = nn.Parameter(paddle.to_tensor([1.0])._value)
        opt = paddle.optimizer.SGD(0.1, parameters=[w])
        w._grad_value = paddle.to_tensor([np.inf])._value
        before = w.numpy().copy()
        s.unscale_guarded_step(opt)
        s.update()
        assert np.allclose(w.numpy(), before)  # step skipped
        assert s._scale == 2.0  # backed off

    def test_auto_cast_flag(self):
        import paddle_tpu.amp as amp
        assert not amp.is_auto_cast_enabled()
        with amp.auto_cast():
            assert amp.is_auto_cast_enabled()
        assert not amp.is_auto_cast_enabled()


class TestJit:
    def test_to_static_function(self):
        calls = []

        @paddle.jit.to_static
        def f(x):
            calls.append(1)
            return x * 2 + 1

        out = f(paddle.to_tensor([1.0, 2.0]))
        assert np.allclose(out.numpy(), [3.0, 5.0])
        out2 = f(paddle.to_tensor([3.0, 4.0]))
        assert np.allclose(out2.numpy(), [7.0, 9.0])
        assert len(calls) == 1  # traced once, compiled after

    def test_jit_save_load(self, tmp_path):
        net = nn.Sequential(nn.Linear(4, 3), nn.ReLU())
        net.eval()
        path = os.path.join(tmp_path, "model")
        from paddle_tpu.jit import InputSpec
        paddle.jit.save(net, path, input_spec=[InputSpec([1, 4])])
        loaded = paddle.jit.load(path)
        x = paddle.randn([1, 4])
        assert np.allclose(loaded(x).numpy(), net(x).numpy(), atol=1e-6)


def test_network_readable_mid_fit():
    # buffer donation must not invalidate the live layer params (regression)
    net = nn.Sequential(nn.Linear(4, 2))
    from paddle_tpu.hapi.engine import Engine
    eng = Engine(net, loss=nn.MSELoss(),
                 optimizer=paddle.optimizer.SGD(0.01,
                                                parameters=net.parameters()))
    eng.train_batch([paddle.randn([8, 4])], [paddle.randn([8, 2])])
    assert net[0].weight.numpy().shape == (4, 2)
    float(net(paddle.ones([1, 4])).sum())


def test_dataloader_worker_error_propagates():
    class Bad(Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            if i == 3:
                raise RuntimeError("boom")
            return np.zeros(2, dtype="float32")

    with pytest.raises(RuntimeError, match="boom"):
        for _ in DataLoader(Bad(), batch_size=2, num_workers=2):
            pass


def test_auc_saturated():
    auc = Auc()
    auc.update(paddle.to_tensor([1.0, 1.0]), paddle.to_tensor([0, 1]))
    assert abs(auc.accumulate() - 0.5) < 1e-6


def test_text_datasets_contract():
    """paddle.text parity datasets load + batch (SURVEY §2.5)."""
    from paddle_tpu.text import Imdb, Imikolov, UCIHousing, WMT14
    from paddle_tpu.io import DataLoader

    imdb = Imdb(mode="train", n_samples=64)
    doc, label = imdb[0]
    assert doc.dtype == np.int64 and label in (0, 1)

    ng = Imikolov(n_samples=32)
    ctx, nxt = ng[0]
    assert len(ctx) == 4

    uci = UCIHousing(n_samples=32)
    x, y = uci[0]
    assert x.shape == (13,) and y.shape == (1,)

    for bx, by in DataLoader(UCIHousing(n_samples=32), batch_size=8):
        assert tuple(bx.shape) == (8, 13)
        break

    src, trg, nxt = WMT14(n_samples=8)[0]
    assert len(src) == 16 and len(trg) == len(nxt) == 15
