"""Resilience chaos suite (ISSUE 3): every failure-handling behavior
in the stack, drilled deterministically via paddle_tpu.resilience.

- fault registry semantics (pinning, counts, env grammar, scenarios)
- TrainGuard: NaN-storm skip + rollback with loss continuity vs an
  uninjected run with those steps skipped (acceptance criterion),
  GradScaler composition, transient-dispatch retry
- preemption: SIGTERM at a step boundary -> finalized checkpoint ->
  loss-exact resume
- CheckpointManager crash-safe finalize: torn writes and corrupt dirs
  are skipped, never crashed on
- ServingEngine degradation: deadlines, cancel, reject/evict admission
  policies, injected page exhaustion, watchdog wedge detection —
  with compile_counts() frozen after warmup (zero-recompile survives
  chaos)

Runs as part of tier-1 and standalone as the campaign's chaos_smoke
stage: pytest -m chaos (seeded, CPU).
"""
import os
import signal
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.amp import GradScaler
from paddle_tpu.hapi.engine import Engine
from paddle_tpu.io.checkpoint import CheckpointManager
from paddle_tpu.resilience import (TrainGuard, Watchdog, faults,
                                   preemption)
from paddle_tpu.resilience.retry import (RetryStats, TransientError,
                                         call_with_retries, is_transient)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_slate():
    faults.clear()
    preemption.clear()
    yield
    faults.clear()
    preemption.clear()
    preemption.uninstall()


# -- fault registry -------------------------------------------------------

class TestFaultRegistry:
    def test_pull_consumes_and_pins(self):
        faults.inject("nan_grads", step=5)
        assert faults.pull("nan_grads", 4) is None
        assert faults.pull("nan_grads", 5) == {}
        assert faults.pull("nan_grads", 5) is None, "count=1 exhausted"

    def test_unpinned_fires_count_times(self):
        faults.inject("slow_step", count=2, seconds=0.0)
        assert faults.pull("slow_step", 1) is not None
        assert faults.pull("slow_step", 9) is not None
        assert faults.pull("slow_step", 10) is None
        assert faults.fired_log() == [("slow_step", 1), ("slow_step", 9)]

    def test_env_grammar(self, monkeypatch):
        monkeypatch.setenv(
            "PADDLE_TPU_FAULTS",
            "nan_grads@10x3, sigterm@25, slow_step@5:seconds=0.5,"
            "page_exhaustion")   # bare kind CONTAINING 'x': no suffix
        faults.clear()
        faults.load_env(force=True)
        # @10x3 is a STORM: consecutive steps 10-12, as a train loop
        # consults them — not 3 firings at one step
        assert faults.pull("nan_grads", 10) == {}
        assert faults.pull("nan_grads", 11) == {}
        assert faults.pull("nan_grads", 12) == {}
        assert faults.pull("nan_grads", 13) is None
        assert faults.pull("sigterm", 25) == {}
        assert faults.pull("slow_step", 5) == {"seconds": 0.5}
        assert faults.pull("sigterm", 25) is None
        assert faults.pull("page_exhaustion", 1) == {}

    def test_scenario_restores_registry(self):
        outer = faults.inject("nan_grads", step=99)
        with faults.scenario(("dispatch_error", {"count": 1})):
            assert faults.armed("dispatch_error")
            assert not faults.armed("nan_grads")
        assert not faults.armed("dispatch_error")
        assert faults.armed("nan_grads") and outer.fired == 0

    def test_nan_scale_seam(self):
        assert faults.nan_scale(1) == 1.0
        faults.inject("nan_grads", step=2)
        assert np.isnan(faults.nan_scale(2))


# -- retry ----------------------------------------------------------------

class TestRetry:
    def test_transient_grammar(self):
        assert is_transient(TransientError("boom"))
        assert is_transient(RuntimeError("RESOURCE_EXHAUSTED: oom"))
        assert is_transient(RuntimeError("backend UNAVAILABLE"))
        assert not is_transient(RuntimeError("shape mismatch"))
        assert not is_transient(ValueError("RESOURCE_EXHAUSTED"))

    def test_retries_then_succeeds(self):
        calls = []
        stats = RetryStats()

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientError("RESOURCE_EXHAUSTED: injected")
            return "ok"

        assert call_with_retries(flaky, retries=3, base_delay=0.001,
                                 stats=stats) == "ok"
        assert len(calls) == 3 and stats.retries == 2

    def test_gives_up_and_reraises(self):
        stats = RetryStats()
        with pytest.raises(TransientError):
            call_with_retries(
                lambda: (_ for _ in ()).throw(TransientError("x")),
                retries=1, base_delay=0.001, stats=stats)
        assert stats.gave_up == 1

    def test_non_transient_propagates_immediately(self):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("nope")

        with pytest.raises(ValueError):
            call_with_retries(bad, retries=5, base_delay=0.001)
        assert len(calls) == 1


# -- train guard ----------------------------------------------------------

def _make_engine(guard=None, seed=0):
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                               paddle.nn.Linear(16, 4))
    opt = paddle.optimizer.AdamW(1e-2, parameters=net.parameters())
    return Engine(net, loss=paddle.nn.CrossEntropyLoss(), optimizer=opt,
                  guard=guard)


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal((8, 8)).astype("float32"),
             rng.integers(0, 4, (8,)).astype("int64")) for _ in range(n)]


class TestTrainGuard:
    BAD = (5, 6, 7)  # 1-indexed steps hit by the injected NaN storm

    def test_nan_storm_skip_rollback_loss_continuity(self):
        """Acceptance criterion: under a 3-consecutive-bad-step NaN
        storm at step K the guard skips/rolls back and the surviving
        loss curve matches an uninjected run that never saw those
        batches (same params, moments, bias-correction count)."""
        batches = _batches(12)
        golden_eng = _make_engine()
        golden = [float(np.asarray(golden_eng.train_batch([x], [y])[0]))
                  for i, (x, y) in enumerate(batches)
                  if i + 1 not in self.BAD]

        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        eng = _make_engine(guard)
        # the storm form: one fault covering steps 5-7
        faults.inject("nan_grads", step=self.BAD[0], count=len(self.BAD))
        observed = [float(np.asarray(eng.train_batch([x], [y])[0]))
                    for (x, y) in batches]
        bad_losses = [observed[s - 1] for s in self.BAD]
        good_losses = [l for i, l in enumerate(observed)
                       if i + 1 not in self.BAD]
        assert all(np.isnan(v) for v in bad_losses), \
            "the injected steps must OBSERVE the NaN loss"
        np.testing.assert_allclose(good_losses, golden, rtol=1e-5,
                                   atol=1e-7)
        assert guard.skipped_steps == 3
        assert guard.rollbacks == 1, \
            "3 consecutive bad steps == rollback_after must roll back"
        assert guard.good_steps == 9

    def test_rollback_restores_update_counter(self):
        guard = TrainGuard(snapshot_every=1, rollback_after=1)
        eng = _make_engine(guard)
        (x, y), = _batches(1)
        eng.train_batch([x], [y])
        opt_step_before = eng._opt_step
        faults.inject("nan_grads", step=2)
        eng.train_batch([x], [y])
        assert eng._opt_step == opt_step_before, \
            "a skipped step must not advance Adam's bias correction"
        assert guard.rollbacks == 1

    def test_dispatch_error_retried(self):
        guard = TrainGuard(snapshot_every=10, retries=2,
                           retry_base_delay=0.001)
        eng = _make_engine(guard)
        (x, y), = _batches(1)
        faults.inject("dispatch_error", count=2)
        loss, _ = eng.train_batch([x], [y])
        assert np.isfinite(float(np.asarray(loss)))
        assert guard.retry_stats.retries == 2
        assert not faults.armed("dispatch_error")

    def test_retry_budget_exhausted_raises(self):
        guard = TrainGuard(retries=1, retry_base_delay=0.001)
        eng = _make_engine(guard)
        (x, y), = _batches(1)
        faults.inject("dispatch_error", count=5)
        with pytest.raises(TransientError):
            eng.train_batch([x], [y])
        assert guard.retry_stats.gave_up == 1

    def test_scaler_composition(self):
        """GradScaler rides the guarded step: found-inf drops the
        dynamic scale in-step and the host counters track it."""
        scaler = GradScaler(init_loss_scaling=1024.0,
                            incr_every_n_steps=10_000)
        guard = TrainGuard(snapshot_every=5, rollback_after=5,
                           scaler=scaler)
        eng = _make_engine(guard)
        faults.inject("nan_grads", step=2)
        for x, y in _batches(4, seed=3):
            eng.train_batch([x], [y])
        assert scaler.found_inf_count == 1
        assert scaler.skip_count == 1
        assert float(np.asarray(eng._scaler_state["scale"])) == 512.0

    def test_rollback_restores_lr_schedule(self):
        """A rollback that rewinds opt_step must rewind the LR
        scheduler with it — and the resulting loss curve must still
        match the skip-equivalent golden run UNDER A SCHEDULE (the
        review finding: constant-LR tests could not see this)."""
        def build(guard=None):
            paddle.seed(0)
            net = paddle.nn.Linear(8, 4)
            model = paddle.Model(net)
            sched = paddle.optimizer.lr.StepDecay(0.05, step_size=2,
                                                  gamma=0.5)
            model.prepare(
                paddle.optimizer.AdamW(sched,
                                       parameters=net.parameters()),
                paddle.nn.CrossEntropyLoss(), guard=guard)
            return model, sched

        rng = np.random.default_rng(7)
        X = rng.standard_normal((48, 8)).astype("float32")
        Y = rng.integers(0, 4, (48,)).astype("int64")
        bad = (3, 4, 5)   # 1-indexed steps of the storm
        keep = [i for i in range(12) if i + 1 not in bad]
        Xg = np.concatenate([X[i * 4:(i + 1) * 4] for i in keep])
        Yg = np.concatenate([Y[i * 4:(i + 1) * 4] for i in keep])

        golden_model, golden_sched = build()
        gl = []

        class G(paddle.callbacks.Callback):
            def on_train_batch_end(self, s, logs=None):
                gl.append(float(logs["loss"][0]))

        golden_model.fit(paddle.io.TensorDataset([Xg, Yg]), epochs=1,
                         batch_size=4, verbose=0, shuffle=False,
                         callbacks=[G()])

        guard = TrainGuard(snapshot_every=1, rollback_after=3)
        model, sched = build(guard)
        il = []

        class R(paddle.callbacks.Callback):
            def on_train_batch_end(self, s, logs=None):
                il.append(float(logs["loss"][0]))

        faults.inject("nan_grads", step=bad[0], count=len(bad))
        model.fit(paddle.io.TensorDataset([X, Y]), epochs=1,
                  batch_size=4, verbose=0, shuffle=False,
                  callbacks=[R()])
        assert guard.rollbacks == 1
        survived = [l for i, l in enumerate(il) if i + 1 not in bad]
        np.testing.assert_allclose(survived, gl, rtol=1e-5, atol=1e-7)
        # schedule position tracks APPLIED updates on both runs
        assert float(sched()) == float(golden_sched())

    def test_guard_refuses_accumulation_paths(self):
        eng = _make_engine(TrainGuard())
        (x, y), = _batches(1)
        with pytest.raises(ValueError, match="TrainGuard"):
            eng.train_batch_accum([x], [y], apply_update=True)
        with pytest.raises(ValueError, match="TrainGuard"):
            eng.train_batch_multi([x[None]], [y[None]])

    def test_guard_swap_resets_scaler_state(self):
        """A new guard's scaler must start from ITS init scale, not
        inherit the previous scaler's decayed in-step state."""
        s1 = GradScaler(init_loss_scaling=1024.0)
        eng = _make_engine(TrainGuard(scaler=s1, snapshot_every=10))
        (x, y), = _batches(1)
        faults.inject("nan_grads", step=1)
        eng.train_batch([x], [y])            # found-inf: 1024 -> 512
        assert float(np.asarray(eng._scaler_state["scale"])) == 512.0
        s2 = GradScaler(init_loss_scaling=256.0)
        eng.guard = TrainGuard(scaler=s2, snapshot_every=10)
        eng.train_batch([x], [y])
        assert float(np.asarray(eng._scaler_state["scale"])) == 256.0

    def test_detach_via_assignment(self):
        """engine.guard = None (the error messages' advice) must drop
        the guarded executable, not feed it plain-signature args."""
        eng = _make_engine(TrainGuard(snapshot_every=10))
        (x, y), = _batches(1)
        eng.train_batch([x], [y])          # compiles the guarded step
        eng.guard = None
        loss, _ = eng.train_batch([x], [y])  # plain step, fresh build
        assert np.isfinite(float(np.asarray(loss)))
        eng.guard = TrainGuard()             # and back
        loss, _ = eng.train_batch([x], [y])
        assert np.isfinite(float(np.asarray(loss)))

    def test_eager_unscale_then_step_divides_once(self):
        """Explicit unscale_() -> step() (the standard AMP pattern for
        gradient clipping between the two) must divide by the loss
        scale exactly ONCE — step() used to re-unscale."""
        paddle.seed(0)
        net = paddle.nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(learning_rate=1.0,
                                   parameters=net.parameters())
        scaler = GradScaler(init_loss_scaling=1024.0)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = scaler.scale(net(x).sum())
        loss.backward()
        w0 = np.array(net.weight.numpy())
        scaler.unscale_(opt)
        g = np.array(net.weight._grad_value)   # unscaled exactly once
        scaler.step(opt)
        w1 = np.array(net.weight.numpy())
        np.testing.assert_allclose(w0 - w1, g, rtol=1e-5,
                                   err_msg="step() re-unscaled grads")
        assert scaler.skip_count == 0

    def test_fit_logs_guard_scalars(self):
        """hapi fit() surfaces skip/found-inf counters in batch logs
        (the satellite mirroring criterion.last_mlm_overflow)."""
        paddle.seed(0)
        net = paddle.nn.Linear(8, 4)
        model = paddle.Model(net)
        scaler = GradScaler(init_loss_scaling=256.0)
        model.prepare(
            paddle.optimizer.AdamW(1e-2, parameters=net.parameters()),
            paddle.nn.CrossEntropyLoss(),
            guard=TrainGuard(snapshot_every=2, rollback_after=4,
                             scaler=scaler))
        rng = np.random.default_rng(0)
        X = rng.standard_normal((16, 8)).astype("float32")
        Y = rng.integers(0, 4, (16,)).astype("int64")
        ds = paddle.io.TensorDataset([X, Y])
        seen = {}

        class Rec(paddle.callbacks.Callback):
            def on_train_batch_end(self, step, logs=None):
                seen.update(logs or {})

        faults.inject("nan_grads", step=2)
        model.fit(ds, epochs=1, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[Rec()])
        assert seen["skipped"] == 1
        assert seen["found_inf"] == 1
        assert seen["rollbacks"] == 0


# -- preemption -----------------------------------------------------------

def _fit_run(ckdir, total_steps, seed=0, resume=False, sigterm_at=None,
             losses=None):
    """One fit 'process': deterministic per-step batches; optionally a
    sigterm fault armed at an engine step; optionally resumes from the
    manager first. Returns (model, manager, callback)."""
    paddle.seed(seed)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                               paddle.nn.Linear(16, 4))
    model = paddle.Model(net)
    sched = paddle.optimizer.lr.StepDecay(0.05, step_size=3, gamma=0.5)
    model.prepare(paddle.optimizer.AdamW(sched,
                                         parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss())
    mgr = CheckpointManager(ckdir, keep_max=3)
    start = 0
    if resume:
        restored = preemption.restore_training_state(model, mgr)
        assert restored is not None, "nothing to resume from"
        start = restored

    rng = np.random.default_rng(42)
    all_b = [(rng.standard_normal((8, 8)).astype("float32"),
              rng.integers(0, 4, (8,)).astype("int64"))
             for _ in range(total_steps)]
    X = np.stack([b[0] for b in all_b[start:]]).reshape(-1, 8)
    Y = np.stack([b[1] for b in all_b[start:]]).reshape(-1)
    ds = paddle.io.TensorDataset([X, Y])

    class Rec(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            l = logs["loss"]
            losses[start + step + 1] = float(
                l[0] if isinstance(l, (list, tuple)) else l)

    cb = paddle.callbacks.PreemptionCheckpoint(mgr)
    if sigterm_at is not None:
        faults.inject("sigterm", step=sigterm_at)
    model.fit(ds, epochs=1, batch_size=8, verbose=0, shuffle=False,
              callbacks=[Rec(), cb])
    return model, mgr, cb


class TestPreemption:
    def test_flag_mechanics(self):
        assert not preemption.requested()
        preemption.request()
        assert preemption.requested()
        preemption.clear()
        assert not preemption.requested()

    def test_sigterm_checkpoint_and_exact_resume(self, tmp_path):
        """Acceptance criterion: a SIGTERM-injected run checkpoints at
        the step boundary (finalized) and resumes loss-exact."""
        TOTAL, KILL = 10, 6
        golden = {}
        _fit_run(str(tmp_path / "gold"), TOTAL, losses=golden)
        assert len(golden) == TOTAL

        victim = {}
        _, mgr, cb = _fit_run(str(tmp_path / "ck"), TOTAL,
                              sigterm_at=KILL, losses=victim)
        assert cb.preempted and cb.saved_step == KILL
        assert max(victim) == KILL, "fit must stop at the boundary"
        assert mgr.is_finalized(KILL), "preemption save must finalize"
        # pre-kill curve identical to golden
        for s in range(1, KILL + 1):
            np.testing.assert_allclose(victim[s], golden[s], rtol=1e-6)

        # note: NO manual preemption.clear() — restore_training_state
        # resets the sticky flag itself (the documented resume recipe
        # must work in-process too)
        resumed = {}
        _fit_run(str(tmp_path / "ck"), TOTAL, resume=True,
                 losses=resumed)
        assert min(resumed) == KILL + 1 and max(resumed) == TOTAL
        for s in sorted(resumed):
            np.testing.assert_allclose(
                resumed[s], golden[s], rtol=1e-6, atol=1e-8,
                err_msg=f"resume diverged at step {s}")

    def test_real_signal_sets_flag(self):
        preemption.install()
        signal.raise_signal(signal.SIGTERM)
        assert preemption.requested()

    def test_sigint_does_not_raise_keyboardinterrupt(self):
        """Python's default SIGINT handler must NOT be chained — a
        KeyboardInterrupt mid-step is the unclean unwind this module
        replaces with a boundary checkpoint."""
        preemption.install()
        signal.raise_signal(signal.SIGINT)   # would raise if chained
        assert preemption.requested()


# -- checkpoint finalize --------------------------------------------------

class TestCheckpointFinalize:
    def _st(self, v):
        return {"w": jnp.full((4,), float(v)), "step": int(v)}

    def test_torn_write_skipped(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "ck", keep_max=5)
        mgr.save(1, self._st(1))
        mgr.save(2, self._st(2))
        faults.inject("torn_ckpt", step=3)
        mgr.save(3, self._st(3))
        assert not mgr.is_finalized(3) and mgr.is_finalized(2)
        assert mgr.latest_step() == 2
        assert mgr.restore()["step"] == 2
        assert mgr.finalized_steps() == [1, 2]

    def test_corrupt_finalized_falls_back(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "ck", keep_max=5)
        mgr.save(1, self._st(1))
        mgr.save(2, self._st(2))
        with open(os.path.join(mgr._step_dir(2), "state.pdparams"),
                  "wb") as f:
            f.write(b"not a checkpoint")
        with pytest.warns(UserWarning, match="unreadable"):
            st = mgr.restore()
        assert st["step"] == 1

    def test_explicit_step_still_raises(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "ck")
        mgr.save(1, self._st(1))
        with open(os.path.join(mgr._step_dir(1), "state.pdparams"),
                  "wb") as f:
            f.write(b"junk")
        with pytest.raises(Exception):
            mgr.restore(step=1)

    def test_best_requires_finalized(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "ck", keep_max=5)
        mgr.save(1, self._st(1), metric=0.5)
        faults.inject("torn_ckpt", step=2)
        mgr.save(2, self._st(2), metric=0.9)   # torn best candidate
        assert mgr.best_step() is None or mgr.is_finalized(
            mgr.best_step())
        mgr.save(3, self._st(3), metric=0.7)
        assert mgr.restore(best=False)["step"] == 3

    def test_empty_dir_returns_none(self, tmp_path):
        mgr = CheckpointManager(tmp_path / "ck")
        assert mgr.restore() is None and mgr.latest_step() is None

    def test_legacy_premarker_checkpoints_still_restore(self, tmp_path):
        """Dirs written by the pre-marker CheckpointManager (no
        COMPLETE file, format-1 index) were finalized by the old
        atomic-rename contract — an upgrade must keep them
        restorable."""
        import json
        mgr = CheckpointManager(tmp_path / "ck", keep_max=5)
        mgr.save(1, self._st(1))
        mgr.save(2, self._st(2))
        # rewrite history: strip markers + the format field
        for s in (1, 2):
            os.remove(os.path.join(mgr._step_dir(s), "COMPLETE"))
        with open(mgr._index_path()) as f:
            idx = json.load(f)
        idx.pop("format"), idx.pop("legacy_steps")
        with open(mgr._index_path(), "w") as f:
            json.dump(idx, f)
        mgr2 = CheckpointManager(tmp_path / "ck", keep_max=5)
        assert mgr2.latest_step() == 2
        assert mgr2.restore()["step"] == 2
        # new saves coexist and torn detection still works on them
        faults.inject("torn_ckpt", step=3)
        mgr2.save(3, self._st(3))
        assert mgr2.latest_step() == 2

    def test_torn_saves_never_age_out_finalized(self, tmp_path):
        """Retention counts finalized checkpoints only: a burst of
        torn saves must not crowd every restorable dir out of the
        keep_max window."""
        mgr = CheckpointManager(tmp_path / "ck", keep_max=2)
        mgr.save(1, self._st(1))
        mgr.save(2, self._st(2))
        for s in (3, 4, 5):
            faults.inject("torn_ckpt", step=s)
            mgr.save(s, self._st(s))
        assert mgr.finalized_steps() == [1, 2]
        assert mgr.restore()["step"] == 2


# -- watchdog -------------------------------------------------------------

class TestWatchdog:
    def test_flags_overrun_and_recovers(self):
        wd = Watchdog(timeout_s=0.01, poll_s=0.005)
        wedges = []
        wd.on_wedge = lambda op, dt: wedges.append((op, dt))
        wd.begin("decode")
        time.sleep(0.03)
        assert wd.check(), "op past timeout must read as wedged"
        assert wd.wedged and wd.wedge_count == 1
        assert wd.check() and wd.wedge_count == 1, \
            "one wedge event per in-flight op"
        wd.end()
        assert not wd.wedged, "a returned op clears the live flag"
        assert wedges and wedges[0][0] == "decode"
        h = wd.health()
        assert h["wedge_count"] == 1 and h["inflight_op"] is None

    def test_fast_op_never_flags(self):
        wd = Watchdog(timeout_s=5.0)
        with wd.watch("prefill"):
            pass
        assert not wd.check() and wd.wedge_count == 0


# -- serving chaos --------------------------------------------------------

@pytest.fixture(scope="module")
def gpt_model():
    from paddle_tpu.nlp.gpt import GPTForCausalLM, _resolve_config
    paddle.seed(0)
    m = GPTForCausalLM(_resolve_config("gpt-tiny"))
    m.eval()
    return m


@pytest.fixture(scope="module")
def serve_eng(gpt_model):
    """ONE engine for the whole chaos class (compiles once): the
    degradation knobs under test — admission_policy, deadlines,
    cancels, faults — are host-side state, so tests flip them between
    (fully drained) waves instead of paying a fresh engine's traces."""
    from paddle_tpu.nlp.serving import ServingEngine
    eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                        max_seq_len=48, num_pages=5,
                        steps_per_dispatch=2, watchdog_timeout=0.05)
    yield eng
    eng.close()
    assert eng._watchdog is None, "close() must stop the watchdog"


@pytest.fixture(autouse=True)
def _drained(request):
    """Every serving test must leave the shared engine empty."""
    yield
    if "serve_eng" in request.fixturenames:
        eng = request.getfixturevalue("serve_eng")
        eng.admission_policy = "wait"
        assert not eng._queue and all(s is None for s in eng._slots)
        assert eng.free_page_count == eng.num_pages - 1


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n,)).astype(np.int32)


class TestServingChaos:
    def test_deadline_expiry_and_cancel(self, serve_eng):
        eng = serve_eng
        ok_r = eng.submit(_prompt(5), max_new_tokens=6)
        dead = eng.submit(_prompt(7, 1), max_new_tokens=6,
                          deadline_ms=0)
        time.sleep(0.002)
        res = {r["id"]: r for r in eng.run_to_completion()}
        assert res[dead]["status"] == "expired"
        assert res[dead]["tokens"] == []
        assert res[ok_r]["status"] == "ok"
        assert len(res[ok_r]["tokens"]) == 6

        # cancel a RUNNING request: partial tokens, pages recycled
        free0 = eng.free_page_count
        a = eng.submit(_prompt(5), max_new_tokens=12)
        b = eng.submit(_prompt(6, 2), max_new_tokens=12)
        eng.step()
        assert eng.cancel(b)
        assert not eng.cancel(12345), "unknown rid -> False"
        res = {r["id"]: r for r in eng.run_to_completion()}
        assert res[b]["status"] == "cancelled"
        assert 0 < len(res[b]["tokens"]) < 12
        assert res[a]["status"] == "ok" and len(res[a]["tokens"]) == 12
        assert eng.free_page_count == free0, "cancel leaked pages"

    def test_submit_rejects_impossible_request(self, gpt_model):
        """Satellite: a prompt needing more pages than the pool can
        EVER hold must fail fast, not wedge the admission queue.
        (Engine construction traces nothing, so this stays cheap.)"""
        from paddle_tpu.nlp.serving import ServingEngine
        eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                            max_seq_len=64, num_pages=3)
        with pytest.raises(ValueError, match="wedge"):
            eng.submit(_prompt(40), max_new_tokens=10)
        # boundary: exactly pool-sized request queues fine
        eng.submit(_prompt(20), max_new_tokens=10)
        assert eng.health()["queued"] == 1

    def test_reject_policy_under_injected_exhaustion(self, serve_eng):
        eng = serve_eng
        eng.admission_policy = "reject"
        faults.inject("page_exhaustion", count=100)
        rid = eng.submit(_prompt(5), max_new_tokens=6)
        res = {r["id"]: r for r in eng.run_to_completion()}
        faults.clear()
        assert res[rid]["status"] == "rejected"
        assert eng.health()["status_counts"]["rejected"] == 1
        # exhaustion cleared: the engine serves again
        rid2 = eng.submit(_prompt(5), max_new_tokens=6)
        res = {r["id"]: r for r in eng.run_to_completion()}
        assert res[rid2]["status"] == "ok"

    def test_evict_lowest_priority(self, serve_eng):
        eng = serve_eng
        eng.admission_policy = "evict"
        lo = eng.submit(_prompt(5), max_new_tokens=20, priority=0)
        mid = eng.submit(_prompt(6, 5), max_new_tokens=20, priority=1)
        eng.step()
        hi = eng.submit(_prompt(5, 6), max_new_tokens=8, priority=5)
        res = {r["id"]: r for r in eng.run_to_completion()}
        assert res[lo]["status"] == "evicted"
        assert 0 < len(res[lo]["tokens"]) < 20, "partial result kept"
        assert res[hi]["status"] == "ok" and len(res[hi]["tokens"]) == 8
        assert res[mid]["status"] == "ok"
        assert eng.free_page_count == 4, "eviction leaked pages"
        # equal priority never evicts: both complete via back-pressure
        a = eng.submit(_prompt(5, 8), max_new_tokens=6, priority=3)
        b = eng.submit(_prompt(6, 9), max_new_tokens=6, priority=3)
        res = {r["id"]: r for r in eng.run_to_completion()}
        assert res[a]["status"] == res[b]["status"] == "ok"

    def test_chaos_wave_zero_recompile(self, serve_eng):
        """Acceptance criterion: a chaos wave (slow step, transient
        dispatch errors, injected page exhaustion, a cancel, a
        deadline) completes every non-expired request with
        compile_counts() UNCHANGED after warmup — degradation is pure
        host-side scheduling."""
        eng = serve_eng
        ref = eng.generate([_prompt(5), _prompt(9, 7)],
                           max_new_tokens=6)           # warmup wave
        frozen = eng.compile_counts()
        wedges0 = eng.health()["watchdog"]["wedge_count"]

        faults.inject("slow_step", seconds=0.25)
        faults.inject("dispatch_error", count=2)
        faults.inject("page_exhaustion", count=2)
        r1 = eng.submit(_prompt(5), max_new_tokens=6)   # same bucket
        r2 = eng.submit(_prompt(9, 7), max_new_tokens=6)
        r3 = eng.submit(_prompt(6, 8), max_new_tokens=12)
        r4 = eng.submit(_prompt(7, 9), max_new_tokens=6,
                        deadline_ms=0)                  # will expire
        early = eng.step()   # r4 may already expire this round
        eng.cancel(r3)
        res = {r["id"]: r
               for r in early + eng.run_to_completion()}
        faults.clear()

        assert res[r1]["status"] == "ok" and res[r1]["tokens"] == ref[0]
        assert res[r2]["status"] == "ok" and res[r2]["tokens"] == ref[1]
        assert res[r3]["status"] == "cancelled"
        assert res[r4]["status"] == "expired"
        assert eng.compile_counts() == frozen, \
            "chaos must not trigger a single new trace"
        h = eng.health()
        assert h["dispatch_retries"] == 2
        assert h["watchdog"]["wedge_count"] > wedges0, \
            "the injected stall must register as a wedge"
        assert h["running"] == 0 and h["queued"] == 0

    def test_health_snapshot_shape(self, gpt_model, serve_eng):
        from paddle_tpu.nlp.serving import ServingEngine
        eng = ServingEngine(gpt_model, max_slots=2, page_size=16,
                            max_seq_len=48)   # traces nothing unused
        eng.submit(_prompt(5), max_new_tokens=4)
        h = eng.health()
        assert h["queued"] == 1 and h["running"] == 0
        assert h["free_pages"] == h["total_pages"]
        for k in ("rounds", "decode_dispatches", "status_counts",
                  "compile_counts", "admission_policy"):
            assert k in h
        assert "watchdog" not in h, "no watchdog armed -> no section"
        # the shared (armed) engine carries the section + ok counts
        h2 = serve_eng.health()
        assert "watchdog" in h2
        assert h2["status_counts"]["ok"] >= 1
        # drain the queued request cheaply: cancel resolves host-side
        eng.cancel(0)
        assert eng.step()[0]["status"] == "cancelled"
