"""Seeded tpulint violations — the staticcheck gate-trip fixture.

tests/test_tpulint.py runs ``python -m tools.tpulint --root
tests/fixtures/tpulint bad`` and asserts exit 1 with exactly this
finding mix; the ``good/`` twin must exit 0. Together they prove the
campaign's staticcheck gate in BOTH directions without touching the
shipping tree. (tests/ is outside the default scan targets, so these
seeds can never leak into the real repo sweep.)
"""
import os

import jax


def untraced(fn):
    return jax.jit(fn)                                    # TRC01


def clock_in_trace():
    import time

    def body(x):
        return x + time.time()                            # TRC02

    return jax.jit(body)                                  # TRC01


def clobber_golden(doc):
    golden = os.path.join("tools", "golden", "wave.json")
    with open(golden, "w") as f:                          # DUR01
        f.write(doc)


def undocumented_knob():
    return os.environ.get("PADDLE_TPU_SEEDED_BOGUS")      # DOC01
