"""The gate-proof twin of ``bad/violations.py``: every contract the
seeded file breaks, honored — tpulint over this tree must exit 0."""
import os


def traced(tracer, fn):
    # routed through a RecompileTracer site: TRC01-clean
    return tracer.jit("fixture_site", fn)


def durable_write(doc):
    from paddle_tpu.io import atomic
    golden = os.path.join("tools", "golden", "wave.json")
    return atomic.atomic_replace(golden, doc)
